"""The batched (model-axis) chunk program: ``jax.vmap`` over the EXACT
solo chunk body.

``macro.make_chunk_fn`` returns the unjitted fused-chunk callable; the
solo program is ``jit(chunk_fn)`` and the batched program built here is
``jit(vmap(chunk_fn))`` over a leading lane axis — the same trace, so a
lane's math is the solo math.  Bit-parity of the extracted models
(tests/test_multi.py byte-compares model text) additionally needs the
device ops the body reaches to accumulate order-invariantly under
batching, which holds for the scatter-add and integer histogram paths
(the families elected on CPU and for quantized training) — measured, not
assumed: the parity matrix pins it per mode.  f32 matmul histogram
variants reassociate under a batch dimension and carry no bitwise claim
(docs/PERF.md "model axis").

Liveness: a finished lane (early stop, per-lane round budget) keeps its
slot — the driver feeds it inert zero inputs drawn from NO RNG stream
(`dead_inputs`) and discards its outputs, so the batch never retraces
when one booster finishes and the survivors' lanes stay bit-identical.
vmap lanes never mix data, so a dead lane's garbage cannot leak into a
live one.

Stacked-data groups (CV folds) additionally swap the objective's baked
per-dataset arrays (label, binary's label_sign, multiclass one-hots)
for traced lane-stacked arguments during the ONE vmap trace — the
rebind-at-trace trick below — because ``gradients_fn`` reads them off
the live objective instance as closure constants.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..boosting.macro import chunk_host_inputs, make_chunk_fn
from .group import MultiGroup, objective_array_attrs


def _put_rows_last(b0, arr: jax.Array) -> jax.Array:
    """Re-place a lane-stacked array whose LAST axis is the row axis so
    rows keep the data sharding (the lane/model axis is replicated) —
    the batched twin of parallel.learners.put_stacked_rows."""
    if b0._mesh is None or b0._data_axis is None:
        return arr
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(*([None] * (arr.ndim - 1) + [b0._data_axis]))
    return jax.device_put(arr, NamedSharding(b0._mesh, spec))


def stack_lanes(b0, arrs: Sequence[jax.Array], rows_last: bool) -> jax.Array:
    """Stack per-lane arrays along a new leading model axis; arrays whose
    trailing axis is the (possibly sharded) row axis keep that sharding."""
    out = jnp.stack(list(arrs))
    return _put_rows_last(b0, out) if rows_last else out


class BatchedChunkProgram:
    """One group's vmapped chunk program + lane input assembly.

    ``dispatch(c, lanes, lr_lists)`` advances every live lane ``c``
    iterations in ONE device program and runs each live booster's
    ``_finish_chunk`` host bookkeeping (the same code path solo training
    uses, so model extraction, deferred-tree banking, valid-score
    updates and stop detection are inherited, not reimplemented).
    """

    def __init__(self, group: MultiGroup):
        self.group = group
        self.b0 = b0 = group.boosters[0]
        self.stacked = group.stacked
        self._obj_attrs = (objective_array_attrs(b0.objective)
                          if group.stacked else [])
        self._dead_xs_templates = {}    # chunk size c -> inert zero xs
        chunk_fn = make_chunk_fn(b0)
        obj = b0.objective

        def wrapped(binned, score, cu, cr, n_steps, xs, label_r, weight_r,
                    grad_c, hess_c, obj_arrs):
            # rebind-at-trace: vmap traces this body once with ``obj_arrs``
            # as lane-batched tracers; gradients_fn reads the objective's
            # arrays at trace time, so pointing them at the tracers makes
            # the ONE trace consume per-lane labels.  Restored immediately
            # — the live objective never holds tracers after tracing.
            saved = {k: getattr(obj, k) for k in obj_arrs}
            for k, v in obj_arrs.items():
                setattr(obj, k, v)
            try:
                return chunk_fn(binned, score, cu, cr, n_steps, xs,
                                label_r, weight_r, grad_c, hess_c)
            finally:
                for k, v in saved.items():
                    setattr(obj, k, v)

        data_ax = 0 if self.stacked else None
        self._fn = jax.jit(
            jax.vmap(wrapped,
                     in_axes=(data_ax, 0, 0, 0, None, 0, data_ax, data_ax,
                              None, None, 0)),
            donate_argnums=(1,))
        if self.stacked:
            self._binned_B = stack_lanes(
                b0, [b.binned for b in group.boosters], rows_last=True)
            self._label_B = stack_lanes(
                b0, [b._macro_ctx["label"] for b in group.boosters],
                rows_last=True)
            self._weight_B = stack_lanes(
                b0, [b._macro_ctx["weight"] for b in group.boosters],
                rows_last=True)
            self._obj_arrs_B = {
                k: stack_lanes(
                    b0, [jnp.asarray(getattr(b.objective, k))
                         for b in group.boosters],
                    rows_last=False)
                for k in self._obj_attrs}
        else:
            self._binned_B = b0.binned
            self._label_B = b0._macro_ctx["label"]
            self._weight_B = b0._macro_ctx["weight"]
            self._obj_arrs_B = {}

    # ------------------------------------------------------------ inputs

    def _lane_inputs(self, b, live: bool, c: int, lrs):
        """One lane's per-chunk host inputs.  Live lanes draw from the
        booster's real RNG streams (exact solo order — chunk_host_inputs
        is the same helper run_chunk uses); dead lanes get inert zeros
        drawn from NO stream, so a finished booster's replayable state
        never advances."""
        if live:
            b.boost_from_average()
            xs, lr_list = chunk_host_inputs(b, c, lrs)
            # xs shapes carry the chunk size in their leading axis, so
            # the inert template is cached PER chunk size
            if c not in self._dead_xs_templates:
                self._dead_xs_templates[c] = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, a.dtype), xs)
            return xs, lr_list
        if c not in self._dead_xs_templates:
            raise RuntimeError("batched chunk dispatched with no live lane")
        return self._dead_xs_templates[c], [0.0] * c

    # ---------------------------------------------------------- dispatch

    def dispatch(self, c: int, live: List[bool],
                 lr_lists: Sequence) -> List[bool]:
        """Advance live lanes ``c`` iterations; returns per-lane
        ``stopped`` flags (True = no more splittable leaves, the solo
        ``run_chunk`` contract; dead lanes report False)."""
        bs = self.group.boosters
        b0 = self.b0
        n_lanes = len(bs)
        lane_xs = [None] * n_lanes
        lane_lrs = [None] * n_lanes
        it0s = [b.iter for b in bs]
        # live lanes first: they seed the inert template a dead lane
        # earlier in the list needs for this chunk size
        for i in range(n_lanes):
            if live[i]:
                lane_xs[i], lane_lrs[i] = self._lane_inputs(
                    bs[i], True, c, lr_lists[i])
        for i in range(n_lanes):
            if not live[i]:
                lane_xs[i], lane_lrs[i] = self._lane_inputs(
                    bs[i], False, c, None)
        xs_B = jax.tree_util.tree_map(
            lambda *a: stack_lanes(b0, a, rows_last=a[0].ndim == 2
                                   and a[0].shape[-1] == b0._n_pad),
            *lane_xs)
        score_B = stack_lanes(b0, [b.train_score for b in bs],
                              rows_last=True)
        cu_B = jnp.stack([b._cegb_state[0] for b in bs])
        cr_B = jnp.stack([b._cegb_state[1] for b in bs])
        grad_c, hess_c = b0._macro_const_grads()

        from ..obs.metrics import global_registry as _obs_registry
        from ..obs.trace import span as _span
        from ..utils.timer import global_timer
        _obs_registry.counter("multi_chunk_dispatches").inc()
        _obs_registry.histogram(
            "multi_batch_lanes",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0)).observe(len(bs))
        with global_timer.section("TreeLearner::Train(dispatch)"), \
                _span("multi.dispatch", lanes=len(bs), c=c,
                      live=sum(map(bool, live))):
            score_B, cu_B, cr_B, ys_B, qss_B = self._fn(
                self._binned_B, score_B, cu_B, cr_B, np.int32(c), xs_B,
                self._label_B, self._weight_B, grad_c, hess_c,
                self._obj_arrs_B)

        stopped = [False] * len(bs)
        for i, (b, is_live) in enumerate(zip(bs, live)):
            if not is_live:
                continue
            b.train_score = score_B[i]
            b._cegb_state = (cu_B[i], cr_B[i])
            if getattr(b, "_quant_on", False):
                b._quant_scales = qss_B[i][c - 1]
            seq_i = jax.tree_util.tree_map(lambda a, _i=i: a[_i], ys_B)
            stopped[i] = b._finish_chunk(seq_i, c, lane_lrs[i], it0s[i])
        return stopped
