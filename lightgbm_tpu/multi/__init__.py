"""Batched multi-booster training: a model axis that fills the idle MXU.

Train B boosters — CV folds, a hyperparameter sweep, per-segment model
families — in ONE device dispatch by vmapping the fused macro-chunk
program (boosting/macro.py) over a leading lane axis.  Each extracted
booster is byte-identical in model text to the same config trained
alone; `ops.planner.plan_model_batch` elects how many lanes one dispatch
may carry under the HBM budget.  docs/PERF.md "model axis" has the
design; tests/test_multi.py pins the parity matrix.
"""

from .batch import BatchedChunkProgram
from .driver import CVStepper, expand_param_grid, train_many
from .group import MultiGroup, group_boosters, structural_key

__all__ = [
    "BatchedChunkProgram", "CVStepper", "MultiGroup",
    "expand_param_grid", "group_boosters", "structural_key",
    "train_many",
]
