"""Structural grouping: which boosters may share one batched program.

``jax.vmap`` traces the chunk body ONCE, so everything the trace bakes in
as a compile-time constant must be EQUAL across the boosters sharing a
lane axis — tree shape (GrowerConfig), objective family and its baked
scalars, padded array shapes, the mesh.  Everything that rides into the
program as a runtime argument — bagging/feature masks, learning-rate
schedules, per-round node keys, GOSS subkeys, row counts via masks — may
differ per lane.  This module computes a conservative structural key:
two boosters land in the same group only when every non-whitelisted
``Config`` field, the derived ``grower_cfg``, and the objective's baked
constants match.  Conservative means CORRECT — an over-split key costs
batching efficiency (smaller groups), never bit-parity.

Two data modes:

* ``shared`` — every booster trains on the SAME ``Dataset`` (a sweep):
  the binned matrix rides into the batched program unbatched
  (``in_axes=None``) and its HBM cost does not scale with B;
* ``stacked`` — per-booster Datasets of identical padded shape (CV
  folds, per-segment families): binned matrices stack along the lane
  axis (×B HBM — ops/planner.plan_model_batch models the difference),
  and the objective's baked per-dataset arrays (labels, binary's
  label_sign, multiclass one-hots) are swapped for traced lane-stacked
  arguments at trace time (multi/batch.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

# Config fields that ride into the batched program as runtime inputs (or
# pure host-side schedule/bookkeeping) and therefore may differ between
# boosters sharing one batched program.  Everything NOT listed here must
# be equal across a group.
RUNTIME_VARYING_FIELDS = frozenset({
    "learning_rate",            # [c]-stacked lr schedule argument
    "bagging_fraction",         # host RNG -> stacked row masks
    "bagging_freq",
    "bagging_seed",
    "feature_fraction",         # host RNG -> stacked feature masks
    "feature_fraction_seed",
    "num_iterations",           # per-lane liveness, not trace structure
    "early_stopping_round",     # host-side callback
    "first_metric_only",
    "metric",                   # host-side evaluation only
    "metric_freq",
    "is_provide_training_metric",
    "verbosity",
    "seed",                     # master seed: only consumed via the
                                # derived per-concern seeds above
    "snapshot_freq",
})


class MultiGroup:
    """One structurally-compatible set of boosters (GBDT objects) that a
    single vmapped chunk program can train; ``stacked`` marks the data
    mode (per-lane binned matrices vs one shared matrix)."""

    def __init__(self, key: tuple, boosters: List, stacked: bool):
        self.key = key
        self.boosters = boosters
        self.stacked = stacked

    def __len__(self) -> int:
        return len(self.boosters)


def objective_array_attrs(obj) -> List[str]:
    """Names of the objective's baked per-dataset device/host arrays —
    the attributes multi/batch.py swaps for traced lane-stacked
    arguments in stacked mode (labels, binary's ``label_sign``,
    multiclass ``label_onehot``...).  Sorted for a deterministic
    argument order."""
    import jax
    if obj is None:
        return []
    return sorted(k for k, v in vars(obj).items()
                  if isinstance(v, (jax.Array, np.ndarray)))


def _objective_fingerprint(obj) -> tuple:
    """The objective's trace-relevant baked scalars.  Private attrs
    (leading underscore, e.g. binary's host-only ``_pavg``) are derived
    caches that never enter the traced program; public scalars (binary's
    is_unbalance class weights, sigmoid steepness riding on config is
    covered by the Config filter) DO bake in and must match."""
    if obj is None:
        return ("none",)
    scalars = tuple(sorted(
        (k, v) for k, v in vars(obj).items()
        if not k.startswith("_") and isinstance(v, (bool, int, float, str))))
    return (type(obj).__name__, scalars, tuple(objective_array_attrs(obj)),
            obj.weight is None if hasattr(obj, "weight") else True)


def _config_fingerprint(cfg) -> tuple:
    """Every Config field that may bake into the traced program, as a
    hashable tuple.  ``repr`` normalizes list-valued fields."""
    return tuple(sorted(
        (k, repr(v)) for k, v in vars(cfg).items()
        if k not in RUNTIME_VARYING_FIELDS))


def structural_key(b, stacked: bool) -> Optional[tuple]:
    """The structural group key for GBDT ``b``, or None when ``b`` cannot
    join ANY batched group (it then trains through the solo chunk path).

    ``stacked=False`` keys on the training Dataset's identity — lanes of
    a shared-data group index one device matrix.  ``stacked=True`` keys
    on shape/dtype instead, and excludes boosting families whose traced
    closures bake per-dataset values beyond the swappable objective
    arrays (RF's init-score column)."""
    if not b.chunk_supported():
        return None
    if stacked and b.boosting_type == "rf":
        # rf bakes init_scores (data-derived) into the chunk closure;
        # per-lane datasets would need per-lane closures — not vmappable
        return None
    if b.binned is None:      # out-of-core streamed executor
        return None
    data_key = (("stacked",) + tuple(b.binned.shape) + (str(b.binned.dtype),)
                if stacked else ("shared", id(b.train_set), id(b.binned)))
    mesh_key = (id(b._mesh) if b._mesh is not None else None,
                b._data_axis, b._feature_axis)
    return (data_key, mesh_key,
            b.boosting_type, b.num_tree_per_iteration,
            b.num_data, b._n_pad,
            # GrowerConfig.learning_rate is carried for bookkeeping but
            # never read in a traced body (shrinkage rides the runtime
            # [c] lr input) — normalize it so heterogeneous-lr sweeps
            # share one program
            b.grower_cfg._replace(learning_rate=0.0),
            _config_fingerprint(b.config),
            _objective_fingerprint(b.objective),
            b.train_set.metadata.init_score is not None,
            bool(getattr(b, "_quant_on", False)))


def group_boosters(bs: Sequence, stacked: bool) -> List[MultiGroup]:
    """Partition GBDTs into batched groups (insertion-ordered, so the
    driver trains lanes in a deterministic order).  Boosters with key
    None become singleton groups with ``key=None`` — the driver routes
    those through the solo chunk path."""
    groups: dict = {}
    out: List[MultiGroup] = []
    for b in bs:
        key = structural_key(b, stacked)
        if key is None:
            out.append(MultiGroup(None, [b], stacked))
            continue
        g = groups.get(key)
        if g is None:
            g = groups[key] = MultiGroup(key, [], stacked)
            out.append(g)
        g.boosters.append(b)
    return out
