"""train_many: B boosters, one device dispatch — the model-axis driver.

A single booster's macro-chunk program leaves most of the chip idle at
small-data shapes (the bench's MFU column): one tree's histogram passes
cannot fill the MXU.  CV folds, hyperparameter sweeps and per-segment
model families are embarrassingly parallel ACROSS MODELS, so this driver
trains them along a vmapped lane axis of ONE program over one (shared
or lane-stacked) binned matrix instead of B sequential runs.

Pipeline:

1. build a ``Booster`` per config (the ordinary constructor — nothing
   about a lane's host state knows it is batched);
2. partition structurally (multi/group.py): lanes sharing one compiled
   program must agree on everything the trace bakes in;
3. per group, ask ``ops.planner.plan_model_batch`` for the largest lane
   chunk the HBM budget admits and split into sequential dispatch groups
   when it says no;
4. drive each dispatch group through the engine's OWN scheduling rules —
   chunk sizes from ``pow2_chunk`` over the nearest live lane's boundary
   (eval cadence, snapshots, per-lane round budgets), per-lane
   callbacks/eval/early-stop at boundaries — with dead lanes frozen via
   inert inputs (multi/batch.py), never a retrace;
5. each finished lane IS an ordinary trained ``Booster``: model text is
   byte-identical to the same config trained alone
   (tests/test_multi.py), so checkpoint capture, serving and the fleet's
   probe-quarantine hot-swap consume them unchanged.

Unbatchable configs (no chunk support: DART, CEGB, forced splits,
custom fobj) and singleton groups fall back to the solo path, same
scheduling loop.
"""

from __future__ import annotations

import collections
import itertools
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .. import callback as callback_mod
from ..basic import Booster
from ..config import Config
from ..dataset import Dataset
from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import span as _span
from .batch import BatchedChunkProgram
from .group import MultiGroup, group_boosters


def expand_param_grid(grid: dict) -> List[dict]:
    """Cartesian product over the grid's list-valued entries, in sorted
    key order, each point a full params dict::

        expand_param_grid({"objective": "binary",
                           "learning_rate": [0.05, 0.1],
                           "num_leaves": [15, 31]})
        # -> 4 configs

    A list-valued field whose lists should NOT expand (e.g.
    ``interaction_constraints``) must be wrapped one level:
    ``[[...constraint lists...]]`` expands to the inner list.
    """
    fixed = {k: v for k, v in grid.items() if not isinstance(v, list)}
    sweep = {k: v for k, v in grid.items() if isinstance(v, list)}
    if not sweep:
        return [dict(fixed)]
    keys = sorted(sweep)
    out = []
    for combo in itertools.product(*(sweep[k] for k in keys)):
        p = dict(fixed)
        p.update(zip(keys, combo))
        out.append(p)
    return out


class _Lane:
    """One booster's host-side training state inside the driver's loop —
    the per-lane half of what engine.train keeps in locals."""

    def __init__(self, index: int, booster: Booster, params: dict,
                 rounds: int, cbs: list, feval, verbose_eval,
                 snapshot_freq: int, snapshot_out: Optional[str],
                 snapshot_keep: int, train_in_valid: bool = False):
        self.index = index
        self.booster = booster
        self.params = params
        self.rounds = rounds
        self.feval = feval
        self.train_in_valid = train_in_valid
        self.it = 0
        self.live = True
        self.evaluation_result_list: list = []
        cfg = booster.config
        cbs = set(cbs)
        if cfg.early_stopping_round and cfg.early_stopping_round > 0:
            cbs.add(callback_mod.early_stopping(
                cfg.early_stopping_round, cfg.first_metric_only,
                verbose=bool(verbose_eval)))
        if verbose_eval is True:
            cbs.add(callback_mod.print_evaluation())
        elif isinstance(verbose_eval, int) and verbose_eval > 0:
            cbs.add(callback_mod.print_evaluation(verbose_eval))
        before = {cb for cb in cbs if getattr(cb, "before_iteration", False)}
        self.cbs_before = sorted(before,
                                 key=lambda cb: getattr(cb, "order", 0))
        self.cbs_after = sorted(cbs - before,
                                key=lambda cb: getattr(cb, "order", 0))
        self.lr_cbs = [cb for cb in self.cbs_before
                       if getattr(cb, "_lr_schedule", None) is not None]
        lr_lists_ok = all(
            not isinstance(cb._lr_schedule, list)
            or len(cb._lr_schedule) == rounds for cb in self.lr_cbs)
        self.can_chunk = (booster.boosting.chunk_supported()
                          and len(self.lr_cbs) == len(self.cbs_before)
                          and lr_lists_ok
                          and all(getattr(cb, "_chunk_safe", False)
                                  for cb in self.cbs_after))
        self.mf = max(int(cfg.metric_freq), 1)
        self.eval_possible = bool(
            booster.boosting.valid_metrics or feval is not None
            or cfg.is_provide_training_metric or train_in_valid)
        if any(str(getattr(cb, "_resume_token", "")).startswith(
                "early_stopping") for cb in self.cbs_after) \
                and not self.eval_possible and rounds > 0:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        self.ckpt_mgr = None
        self.snapshot_freq = snapshot_freq
        if snapshot_freq > 0 and snapshot_out:
            from ..resilience.checkpoint import CheckpointManager
            self.ckpt_mgr = CheckpointManager(f"{snapshot_out}.ckpt",
                                              keep_last=snapshot_keep)

    # -- engine.train's chunk-boundary rule, per lane
    def boundary_distance(self) -> int:
        d = self.rounds - self.it
        if self.eval_possible:
            d = min(d, self.mf - (self.it % self.mf))
        if self.ckpt_mgr is not None:
            d = min(d, self.snapshot_freq - (self.it % self.snapshot_freq))
        return max(d, 1)

    def lr_at(self, j: int) -> float:
        v = None
        for cb in self.lr_cbs:
            s = cb._lr_schedule
            v = s[j] if isinstance(s, list) else s(j)
        return float(v)

    def lrs_for(self, c: int) -> Optional[List[float]]:
        if not self.lr_cbs:
            return None
        return [self.lr_at(j) for j in range(self.it, self.it + c)]

    def after_chunk(self, c: int, stopped: bool,
                    lr_list: Optional[List[float]]) -> None:
        """The post-step boundary work engine.train runs after each
        update: lr reset side effects, eval at the metric_freq boundary,
        after-callbacks with early-stop handling, snapshots, liveness."""
        bst = self.booster
        self.it += c
        if lr_list is not None and self.lr_cbs:
            bst.reset_parameter({"learning_rate": lr_list[-1]})
            self.params["learning_rate"] = lr_list[-1]
        j = self.it - 1
        self.evaluation_result_list = []
        if self.eval_possible and (j + 1) % self.mf == 0:
            with _span("multi.eval", lane=self.index, iteration=j):
                if bst.config.is_provide_training_metric \
                        or self.train_in_valid:
                    self.evaluation_result_list.extend(
                        bst.eval_train(self.feval))
                self.evaluation_result_list.extend(
                    bst.eval_valid(self.feval))
        early_stopped = False
        try:
            for cb in self.cbs_after:
                cb(callback_mod.CallbackEnv(bst, self.params, j, 0,
                                            self.rounds,
                                            self.evaluation_result_list))
        except callback_mod.EarlyStopException as e:
            bst.best_iteration = e.best_iteration + 1
            for item in e.best_score:
                bst.best_score.setdefault(item[0],
                                          collections.OrderedDict())
                bst.best_score[item[0]][item[1]] = item[2]
            early_stopped = True
        if self.ckpt_mgr is not None and (j + 1) % self.snapshot_freq == 0:
            from ..engine import _collect_callback_states
            self.ckpt_mgr.save(
                bst, iteration=j + 1,
                engine_state={"callbacks": _collect_callback_states(
                    self.cbs_before + self.cbs_after)})
        if early_stopped or stopped or self.it >= self.rounds:
            self.live = False
            if bst.best_iteration <= 0:
                bst.best_iteration = bst.current_iteration()
                for item in self.evaluation_result_list:
                    bst.best_score.setdefault(item[0],
                                              collections.OrderedDict())
                    bst.best_score[item[0]][item[1]] = item[2]


class _SoloProgram:
    """Dispatch adapter for a single-lane (or unbatchable) group: the
    same scheduling loop, the booster's own solo programs underneath."""

    def __init__(self, lane: _Lane):
        self.lane = lane

    def dispatch(self, c: int, live: List[bool],
                 lr_lists: Sequence) -> List[bool]:
        l = self.lane
        bst = l.booster
        if bst.boosting.chunk_supported():
            return [bst.update_chunk(c, lr_lists[0])]
        # per-iteration path (DART/CEGB/forced splits): c is pinned to 1
        # by the caller; before-callbacks run exactly like engine.train
        for cb in l.cbs_before:
            cb(callback_mod.CallbackEnv(bst, l.params, l.it, 0,
                                        l.rounds, None))
        return [bst.update()]


def _chunk_for(lanes: List[_Lane], cap: int) -> int:
    from ..boosting.macro import pow2_chunk
    live = [l for l in lanes if l.live]
    if cap <= 1 or not all(l.can_chunk for l in live):
        return 1
    return pow2_chunk(min(l.boundary_distance() for l in live), cap)


def _train_lanes(lanes: List[_Lane], program) -> None:
    """Drive one dispatch group to completion: every live lane advances
    by the same chunk; boundaries are handled per lane."""
    from ..boosting.macro import chunk_cap
    cap = chunk_cap()
    while any(l.live for l in lanes):
        c = _chunk_for(lanes, cap)
        lr_lists = [l.lrs_for(c) if l.live else None for l in lanes]
        stopped = program.dispatch(c, [l.live for l in lanes], lr_lists)
        for l, stop, lrl in zip(lanes, stopped, lr_lists):
            if l.live:
                l.after_chunk(c, stop, lrl)


def _group_plan(g: MultiGroup):
    """The planner's lane-chunk verdict for one structural group."""
    from ..ops.planner import plan_model_batch
    b0 = g.boosters[0]
    cfg = b0.grower_cfg
    return plan_model_batch(
        b_total=len(g), rows=b0.num_data, features=b0._binned_shape[1],
        num_bins=b0.num_bins, num_leaves=cfg.num_leaves,
        num_class=b0.num_tree_per_iteration,
        quant=bool(getattr(b0, "_quant_on", False)),
        method=cfg.hist_method, round_width=cfg.round_width,
        stacked=g.stacked, tile_rows=cfg.tile_rows)


def _dispatch_groups(g: MultiGroup) -> List[MultiGroup]:
    """Split a structural group into the planner's sequential dispatch
    groups of at most ``b_chunk`` lanes each."""
    if g.key is None or len(g) == 1:
        return [g]
    bc = _group_plan(g).b_chunk
    if bc >= len(g):
        return [g]
    return [MultiGroup(g.key, g.boosters[i:i + bc], g.stacked)
            for i in range(0, len(g), bc)]


def train_many(
    params_list: Union[List[dict], dict],
    train_set: Union[Dataset, Sequence[Dataset]],
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    feval=None,
    early_stopping_rounds: Optional[int] = None,
    evals_results: Optional[List[dict]] = None,
    verbose_eval: Union[bool, int] = False,
    callbacks: Optional[List[list]] = None,
    init_models: Optional[list] = None,
    snapshot_freq: int = -1,
    snapshot_outs: Optional[List[str]] = None,
    snapshot_keep: int = 3,
) -> List[Booster]:
    """Train one booster per config in ``params_list`` — batched along a
    model axis wherever the configs' traces agree — and return them in
    input order, each byte-identical to the same config trained alone.

    ``params_list``: a list of params dicts, or ONE dict whose
    list-valued entries expand as a grid (``expand_param_grid``).
    ``train_set``: one shared ``Dataset`` (sweep mode: the binned matrix
    rides into the program unbatched), or one Dataset per config
    (stacked mode: per-segment families; matrices stack along the lane
    axis and the planner charges ×B for them).  ``valid_sets`` attach to
    EVERY booster.  ``callbacks`` must be per-config lists (stateful
    callbacks like early_stopping cannot be shared between lanes);
    ``evals_results`` likewise a list of dicts, filled per config.
    ``init_models`` (per-config, entries may be None) continues training
    from existing models — lifecycle.refresh_many rides on this.
    ``snapshot_outs``: per-config checkpoint-bundle paths (with
    ``snapshot_freq``), the batched twin of ``train()``'s snapshots —
    bundles resume bit-identically through ``train(resume_from=...)``.
    """
    from ..utils.platform import enable_compile_cache
    enable_compile_cache(family="train")
    if isinstance(params_list, dict):
        params_list = expand_param_grid(params_list)
    if not params_list:
        raise ValueError("train_many needs at least one config")
    B = len(params_list)
    stacked = not isinstance(train_set, Dataset)
    if stacked:
        datasets = list(train_set)
        if len(datasets) != B:
            raise ValueError(
                f"got {len(datasets)} datasets for {B} configs; stacked "
                "mode needs exactly one Dataset per config")
    else:
        datasets = [train_set] * B

    def _per_lane(arg, name):
        if arg is None:
            return [None] * B
        if len(arg) != B:
            raise ValueError(f"{name} must have one entry per config "
                             f"({B}), got {len(arg)}")
        return list(arg)

    lane_cbs = _per_lane(callbacks, "callbacks")
    lane_evals = _per_lane(evals_results, "evals_results")
    lane_inits = _per_lane(init_models, "init_models")
    lane_snaps = _per_lane(snapshot_outs, "snapshot_outs")

    lanes: List[_Lane] = []
    for i, params in enumerate(params_list):
        params = dict(params)
        cfg = Config.from_params(params)
        rounds = num_boost_round
        if "num_iterations" in {Config.canonical_key(k) for k in params}:
            rounds = cfg.num_iterations
        params["num_iterations"] = rounds
        predictor = None
        if lane_inits[i] is not None:
            predictor = (lane_inits[i]
                         if isinstance(lane_inits[i], Booster)
                         else Booster(model_file=lane_inits[i],
                                      params=params))
        raw = datasets[i].raw_data if predictor is not None else None
        bst = Booster(params=params, train_set=datasets[i])
        if predictor is not None:
            from ..engine import _apply_init_model
            _apply_init_model(bst, predictor, datasets[i], raw=raw)
        train_in_valid = False
        if valid_sets:
            names = valid_names or [f"valid_{k}"
                                    for k in range(len(valid_sets))]
            for vs, name in zip(valid_sets, names):
                if vs is datasets[i]:
                    # reference semantics: a valid set identical to the
                    # train set reports the TRAINING metrics (engine.py)
                    train_in_valid = True
                    if valid_names is not None:
                        bst.set_train_data_name(name)
                    continue
                bst.add_valid(vs, name)
        cbs = list(lane_cbs[i] or [])
        if early_stopping_rounds is not None and early_stopping_rounds > 0:
            cbs.append(callback_mod.early_stopping(
                early_stopping_rounds, cfg.first_metric_only,
                verbose=bool(verbose_eval)))
        if lane_evals[i] is not None:
            cbs.append(callback_mod.record_evaluation(lane_evals[i]))
        lanes.append(_Lane(i, bst, params, rounds, cbs, feval,
                           verbose_eval, snapshot_freq, lane_snaps[i],
                           snapshot_keep, train_in_valid))

    by_booster = {id(l.booster.boosting): l for l in lanes}
    groups = group_boosters([l.booster.boosting for l in lanes], stacked)
    _obs_registry.counter("multi_train_many_calls").inc()
    with _span("multi.train_many", configs=B, stacked=stacked,
               groups=len(groups)):
        for g in groups:
            for dg in _dispatch_groups(g):
                g_lanes = [by_booster[id(b)] for b in dg.boosters]
                if dg.key is None or len(dg) == 1:
                    _train_lanes(g_lanes, _SoloProgram(g_lanes[0]))
                else:
                    _train_lanes(g_lanes, BatchedChunkProgram(dg))
    return [l.booster for l in lanes]


# ======================================================================
# Fused cross-validation: engine.cv's per-round loop, folds batched
# ======================================================================


class CVStepper:
    """Advance every fold one boosting round; ``fused=True`` batches the
    folds' single-iteration chunk programs along the model axis (fold
    sizes differ by at most one row-group when N % nfold != 0, so at
    most two batched groups form).  The serial stepper routes supported
    folds through the SAME c=1 chunk program solo (GBDT._chunk_single),
    which is why fused and serial cv agree bit-for-bit."""

    def __init__(self, boosters: List[Booster], fused: bool, fobj=None):
        self.boosters = boosters
        self.fobj = fobj
        self.fused = fused and fobj is None
        self._programs: List = []
        if self.fused:
            by_b = {id(b.boosting): b for b in boosters}
            batched = 0
            for g in group_boosters([b.boosting for b in boosters],
                                    stacked=True):
                for dg in _dispatch_groups(g):
                    if dg.key is None or len(dg) == 1:
                        self._programs.append(
                            ("solo", by_b[id(dg.boosters[0])]))
                    else:
                        batched += len(dg)
                        self._programs.append(
                            ("batched", BatchedChunkProgram(dg)))
            if batched == 0:
                from ..utils.log import log_warning
                log_warning(
                    "cv(fused=True): no fold pair is batchable under "
                    "this config (per-iteration host logic or custom "
                    "fobj); stepping folds serially")
                self.fused = False

    def step(self) -> None:
        if not self.fused:
            for bst in self.boosters:
                bst.update(fobj=self.fobj)
            return
        for kind, prog in self._programs:
            if kind == "solo":
                prog.update(fobj=self.fobj)
            else:
                n = len(prog.group.boosters)
                # serial cv ignores update()'s stopped flag, so every
                # lane stays live for the whole cv loop — parity demands
                # the same here
                prog.dispatch(1, [True] * n, [None] * n)
