"""Fast batch prediction over a stacked forest.

reference: src/application/predictor.hpp:29 (OpenMP row-parallel Predictor),
include/LightGBM/tree.h:190 (inline Tree::Predict traversal), and
src/boosting/prediction_early_stop.cpp:13-90 (margin-based early stop).

The reference parallelizes rows across threads, each doing a scalar
root-to-leaf walk per tree.  The vectorized inversion here packs all trees
into padded [T, nodes] arrays and advances EVERY row one level per step
("depth stepping"): a gather of per-row node attributes, one vectorized
decision, one child gather.  Rows that reach a leaf freeze (child pointers
of leaves are < 0).  Work is O(rows * avg_depth) fused vector ops per tree
instead of a Python loop per (tree, node) — the round-2 implementation's
per-node ``np.unique`` passes made 500-tree x 1M-row prediction minutes;
this is seconds.

Prediction early stop (binary/multiclass margins) follows the reference
semantics: every ``freq`` trees, rows whose margin exceeds the threshold are
compacted out of the working set.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
K_ZERO_THRESHOLD = 1e-35

_CHUNK_ROWS = 1 << 16


def gather_leaf_sum(forest, leaves: np.ndarray, num_class: int) -> np.ndarray:
    """Host float64 leaf-value gather + iteration-sum epilogue:
    [T, rows] leaf indices -> [K, rows] raw scores.

    Shared by ``DeviceForest.predict_raw_padded`` and the AOT-restored
    serving programs (fleet/aot.py) so the two epilogues cannot drift —
    the serving bit-parity contract hangs on this exact gather +
    ``sum(axis=0)`` reduction order matching ``StackedForest.predict_raw``.
    """
    K = max(num_class, 1)
    iters = forest.num_trees // K
    rows = leaves.shape[1]
    tid = np.arange(forest.num_trees)
    lv = forest.leaf_value[tid[:, None], leaves]             # [T, rows] f64
    return lv.reshape(iters, K, rows).sum(axis=0)            # [K, rows]


class StackedForest:
    """Padded [T, nodes] arrays for a list of HostTrees (raw-feature space)."""

    def __init__(self, trees: List):
        T = len(trees)
        self.num_trees = T
        I = max([max(t.num_leaves - 1, 1) for t in trees], default=1)
        L = max([max(t.num_leaves, 1) for t in trees], default=1)
        self.split_feature = np.zeros((T, I), np.int32)
        self.threshold = np.full((T, I), np.inf, np.float64)
        self.left = np.full((T, I), -1, np.int32)     # ~0 = leaf 0
        self.right = np.full((T, I), -1, np.int32)
        self.is_cat = np.zeros((T, I), bool)
        self.default_left = np.zeros((T, I), bool)
        self.missing_type = np.zeros((T, I), np.int8)
        self.leaf_value = np.zeros((T, L), np.float64)
        self.depth = np.ones(T, np.int32)
        # categorical bitsets: flat word array + per-node offset/word-count
        self.cat_offset = np.zeros((T, I), np.int64)
        self.cat_nwords = np.zeros((T, I), np.int32)
        words: List[np.ndarray] = []
        wpos = 0
        self.has_cat = False
        for t, tr in enumerate(trees):
            ns = tr.num_leaves - 1
            self.leaf_value[t, :tr.num_leaves] = tr.leaf_value[:tr.num_leaves]
            if ns <= 0:
                continue  # single-leaf tree: sentinel node routes to leaf 0
            self.split_feature[t, :ns] = tr.split_feature[:ns]
            self.threshold[t, :ns] = tr.threshold[:ns]
            self.left[t, :ns] = tr.left_child[:ns]
            self.right[t, :ns] = tr.right_child[:ns]
            dt = tr.decision_type[:ns].astype(np.int32)
            self.is_cat[t, :ns] = (dt & K_CATEGORICAL_MASK) != 0
            self.default_left[t, :ns] = (dt & K_DEFAULT_LEFT_MASK) != 0
            self.missing_type[t, :ns] = (dt >> 2) & 3
            self.depth[t] = tr.max_depth()
            for s in np.flatnonzero(self.is_cat[t, :ns]):
                self.has_cat = True
                ci = int(tr.threshold[s])
                lo = int(tr.cat_boundaries[ci])
                hi = int(tr.cat_boundaries[ci + 1])
                w = np.asarray(tr.cat_threshold[lo:hi], np.uint32)
                self.cat_offset[t, s] = wpos
                self.cat_nwords[t, s] = len(w)
                words.append(w)
                wpos += len(w)
        self.cat_words = (np.concatenate(words) if words
                          else np.zeros(1, np.uint32))
        self.max_depth = int(self.depth.max(initial=1))

    # ------------------------------------------------------------- traversal
    #
    # All trees of a block advance one level per step with [T', nc] state
    # arrays — one fused numpy op serves every (tree, row) pair, amortizing
    # interpreter overhead across the block (the reference amortizes its
    # scalar walks across OpenMP threads instead, predictor.hpp:152).

    def _decide_block(self, tid2, nd, fval):
        """Vectorized go-left for a [T', nc] block of (tree, node) states."""
        thr = self.threshold[tid2, nd]
        mt = self.missing_type[tid2, nd]
        nan = np.isnan(fval)
        fz = np.where(nan & (mt != 2), 0.0, fval)
        is_missing = ((mt == 1) & (np.abs(fz) <= K_ZERO_THRESHOLD)) | \
                     ((mt == 2) & nan)
        with np.errstate(invalid="ignore"):
            gl = np.where(is_missing, self.default_left[tid2, nd], fz <= thr)
        if self.has_cat:
            cat = self.is_cat[tid2, nd]
            if cat.any():
                # truncation toward zero matches the reference's
                # static_cast<int> (so -0.5 -> category 0, not "invalid")
                iv = np.where(nan, -1.0, fval).astype(np.int64)
                nw = self.cat_nwords[tid2, nd]
                valid = (iv >= 0) & (iv < nw.astype(np.int64) * 32)
                ivc = np.clip(iv, 0, None)
                widx = self.cat_offset[tid2, nd] + np.minimum(
                    ivc // 32, np.maximum(nw - 1, 0))
                inset = (self.cat_words[widx]
                         >> (ivc % 32).astype(np.uint32)) & 1
                gl = np.where(cat, valid & (inset == 1), gl)
        return gl

    def _leaves_chunk(self, Xc: np.ndarray, tree_ids,
                      block_elems: int = 1 << 23) -> np.ndarray:
        """Leaf index per (tree, row) for one row chunk. Returns [T', nc].

        Trees are processed depth-sorted in blocks so a block's step count
        is its own max depth, not the forest's.
        """
        nc = Xc.shape[0]
        tid = np.asarray(list(tree_ids), np.int32)
        out = np.zeros((len(tid), nc), np.int32)
        rows = np.arange(nc)[None, :]
        order = np.argsort(self.depth[tid], kind="stable")
        t_blk = max(1, block_elems // max(nc, 1))
        for bs in range(0, len(tid), t_blk):
            sel = order[bs:bs + t_blk]
            tb = tid[sel]
            tid2 = tb[:, None]
            node = np.zeros((len(tb), nc), np.int32)
            while True:
                nd = np.maximum(node, 0)
                fval = Xc[rows, self.split_feature[tid2, nd]]
                gl = self._decide_block(tid2, nd, fval)
                nxt = np.where(gl, self.left[tid2, nd], self.right[tid2, nd])
                node = np.where(node < 0, node, nxt)
                if (node < 0).all():
                    break
            out[sel] = ~node
        return out

    # ---------------------------------------------------------- native path

    def _native(self):
        """ctypes handle to the C++ OpenMP predictor, or None."""
        if not hasattr(self, "_native_lib"):
            from .native.build import load_native_lib
            self._native_lib = load_native_lib()
        return self._native_lib

    def _native_predict(self, X: np.ndarray, num_class: int,
                        early_stop=None, want_leaf: bool = False):
        """Run lgbt_predict; returns (raw [K, n] or None, leaf [n, T] or
        None), or None if the native lib is unavailable."""
        lib = self._native()
        if lib is None:
            return None
        import ctypes as ct
        n, _ = X.shape
        K = max(num_class, 1)
        X = np.ascontiguousarray(X, np.float64)
        out = None if want_leaf else np.zeros((K, n), np.float64)
        leaf = np.zeros((n, self.num_trees), np.int32) if want_leaf else None
        kind, freq, margin = 0, 0, 0.0
        if early_stop is not None:
            kind, freq, margin = early_stop
        p = lambda a, t: a.ctypes.data_as(ct.POINTER(t)) if a is not None \
            else None
        lib.lgbt_predict(
            p(X, ct.c_double), ct.c_int64(n), ct.c_int64(X.shape[1]),
            ct.c_int64(self.num_trees), ct.c_int64(self.split_feature.shape[1]),
            ct.c_int64(self.leaf_value.shape[1]),
            p(self.split_feature, ct.c_int32), p(self.threshold, ct.c_double),
            p(self.left, ct.c_int32), p(self.right, ct.c_int32),
            p(self._cat_u8, ct.c_uint8), p(self._dl_u8, ct.c_uint8),
            p(self.missing_type, ct.c_int8), p(self.leaf_value, ct.c_double),
            p(self.cat_offset, ct.c_int64), p(self.cat_nwords, ct.c_int32),
            p(self.cat_words, ct.c_uint32),
            ct.c_int64(K), ct.c_int(kind), ct.c_int(freq), ct.c_double(margin),
            p(out, ct.c_double), p(leaf, ct.c_int32))
        return out, leaf

    @property
    def _cat_u8(self):
        if not hasattr(self, "_cat_u8_arr"):
            self._cat_u8_arr = np.ascontiguousarray(self.is_cat, np.uint8)
        return self._cat_u8_arr

    @property
    def _dl_u8(self):
        if not hasattr(self, "_dl_u8_arr"):
            self._dl_u8_arr = np.ascontiguousarray(self.default_left, np.uint8)
        return self._dl_u8_arr

    def predict_leaf(self, X: np.ndarray,
                     chunk_rows: int = _CHUNK_ROWS) -> np.ndarray:
        """Leaf indices [n, T] (reference pred_leaf output layout)."""
        native = self._native_predict(
            np.asarray(X, np.float64), 1, want_leaf=True)
        if native is not None:
            return native[1]
        n = X.shape[0]
        out = np.zeros((n, self.num_trees), np.int32)
        for s in range(0, n, chunk_rows):
            e = min(s + chunk_rows, n)
            out[s:e] = self._leaves_chunk(X[s:e], range(self.num_trees)).T
        return out

    def predict_raw(
        self,
        X: np.ndarray,
        num_class: int = 1,
        early_stop=None,
        chunk_rows: int = _CHUNK_ROWS,
    ) -> np.ndarray:
        """Summed raw scores [K, n].  Trees are laid out iteration-major
        (iteration i, class k -> tree i*K + k) as in the reference.

        ``early_stop``: optional (freq, margin_fn) pair; every ``freq``
        iterations rows with margin_fn(raw_scores) True are frozen and
        compacted out (reference: prediction_early_stop.cpp:13-60).
        """
        n = X.shape[0]
        K = max(num_class, 1)
        iters = self.num_trees // K
        X = np.ascontiguousarray(X, np.float64)
        es_tuple = (early_stop.kind_code, early_stop.freq,
                    early_stop.margin) if early_stop is not None else None
        native = self._native_predict(X, K, early_stop=es_tuple)
        if native is not None:
            return native[0]
        out = np.zeros((K, n), np.float64)
        for s in range(0, n, chunk_rows):
            e = min(s + chunk_rows, n)
            Xc = X[s:e]
            if early_stop is None:
                leaves = self._leaves_chunk(Xc, range(self.num_trees))
                tid = np.arange(self.num_trees)
                lv = self.leaf_value[tid[:, None], leaves]      # [T, nc]
                out[:, s:e] += lv.reshape(iters, K, e - s).sum(axis=0)
            else:
                freq, margin_fn = early_stop.freq, early_stop.margin_fn
                live = np.arange(e - s)
                acc = np.zeros((K, e - s), np.float64)
                Xl = Xc
                for it in range(iters):
                    ids = range(it * K, (it + 1) * K)
                    leaves = self._leaves_chunk(Xl, ids)
                    for j, t in enumerate(ids):
                        acc[t % K, live] += self.leaf_value[t, leaves[j]]
                    if freq > 0 and (it + 1) % freq == 0 and it + 1 < iters:
                        stop = margin_fn(acc[:, live])
                        if stop.any():
                            live = live[~stop]
                            if live.size == 0:
                                break
                            Xl = Xc[live]
                out[:, s:e] = acc
        return out


class DeviceForest:
    """Jitted stacked-forest traversal (XLA: multithreaded on CPU, fast on
    TPU).  Same depth-stepping algorithm as StackedForest but with [T, nc]
    device state advanced under ``lax.while_loop``.

    Exactness: inputs are compared in float32, with each node threshold
    rounded DOWN to the nearest float32.  For float32 feature values x,
    ``x <= t64``  ⟺  ``x <= round_down_f32(t64)``, so routing matches the
    float64 host path exactly for f32-precision data (float64 inputs with
    sub-f32 precision may route differently at bin boundaries — use the
    host path when that matters).

    ``precision`` controls the device STORAGE of the numeric thresholds
    (the fixed-point serving direction of arXiv 2011.02022): "bf16"
    stores them as bfloat16 and "int8" as int8 codes plus one f32
    dequantization scale per tree — both expect a forest whose host
    thresholds already sit on that grid (fleet/lowprec.quantize_forest),
    so the narrowing is lossless relative to the quantized host forest
    and routing still matches ITS host path exactly.  ``routing_only``
    skips the leaf-value upload entirely (the serving path gathers
    leaves on the host): ``predict_raw`` then refuses; the leaf-index
    paths still work.
    """

    def __init__(self, forest: StackedForest, chunk_rows: Optional[int] = None,
                 precision: str = "f32", routing_only: bool = False,
                 variant: Optional[str] = None,
                 tile_rows: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        if precision not in ("f32", "bf16", "int8"):
            raise ValueError(f"unknown DeviceForest precision {precision!r}")
        self.forest = forest
        self.precision = precision
        self.routing_only = routing_only
        f = forest
        # round thresholds toward -inf in f32 (identity for bf16/int8-grid
        # forests: their values are exactly f32-representable)
        thr32 = f.threshold.astype(np.float32)
        over = thr32.astype(np.float64) > f.threshold
        thr32[over] = np.nextafter(thr32[over], -np.inf, dtype=np.float32)
        self._thr_scale = None
        if precision == "bf16":
            self.threshold = jnp.asarray(thr32, dtype=jnp.bfloat16)
        elif precision == "int8":
            # the quantized forest carries its own int8 artifacts
            # (fleet/lowprec.quantize_forest): code array + per-tree f32
            # scale, so the in-kernel dequantization q * scale reproduces
            # the host threshold grid BIT-exactly instead of re-deriving
            # a scale that could drift an ulp
            q = getattr(f, "threshold_q", None)
            if q is None:
                raise ValueError(
                    "int8 DeviceForest needs a forest quantized by "
                    "fleet/lowprec.quantize_forest (threshold_q missing)")
            self.threshold = jnp.asarray(q)                # int8 codes
            self._thr_scale = jnp.asarray(
                f.threshold_scale.astype(np.float32)[:, None])  # [T, 1]
            # non-quantized nodes (non-finite padding, categorical
            # bitset indices) keep their f32 value through a sparse
            # correction applied at decision time
            self._thr_fix_mask = jnp.asarray(f.threshold_skip)
            self._thr_fix = jnp.asarray(thr32)
        else:
            self.threshold = jnp.asarray(thr32)
        self.split_feature = jnp.asarray(f.split_feature)
        self.left = jnp.asarray(f.left)
        self.right = jnp.asarray(f.right)
        self.is_cat = jnp.asarray(f.is_cat)
        self.default_left = jnp.asarray(f.default_left)
        self.missing_type = jnp.asarray(f.missing_type.astype(np.int32))
        self.leaf_value = (None if routing_only else
                           jnp.asarray(f.leaf_value.astype(np.float32)))
        self.cat_offset = jnp.asarray(f.cat_offset)
        self.cat_nwords = jnp.asarray(f.cat_nwords)
        self.cat_words = jnp.asarray(f.cat_words)
        # kernel + chunk election (ops/planner.plan_predict): HBM-aware
        # chunk, measured-or-analytic variant, fused VMEM row tile.
        # Explicit arguments always win — tests pin shapes, serving pins
        # the bucket ladder.
        from .ops import planner as _planner
        from .ops import predict_kernels as _pk
        if chunk_rows is None or variant is None or tile_rows is None:
            plan = _planner.plan_predict(
                num_trees=f.num_trees,
                nodes_dim=f.split_feature.shape[1],
                leaves_dim=f.leaf_value.shape[1],
                features=int(f.split_feature.max(initial=0)) + 1,
                precision=precision, routing_only=routing_only,
                cat_words=int(f.cat_words.size),
                ledger=_planner.active_ledger())
            chunk_rows = plan.chunk_rows if chunk_rows is None else chunk_rows
            variant = plan.variant if variant is None else variant
            tile_rows = plan.tile_rows if tile_rows is None else tile_rows
        self.chunk_rows = int(chunk_rows)
        self.tile_rows = int(tile_rows) or 512
        if variant not in _pk.PREDICT_VARIANTS:
            raise ValueError(f"unknown predict kernel variant {variant!r}")
        if variant == "fused" and not _pk.fused_predict_verified(self):
            variant = "fori"               # probe demotion, warned there
        self.variant = variant
        if variant == "while":
            leaves_fn = self._leaves
        elif variant == "fori":
            leaves_fn = lambda X: _pk.leaves_fori(self, X)  # noqa: E731
        else:
            leaves_fn = lambda X: _pk.fused_traverse(  # noqa: E731
                self, X, self.tile_rows)
        self._leaves_jit = jax.jit(leaves_fn)
        # AOT export arm: the fixed-trip fori variant serializes cleanly
        # (static trip count, no convergence sync); a fused election
        # keeps it as the bit-identical export twin (fleet/aot.py)
        self._leaves_export = (jax.jit(lambda X: _pk.leaves_fori(self, X))
                               if variant == "fused" else self._leaves_jit)
        self._epilogue_ok: dict = {}
        self._leaf_sum_jit = jax.jit(self._leaf_sum, static_argnums=1)
        # fused score mode: leaf gather + class accumulation stay
        # in-kernel, only a [K, tile] block ever leaves HBM
        self._scores_jit = (
            jax.jit(lambda X, k: _pk.fused_traverse(
                self, X, self.tile_rows, k, emit_scores=True),
                static_argnums=1)
            if variant == "fused" and self.leaf_value is not None else None)

    def _call_chunk(self, n: int) -> int:
        """Per-call chunk: the elected ``chunk_rows`` ceiling, shrunk to
        the row-count's ladder rung so a small batch is not padded out
        to the full chunk (the compiled-shape set stays ladder-bounded
        either way)."""
        from .ops.planner import bucket_rows
        return max(min(self.chunk_rows, bucket_rows(max(n, 1))), 1)

    def _thr_at(self, tid2, nd):
        """Gather the [T', nc] threshold block in f32 whatever the device
        storage precision is."""
        import jax.numpy as jnp
        if self.precision == "bf16":
            return self.threshold[tid2, nd].astype(jnp.float32)
        if self.precision == "int8":
            thr = (self.threshold[tid2, nd].astype(jnp.float32)
                   * self._thr_scale[tid2, 0])
            return jnp.where(self._thr_fix_mask[tid2, nd],
                             self._thr_fix[tid2, nd], thr)
        return self.threshold[tid2, nd]

    def _leaves(self, Xc):
        """[nc, F] f32 -> leaf index [T, nc] — the legacy while_loop arm
        (ops/predict_kernels shares ONE decision-step expression across
        while/fori/fused, so variant parity is structural)."""
        from .ops import predict_kernels as _pk
        return _pk.leaves_while(self, Xc)

    def _leaf_sum(self, leaves, num_class: int):
        """Device leaf-value epilogue: [T, rows] leaf indices ->
        [K, rows] f32 raw scores, accumulated in pinned iteration-major
        order (bit-stable run to run).  Only promoted into
        ``predict_raw_padded`` after ``_epilogue_verified``."""
        import jax.numpy as jnp
        from jax import lax
        K = max(num_class, 1)
        T = self.forest.num_trees
        tid2 = jnp.arange(T)[:, None]
        lv3 = self.leaf_value[tid2, leaves].reshape(
            T // K, K, leaves.shape[1])
        return lax.fori_loop(
            0, T // K, lambda i, acc: acc + lv3[i],
            jnp.zeros((K, leaves.shape[1]), jnp.float32))

    def _epilogue_verified(self, num_class: int) -> bool:
        """One-time per (forest, K) probe: the float32 device leaf-sum
        epilogue may replace the host float64 ``gather_leaf_sum`` ONLY
        if it reproduces it bit-exactly on a battery of synthetic leaf
        patterns (the ``take_from_table`` demotion precedent) — any
        divergence, now or from a quirky leaf-value distribution, keeps
        the serving bit-parity contract on the host path.
        ``LGBM_TPU_PREDICT_EPILOGUE=0`` pins the host path outright."""
        K = max(num_class, 1)
        if self.leaf_value is None or self.forest.num_trees % K:
            return False
        if os.environ.get("LGBM_TPU_PREDICT_EPILOGUE", "").strip() == "0":
            return False
        ok = self._epilogue_ok.get(K)
        if ok is None:
            import jax.numpy as jnp
            T = self.forest.num_trees
            L = self.forest.leaf_value.shape[1]
            rng = np.random.RandomState(20260807)
            leaves = rng.randint(0, L, size=(T, 128)).astype(np.int32)
            leaves[:, 0] = 0                     # adversarial same-leaf
            leaves[:, 1] = L - 1                 # columns stress carries
            try:
                dev = np.asarray(self._leaf_sum_jit(jnp.asarray(leaves), K),
                                 np.float64)
                ok = bool(np.array_equal(
                    dev, gather_leaf_sum(self.forest, leaves, K)))
            except Exception:
                ok = False
            if not ok:
                # the COMMON case for real-valued forests (f32 sums
                # rarely reproduce f64 bit-for-bit) — a debug note, not
                # a warning; the host path is the contract's default
                from .utils.log import log_debug
                log_debug(
                    "device leaf-sum epilogue demoted: float32 sums not "
                    "bit-identical to the float64 host gather for this "
                    "forest; predict_raw_padded keeps the host path")
            self._epilogue_ok[K] = ok
        return bool(ok)

    def predict_raw_padded(self, Xpad: np.ndarray,
                           num_class: int = 1) -> np.ndarray:
        """Raw scores [K, rows] for ONE already-padded, bucket-shaped
        batch — the serving subsystem's entry point (serving/registry.py).

        Unlike ``predict_raw`` there is no internal chunking or padding:
        the caller owns the shape, so ``jax.jit`` holds exactly one
        executable per distinct (rows, features) it ever passes — the
        shape-bucket ladder guarantees that set stays tiny.

        Routing runs on device; leaf-value accumulation happens on the
        HOST in float64, with the same gather + ``sum(axis=0)`` (a
        sequential reduction over the leading axis in NumPy) that
        ``StackedForest.predict_raw`` uses — so for float32-precision
        feature values the output is bit-identical to the offline host
        path, padding rows included-then-sliced notwithstanding.

        When the one-time ``_epilogue_verified`` probe shows the float32
        device leaf-sum reproduces that host gather BIT-exactly for this
        forest, the epilogue stays on device (only [K, rows] crosses the
        wire); otherwise — and under ``LGBM_TPU_PREDICT_EPILOGUE=0`` —
        the host path runs, so the contract holds either way.
        """
        import jax.numpy as jnp
        leaves = self._leaves_jit(
            jnp.asarray(np.asarray(Xpad, np.float32)))       # [T, rows]
        if self._epilogue_verified(num_class):
            return np.asarray(
                self._leaf_sum_jit(leaves, max(num_class, 1)), np.float64)
        return gather_leaf_sum(self.forest, np.asarray(leaves), num_class)

    def predict_raw(self, X: np.ndarray, num_class: int = 1) -> np.ndarray:
        """Summed raw scores [K, n] (float32 accumulation on device)."""
        import jax.numpy as jnp
        if self.leaf_value is None:
            raise ValueError(
                "routing-only DeviceForest has no device leaf values; use "
                "predict_raw_padded (host leaf gather) instead")
        n = X.shape[0]
        K = max(num_class, 1)
        T = self.forest.num_trees
        iters = T // K
        tid2 = jnp.arange(T)[:, None]
        out = np.zeros((K, n), np.float64)
        cr = self._call_chunk(n)
        for s in range(0, n, cr):
            e = min(s + cr, n)
            Xc = np.asarray(X[s:e], np.float32)
            if e - s < cr:   # pad to the compiled chunk shape
                Xc = np.pad(Xc, ((0, cr - (e - s)), (0, 0)))
            if self._scores_jit is not None:     # fused in-kernel epilogue
                out[:, s:e] = np.asarray(self._scores_jit(
                    jnp.asarray(Xc), K), np.float64)[:, :e - s]
                continue
            leaves = self._leaves_jit(jnp.asarray(Xc))
            lv = self.leaf_value[tid2, leaves].reshape(iters, K, cr)
            out[:, s:e] = np.asarray(jnp.sum(lv, axis=0),
                                     np.float64)[:, :e - s]
        return out

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        n = X.shape[0]
        out = np.zeros((n, self.forest.num_trees), np.int32)
        cr = self._call_chunk(n)
        for s in range(0, n, cr):
            e = min(s + cr, n)
            Xc = np.asarray(X[s:e], np.float32)
            if e - s < cr:
                Xc = np.pad(Xc, ((0, cr - (e - s)), (0, 0)))
            out[s:e] = np.asarray(self._leaves_jit(jnp.asarray(Xc))).T[:e - s]
        return out


class EarlyStop:
    """Prediction early-stop spec (reference:
    CreatePredictionEarlyStopInstance, prediction_early_stop.cpp:62-90):
    'binary' stops when |2*score| > margin, 'multiclass' when the top-2
    score gap > margin, checked every ``freq`` iterations."""

    def __init__(self, kind_code: int, freq: int, margin: float, margin_fn):
        self.kind_code = kind_code
        self.freq = freq
        self.margin = margin
        self.margin_fn = margin_fn


def make_early_stop(kind: str, margin: float, freq: int):
    if freq <= 0 or kind == "none":
        return None
    if kind == "binary":
        def margin_fn(raw):  # [1, rows]
            return np.abs(2.0 * raw[0]) > margin
        return EarlyStop(1, freq, margin, margin_fn)
    if kind == "multiclass":
        def margin_fn(raw):  # [K, rows]
            if raw.shape[0] < 2:
                return np.zeros(raw.shape[1], bool)
            part = np.partition(raw, raw.shape[0] - 2, axis=0)
            return (part[-1] - part[-2]) > margin
        return EarlyStop(2, freq, margin, margin_fn)
    raise ValueError(f"unknown early-stop type {kind!r}")


def predict_csr_chunked(forest_predict, data,
                        chunk_rows: Optional[int] = None):
    """Predict a scipy CSR/CSC matrix without materializing it densely:
    each row chunk is densified on its own (bounded memory), predicted, and
    discarded.  reference predicts CSR natively row-by-row (c_api.h:698);
    bounded chunk densification is the vectorized equivalent.

    ``forest_predict`` maps a dense [nc, F] float64 chunk to its result
    (row-major leading axis); results are concatenated on axis 0.
    ``chunk_rows`` defaults to the planner's host-memory-aware election
    (``LGBM_TPU_PREDICT_CHUNK`` overrides) instead of a hard-coded size.
    """
    if hasattr(data, "tocsr"):
        data = data.tocsr()
    if chunk_rows is None:
        from .ops import planner as _planner
        chunk_rows = _planner.elect_csr_chunk(int(data.shape[1]))
    n = data.shape[0]
    outs = []
    for s in range(0, n, chunk_rows):
        e = min(s + chunk_rows, n)
        chunk = np.asarray(data[s:e].todense(), np.float64)
        outs.append(forest_predict(chunk))
    return np.concatenate(outs, axis=0) if outs else np.zeros((0,))
