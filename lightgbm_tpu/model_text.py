"""Model text (de)serialization, reference-format compatible.

reference: src/boosting/gbdt_model_text.cpp — SaveModelToString (:301),
LoadModelFromString (:405), Tree::ToString (src/io/tree.cpp:560+),
Tree::Tree(const char*) text parsing ctor.  The emitted format is the
reference's: a model saved here loads in stock LightGBM and vice versa.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .tree import HostTree

MODEL_VERSION = "v3"


def _arr2str(arr, fmt="{:g}") -> str:
    return " ".join(fmt.format(x) for x in arr)


def _arr2str_precise(arr) -> str:
    return " ".join(repr(float(x)) for x in arr)


def tree_to_string(t: HostTree) -> str:
    nl = t.num_leaves
    ns = max(nl - 1, 0)
    lines = [f"num_leaves={nl}", f"num_cat={t.num_cat}"]
    lines.append("split_feature=" + _arr2str(t.split_feature[:ns], "{:d}"))
    lines.append("split_gain=" + _arr2str(t.split_gain[:ns]))
    lines.append("threshold=" + _arr2str_precise(t.threshold[:ns]))
    lines.append("decision_type=" + _arr2str(t.decision_type[:ns], "{:d}"))
    lines.append("left_child=" + _arr2str(t.left_child[:ns], "{:d}"))
    lines.append("right_child=" + _arr2str(t.right_child[:ns], "{:d}"))
    lines.append("leaf_value=" + _arr2str_precise(t.leaf_value[:nl]))
    lines.append("leaf_weight=" + _arr2str(t.leaf_weight[:nl]))
    lines.append("leaf_count=" + _arr2str(t.leaf_count[:nl].astype(np.int64), "{:d}"))
    lines.append("internal_value=" + _arr2str(t.internal_value[:ns]))
    lines.append("internal_weight=" + _arr2str(t.internal_weight[:ns]))
    lines.append("internal_count=" + _arr2str(t.internal_count[:ns].astype(np.int64), "{:d}"))
    if t.num_cat > 0:
        lines.append("cat_boundaries=" + _arr2str(t.cat_boundaries, "{:d}"))
        lines.append("cat_threshold=" + _arr2str(t.cat_threshold, "{:d}"))
    lines.append(f"shrinkage={t.shrinkage:g}")
    lines.append("")
    return "\n".join(lines)


def save_model_to_string(booster, num_iteration=None,
                         start_iteration: int = 0) -> str:
    """booster: lightgbm_tpu.basic.Booster (or GBDT-like with .models).

    ``num_iteration``/``start_iteration`` slice whole boosting iterations
    (reference: GBDT::SaveModelToString start_iteration/num_iteration,
    gbdt_model_text.cpp:301; num_iteration <= 0 means all remaining).
    """
    b = booster
    K = max(b.num_tree_per_iteration, 1)
    total_iter = len(b.models) // K
    start = max(0, int(start_iteration))
    if num_iteration is None or num_iteration <= 0:
        stop = total_iter
    else:
        stop = min(total_iter, start + int(num_iteration))
    models = b.models[start * K: stop * K]
    ss: List[str] = []
    ss.append(b.sub_model_name)
    ss.append(f"version={MODEL_VERSION}")
    ss.append(f"num_class={b.num_class}")
    ss.append(f"num_tree_per_iteration={b.num_tree_per_iteration}")
    ss.append(f"label_index={b.label_index}")
    ss.append(f"max_feature_idx={b.max_feature_idx}")
    if b.objective_name:
        ss.append(f"objective={b.objective_name}")
    if b.average_output:
        ss.append("average_output")
    ss.append("feature_names=" + " ".join(b.feature_names))
    ss.append("feature_infos=" + " ".join(b.feature_infos))

    tree_strs = []
    for i, t in enumerate(models):
        tree_strs.append(f"Tree={i}\n" + tree_to_string(t) + "\n")
    sizes = [len(s) for s in tree_strs]
    ss.append("tree_sizes=" + " ".join(map(str, sizes)))
    ss.append("")
    out = "\n".join(ss) + "\n" + "".join(tree_strs)
    out += "end of trees\n"
    # feature importances
    imp = b.feature_importance_int()
    pairs = sorted([(v, n) for n, v in imp if v > 0], key=lambda p: -p[0])
    out += "\nfeature_importances:\n"
    for v, n in pairs:
        out += f"{n}={v}\n"
    if b.params_str:
        out += "\nparameters:\n" + b.params_str + "\nend of parameters\n"
    return out


def parse_tree(block: str) -> HostTree:
    kv: Dict[str, str] = {}
    for line in block.splitlines():
        line = line.strip()
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k] = v

    def geti(key, default=None):
        if key not in kv:
            return default
        s = kv[key].split()
        return np.asarray([int(float(x)) for x in s], np.int64) if s else np.zeros(0, np.int64)

    def getf(key):
        if key not in kv or not kv[key].strip():
            return np.zeros(0, np.float64)
        return np.asarray([float(x) for x in kv[key].split()], np.float64)

    nl = int(kv["num_leaves"])
    num_cat = int(kv.get("num_cat", 0))
    ns = max(nl - 1, 0)
    t = HostTree(
        num_leaves=nl,
        split_feature=geti("split_feature", np.zeros(0, np.int64)).astype(np.int32),
        split_feature_inner=geti("split_feature", np.zeros(0, np.int64)).astype(np.int32),
        threshold=getf("threshold"),
        threshold_in_bin=np.zeros(ns, np.int32),
        decision_type=geti("decision_type", np.zeros(ns, np.int64)).astype(np.int8)
        if "decision_type" in kv else np.zeros(ns, np.int8),
        left_child=geti("left_child", np.zeros(0, np.int64)).astype(np.int32),
        right_child=geti("right_child", np.zeros(0, np.int64)).astype(np.int32),
        split_gain=getf("split_gain"),
        internal_value=getf("internal_value"),
        internal_weight=getf("internal_weight") if "internal_weight" in kv else np.zeros(ns),
        internal_count=getf("internal_count"),
        leaf_value=getf("leaf_value"),
        leaf_weight=getf("leaf_weight") if "leaf_weight" in kv else np.zeros(nl),
        leaf_count=getf("leaf_count"),
        num_cat=num_cat,
        cat_boundaries=geti("cat_boundaries", np.zeros(1, np.int64)).astype(np.int32),
        cat_threshold=geti("cat_threshold", np.zeros(0, np.int64)).astype(np.uint32),
        shrinkage=float(kv.get("shrinkage", 1.0)),
        real_feature_index=geti("split_feature", np.zeros(0, np.int64)).astype(np.int32),
    )
    return t


def load_model_from_string(s: str) -> dict:
    """Parse a reference-format model string into a dict of attributes +
    HostTree list."""
    header, sep, rest = s.partition("tree_sizes=")
    if not sep:
        # tree_sizes is advisory (the reference re-parses on mismatch,
        # gbdt_model_text.cpp LoadModelFromString) — a model string
        # without it still loads by scanning the Tree= blocks
        i = s.find("Tree=")
        header, rest = (s[:i], "sizes\n" + s[i:]) if i >= 0 else (s, "")
    lines = header.splitlines()
    out = {
        "sub_model_name": lines[0].strip() if lines else "tree",
        "num_class": 1, "num_tree_per_iteration": 1, "label_index": 0,
        "max_feature_idx": 0, "objective_name": "", "average_output": False,
        "feature_names": [], "feature_infos": [], "params_str": "",
    }
    for ln in lines[1:]:
        ln = ln.strip()
        if ln == "average_output":
            out["average_output"] = True
        elif ln.startswith("num_class="):
            out["num_class"] = int(ln.split("=", 1)[1])
        elif ln.startswith("num_tree_per_iteration="):
            out["num_tree_per_iteration"] = int(ln.split("=", 1)[1])
        elif ln.startswith("label_index="):
            out["label_index"] = int(ln.split("=", 1)[1])
        elif ln.startswith("max_feature_idx="):
            out["max_feature_idx"] = int(ln.split("=", 1)[1])
        elif ln.startswith("objective="):
            out["objective_name"] = ln.split("=", 1)[1]
        elif ln.startswith("feature_names="):
            out["feature_names"] = ln.split("=", 1)[1].split()
        elif ln.startswith("feature_infos="):
            out["feature_infos"] = ln.split("=", 1)[1].split()

    body = rest.partition("\n")[2]
    trees_part, _, tail = body.partition("end of trees")
    models = []
    for block in trees_part.split("Tree="):
        block = block.strip()
        if not block:
            continue
        block = block.partition("\n")[2]  # drop tree index line remainder
        if "num_leaves=" in block:
            models.append(parse_tree(block))
    out["models"] = models
    if "parameters:" in tail:
        pstr = tail.partition("parameters:")[2].partition("end of parameters")[0]
        out["params_str"] = pstr.strip()
    # category value lists (reference: _load_pandas_categorical, basic.py:395)
    key = "pandas_categorical:"
    pos = s.rfind(key)
    if pos >= 0:
        import json as _json
        try:
            out["pandas_categorical"] = _json.loads(
                s[pos + len(key):].partition("\n")[0])
        except ValueError:
            out["pandas_categorical"] = None
    return out


def _tree_to_if_else(t: HostTree, idx: int) -> str:
    """One tree as a C++ function (reference: gbdt_model_text.cpp:117
    ModelToIfElse / Tree::ToIfElse, src/io/tree.cpp)."""
    lines = [f"double PredictTree{idx}(const double* arr) {{"]

    # explicit work stack — deep unbalanced trees (depth > ~1000) would
    # overflow Python recursion
    if t.num_leaves <= 1:
        val = t.leaf_value[0] if len(t.leaf_value) else 0.0
        lines.append(f"  return {float(val)!r};")
        lines.append("}")
        return "\n".join(lines)

    stack = [("node", 0, 0)]
    while stack:
        kind, a, depth = stack.pop()
        pad = "  " * (depth + 1)
        if kind == "text":
            lines.append(a)
            continue
        node = a
        if node < 0:
            lines.append(f"{pad}return {float(t.leaf_value[~node])!r};")
            continue
        f = int(t.split_feature[node])
        dt = int(t.decision_type[node])
        left, right = int(t.left_child[node]), int(t.right_child[node])
        if dt & 1:  # categorical: bitset membership goes left
            cat_idx = int(t.threshold[node])
            lo, hi = int(t.cat_boundaries[cat_idx]), int(t.cat_boundaries[cat_idx + 1])
            words = ",".join(f"{int(w)}u" for w in t.cat_threshold[lo:hi])
            nw = hi - lo
            lines.append(
                f"{pad}{{ static const uint32_t bits[] = {{{words}}};"
                f" int iv = std::isnan(arr[{f}]) ? -1 : (int)arr[{f}];"
                f" if (iv >= 0 && iv < {nw * 32} && ((bits[iv / 32] >> (iv % 32)) & 1)) {{")
            close = f"{pad}}} }}"
        else:
            missing_type = (dt >> 2) & 3
            default_left = bool(dt & 2)
            thr = repr(float(t.threshold[node]))
            v = f"arr[{f}]"
            if missing_type == 2:       # NaN-aware
                cond = (f"(std::isnan({v}) ? {str(default_left).lower()} : "
                        f"{v} <= {thr})")
            elif missing_type == 1:     # zero as missing
                zv = f"(std::isnan({v}) ? 0.0 : {v})"
                cond = (f"(std::fabs({zv}) <= 1e-35 ? {str(default_left).lower()} : "
                        f"{zv} <= {thr})")
            else:
                cond = f"((std::isnan({v}) ? 0.0 : {v}) <= {thr})"
            lines.append(f"{pad}if ({cond}) {{")
            close = f"{pad}}}"
        stack.extend(reversed([
            ("node", left, depth + 1),
            ("text", f"{pad}}} else {{", 0),
            ("node", right, depth + 1),
            ("text", close, 0),
        ]))
    lines.append("}")
    return "\n".join(lines)


def model_to_if_else(booster) -> str:
    """Standalone C++ source evaluating the model
    (reference: ModelToIfElse, gbdt_model_text.cpp:117)."""
    models = booster.models
    K = booster.num_tree_per_iteration
    avg = getattr(booster, "average_output", False)
    parts = ["#include <cmath>", "#include <cstdint>", ""]
    for i, t in enumerate(models):
        parts.append(_tree_to_if_else(t, i))
        parts.append("")
    n_iter = len(models) // max(K, 1)
    parts.append("extern \"C\" void Predict(const double* features, "
                 "double* output) {")
    for k in range(K):
        calls = " + ".join(f"PredictTree{it * K + k}(features)"
                           for it in range(n_iter)) or "0.0"
        scale = f" / {n_iter}.0" if (avg and n_iter) else ""
        parts.append(f"  output[{k}] = ({calls}){scale};")
    parts.append("}")
    return "\n".join(parts)
