"""Brownout pause/throttle control: the engine-side seam of co-resident
training (docs/PERF.md co-residency).

``PauseControl`` is the small thread-safe state machine
``engine.train(pause_control=...)`` consults at every chunk boundary:

- **run** — train at the negotiated macro-chunk cap;
- **throttle** — keep training, but halve the chunk cap and sleep a
  short host-side delay per consult, so the serving batcher reclaims
  the device between chunks (the tier-1 brownout, mirroring how the
  fleet sheds batch class before interactive — fleet/pressure);
- **pause** — order the engine to evict its full training state to a
  checkpoint bundle and raise ``engine.TrainingPaused`` (the tier-2
  brownout: serving keeps the whole device until the breach clears,
  then the scheduler resumes byte-identically from the bundle).

Who flips the states is the scheduler's business (scheduler.py reacts
to watchdog breach signals); this module is deliberately mechanism-only
so tests can drive the seam directly (``request_pause`` mid-training
must produce a bundle whose resumed run is bit-identical).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class PauseControl:
    """Thread-safe run/throttle/pause verdict the engine polls.

    ``consult(i)`` is called by ``engine.train`` at every chunk
    boundary: it first runs the ``on_step`` hook (the scheduler's
    sweep), then applies the current verdict — sleeping
    ``throttle_delay_s`` when throttled, returning ``"pause"`` when a
    pause is ordered.  ``chunk_cap()`` is the engine's macro-chunk
    ceiling under the current state (halved while throttled).
    """

    RUN = "run"
    THROTTLE = "throttle"
    PAUSE = "pause"

    def __init__(self, base_chunk_cap: int = 32,
                 throttle_delay_s: float = 0.0,
                 on_step: Optional[Callable[[int], None]] = None):
        self._on_step = on_step
        self._lock = threading.Lock()
        self._state = self.RUN                      # guarded-by: _lock
        self._base_cap = max(int(base_chunk_cap), 1)  # guarded-by: _lock
        self._throttle_delay_s = float(throttle_delay_s)  # guarded-by: _lock
        self._consults = 0                          # guarded-by: _lock

    # ------------------------------------------------------------ verdicts

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consults(self) -> int:
        with self._lock:
            return self._consults

    def chunk_cap(self) -> int:
        """The engine's macro-chunk ceiling under the current state."""
        with self._lock:
            if self._state == self.THROTTLE:
                return max(self._base_cap // 2, 1)
            return self._base_cap

    def set_base_cap(self, cap: int) -> None:
        """Install the negotiated chunk cap (scheduler: p99 headroom)."""
        with self._lock:
            self._base_cap = max(int(cap), 1)

    def set_throttle_delay(self, delay_s: float) -> None:
        with self._lock:
            self._throttle_delay_s = max(float(delay_s), 0.0)

    def consult(self, iteration: int) -> str:
        """The engine's per-chunk check-in; returns "run" or "pause"."""
        hook = self._on_step
        if hook is not None:
            try:
                hook(iteration)
            except Exception:  # noqa: BLE001 — a broken sweep must not
                pass           # kill training
        with self._lock:
            self._consults += 1
            state = self._state
            delay = self._throttle_delay_s
        if state == self.PAUSE:
            return "pause"
        if state == self.THROTTLE and delay > 0:
            # yield the host (and with it the device dispatch queue) to
            # the serving plane between chunks
            time.sleep(delay)
        return "run"

    # ------------------------------------------------------- transitions

    def request_throttle(self) -> bool:
        """RUN -> THROTTLE; returns whether the state changed."""
        with self._lock:
            if self._state == self.RUN:
                self._state = self.THROTTLE
                return True
            return False

    def request_pause(self) -> bool:
        """Any state -> PAUSE; returns whether the state changed."""
        with self._lock:
            if self._state != self.PAUSE:
                self._state = self.PAUSE
                return True
            return False

    def request_run(self) -> bool:
        """Any state -> RUN (recovery); returns whether it changed."""
        with self._lock:
            if self._state != self.RUN:
                self._state = self.RUN
                return True
            return False
