"""Co-resident train+serve on one pod (docs/PERF.md co-residency).

The co-residency stack runs lifecycle refreshes on the SAME devices
that serve traffic, behind the shared residency ledger
(``ops.planner.ResidencyLedger``): training plans against the bytes
serving left over, throttles and pauses through the engine's
``pause_control`` seam when the serving plane brownouts, and shrinks
its world in the same coordinated replan that drains serving replicas
when a device is lost.

- :class:`Scheduler` — the brownout-aware refresh driver;
- :class:`PauseControl` — the run/throttle/pause seam the engine polls;
- :class:`CoresidentConfig` — the brownout policy knobs;
- :class:`CoresidencyInfeasible` — the loud refuse-don't-OOM verdict.
"""

from .control import PauseControl
from .scheduler import CoresidencyInfeasible, CoresidentConfig, Scheduler

__all__ = ["Scheduler", "PauseControl", "CoresidentConfig",
           "CoresidencyInfeasible"]
