"""Co-resident train+serve scheduler: lifecycle refreshes in the
serving troughs of the SAME device set, behind the shared residency
ledger (docs/PERF.md co-residency; ROADMAP item 4).

The pieces, composed rather than reinvented:

* **budget** — every refresh plans through
  ``ops.planner.plan_histograms(ledger=...)`` against the ledger's
  REMAINING bytes (serving residency leased out first), so the plan
  degrades its tile size before anyone touches serving residency, and
  an infeasible co-residency raises ``CoresidencyInfeasible`` — a loud
  verdict carrying the lease table, never a compile-OOM.  During
  training the ledger pins ``LGBM_TPU_HBM_BYTES`` to the training
  plane's envelope (``ResidencyLedger.train_env``) so planners deep
  inside ``engine.train`` agree.
* **troughs** — the macro-chunk cap is negotiated from the fleet's
  observed p99 headroom under the brownout ceiling
  (``negotiate_chunk_cap``): a loaded fleet trains in small chunks that
  fit between batcher deadlines, an idle one gets the full cap.
* **brownout** — the scheduler registers WINDOWED p99 watches over the
  serving latency histograms at ``brownout_fraction`` of the serving
  SLO (``guard_latency``/``guard_fleet``) and hooks the watchdog's
  breach stream: a breach ping throttles training (halved chunks + a
  host-side yield per consult), a persistent one pauses it through the
  engine's ``pause_control`` seam (state evicted to a checkpoint
  bundle; the resumed refresh is byte-identical — PR 2 capture/restore),
  and ``recovery_s`` of quiet resumes.  Throttling fires BEFORE the real
  serving SLO would breach — that is the point of brownout-aware
  training.
* **dual-plane device loss** — hooked on
  ``PodFleet.add_device_lost_listener``: one lost device drains the
  serving replicas (the fleet's own replan) AND shrinks the training
  world (``resilience/elastic.plan_shrunk_world`` + ``apply_world``) in
  the same coordinated replan, with a ``coresident:device_lost`` flight
  bundle naming both planes' outcomes (docs/RESILIENCE.md §8).

Telemetry: ``coresident_throttle_total`` / ``coresident_pause_total``
counters, ``coresident.pause`` spans and ``coresident.resume`` /
``coresident.throttle`` instants (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant
from ..obs.trace import span as _span
from ..obs.watchdog import global_watchdog, histogram_p99_ms
from ..ops.planner import (LedgerError, ResidencyLedger, set_active_ledger,
                           active_ledger)
from .control import PauseControl

_CHUNK_CAP_ENV = "LGBM_TPU_CORESIDENT_CHUNK_CAP"
_THROTTLE_ENV = "LGBM_TPU_CORESIDENT_THROTTLE_S"
_RECOVERY_ENV = "LGBM_TPU_CORESIDENT_RECOVERY_S"


class CoresidencyInfeasible(RuntimeError):
    """Training cannot fit beside the current serving residency — the
    loud refuse-don't-OOM verdict, carrying the plan summary and the
    ledger's lease table."""


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


@dataclass
class CoresidentConfig:
    """Brownout policy knobs (env-overridable, utils/envflags.py)."""

    # brownout ceiling = fraction * the serving p99 SLO — throttling
    # must engage BEFORE the real SLO breaches
    brownout_fraction: float = 0.6
    # explicit brownout ceiling (ms); overrides the fraction when set
    brownout_p99_ms: Optional[float] = None
    # host-side yield per engine consult while throttled
    # (LGBM_TPU_CORESIDENT_THROTTLE_S)
    throttle_delay_s: float = 0.02
    # persistent breach pings past this escalate throttle -> pause
    escalate_s: float = 0.25
    # quiet (no breach pings) for this long de-escalates to run
    # (LGBM_TPU_CORESIDENT_RECOVERY_S)
    recovery_s: float = 1.0
    # macro-chunk cap ceiling (LGBM_TPU_CORESIDENT_CHUNK_CAP; None =
    # boosting.macro.chunk_cap())
    chunk_cap: Optional[int] = None
    # paused-refresh poll cadence and give-up bound
    poll_interval_s: float = 0.05
    max_pause_s: float = 120.0

    @classmethod
    def from_env(cls) -> "CoresidentConfig":
        cfg = cls()
        v = _env_float(_CHUNK_CAP_ENV)
        if v is not None and v >= 1:
            cfg.chunk_cap = int(v)
        v = _env_float(_THROTTLE_ENV)
        if v is not None:
            cfg.throttle_delay_s = max(v, 0.0)
        v = _env_float(_RECOVERY_ENV)
        if v is not None:
            cfg.recovery_s = max(v, 0.0)
        return cfg


class Scheduler:
    """One pod, whole lifecycle: run guarded refreshes beside serving.

    ``fleet`` is a ``PodFleet`` (or None for ledger-only use);
    ``ledger`` defaults to a fresh ``ResidencyLedger`` over the device
    limit; ``world`` optionally carries the training mesh as
    ``{"num_slices": s, "devices_per_slice": d}`` for the dual-plane
    shrink.  ``workdir`` hosts pause/snapshot bundles.
    """

    def __init__(self, fleet=None, ledger: Optional[ResidencyLedger] = None,
                 config: Optional[CoresidentConfig] = None,
                 watchdog=None, world: Optional[dict] = None,
                 workdir: Optional[str] = None):
        self.fleet = fleet
        self.ledger = ledger if ledger is not None else ResidencyLedger()
        self.config = config or CoresidentConfig.from_env()
        self.world = dict(world) if world else None
        self.workdir = workdir or "coresident_work"
        self._wd = watchdog or global_watchdog
        import threading
        self._lock = threading.Lock()
        self._guards: dict = {}       # guarded-by: _lock
        #                               watch name -> (hist, ceiling_ms)
        self._last_ping = 0.0         # guarded-by: _lock
        self._first_ping = 0.0        # guarded-by: _lock
        self._last_sweep = 0.0        # guarded-by: _lock
        self._throttles = 0           # guarded-by: _lock
        self._pauses = 0              # guarded-by: _lock
        self._device_losses = 0       # guarded-by: _lock
        self._closed = False          # guarded-by: _lock
        self.control = PauseControl(
            base_chunk_cap=self.config.chunk_cap or 32,
            throttle_delay_s=self.config.throttle_delay_s,
            on_step=self._on_step)
        self._prev_ledger = set_active_ledger(self.ledger)
        self._wd.add_breach_listener(self._on_breach)
        if fleet is not None and hasattr(fleet, "add_device_lost_listener"):
            fleet.add_device_lost_listener(self._on_device_lost)

    # ----------------------------------------------------------- guards

    def _brownout_ceiling_ms(self,
                             slo_ms: Optional[float]) -> Optional[float]:
        if self.config.brownout_p99_ms is not None:
            return float(self.config.brownout_p99_ms)
        slo = slo_ms if slo_ms is not None else self._wd.config.serving_p99_ms
        if slo is None:
            return None
        return float(slo) * float(self.config.brownout_fraction)

    def guard_latency(self, name: str, hist,
                      slo_ms: Optional[float] = None) -> Optional[str]:
        """Watch ``hist``'s WINDOWED p99 at the brownout ceiling (a
        fraction of the serving SLO ``slo_ms``); breach pings throttle
        and pause training.  Returns the watch name, or None when no
        ceiling is derivable (no SLO configured anywhere)."""
        ceiling = self._brownout_ceiling_ms(slo_ms)
        if ceiling is None:
            return None
        wname = f"coresident:{name}"
        self._wd.watch_histogram_p99(wname, hist, ceiling_ms=ceiling,
                                     windowed=True)
        with self._lock:
            self._guards[wname] = (hist, ceiling)
        return wname

    def guard_fleet(self, slo_ms: Optional[float] = None) -> list:
        """Guard every live replica's request-latency histogram of the
        attached pod fleet; returns the watch names registered."""
        if self.fleet is None:
            return []
        names = []
        for (model, device), hist in \
                self.fleet.latency_histograms().items():
            w = self.guard_latency(f"{model}:d{device}", hist, slo_ms)
            if w is not None:
                names.append(w)
        return names

    def lease_serving_residency(self):
        """Lease the serving plane's planned resident bytes (the pod
        topology's busiest device) so training planning sees only the
        true remainder.  Returns the lease, or None without a planned
        fleet."""
        if self.fleet is None:
            return None
        topo = getattr(self.fleet, "topology", None)
        if topo is None:
            return None
        resident = max((p.total_resident_bytes
                        for p in topo.device_plans.values()), default=0)
        if resident <= 0:
            resident = max(topo.device_load_bytes.values(), default=0)
        if resident <= 0:
            return None
        return self.ledger.lease("fleet:resident", resident,
                                 plane="serving", preemptible=False)

    # ------------------------------------------------- brownout machine

    def _on_breach(self, slo: str, evidence: dict, rising: bool) -> None:
        # the signals that mean "serving is hurting on our devices":
        # our own windowed brownout guards, the server's serving-p99
        # SLO, and fleet availability
        if not (slo.startswith("slo:coresident:")
                or slo.startswith("slo:serving_p99:")
                or slo.startswith("availability:")):
            return
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return
            self._last_ping = now
            if self.control.state == PauseControl.RUN:
                self._first_ping = now
            escalate = (self.control.state == PauseControl.THROTTLE
                        and now - self._first_ping
                        >= self.config.escalate_s)
        if self.control.request_throttle():
            with self._lock:
                self._throttles += 1
            _obs_registry.counter("coresident_throttle_total").inc()
            _instant("coresident.throttle", slo=slo, **{
                k: v for k, v in evidence.items()
                if isinstance(v, (int, float, str))})
        elif escalate and self.control.request_pause():
            with self._lock:
                self._pauses += 1
            _obs_registry.counter("coresident_pause_total").inc()
            _instant("coresident.pause_requested", slo=slo)

    def _on_step(self, iteration: int) -> None:
        """The engine's per-chunk check-in (PauseControl.on_step)."""
        self._tick()

    def _tick(self) -> None:
        """One brownout-machine turn: sweep the watchdog (when no sentry
        thread owns the cadence) and de-escalate after quiet."""
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return
            sweep = (now - self._last_sweep
                     >= max(self.config.poll_interval_s, 0.0))
            if sweep:
                self._last_sweep = now
        if sweep and not self._wd.running:
            try:
                self._wd.check_once()
            except Exception:  # noqa: BLE001 — the tick never kills
                pass           # training
        with self._lock:
            last = self._last_ping
        if self.control.state != PauseControl.RUN and last > 0 and \
                time.monotonic() - last >= self.config.recovery_s:
            if self.control.request_run():
                _instant("coresident.recover",
                         quiet_s=round(time.monotonic() - last, 3))

    def negotiate_chunk_cap(self) -> int:
        """Macro-chunk cap from observed p99 headroom under the brownout
        ceiling: full cap with ample headroom (or no data), down to 1 as
        observed p99 approaches the ceiling — chunks sized to fit
        between batcher deadlines."""
        from ..boosting.macro import chunk_cap as _env_cap, pow2_chunk
        base = max(int(self.config.chunk_cap or _env_cap()), 1)
        with self._lock:
            guards = dict(self._guards)
        fracs = []
        for _wname, (hist, ceiling) in guards.items():
            p99 = histogram_p99_ms(hist)
            if p99 is None or ceiling <= 0:
                continue
            fracs.append(max(1.0 - p99 / ceiling, 0.0))
        if not fracs:
            return base
        want = max(int(base * min(fracs)), 1)
        return pow2_chunk(want, base)

    # ------------------------------------------------------ the refresh

    def refresh(self, name: str, train_set, params: dict,
                num_boost_round: int, init_model=None, swap: bool = True,
                **train_kw):
        """One guarded lifecycle refresh beside live serving.

        Plans against the ledger's remainder (raising
        ``CoresidencyInfeasible`` when even the degraded plan does not
        fit), leases the predicted peak as a PREEMPTIBLE training
        claim, trains with the brownout ``pause_control`` under
        ``ResidencyLedger.train_env`` — riding out any number of
        pause/resume cycles byte-identically — then hot-swaps the fleet
        model and marks it fresh.  Returns ``(booster, stats)``.
        """
        from ..config import Config
        from ..engine import TrainingPaused, train

        train_set.construct()
        cfg = Config.from_params(dict(params))
        rows = int(train_set.num_data)
        features = max(int(train_set.num_total_features or 1), 1)
        from ..ops.planner import plan_histograms
        plan = plan_histograms(
            rows=rows, features=features, num_bins=cfg.max_bin + 1,
            num_leaves=cfg.num_leaves, num_class=max(cfg.num_class, 1),
            ledger=self.ledger)
        if not plan.feasible:
            raise CoresidencyInfeasible(
                f"refresh {name!r} cannot fit beside serving residency: "
                f"predicted peak {plan.predicted_peak_bytes} bytes at "
                f"tile {plan.tile_rows} > remaining "
                f"{self.ledger.available_bytes()} of the "
                f"{self.ledger.budget_bytes}-byte budget; plan="
                f"{plan.summary()}; leases={self.ledger.table()}")
        try:
            lease = self.ledger.lease(f"refresh:{name}",
                                      plan.predicted_peak_bytes,
                                      plane="train", preemptible=True)
        except LedgerError as e:
            raise CoresidencyInfeasible(str(e)) from e

        cap = self.negotiate_chunk_cap()
        self.control.set_base_cap(cap)
        self.control.request_run()
        os.makedirs(self.workdir, exist_ok=True)
        train_kw.setdefault("snapshot_out",
                            os.path.join(self.workdir, f"{name}.txt"))
        with self._lock:
            throttles0, pauses0 = self._throttles, self._pauses
        resume_from = train_kw.pop("resume_from", None)
        pauses = 0
        t0 = time.monotonic()
        while True:
            try:
                with self.ledger.train_env(lease):
                    booster = train(dict(params), train_set,
                                    num_boost_round,
                                    init_model=init_model,
                                    verbose_eval=False,
                                    resume_from=resume_from,
                                    pause_control=self.control,
                                    **train_kw)
                break
            except TrainingPaused as e:
                pauses += 1
                resume_from = e.bundle_path
                # training state lives in the bundle now: give the HBM
                # back to serving for the duration of the brownout
                self.ledger.release(lease)
                with _span("coresident.pause", model=name,
                           iteration=e.iteration, pauses=pauses):
                    lease = self._await_resume(name,
                                               plan.predicted_peak_bytes)
                _instant("coresident.resume", model=name,
                         iteration=e.iteration, pauses=pauses)
            except BaseException:
                self.ledger.release(lease)
                raise
        self.ledger.release(lease)
        if swap and self.fleet is not None:
            self.fleet.swap_model(name, booster)
        # freshness SLO: the refresh IS the promotion — age resets to
        # zero only now, never during a pause (a paused refresh must not
        # fake freshness, nor reset the deployed model's age)
        self._wd.mark_fresh(name)
        with self._lock:
            throttled = self._throttles - throttles0
            paused_total = self._pauses - pauses0
        stats = {"model": name, "rows": rows,
                 "num_boost_round": int(num_boost_round),
                 "chunk_cap": cap, "pauses": pauses,
                 "throttles": throttled,
                 "pause_requests": paused_total,
                 "tile_rows": plan.tile_rows,
                 "predicted_peak_bytes": plan.predicted_peak_bytes,
                 "wall_s": round(time.monotonic() - t0, 3)}
        _instant("coresident.refresh", **stats)
        return booster, stats

    def _await_resume(self, name: str, want_bytes: int):
        """Block until the brownout clears AND the training bytes can be
        re-leased; loud RuntimeError past ``max_pause_s`` (a refresh
        must never vanish into a silent forever-pause)."""
        deadline = time.monotonic() + max(self.config.max_pause_s, 0.0)
        while True:
            self._tick()
            if self.control.state != PauseControl.PAUSE:
                lease = self.ledger.try_lease(
                    f"refresh:{name}", want_bytes, plane="train",
                    preemptible=True)
                if lease is not None:
                    return lease
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"coresident refresh {name!r}: paused longer than "
                    f"max_pause_s={self.config.max_pause_s}s (state="
                    f"{self.control.state}, leases="
                    f"{self.ledger.table()}); refusing to wait forever")
            time.sleep(max(self.config.poll_interval_s, 0.005))

    # ------------------------------------------------- dual-plane loss

    def _on_device_lost(self, device_id: int, reason: str,
                        recovered: bool) -> None:
        """PodFleet drain hook: shrink the training world in the SAME
        coordinated replan that drained the serving replicas, and bundle
        both planes' outcomes."""
        with self._lock:
            if self._closed:
                return
            self._device_losses += 1
        world_before = dict(self.world) if self.world else None
        world_after = world_before
        # a paused/running refresh must re-plan onto the shrunk world:
        # order a pause (state rides a bundle), re-plan, then resume —
        # the resumed train() constructs its mesh from the new env
        was_training = self.control.state != PauseControl.PAUSE
        self.control.request_pause()
        if self.world and int(self.world.get("num_slices", 1)) > 1:
            from ..resilience.elastic import apply_world, plan_shrunk_world
            mp = plan_shrunk_world(
                int(self.world["num_slices"]),
                int(self.world.get("devices_per_slice", 1)),
                lost_slices=1)
            apply_world(mp)
            self.world = {"num_slices": mp.num_slices,
                          "devices_per_slice": mp.devices_per_slice}
            world_after = dict(self.world)
        serving = {"device": device_id, "reason": reason,
                   "replanned": True, "recovered_one_tick": bool(recovered)}
        if self.fleet is not None:
            try:
                serving["live_devices"] = self.fleet.live_devices()
                serving["models"] = self.fleet.models()
            except Exception:  # noqa: BLE001 — forensics never fail the
                pass           # replan
        training = {"world_before": world_before,
                    "world_after": world_after,
                    "was_training": was_training,
                    "state": self.control.state}
        from ..obs.flight import global_flight
        global_flight.dump("coresident:device_lost", extra={
            "serving": serving, "training": training,
            "ledger": self.ledger.table()})
        _obs_registry.counter("coresident_device_lost_total").inc()
        # both planes replanned: release the brownout hold so the
        # paused refresh re-leases and resumes on the shrunk world
        with self._lock:
            self._last_ping = 0.0
        self.control.request_run()

    # ------------------------------------------------------------ teardown

    def stats(self) -> dict:
        with self._lock:
            return {"throttles": self._throttles, "pauses": self._pauses,
                    "device_losses": self._device_losses,
                    "state": self.control.state,
                    "ledger": self.ledger.summary()}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            guards = list(self._guards)
            self._guards.clear()
        self._wd.remove_breach_listener(self._on_breach)
        for wname in guards:
            self._wd.unwatch_histogram(wname)
        if self.fleet is not None and \
                hasattr(self.fleet, "remove_device_lost_listener"):
            self.fleet.remove_device_lost_listener(self._on_device_lost)
        if active_ledger() is self.ledger:
            set_active_ledger(self._prev_ledger)
