"""C-API-shaped stable entry points for external runtimes.

reference: include/LightGBM/c_api.h (~70 ``LGBM_*`` functions wrapped by
ctypes/R/SWIG).  The reference's stable ABI exists so non-Python runtimes
can drive the library; the TPU build's compute lives behind JAX, so the
equivalent seam is a FLAT, STABLE, ctypes-convention Python module: every
function is named after its c_api.h counterpart, returns 0 on success and
-1 on failure, reports through ``LGBM_GetLastError``, and passes handles +
out-parameters instead of objects — exactly the calling convention an
embedding runtime (JNI/pyo3/R's reticulate) binds against.

Covered surface (the subset every reference binding actually uses):
dataset create (mat/file/sample+push), field set/get, booster create/train/
predict/save/load, eval, model introspection.  Streaming push mirrors
c_api.h:98-144.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

_last_error = threading.local()


def _set_error(msg: str) -> int:
    _last_error.msg = str(msg)
    return -1


def LGBM_GetLastError() -> str:
    """reference: c_api.h LGBM_GetLastError."""
    return getattr(_last_error, "msg", "")


def _guard(fn):
    import functools

    @functools.wraps(fn)
    def inner(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:   # noqa: BLE001 - ABI boundary
            return _set_error(f"{type(e).__name__}: {e}")

    return inner


_handles: Dict[int, object] = {}
_next_handle = [1]
_lock = threading.Lock()


def _register(obj) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(handle: int):
    try:
        return _handles[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}") from None


def _parse_params(parameters: str) -> dict:
    """reference: Config::Str2Map (config.h:81) — 'k=v k2=v2' strings,
    with value typing ('false' must parse as False, not a truthy str)."""
    out = {}
    for tok in str(parameters or "").replace("\n", " ").split():
        if "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        low = v.strip().lower()
        if low in ("true", "false"):
            out[k] = low == "true"
            continue
        try:
            out[k] = int(v)
            continue
        except ValueError:
            pass
        try:
            out[k] = float(v)
            continue
        except ValueError:
            pass
        out[k] = v
    return out


# ------------------------------------------------------------------ dataset

@_guard
def LGBM_DatasetCreateFromMat(data, parameters: str, label,
                              out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromMat.  Accepts
    ``free_raw_data=false`` in the parameter string (needed for
    LGBM_BoosterResetTrainingData's score replay)."""
    from .dataset import Dataset
    params = _parse_params(parameters)
    keep_raw = not params.pop("free_raw_data", True)
    ds = Dataset(np.asarray(data), label=label, params=params,
                 free_raw_data=not keep_raw)
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetCreateFromFile(filename: str, parameters: str,
                               reference_handle: Optional[int],
                               out_handle: List[int]) -> int:
    from .dataset import Dataset
    ref = _get(reference_handle) if reference_handle else None
    ds = Dataset(str(filename), params=_parse_params(parameters),
                 reference=ref)
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetCreateFromSampledColumn(sample_data, num_total_row: int,
                                        parameters: str,
                                        out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromSampledColumn — start a
    streaming load; push blocks with LGBM_DatasetPushRows."""
    from .dataset import Dataset
    ds = Dataset.from_sample(np.asarray(sample_data), int(num_total_row),
                             params=_parse_params(parameters))
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetPushRows(dataset_handle: int, data,
                         start_row: int = None) -> int:
    """reference: c_api.h:98 LGBM_DatasetPushRows (start_row None
    appends after the previous push)."""
    sr = None if start_row is None else int(start_row)
    _get(dataset_handle).push_rows(data, start_row=sr)
    return 0


@_guard
def LGBM_DatasetSetField(dataset_handle: int, field_name: str,
                         field_data) -> int:
    """reference: c_api.h LGBM_DatasetSetField (label/weight/group/
    init_score)."""
    ds = _get(dataset_handle)
    field = str(field_name)
    if field == "label":
        ds.set_label(field_data)
    elif field == "weight":
        ds.set_weight(field_data)
    elif field in ("group", "query"):
        ds.set_group(field_data)
    elif field == "init_score":
        ds.set_init_score(field_data)
    else:
        raise ValueError(f"unknown field {field!r}")
    return 0


@_guard
def LGBM_DatasetGetNumData(dataset_handle: int, out: List[int]) -> int:
    ds = _get(dataset_handle)
    ds.construct()
    out[:] = [ds.num_data]
    return 0


@_guard
def LGBM_DatasetGetNumFeature(dataset_handle: int, out: List[int]) -> int:
    ds = _get(dataset_handle)
    ds.construct()
    out[:] = [len(ds.used_features)]
    return 0


@_guard
def LGBM_DatasetSaveBinary(dataset_handle: int, filename: str) -> int:
    _get(dataset_handle).construct().save_binary(str(filename))
    return 0


@_guard
def LGBM_DatasetFree(dataset_handle: int) -> int:
    with _lock:
        _handles.pop(dataset_handle, None)
    return 0


# ------------------------------------------------------------------ booster

@_guard
def LGBM_BoosterCreate(train_data_handle: int, parameters: str,
                       out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_BoosterCreate."""
    from .basic import Booster
    bst = Booster(params=_parse_params(parameters),
                  train_set=_get(train_data_handle))
    out_handle[:] = [_register(bst)]
    return 0


@_guard
def LGBM_BoosterCreateFromModelfile(filename: str, out_num_iterations: List[int],
                                    out_handle: List[int]) -> int:
    from .basic import Booster
    bst = Booster(model_file=str(filename))
    out_num_iterations[:] = [bst.current_iteration()]
    out_handle[:] = [_register(bst)]
    return 0


@_guard
def LGBM_BoosterLoadModelFromString(model_str: str,
                                    out_num_iterations: List[int],
                                    out_handle: List[int]) -> int:
    from .basic import Booster
    bst = Booster(model_str=str(model_str))
    out_num_iterations[:] = [bst.current_iteration()]
    out_handle[:] = [_register(bst)]
    return 0


@_guard
def LGBM_BoosterAddValidData(booster_handle: int,
                             valid_data_handle: int) -> int:
    bst = _get(booster_handle)
    bst.add_valid(_get(valid_data_handle),
                  f"valid_{len(bst.name_valid_sets)}")
    return 0


@_guard
def LGBM_BoosterUpdateOneIter(booster_handle: int,
                              out_is_finished: List[int]) -> int:
    """reference: c_api.h LGBM_BoosterUpdateOneIter."""
    stopped = _get(booster_handle).update()
    out_is_finished[:] = [1 if stopped else 0]
    return 0


@_guard
def LGBM_BoosterUpdateOneIterCustom(booster_handle: int, grad, hess,
                                    out_is_finished: List[int]) -> int:
    """reference: c_api.h:507 custom-objective update."""
    bst = _get(booster_handle)
    stopped = bst.boosting.train_one_iter(np.asarray(grad, np.float32),
                                          np.asarray(hess, np.float32))
    out_is_finished[:] = [1 if stopped else 0]
    return 0


@_guard
def LGBM_BoosterRollbackOneIter(booster_handle: int) -> int:
    _get(booster_handle).rollback_one_iter()
    return 0


@_guard
def LGBM_BoosterGetEval(booster_handle: int, data_idx: int,
                        out_results: List[float]) -> int:
    """reference: c_api.h LGBM_BoosterGetEval — data_idx 0 is the train
    set, i >= 1 the (i-1)-th validation set."""
    bst = _get(booster_handle)
    if data_idx == 0:
        res = bst.boosting.eval_train()
    else:
        name = bst.boosting.valid_names[data_idx - 1]
        res = [r for r in bst.boosting.eval_valid() if r[0] == name]
    out_results[:] = [float(v) for (_, _, v, _) in res]
    return 0


@_guard
def LGBM_BoosterGetNumClasses(booster_handle: int, out: List[int]) -> int:
    out[:] = [_get(booster_handle).num_class]
    return 0


@_guard
def LGBM_BoosterNumberOfTotalModel(booster_handle: int,
                                   out: List[int]) -> int:
    out[:] = [_get(booster_handle).num_trees()]
    return 0


@_guard
def LGBM_BoosterGetCurrentIteration(booster_handle: int,
                                    out: List[int]) -> int:
    out[:] = [_get(booster_handle).current_iteration()]
    return 0


@_guard
def LGBM_BoosterPredictForMat(booster_handle: int, data, predict_type: int,
                              num_iteration: int,
                              out_result: List[np.ndarray]) -> int:
    """reference: c_api.h:822; predict_type 0=normal 1=raw 2=leaf 3=contrib
    (C_API_PREDICT_* constants)."""
    bst = _get(booster_handle)
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    ni = None if num_iteration <= 0 else int(num_iteration)
    out_result[:] = [bst.predict(np.asarray(data), num_iteration=ni,
                                 **kwargs)]
    return 0


@_guard
def LGBM_BoosterSaveModel(booster_handle: int, start_iteration: int,
                          num_iteration: int, filename: str) -> int:
    # C semantics: num_iteration <= 0 saves ALL iterations (the Python
    # layer's best_iteration defaulting happens above this ABI)
    ni = int(num_iteration)
    _get(booster_handle).save_model(str(filename), num_iteration=ni,
                                    start_iteration=int(start_iteration))
    return 0


@_guard
def LGBM_BoosterSaveModelToString(booster_handle: int,
                                  out_str: List[str]) -> int:
    out_str[:] = [_get(booster_handle).model_to_string()]
    return 0


@_guard
def LGBM_BoosterFree(booster_handle: int) -> int:
    with _lock:
        _handles.pop(booster_handle, None)
    return 0


# --------------------------------------------------------------------------
# round-4 additions: the remaining c_api.h surface


def LGBM_SetLastError(msg: str) -> int:
    """reference: c_api.h LGBM_SetLastError."""
    _set_error(msg)
    return 0


@_guard
def LGBM_DatasetCreateByReference(reference_handle: int, num_total_row: int,
                                  out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateByReference — an empty aligned
    dataset to be filled by PushRows."""
    from .dataset import Dataset
    ref = _get(reference_handle)
    ds = Dataset.from_reference_streaming(ref, int(num_total_row))
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetPushRowsByCSR(dataset_handle: int, indptr, indices, data,
                              num_rows: int, start_row: int = None) -> int:
    """reference: c_api.h:123 — push a CSR block into a streaming dataset."""
    from scipy import sparse
    indptr = np.asarray(indptr, np.int64)
    ds = _get(dataset_handle)
    ncol = ds.num_total_features or (int(np.max(indices)) + 1 if len(indices) else 0)
    block = sparse.csr_matrix(
        (np.asarray(data, np.float64), np.asarray(indices, np.int32), indptr),
        shape=(int(num_rows), ncol))
    ds.push_rows(block, start_row=start_row)
    return 0


@_guard
def LGBM_DatasetCreateFromCSR(indptr, indices, data, num_rows: int,
                              num_col: int, parameters: str, label,
                              reference_handle: int,
                              out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromCSR."""
    from scipy import sparse
    from .dataset import Dataset
    mat = sparse.csr_matrix(
        (np.asarray(data, np.float64), np.asarray(indices, np.int32),
         np.asarray(indptr, np.int64)),
        shape=(int(num_rows), int(num_col)))
    ref = _get(reference_handle) if reference_handle else None
    ds = Dataset(mat, label=label, reference=ref,
                 params=_parse_params(parameters)).construct()
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetCreateFromCSC(col_ptr, indices, data, num_rows: int,
                              num_col: int, parameters: str, label,
                              reference_handle: int,
                              out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromCSC."""
    from scipy import sparse
    from .dataset import Dataset
    mat = sparse.csc_matrix(
        (np.asarray(data, np.float64), np.asarray(indices, np.int32),
         np.asarray(col_ptr, np.int64)),
        shape=(int(num_rows), int(num_col)))
    ref = _get(reference_handle) if reference_handle else None
    ds = Dataset(mat, label=label, reference=ref,
                 params=_parse_params(parameters)).construct()
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetCreateFromMats(mats, parameters: str, label,
                               out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromMats — row-block list."""
    from .dataset import Dataset
    data = np.vstack([np.asarray(m) for m in mats])
    ds = Dataset(data, label=label,
                 params=_parse_params(parameters)).construct()
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetGetSubset(dataset_handle: int, used_row_indices,
                          parameters: str, out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetGetSubset."""
    ds = _get(dataset_handle)
    sub = ds.subset(np.asarray(used_row_indices, np.int64),
                    params=_parse_params(parameters))
    sub.construct()
    out_handle[:] = [_register(sub)]
    return 0


@_guard
def LGBM_DatasetSetFeatureNames(dataset_handle: int, names) -> int:
    _get(dataset_handle).set_feature_name(list(names))
    return 0


@_guard
def LGBM_DatasetGetFeatureNames(dataset_handle: int,
                                out_names: List[str]) -> int:
    ds = _get(dataset_handle)
    ds.construct()
    out_names[:] = list(ds.feature_names)
    return 0


@_guard
def LGBM_DatasetGetField(dataset_handle: int, field_name: str,
                         out: List[np.ndarray]) -> int:
    val = _get(dataset_handle).get_field(str(field_name))
    out[:] = [val]
    return 0


@_guard
def LGBM_DatasetAddFeaturesFrom(target_handle: int, source_handle: int) -> int:
    _get(target_handle).add_features_from(_get(source_handle))
    return 0


@_guard
def LGBM_DatasetDumpText(dataset_handle: int, filename: str) -> int:
    _get(dataset_handle)._dump_text(str(filename))
    return 0


@_guard
def LGBM_DatasetUpdateParamChecking(old_parameters: str,
                                    new_parameters: str) -> int:
    """reference: c_api.h LGBM_DatasetUpdateParamChecking — error when a
    dataset-level parameter changes between boosters sharing a dataset."""
    from .config import Config
    old = Config.from_params(_parse_params(old_parameters)).to_dataset_params()
    new = Config.from_params(_parse_params(new_parameters)).to_dataset_params()
    diff = {k for k in set(old) | set(new) if old.get(k) != new.get(k)}
    if diff:
        return _set_error(
            f"Cannot change dataset parameters during training: {sorted(diff)}")
    return 0


@_guard
def LGBM_BoosterMerge(booster_handle: int, other_handle: int) -> int:
    """reference: c_api.h LGBM_BoosterMerge — append the other booster's
    trees to this booster's model."""
    bst = _get(booster_handle)
    other = _get(other_handle)
    bst.models.extend(other.models)
    if bst.boosting is not None:
        bst.boosting.models_version += 1
    return 0


@_guard
def LGBM_BoosterResetParameter(booster_handle: int, parameters: str) -> int:
    _get(booster_handle).reset_parameter(_parse_params(parameters))
    return 0


@_guard
def LGBM_BoosterResetTrainingData(booster_handle: int,
                                  train_data_handle: int) -> int:
    """reference: c_api.h LGBM_BoosterResetTrainingData — swap the training
    dataset (same bin mappers) keeping the trained model."""
    import lightgbm_tpu as lgb
    from .engine import _apply_init_model
    bst = _get(booster_handle)
    ds = _get(train_data_handle)
    # continued-training semantics: adopt the trees AND replay their score
    # contributions on the new data (GBDT::ResetTrainingData replays
    # AddScore for every existing model, src/boosting/gbdt.cpp:648) —
    # otherwise the next UpdateOneIter would fit gradients as if the
    # model were empty.  Requires the new dataset's raw features
    # (free_raw_data=False) for the replay.
    loaded = lgb.Booster(model_str=bst.model_to_string(num_iteration=0))
    fresh = lgb.Booster(params=dict(bst.params), train_set=ds)
    _apply_init_model(fresh, loaded, ds)
    # the reference preserves Python-side booster attributes across a
    # training-data swap: carry over attrs/best_iteration/name explicitly
    # and drop every stale key (a blind update would leave caches behind)
    preserved = {k: bst.__dict__[k]
                 for k in ("_attr", "best_iteration", "best_score",
                           "_train_data_name")
                 if k in bst.__dict__}
    bst.__dict__.clear()
    bst.__dict__.update(fresh.__dict__)
    bst.__dict__.update(preserved)
    return 0


@_guard
def LGBM_BoosterRefit(booster_handle: int, leaf_preds) -> int:
    """reference: c_api.h LGBM_BoosterRefit."""
    bst = _get(booster_handle)
    bst.boosting.refit_leaf_values(np.asarray(leaf_preds),
                                   bst.config.refit_decay_rate)
    return 0


@_guard
def LGBM_BoosterShuffleModels(booster_handle: int, start_iter: int,
                              end_iter: int) -> int:
    _get(booster_handle).shuffle_models(int(start_iter), int(end_iter))
    return 0


@_guard
def LGBM_BoosterNumModelPerIteration(booster_handle: int,
                                     out: List[int]) -> int:
    out[:] = [_get(booster_handle).num_model_per_iteration()]
    return 0


@_guard
def LGBM_BoosterGetNumFeature(booster_handle: int, out: List[int]) -> int:
    out[:] = [_get(booster_handle).num_feature()]
    return 0


@_guard
def LGBM_BoosterGetFeatureNames(booster_handle: int,
                                out_names: List[str]) -> int:
    out_names[:] = list(_get(booster_handle).feature_name())
    return 0


def _eval_names(bst) -> List[str]:
    """Metric names, derived from the configured metric objects without an
    evaluation pass (Metric.names); recomputed on every call so parameter
    resets that change the metric list are always reflected."""
    return [n for m in bst.boosting.train_metrics for n in m.names()]


@_guard
def LGBM_BoosterGetEvalCounts(booster_handle: int, out: List[int]) -> int:
    out[:] = [len(_eval_names(_get(booster_handle)))]
    return 0


@_guard
def LGBM_BoosterGetEvalNames(booster_handle: int,
                             out_names: List[str]) -> int:
    out_names[:] = list(_eval_names(_get(booster_handle)))
    return 0


@_guard
def LGBM_BoosterGetLeafValue(booster_handle: int, tree_idx: int,
                             leaf_idx: int, out: List[float]) -> int:
    out[:] = [_get(booster_handle).get_leaf_output(int(tree_idx),
                                                   int(leaf_idx))]
    return 0


@_guard
def LGBM_BoosterSetLeafValue(booster_handle: int, tree_idx: int,
                             leaf_idx: int, val: float) -> int:
    """reference: c_api.h LGBM_BoosterSetLeafValue."""
    bst = _get(booster_handle)
    bst.models[int(tree_idx)].leaf_value[int(leaf_idx)] = float(val)
    if bst.boosting is not None:
        bst.boosting.models_version += 1
    return 0


@_guard
def LGBM_BoosterGetUpperBoundValue(booster_handle: int,
                                   out: List[float]) -> int:
    out[:] = [_get(booster_handle).upper_bound()]
    return 0


@_guard
def LGBM_BoosterGetLowerBoundValue(booster_handle: int,
                                   out: List[float]) -> int:
    out[:] = [_get(booster_handle).lower_bound()]
    return 0


def _inner_scores(bst, data_idx: int) -> np.ndarray:
    """Inner raw scores for a dataset, trimmed of any device row padding
    (train_score is padded to the sharding multiple, _n_pad)."""
    b = bst.boosting
    if data_idx == 0:
        return np.asarray(b.train_score)[..., :b.num_data].reshape(-1)
    s = np.asarray(b.valid_scores[data_idx - 1])
    nv = b.valid_sets[data_idx - 1].num_data
    return s[..., :nv].reshape(-1)


@_guard
def LGBM_BoosterGetNumPredict(booster_handle: int, data_idx: int,
                              out: List[int]) -> int:
    """reference: c_api.h LGBM_BoosterGetNumPredict — size of the inner
    score vector for the data_idx-th dataset."""
    out[:] = [int(_inner_scores(_get(booster_handle), data_idx).size)]
    return 0


@_guard
def LGBM_BoosterGetPredict(booster_handle: int, data_idx: int,
                           out_result: List[np.ndarray]) -> int:
    """reference: c_api.h LGBM_BoosterGetPredict — inner raw scores kept
    for the training / validation datasets."""
    out_result[:] = [_inner_scores(_get(booster_handle), data_idx)]
    return 0


@_guard
def LGBM_BoosterCalcNumPredict(booster_handle: int, num_row: int,
                               predict_type: int, num_iteration: int,
                               out: List[int]) -> int:
    """reference: c_api.h LGBM_BoosterCalcNumPredict."""
    bst = _get(booster_handle)
    K = bst.num_tree_per_iteration
    total_iter = len(bst.models) // max(K, 1)
    ni = total_iter if num_iteration <= 0 else min(int(num_iteration),
                                                   total_iter)
    if predict_type == 2:      # leaf indices
        per_row = ni * K
    elif predict_type == 3:    # SHAP contribs
        per_row = (bst.num_features() + 1) * max(bst.num_class, 1)
    else:
        per_row = max(bst.num_class, 1)
    out[:] = [int(num_row) * per_row]
    return 0


def _predict_with_type(bst, data, predict_type, num_iteration):
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    ni = None if num_iteration <= 0 else int(num_iteration)
    return bst.predict(data, num_iteration=ni, **kwargs)


@_guard
def LGBM_BoosterPredictForCSR(booster_handle: int, indptr, indices, data,
                              num_rows: int, num_col: int, predict_type: int,
                              num_iteration: int,
                              out_result: List[np.ndarray]) -> int:
    from scipy import sparse
    mat = sparse.csr_matrix(
        (np.asarray(data, np.float64), np.asarray(indices, np.int32),
         np.asarray(indptr, np.int64)),
        shape=(int(num_rows), int(num_col)))
    out_result[:] = [_predict_with_type(_get(booster_handle), mat,
                                        predict_type, num_iteration)]
    return 0


@_guard
def LGBM_BoosterPredictForCSC(booster_handle: int, col_ptr, indices, data,
                              num_rows: int, num_col: int, predict_type: int,
                              num_iteration: int,
                              out_result: List[np.ndarray]) -> int:
    from scipy import sparse
    mat = sparse.csc_matrix(
        (np.asarray(data, np.float64), np.asarray(indices, np.int32),
         np.asarray(col_ptr, np.int64)),
        shape=(int(num_rows), int(num_col))).tocsr()
    out_result[:] = [_predict_with_type(_get(booster_handle), mat,
                                        predict_type, num_iteration)]
    return 0


@_guard
def LGBM_BoosterPredictForCSRSingleRow(booster_handle: int, indptr, indices,
                                       data, num_col: int, predict_type: int,
                                       num_iteration: int,
                                       out_result: List[np.ndarray]) -> int:
    return LGBM_BoosterPredictForCSR(booster_handle, indptr, indices, data,
                                     1, num_col, predict_type, num_iteration,
                                     out_result)


@_guard
def LGBM_BoosterPredictForMatSingleRow(booster_handle: int, row,
                                       predict_type: int, num_iteration: int,
                                       out_result: List[np.ndarray]) -> int:
    out_result[:] = [_predict_with_type(
        _get(booster_handle), np.asarray(row).reshape(1, -1), predict_type,
        num_iteration)]
    return 0


@_guard
def LGBM_BoosterPredictForMats(booster_handle: int, rows, predict_type: int,
                               num_iteration: int,
                               out_result: List[np.ndarray]) -> int:
    data = np.vstack([np.asarray(r).reshape(1, -1) for r in rows])
    out_result[:] = [_predict_with_type(_get(booster_handle), data,
                                        predict_type, num_iteration)]
    return 0


@_guard
def LGBM_BoosterPredictForFile(booster_handle: int, data_filename: str,
                               data_has_header: int, predict_type: int,
                               num_iteration: int,
                               result_filename: str) -> int:
    """reference: c_api.h LGBM_BoosterPredictForFile — predictions written
    one row per line (tab-separated for multi-output)."""
    from .io_utils import load_prediction_file
    bst = _get(booster_handle)
    X = load_prediction_file(str(data_filename), bst.num_features(),
                             {"header": bool(data_has_header)})
    pred = _predict_with_type(bst, X, predict_type, num_iteration)
    pred = np.asarray(pred)
    from .utils.file_io import open_atomic
    with open_atomic(str(result_filename), "w") as fh:
        for row in (pred if pred.ndim > 1 else pred[:, None]):
            fh.write("\t".join(repr(float(v)) for v in row) + "\n")
    return 0


@_guard
def LGBM_BoosterDumpModel(booster_handle: int, start_iteration: int,
                          num_iteration: int, out_str: List[str]) -> int:
    """reference: c_api.h LGBM_BoosterDumpModel (JSON)."""
    import json
    ni = int(num_iteration)          # <= 0 dumps all (C semantics)
    d = _get(booster_handle).dump_model(num_iteration=ni,
                                        start_iteration=int(start_iteration))
    out_str[:] = [json.dumps(d)]
    return 0


@_guard
def LGBM_BoosterFeatureImportance(booster_handle: int, num_iteration: int,
                                  importance_type: int,
                                  out: List[np.ndarray]) -> int:
    """reference: c_api.h LGBM_BoosterFeatureImportance — importance_type
    0 = split counts, 1 = total gain."""
    ni = int(num_iteration)          # <= 0 covers all (C semantics)
    kind = "gain" if importance_type == 1 else "split"
    out[:] = [_get(booster_handle).feature_importance(kind,
                                                      iteration=ni)]
    return 0


@_guard
def LGBM_NetworkInit(machines: str, local_listen_port: int,
                     listen_time_out: int, num_machines: int) -> int:
    """reference: c_api.h LGBM_NetworkInit (socket transport) — here the
    machine list starts the multi-host JAX runtime (parallel/network.py)."""
    from .parallel.network import init_network
    init_network(machines=machines, local_listen_port=local_listen_port,
                 listen_time_out=listen_time_out, num_machines=num_machines)
    return 0


@_guard
def LGBM_NetworkFree() -> int:
    """reference: c_api.h LGBM_NetworkFree."""
    from .parallel.network import free_network
    free_network()
    return 0


def LGBM_NetworkInitWithFunctions(num_machines: int, rank: int,
                                  reduce_scatter_ext_fun,
                                  allgather_ext_fun) -> int:
    """reference: c_api.h:1036 — external collective injection (the Spark/
    Dask seam).  The TPU build's collectives are XLA psum/all_gather inside
    the jitted step; external function injection cannot compose with that.
    Failing fast (reference failure semantics for an unsupported transport)
    keeps a Spark/Dask-style caller from proceeding to train partition-local
    models with no aggregation."""
    return _set_error(
        "LGBM_NetworkInitWithFunctions: external collective injection is "
        "not supported by the TPU build (collectives are XLA psum/"
        "all_gather inside the jitted step); use LGBM_NetworkInit "
        "(jax.distributed) + tree_learner=data instead")
