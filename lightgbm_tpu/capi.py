"""C-API-shaped stable entry points for external runtimes.

reference: include/LightGBM/c_api.h (~70 ``LGBM_*`` functions wrapped by
ctypes/R/SWIG).  The reference's stable ABI exists so non-Python runtimes
can drive the library; the TPU build's compute lives behind JAX, so the
equivalent seam is a FLAT, STABLE, ctypes-convention Python module: every
function is named after its c_api.h counterpart, returns 0 on success and
-1 on failure, reports through ``LGBM_GetLastError``, and passes handles +
out-parameters instead of objects — exactly the calling convention an
embedding runtime (JNI/pyo3/R's reticulate) binds against.

Covered surface (the subset every reference binding actually uses):
dataset create (mat/file/sample+push), field set/get, booster create/train/
predict/save/load, eval, model introspection.  Streaming push mirrors
c_api.h:98-144.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

_last_error = threading.local()


def _set_error(msg: str) -> int:
    _last_error.msg = str(msg)
    return -1


def LGBM_GetLastError() -> str:
    """reference: c_api.h LGBM_GetLastError."""
    return getattr(_last_error, "msg", "")


def _guard(fn):
    import functools

    @functools.wraps(fn)
    def inner(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:   # noqa: BLE001 - ABI boundary
            return _set_error(f"{type(e).__name__}: {e}")

    return inner


_handles: Dict[int, object] = {}
_next_handle = [1]
_lock = threading.Lock()


def _register(obj) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
    return h


def _get(handle: int):
    try:
        return _handles[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}") from None


def _parse_params(parameters: str) -> dict:
    """reference: Config::Str2Map (config.h:81) — 'k=v k2=v2' strings,
    with value typing ('false' must parse as False, not a truthy str)."""
    out = {}
    for tok in str(parameters or "").replace("\n", " ").split():
        if "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        low = v.strip().lower()
        if low in ("true", "false"):
            out[k] = low == "true"
            continue
        try:
            out[k] = int(v)
            continue
        except ValueError:
            pass
        try:
            out[k] = float(v)
            continue
        except ValueError:
            pass
        out[k] = v
    return out


# ------------------------------------------------------------------ dataset

@_guard
def LGBM_DatasetCreateFromMat(data, parameters: str, label,
                              out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromMat."""
    from .dataset import Dataset
    ds = Dataset(np.asarray(data), label=label,
                 params=_parse_params(parameters))
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetCreateFromFile(filename: str, parameters: str,
                               reference_handle: Optional[int],
                               out_handle: List[int]) -> int:
    from .dataset import Dataset
    ref = _get(reference_handle) if reference_handle else None
    ds = Dataset(str(filename), params=_parse_params(parameters),
                 reference=ref)
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetCreateFromSampledColumn(sample_data, num_total_row: int,
                                        parameters: str,
                                        out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_DatasetCreateFromSampledColumn — start a
    streaming load; push blocks with LGBM_DatasetPushRows."""
    from .dataset import Dataset
    ds = Dataset.from_sample(np.asarray(sample_data), int(num_total_row),
                             params=_parse_params(parameters))
    out_handle[:] = [_register(ds)]
    return 0


@_guard
def LGBM_DatasetPushRows(dataset_handle: int, data,
                         start_row: int) -> int:
    """reference: c_api.h:98 LGBM_DatasetPushRows."""
    _get(dataset_handle).push_rows(data, start_row=int(start_row))
    return 0


@_guard
def LGBM_DatasetSetField(dataset_handle: int, field_name: str,
                         field_data) -> int:
    """reference: c_api.h LGBM_DatasetSetField (label/weight/group/
    init_score)."""
    ds = _get(dataset_handle)
    field = str(field_name)
    if field == "label":
        ds.set_label(field_data)
    elif field == "weight":
        ds.set_weight(field_data)
    elif field in ("group", "query"):
        ds.set_group(field_data)
    elif field == "init_score":
        ds.set_init_score(field_data)
    else:
        raise ValueError(f"unknown field {field!r}")
    return 0


@_guard
def LGBM_DatasetGetNumData(dataset_handle: int, out: List[int]) -> int:
    ds = _get(dataset_handle)
    ds.construct()
    out[:] = [ds.num_data]
    return 0


@_guard
def LGBM_DatasetGetNumFeature(dataset_handle: int, out: List[int]) -> int:
    ds = _get(dataset_handle)
    ds.construct()
    out[:] = [len(ds.used_features)]
    return 0


@_guard
def LGBM_DatasetSaveBinary(dataset_handle: int, filename: str) -> int:
    _get(dataset_handle).construct().save_binary(str(filename))
    return 0


@_guard
def LGBM_DatasetFree(dataset_handle: int) -> int:
    with _lock:
        _handles.pop(dataset_handle, None)
    return 0


# ------------------------------------------------------------------ booster

@_guard
def LGBM_BoosterCreate(train_data_handle: int, parameters: str,
                       out_handle: List[int]) -> int:
    """reference: c_api.h LGBM_BoosterCreate."""
    from .basic import Booster
    bst = Booster(params=_parse_params(parameters),
                  train_set=_get(train_data_handle))
    out_handle[:] = [_register(bst)]
    return 0


@_guard
def LGBM_BoosterCreateFromModelfile(filename: str, out_num_iterations: List[int],
                                    out_handle: List[int]) -> int:
    from .basic import Booster
    bst = Booster(model_file=str(filename))
    out_num_iterations[:] = [bst.current_iteration()]
    out_handle[:] = [_register(bst)]
    return 0


@_guard
def LGBM_BoosterLoadModelFromString(model_str: str,
                                    out_num_iterations: List[int],
                                    out_handle: List[int]) -> int:
    from .basic import Booster
    bst = Booster(model_str=str(model_str))
    out_num_iterations[:] = [bst.current_iteration()]
    out_handle[:] = [_register(bst)]
    return 0


@_guard
def LGBM_BoosterAddValidData(booster_handle: int,
                             valid_data_handle: int) -> int:
    bst = _get(booster_handle)
    bst.add_valid(_get(valid_data_handle),
                  f"valid_{len(bst.name_valid_sets)}")
    return 0


@_guard
def LGBM_BoosterUpdateOneIter(booster_handle: int,
                              out_is_finished: List[int]) -> int:
    """reference: c_api.h LGBM_BoosterUpdateOneIter."""
    stopped = _get(booster_handle).update()
    out_is_finished[:] = [1 if stopped else 0]
    return 0


@_guard
def LGBM_BoosterUpdateOneIterCustom(booster_handle: int, grad, hess,
                                    out_is_finished: List[int]) -> int:
    """reference: c_api.h:507 custom-objective update."""
    bst = _get(booster_handle)
    stopped = bst.boosting.train_one_iter(np.asarray(grad, np.float32),
                                          np.asarray(hess, np.float32))
    out_is_finished[:] = [1 if stopped else 0]
    return 0


@_guard
def LGBM_BoosterRollbackOneIter(booster_handle: int) -> int:
    _get(booster_handle).rollback_one_iter()
    return 0


@_guard
def LGBM_BoosterGetEval(booster_handle: int, data_idx: int,
                        out_results: List[float]) -> int:
    """reference: c_api.h LGBM_BoosterGetEval — data_idx 0 is the train
    set, i >= 1 the (i-1)-th validation set."""
    bst = _get(booster_handle)
    if data_idx == 0:
        res = bst.boosting.eval_train()
    else:
        name = bst.boosting.valid_names[data_idx - 1]
        res = [r for r in bst.boosting.eval_valid() if r[0] == name]
    out_results[:] = [float(v) for (_, _, v, _) in res]
    return 0


@_guard
def LGBM_BoosterGetNumClasses(booster_handle: int, out: List[int]) -> int:
    out[:] = [_get(booster_handle).num_class]
    return 0


@_guard
def LGBM_BoosterNumberOfTotalModel(booster_handle: int,
                                   out: List[int]) -> int:
    out[:] = [_get(booster_handle).num_trees()]
    return 0


@_guard
def LGBM_BoosterGetCurrentIteration(booster_handle: int,
                                    out: List[int]) -> int:
    out[:] = [_get(booster_handle).current_iteration()]
    return 0


@_guard
def LGBM_BoosterPredictForMat(booster_handle: int, data, predict_type: int,
                              num_iteration: int,
                              out_result: List[np.ndarray]) -> int:
    """reference: c_api.h:822; predict_type 0=normal 1=raw 2=leaf 3=contrib
    (C_API_PREDICT_* constants)."""
    bst = _get(booster_handle)
    kwargs = {}
    if predict_type == 1:
        kwargs["raw_score"] = True
    elif predict_type == 2:
        kwargs["pred_leaf"] = True
    elif predict_type == 3:
        kwargs["pred_contrib"] = True
    ni = None if num_iteration <= 0 else int(num_iteration)
    out_result[:] = [bst.predict(np.asarray(data), num_iteration=ni,
                                 **kwargs)]
    return 0


@_guard
def LGBM_BoosterSaveModel(booster_handle: int, start_iteration: int,
                          num_iteration: int, filename: str) -> int:
    ni = None if num_iteration <= 0 else int(num_iteration)
    _get(booster_handle).save_model(str(filename), num_iteration=ni,
                                    start_iteration=int(start_iteration))
    return 0


@_guard
def LGBM_BoosterSaveModelToString(booster_handle: int,
                                  out_str: List[str]) -> int:
    out_str[:] = [_get(booster_handle).model_to_string()]
    return 0


@_guard
def LGBM_BoosterFree(booster_handle: int) -> int:
    with _lock:
        _handles.pop(booster_handle, None)
    return 0
