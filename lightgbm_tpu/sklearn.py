"""scikit-learn API wrappers.

reference: python-package/lightgbm/sklearn.py — LGBMModel (:169),
LGBMRegressor (:744), LGBMClassifier (:771), LGBMRanker (:913).
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster
from .callback import EarlyStopException
from .compat import (LGBMNotFittedError, _LGBMClassifierBase,
                     _LGBMModelBase, _LGBMRegressorBase)
from .config import Config
from .dataset import Dataset
from .engine import train as train_fn


def _ensure_1d_y(y):
    """Flatten y, warning on a column vector (sklearn protocol)."""
    y = np.asarray(y)
    if y.ndim == 2 and y.shape[1] == 1:
        import warnings
        try:
            from sklearn.exceptions import DataConversionWarning
        except ImportError:
            DataConversionWarning = UserWarning
        warnings.warn(
            "A column-vector y was passed when a 1d array was expected. "
            "Please change the shape of y to (n_samples, ), for example "
            "using ravel().", DataConversionWarning, stacklevel=2)
    return y.reshape(-1)


def _sample_weight_from_class_weight(class_weight, y):
    """Per-row weights from a class_weight spec.

    A dict may name only SOME classes; absent classes weigh 1.0 — the
    semantics the reference inherited from older scikit-learn (modern
    compute_sample_weight raises on a partial dict instead).
    """
    y = np.asarray(y).reshape(-1)
    if isinstance(class_weight, dict):
        u, inv = np.unique(y, return_inverse=True)
        per_class = np.array([float(class_weight.get(v, 1.0)) for v in u],
                             np.float64)
        return per_class[inv]
    from sklearn.utils.class_weight import compute_sample_weight
    return compute_sample_weight(class_weight, y)


class LGBMModel(_LGBMModelBase):
    """Base sklearn-style estimator (reference: sklearn.py:169).

    Inherits scikit-learn's BaseEstimator (the reference's _LGBMModelBase,
    compat.py) so meta-estimators (GridSearchCV, clone, modern
    __sklearn_tags__ introspection) treat it as a first-class estimator.
    """

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state=None, n_jobs: int = -1, silent: bool = True,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._best_score: Dict = {}
        self._n_features = -1
        self._classes = None
        self._n_classes = -1
        self.set_params(**kwargs)

    # -- sklearn plumbing ----------------------------------------------------

    def get_params(self, deep: bool = True) -> dict:
        params = {
            k: getattr(self, k) for k in (
                "boosting_type", "num_leaves", "max_depth", "learning_rate",
                "n_estimators", "subsample_for_bin", "objective", "class_weight",
                "min_split_gain", "min_child_weight", "min_child_samples",
                "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
                "reg_lambda", "random_state", "n_jobs", "silent",
                "importance_type")
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key) and not key.startswith("_"):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _process_params(self, stage: str) -> dict:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        obj = getattr(self, "_objective_resolved", None) or self.objective
        if callable(obj):
            params["objective"] = "none"
        elif obj is None:
            params["objective"] = self._default_objective()
        else:
            params["objective"] = obj
        nc = getattr(self, "_num_class_fit", 0)
        if nc > 1:
            params.setdefault("num_class", nc)
        self._objective = (obj if callable(obj)
                           else params.get("objective", obj))
        if self.random_state is not None:
            params["seed"] = (self.random_state if isinstance(self.random_state, int)
                              else 0)
        params.pop("random_state", None)
        params.pop("n_jobs", None)
        alias = {
            "boosting_type": "boosting", "min_split_gain": "min_gain_to_split",
            "min_child_weight": "min_sum_hessian_in_leaf",
            "min_child_samples": "min_data_in_leaf", "subsample": "bagging_fraction",
            "subsample_freq": "bagging_freq", "colsample_bytree": "feature_fraction",
            "reg_alpha": "lambda_l1", "reg_lambda": "lambda_l2",
            "subsample_for_bin": "bin_construct_sample_cnt",
        }
        for old, new in alias.items():
            if old in params:
                params[new] = params.pop(old)
        if not params.get("verbosity") and self.silent:
            params["verbosity"] = -1
        return params

    def __sklearn_tags__(self):
        tags = super().__sklearn_tags__()
        tags.input_tags.sparse = True      # scipy CSR/CSC bin host-side
        tags.input_tags.allow_nan = True   # NaN is a first-class missing value
        return tags

    def __sklearn_is_fitted__(self) -> bool:
        # modern check_is_fitted protocol: our fitted state lives behind
        # properties, not trailing-underscore instance attributes
        return self._Booster is not None

    def _default_objective(self) -> str:
        return "regression"

    def _default_eval_metric(self) -> str:
        """Metric deduced from the estimator class when the objective is a
        custom callable (reference: sklearn.py fit's original_metric
        deduction) — keeps early stopping usable with custom objectives."""
        return "l2"

    # -- fitting -------------------------------------------------------------

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMModel":
        params = self._process_params("fit")
        # metric resolution (reference sklearn.py fit): start from the
        # params metric, or — when absent — the objective name as a metric
        # alias (the factory resolves "regression"->l2 etc.) or the class
        # default for callable objectives; then UNION with eval_metric
        # strings (eval_metric adds metrics, it does not replace).
        # A BARE-callable eval_metric skips this whole block (reference
        # sklearn.py:520-524: `if callable(eval_metric): feval = ...` with
        # the deduction in the else branch), so a custom objective + custom
        # metric trains with no built-in metric at all.
        em, feval_fns = [], []
        if eval_metric is not None:
            em_raw = ([eval_metric] if isinstance(eval_metric, str)
                      or callable(eval_metric) else list(eval_metric))
            em = [m for m in em_raw if not callable(m)]
            feval_fns = [m for m in em_raw if callable(m)]
        if not callable(eval_metric):
            pm = params.get("metric")
            if isinstance(pm, (set, frozenset)):
                pm = sorted(pm, key=str)    # deterministic (config._coerce)
            pm = [pm] if isinstance(pm, str) else list(pm or [])
            if not pm:
                if callable(self.objective):
                    pm = [self._default_eval_metric()]
                # else: engine derives the objective's default metric itself
            if em and not pm:
                pm = [str(params.get("objective", self._default_objective()))]
            # eval_metric strings PREPEND (reference order): first_metric_only
            # early stopping keys off the first metric, which must be the
            # caller's eval_metric when one is given
            merged = [m for m in em if m not in pm] + pm
            if merged:
                params["metric"] = merged
        if getattr(self, "_eval_at", None):
            params["eval_at"] = list(self._eval_at)

        X_orig, y_orig = X, y
        if not _is_pandas(X):
            X = _to_array(X)
        y = _ensure_1d_y(y)
        if getattr(X, "ndim", 2) == 1:
            raise ValueError(
                "Expected 2D array, got 1D array instead. Reshape your "
                "data either using array.reshape(-1, 1) if your data has "
                "a single feature or array.reshape(1, -1) if it contains "
                "a single sample.")
        if X.shape[0] == 0:
            raise ValueError(
                f"Found array with 0 sample(s) (shape={X.shape}) while a "
                "minimum of 1 is required.")
        if X.ndim == 2 and X.shape[1] == 0:
            raise ValueError(
                f"Found array with 0 feature(s) (shape={X.shape}) while a "
                "minimum of 1 is required.")
        self._n_features = X.shape[1]
        y_t = self._transform_label(y)
        if self.class_weight is not None and sample_weight is None:
            # computed on ORIGINAL labels so dict keys match caller values
            sample_weight = self._class_weights(y)
        if isinstance(init_model, LGBMModel):
            init_model = init_model.booster_

        train_set = Dataset(X, label=y_t, weight=sample_weight, group=group,
                            init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params, free_raw_data=init_model is None)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                vcw = eval_class_weight[i] if eval_class_weight else None
                if vcw is not None and vw is None:
                    # weights computed on ORIGINAL labels so dict keys
                    # ({'5': 30} / {5: 30}) match the caller's y values
                    vw = _sample_weight_from_class_weight(
                        vcw, np.asarray(vy).reshape(-1))
                vxa = vx if _is_pandas(vx) else _to_array(vx)
                same = (vx is X_orig and vy is y_orig
                        and vw is None and vg is None and vi is None)
                if not same and not _is_pandas(vx) and not _is_pandas(X):
                    try:
                        same = (vxa.shape == X.shape
                                and len(vy) == len(y)
                                and vw is None and vg is None and vi is None
                                and vcw is None
                                and np.allclose(vxa[:5], X[:5],
                                                equal_nan=True))
                    except (TypeError, ValueError):
                        same = False
                if same:
                    valid_sets.append(train_set)
                    continue
                valid_sets.append(Dataset(vxa,
                                          label=self._transform_label(np.asarray(vy).reshape(-1)),
                                          weight=vw, group=vg, init_score=vi,
                                          reference=train_set, params=params))

        feval = None
        if feval_fns:
            wrapped = [_wrap_eval_metric(f, self) for f in feval_fns]
            if len(wrapped) == 1:
                feval = wrapped[0]
            else:
                def feval(score, dataset):
                    out = []
                    for f in wrapped:
                        r = f(score, dataset)
                        out.extend(r if isinstance(r, list) else [r])
                    return out
        fobj = _wrap_objective(self.objective) if callable(self.objective) else None

        self._evals_result = {}
        self._Booster = train_fn(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result, verbose_eval=verbose,
            callbacks=callbacks, init_model=init_model)
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def _transform_label(self, y):
        return y.astype(np.float64)

    def _class_weights(self, y):
        return _sample_weight_from_class_weight(self.class_weight, y)

    def predict(self, X, raw_score: bool = False, num_iteration=None,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise LGBMNotFittedError("Estimator not fitted; call fit first")
        if not _is_pandas(X):
            X = _to_array(X)
        if getattr(X, "ndim", 2) == 1:
            raise ValueError(
                "Expected 2D array, got 1D array instead. Reshape your "
                "data either using array.reshape(-1, 1) if your data has "
                "a single feature or array.reshape(1, -1) if it contains "
                "a single sample.")
        if (X.shape[1] != self._n_features
                and not kwargs.get("predict_disable_shape_check")):
            raise ValueError(
                f"X has {X.shape[1]} features, but "
                f"{type(self).__name__} is expecting "
                f"{self._n_features} features as input")
        # kwargs ride through to Booster.predict (pred_early_stop,
        # pred_early_stop_freq/margin, predict_disable_shape_check, ...)
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib, **kwargs)

    # -- attributes ----------------------------------------------------------

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LGBMNotFittedError("No booster found; call fit first")
        return self._Booster

    @property
    def objective_(self):
        """The concrete objective used while fitting (reference:
        sklearn.py:703)."""
        if self._Booster is None:
            raise LGBMNotFittedError("No objective found; call fit first")
        return self._objective

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def best_score_(self):
        return self._best_score

    @property
    def evals_result_(self):
        # reference semantics: None when no eval set produced results
        # (e.g. metric="None"), not an empty dict
        return self._evals_result or None

    @property
    def n_features_(self):
        return self._n_features

    @property
    def n_features_in_(self):
        if self._Booster is None:
            # NotFittedError subclasses AttributeError, so hasattr() is
            # False before fit — the modern sklearn check_n_features_in
            # contract
            raise LGBMNotFittedError(
                "No fit performed; call fit before n_features_in_")
        return self._n_features

    @property
    def feature_importances_(self):
        return self.booster_.feature_importance(self.importance_type)

    @property
    def feature_name_(self):
        return self.booster_.feature_name()


class LGBMRegressor(_LGBMRegressorBase, LGBMModel):
    """reference: sklearn.py:744."""

    def _default_objective(self):
        return "regression"

    def score(self, X, y, sample_weight=None):
        from sklearn.metrics import r2_score
        return r2_score(y, self.predict(X), sample_weight=sample_weight)


class LGBMClassifier(_LGBMClassifierBase, LGBMModel):
    """reference: sklearn.py:771."""

    def _default_objective(self):
        return "binary" if (self._n_classes is not None and self._n_classes <= 2) \
            else "multiclass"

    def _default_eval_metric(self):
        return ("multi_logloss"
                if (self._n_classes or 0) > 2 else "binary_logloss")

    def score(self, X, y, sample_weight=None):
        from sklearn.metrics import accuracy_score
        return accuracy_score(y, self.predict(X), sample_weight=sample_weight)

    def fit(self, X, y, **kwargs):
        if y is None:
            raise ValueError(
                "This estimator requires y to be passed, but the target "
                "y is None")
        y = _ensure_1d_y(y)
        try:
            from sklearn.utils.multiclass import check_classification_targets
            check_classification_targets(y)
        except ImportError:
            pass
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        # resolve the fit-time objective WITHOUT mutating self.objective
        # (clone/get_params must keep reconstructing the constructor args):
        # >2 classes forces a multiclass objective — any non-ova string,
        # including an unknown one, becomes "multiclass" (reference
        # sklearn.py:794-797 "Switch to using a multiclass objective")
        params_obj = self.objective
        ova_aliases = {"multiclassova", "multiclass_ova", "ova", "ovr"}
        if callable(params_obj):
            resolved = params_obj
        elif self._n_classes > 2:
            resolved = (params_obj if params_obj in ova_aliases
                        else "multiclass")
        else:
            resolved = params_obj if params_obj is not None else "binary"
        self._objective_resolved = resolved
        self._num_class_fit = (self._n_classes if self._n_classes > 2
                               and "num_class" not in self._other_params
                               else 0)
        # an eval_metric of the wrong arity is swapped for its alternative
        # (reference sklearn.py:797-805) so binary_error on a 3-class fit
        # means multi_error instead of a config conflict
        if self._n_classes > 2:
            remap = {"logloss": "multi_logloss", "binary_logloss":
                     "multi_logloss", "error": "multi_error",
                     "binary_error": "multi_error"}
        else:
            remap = {"logloss": "binary_logloss", "multi_logloss":
                     "binary_logloss", "error": "binary_error",
                     "multi_error": "binary_error"}
        em = kwargs.get("eval_metric")
        if isinstance(em, str):
            kwargs["eval_metric"] = remap.get(em, em)
        elif isinstance(em, (list, tuple)):
            kwargs["eval_metric"] = [
                remap.get(m, m) if isinstance(m, str) else m for m in em]
        super().fit(X, y, **kwargs)
        return self

    def _transform_label(self, y):
        """Encode with the TRAIN-time class mapping (self._classes, set in
        fit): an independent np.unique would silently misencode eval sets
        missing one of the train classes (reference uses one fitted
        LabelEncoder for train and eval labels alike)."""
        y = np.asarray(y).reshape(-1)
        if self._classes is None:
            _, y_enc = np.unique(y, return_inverse=True)
            return y_enc.astype(np.float64)
        idx = np.searchsorted(self._classes, y)
        idx_c = np.minimum(idx, len(self._classes) - 1)
        if not np.array_equal(self._classes[idx_c], y):
            raise ValueError("eval set contains labels unseen in training")
        return idx_c.astype(np.float64)

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration, pred_leaf,
                                    pred_contrib, **kwargs)
        if (callable(getattr(self, "_objective", self.objective))
                or raw_score or pred_leaf or pred_contrib):
            # custom objective: outputs are raw scores, not probabilities —
            # thresholding them would mislabel (reference sklearn.py
            # predict returns the raw result for callable objectives)
            return result
        if result.ndim == 1:  # binary probabilities
            idx = (result > 0.5).astype(int)
        else:
            idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        res = super().predict(X, raw_score, num_iteration, pred_leaf,
                              pred_contrib, **kwargs)
        if callable(getattr(self, "_objective", self.objective)) \
                and not (raw_score or pred_leaf or pred_contrib):
            # reference sklearn.py predict_proba: a custom objective means
            # the model's outputs are untransformable raw scores
            import warnings
            warnings.warn("Cannot compute class probabilities or labels "
                          "due to the usage of customized objective "
                          "function.\nReturning raw scores instead.")
            return res
        if raw_score or pred_leaf or pred_contrib:
            return res
        if res.ndim == 1:
            return np.vstack([1.0 - res, res]).T
        return res

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    """reference: sklearn.py:913."""

    def _default_objective(self):
        return "lambdarank"

    def _default_eval_metric(self):
        return "ndcg"

    def fit(self, X, y, group=None, eval_set=None, eval_group=None,
            eval_at=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None:
            if eval_group is None:
                raise ValueError(
                    "Eval_group cannot be None when eval_set is not None")
            n_eval = 1 if isinstance(eval_set, tuple) else len(eval_set)
            if len(eval_group) != n_eval:
                raise ValueError(
                    "Length of eval_group should be equal to eval_set")
            if any(g is None for g in eval_group):
                raise ValueError(
                    "Should set group for all eval datasets for ranking "
                    "task; if you use dict, the index should start from 0")
        # a constructor/params eval_at wins unless fit() overrides it
        # (reference _choose_param_value semantics); the engine's config
        # default (1,2,3,4,5) applies when neither is given
        self._eval_at = eval_at
        return super().fit(X, y, group=group, eval_set=eval_set,
                           eval_group=eval_group, **kwargs)


def _is_pandas(X) -> bool:
    return hasattr(X, "dtypes") and hasattr(X, "columns")


def _to_array(X):
    if hasattr(X, "toarray"):          # scipy sparse (any format) FIRST:
        X = X.toarray()                # dok has a dict-style .values METHOD
    elif hasattr(X, "values") and not callable(X.values):
        X = X.values                   # pandas
    elif hasattr(X, "values"):
        X = X.values()
    X = np.asarray(X)
    if np.iscomplexobj(X):
        raise ValueError("Complex data not supported")
    return np.ascontiguousarray(X, dtype=np.float64)


def _wrap_objective(func: Callable):
    def fobj(score, dataset):
        ret = func(dataset.get_label(), score)
        if len(ret) == 2:
            return ret
        raise ValueError("custom objective must return (grad, hess)")
    return fobj


def _wrap_eval_metric(func: Callable, model):
    def feval(score, dataset):
        return func(dataset.get_label(), score)
    return feval
