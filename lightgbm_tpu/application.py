"""CLI application: config-file driven train / predict.

reference: src/application/application.cpp — LoadParameters (:49),
LoadData (:84), InitTrain (:164), Train (:201), Predict (:212), driven by
``task=`` (src/main.cpp:11).  Usage mirrors the reference CLI:

    python -m lightgbm_tpu config=train.conf [key=value ...]

Config files are ``key = value`` lines with ``#`` comments; command-line
pairs override file entries (reference application.cpp:49-82).  Relative
data paths resolve against the config file's directory so the stock
``examples/*/train.conf`` files run unchanged; outputs go to the CWD.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .engine import train as train_fn
from .utils.log import log_info


def parse_config_file(path: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    with open(path) as fh:
        for ln in fh:
            ln = ln.split("#", 1)[0].strip()
            if not ln or "=" not in ln:
                continue
            k, v = ln.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """key=value pairs; ``config=`` pulls in a config file (CLI wins)."""
    cli: Dict[str, str] = {}
    for a in argv:
        if "=" not in a:
            raise SystemExit(f"unknown argument {a!r}; expected key=value")
        k, v = a.split("=", 1)
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    cfg_path = cli.get("config", cli.get("config_file"))
    if cfg_path:
        params.update(parse_config_file(cfg_path))
        params["__config_dir__"] = os.path.dirname(os.path.abspath(cfg_path))
    params.update(cli)
    return params


def _resolve(path: str, params: Dict[str, str]) -> str:
    if os.path.isabs(path) or os.path.exists(path):
        return path
    base = params.get("__config_dir__", "")
    cand = os.path.join(base, path)
    return cand if os.path.exists(cand) else path


class Application:
    """reference: class Application (src/application/application.h)."""

    def __init__(self, argv: List[str]):
        self.params = parse_argv(argv)
        self.task = self.params.get("task", "train")

    def run(self) -> None:
        if self.task == "train":
            self.train()
        elif self.task in ("refit", "refit_tree"):
            self.refit()
        elif self.task in ("predict", "prediction", "test"):
            self.predict()
        elif self.task == "convert_model":
            self.convert_model()
        else:
            raise SystemExit(f"unknown task {self.task!r}")

    # ------------------------------------------------------------------ train

    def train(self) -> None:
        p = dict(self.params)
        data_path = p.pop("data", None)
        if not data_path:
            raise SystemExit("no training data: set data=...")
        valid_paths = [v for v in p.pop("valid_data",
                                        p.pop("valid", "")).split(",") if v]
        output_model = p.pop("output_model", "LightGBM_model.txt")
        input_model = p.pop("input_model", None)
        # resume_from: a checkpoint bundle or <output_model>.ckpt directory
        # (docs/RESILIENCE.md) — restores full training state, unlike
        # input_model's continued training
        resume_from = p.pop("resume_from", None)
        p.pop("__config_dir__", None)

        cfg = Config.from_params(p)
        if cfg.num_machines > 1:
            # reference: Application ctor calls Network::Init ONLY when
            # num_machines > 1 (src/application/application.cpp:96-98) —
            # stock example confs list mlist.txt at num_machines=1 and
            # expect it ignored
            from .parallel.network import init_network
            init_network(machines=cfg.machines or None,
                         local_listen_port=cfg.local_listen_port,
                         listen_time_out=cfg.time_out,
                         num_machines=cfg.num_machines or None,
                         machine_list_file=(cfg.machine_list_filename
                                            or None))
        train_set = Dataset(_resolve(data_path, self.params), params=p)
        valid_sets = [Dataset(_resolve(v, self.params), params=p,
                              reference=train_set) for v in valid_paths]
        valid_names = [os.path.basename(v) for v in valid_paths]

        num_round = cfg.num_iterations
        booster = train_fn(
            p, train_set, num_boost_round=num_round,
            valid_sets=valid_sets, valid_names=valid_names,
            init_model=input_model,
            verbose_eval=max(cfg.metric_freq, 1),
            snapshot_freq=cfg.snapshot_freq,
            snapshot_out=output_model,
            resume_from=resume_from,
        )
        booster.save_model(output_model)
        log_info(f"Finished training; model saved to {output_model}")

    # ------------------------------------------------------------------ refit

    def refit(self) -> None:
        """reference: Application task=refit (application.cpp:212-248) —
        load input_model, re-fit its leaf values on `data`, save."""
        p = dict(self.params)
        data_path = p.pop("data", None)
        if not data_path:
            raise SystemExit("no refit data: set data=...")
        input_model = p.pop("input_model", "LightGBM_model.txt")
        output_model = p.pop("output_model", "LightGBM_model.txt")
        p.pop("__config_dir__", None)
        p.pop("task", None)
        cfg = Config.from_params(p)
        booster = Booster(model_file=_resolve(input_model, self.params),
                          params=p)
        from .io_utils import load_text_dataset
        tmp_ds = Dataset(None, params=p)
        X = load_text_dataset(_resolve(data_path, self.params), tmp_ds)
        y = tmp_ds.metadata.label
        refitted = booster.refit(X, y, decay_rate=cfg.refit_decay_rate, **p)
        refitted.save_model(output_model)
        log_info(f"Finished refit; model saved to {output_model}")

    # ---------------------------------------------------------------- predict

    def predict(self) -> None:
        p = dict(self.params)
        data_path = p.pop("data", None)
        if not data_path:
            raise SystemExit("no data to predict: set data=...")
        input_model = p.pop("input_model", "LightGBM_model.txt")
        output_result = p.pop("output_result", "LightGBM_predict_result.txt")
        booster = Booster(model_file=_resolve(input_model, self.params),
                          params=p)
        from .io_utils import load_text_dataset
        tmp_ds = Dataset(None, params=p)
        X = load_text_dataset(_resolve(data_path, self.params), tmp_ds)
        pred = booster.predict(
            X,
            raw_score=str(p.get("predict_raw_score", "false")).lower() == "true",
            pred_leaf=str(p.get("predict_leaf_index", "false")).lower() == "true",
            pred_contrib=str(p.get("predict_contrib", "false")).lower() == "true",
        )
        pred = np.atleast_1d(pred)
        from .utils.file_io import open_atomic
        with open_atomic(output_result, "w") as fh:
            if pred.ndim == 1:
                for v in pred:
                    fh.write(f"{v:.18g}\n")
            else:
                for row in pred:
                    fh.write("\t".join(f"{v:.18g}" for v in row) + "\n")
        log_info(f"Finished prediction; results saved to {output_result}")

    # ---------------------------------------------------------- convert_model

    def convert_model(self) -> None:
        from .model_text import model_to_if_else
        p = self.params
        input_model = p.get("input_model", "LightGBM_model.txt")
        out = p.get("convert_model", "gbdt_prediction.cpp")
        booster = Booster(model_file=_resolve(input_model, p))
        from .utils.file_io import write_atomic
        write_atomic(out, model_to_if_else(booster))
        log_info(f"Finished converting model; saved to {out}")


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        raise SystemExit(__doc__)
    Application(argv).run()
