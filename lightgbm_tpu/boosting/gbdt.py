"""GBDT training loop.

reference: src/boosting/gbdt.cpp — GBDT::Init (:42), Train (:246),
TrainOneIter (:338), Boosting (:152), Bagging (:163), BoostFromAverage
(:302), UpdateScore (:459).

TPU re-design:
- the whole per-iteration step (gradients -> bagging mask -> K tree grows ->
  leaf renewal -> shrinkage -> score update) is ONE jitted device program;
  the host only fetches the finished (tiny) tree arrays per iteration.
- bagging and GOSS are weight masks, not index subsets: shapes stay static,
  nothing is compacted (replaces is_use_subset_/bag_data_indices_ machinery,
  gbdt.cpp:163-244); excluded rows keep leaf routing so out-of-bag score
  update (gbdt.cpp:459-478) is free.
- scores live on device [K, n] f32 for train and each valid set.
"""

from __future__ import annotations

import functools
import math
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset, FeatureMeta
from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import span as _span
from ..ops.histogram import (on_accelerator, quantize_gradients,
                             take_from_table)
from ..grower import GrowerConfig, TreeArrays, grow_tree, predict_tree_binned
from ..objectives import ObjectiveFunction
from ..ops.renew import leaf_percentile
from ..tree import HostTree, tree_to_host
from ..utils.log import log_info, log_warning

K_EPSILON = 1e-15

# jitted-program cache shared ACROSS boosters: programs whose only
# booster-specific inputs ride as runtime arguments (bin metadata, labels,
# weights, monotone constraints) are keyed by their structural config, so
# cv folds and repeated sklearn fits trace+compile once instead of per
# Booster.  Bounded FIFO — entries hold compiled executables.
_PROGRAM_CACHE: Dict[tuple, object] = {}
_PROGRAM_CACHE_CAP = 64


def _shared_program(key, fn=None):
    """Get (fn is None) or insert a shared jitted program; key=None
    disables sharing (caller keeps a private program)."""
    if key is None:
        return None if fn is None else fn
    if fn is None:
        return _PROGRAM_CACHE.get(key)
    if len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_CAP:
        _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
    _PROGRAM_CACHE[key] = fn
    return fn


class GBDT:
    """reference: class GBDT (src/boosting/gbdt.h)."""

    boosting_type = "gbdt"
    # subclasses with per-iteration host-side model logic (DART's drop &
    # rescale, RF's averaged extension) must keep the eager finish path
    _defer_host_ok = True
    # fused multi-iteration macro-steps (boosting/macro.py): DART's
    # per-iteration host drop & rescale cannot ride inside a lax.scan
    _macro_ok = True
    # quantized-gradient training (use_quantized_grad): DART overrides to
    # False — its host-side drop & rescale re-weights trees whose leaf
    # outputs came from round-local quantization scales, compounding the
    # discretization error in a way the reference never ships
    _quant_ok = True
    # out-of-core streamed execution (lightgbm_tpu/data/): DART needs
    # device re-evaluation of dropped trees over the full matrix and RF
    # renews against running means per iteration — both stay resident
    _stream_ok = True
    # streamed-execution context (data/stream.py StreamContext); None =
    # resident training
    _stream = None

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[ObjectiveFunction]):
        self.config = config
        self.train_set = train_set.construct()
        self.objective = objective
        self.num_class = config.num_class
        self.num_tree_per_iteration = (objective.num_model_per_iteration
                                       if objective is not None else config.num_class)
        self.iter = 0
        self.num_init_iteration = 0        # iterations loaded via init_model
        self._models: List[HostTree] = []  # length = iter * K (drained)
        self.models_version = 0            # bumped on EVERY models mutation
        # (extend/rollback/refit/DART scale) — cache-invalidation token for
        # prediction caches keyed on the model list
        # deferred host materialization: on the tunneled accelerator
        # backend every device->host copy is a ~70 ms network round-trip,
        # so _finish_iter banks the stacked DEVICE trees here and
        # _drain_pending converts the whole backlog in one bulk transfer
        # when the host list is actually needed (predict/save/eval/len)
        self._pending: List[tuple] = []    # (abs_iter, stacked device trees)
        self._defer_host: Optional[bool] = None   # resolved on first iter
        self.shrinkage_rate = config.learning_rate

        self.meta = self.train_set.feature_meta()
        self.num_data = self.train_set.num_data
        n, F = self.train_set.binned_shape()     # metadata-only accessor:
        # valid for host-resident, released AND block-backed (out-of-core)
        # datasets; captured so _build_jit_fns rebuilds (reset_parameter)
        # never touch the host binned matrix — it may be released below
        self._binned_shape = (n, F)
        # padded bin axis: power-of-two-ish friendly size
        self.num_bins = int(self.meta.max_num_bin)

        # distributed dispatch (reference: GBDT::Init -> CreateTreeLearner,
        # gbdt.cpp:79 + tree_learner.cpp:13-36) — rows (tree_learner=data,
        # voting) or features (tree_learner=feature) are sharded over a
        # device mesh and the WHOLE per-iteration step runs under shard_map
        self._setup_distribution()
        n_pad = self._n_pad
        # out-of-core election (lightgbm_tpu/data/): when the two-level
        # budget planner rules full residency out on either memory (or
        # the Dataset is already block-backed), the matrix stays in the
        # spill store and every histogram pass streams blocks —
        # self.binned stays None and the streamed executor trains
        from ..data.stream import maybe_stream_setup
        if maybe_stream_setup(self):
            self.binned = None
        elif self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            if self._data_axis is not None:
                perm = self._row_perm
                key = ("data", id(self._mesh), self._data_axis, n_pad,
                       None if perm is None else hash(perm.tobytes()))
                self.binned = self._cached_device_binned(key)
                if self.binned is None:
                    src = self.train_set.host_binned()
                    if perm is not None:
                        # query-aligned layout: gather rows (pads -> bin 0)
                        b = np.concatenate(
                            [src, np.zeros((1, src.shape[1]), src.dtype)]
                        )[perm]
                    else:
                        b = np.pad(src, ((0, n_pad - n), (0, 0)))
                    # feature-major device residency (ops/histogram.py LAYOUT
                    # DOCTRINE): minor dim n stays unpadded in the (8,128)/
                    # (32,128) tiles; [n, 28] u8 row-major would pad 4.6x
                    self.binned = self._cache_device_binned(
                        key, jax.device_put(
                            np.ascontiguousarray(b.T),
                            NamedSharding(self._mesh,
                                          P(None, self._data_axis))))
            else:
                perm = self._col_perm
                key = ("feat", id(self._mesh), self._feature_axis,
                       self._f_pad,
                       None if perm is None else hash(perm.tobytes()))
                self.binned = self._cached_device_binned(key)
                if self.binned is None:
                    src = self.train_set.host_binned()
                    if perm is not None:
                        # shard-major EFB columns (pads -> all-zero column)
                        b = np.concatenate(
                            [src, np.zeros((src.shape[0], 1), src.dtype)],
                            axis=1)[:, perm]
                    else:
                        b = np.pad(src, ((0, 0), (0, self._f_pad - F)))
                    self.binned = self._cache_device_binned(
                        key, jax.device_put(
                            np.ascontiguousarray(b.T),
                            NamedSharding(self._mesh,
                                          P(self._feature_axis, None))))
        else:
            # n_pad keys the cache: the shape-bucket ladder can pad the
            # serial row axis too (pads -> bin 0, masked everywhere)
            key = ("serial", n_pad)
            self.binned = self._cached_device_binned(key)
            if self.binned is None:
                src = self.train_set.host_binned()
                if n_pad > n:
                    src = np.pad(src, ((0, n_pad - n), (0, 0)))
                self.binned = self._cache_device_binned(
                    key, jnp.asarray(np.ascontiguousarray(src.T)))
        self._row_valid = jnp.asarray(self._pad_rows_np(np.ones(n, np.float32)))
        if objective is not None:
            objective.init(self.train_set.metadata, self.num_data)

        # (self.grower_cfg is derived inside _build_jit_fns, called below)
        K = self.num_tree_per_iteration
        self.train_score = jnp.zeros((K, n_pad), jnp.float32)
        if self._mesh is not None and self._data_axis is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.train_score = jax.device_put(
                self.train_score,
                NamedSharding(self._mesh, P(None, self._data_axis)))
        self.init_scores = [0.0] * K
        self._init_score_added = False
        # user-provided init score (reference: score_updater has_init_score)
        if self.train_set.metadata.init_score is not None:
            isc = np.asarray(self.train_set.metadata.init_score, np.float32)
            isc = (isc.reshape(-1, n) if isc.size == K * n else
                   np.broadcast_to(isc.reshape(1, n), (K, n)))
            self.train_score = self.train_score + jnp.asarray(
                np.stack([self._pad_rows_np(row) for row in isc]))
            self._init_score_added = True

        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self.valid_binned: List[jax.Array] = []
        self.valid_scores: List[jax.Array] = []
        self.train_metrics = []
        self.valid_metrics: List[list] = []

        self._rng = np.random.RandomState(config.bagging_seed)
        self._goss_rng_key = jax.random.PRNGKey(config.bagging_seed)
        # last round's per-class (g_scale, h_scale) quantization factors —
        # device [K, 2] (zeros when quantized training is off); carried
        # alongside the score state, through macro chunk outputs and
        # checkpoint capture/restore (telemetry + the hist_probe payload
        # accounting read it)
        self._quant_scales = None

        # device-resident history of this run's stacked TreeArrays, so DART
        # drops and rollback re-evaluate trees on device instead of host
        # passes over the full binned matrix ("last": only the most recent
        # iteration, enough for rollback; DART switches to "all")
        self.tree_history: List = []
        self.history_scale: Dict[int, float] = {}
        self._history_mode = "last"

        self._build_jit_fns()

        # device residency established: the host [n, F] binned matrix is a
        # duplicate of self.binned now.  When the user signalled the
        # Dataset is consumed (free_raw_data, the default) drop it —
        # roughly halves peak RSS at HIGGS scale.  Gated to accelerator
        # backends by default (a released Dataset cannot build a second
        # booster / subset / save_binary); LGBM_TPU_FREE_BINNED=1/0
        # overrides either way.
        env_free = os.environ.get("LGBM_TPU_FREE_BINNED", "")
        if self.train_set.free_raw_data and env_free != "0" and (
                env_free == "1" or on_accelerator()):
            self.train_set.release_host_binned()

    # ------------------------------------------------------------------ setup

    def _cached_device_binned(self, key):
        """The Dataset's device-binned cache: a second GBDT on the SAME
        constructed Dataset with the same device layout (mesh, axis,
        padding, permutation) reuses the first upload instead of paying a
        second host->device copy AND a second HBM residency.  This is
        what makes batched multi-booster training (lightgbm_tpu/multi/)
        HBM-cheap in shared-data mode — every lane of a sweep indexes ONE
        matrix (multi/group.py keys shared groups on ``id(binned)``).
        ``release_host_binned`` drops this cache with the host copy — a
        released Dataset keeps its cannot-build-another-booster
        contract."""
        cache = getattr(self.train_set, "_dev_binned_cache", None)
        return cache.get(key) if cache else None

    def _cache_device_binned(self, key, arr):
        cache = getattr(self.train_set, "_dev_binned_cache", None)
        if cache is None:
            cache = self.train_set._dev_binned_cache = {}
        # one entry per layout; two layouts at once (e.g. a serial probe
        # next to a sharded run) is the realistic ceiling — beyond that,
        # evict oldest rather than grow HBM pins unboundedly
        while len(cache) >= 2 and key not in cache:
            cache.pop(next(iter(cache)))
        cache[key] = arr
        return arr

    def _build_forced_plan(self):
        """Parse ``config.forcedsplits_filename`` into plan arrays
        (leaf, inner_feature, threshold_bin), each [n_forced] i32.

        reference: forced_split_json_ loaded at SerialTreeLearner::Init and
        applied by the ForceSplits BFS (serial_tree_learner.cpp:411-521).
        Leaf indices are precomputed here because the grower's split order
        is deterministic: splits apply in BFS order, the left child keeps
        the parent's leaf index, and the right child of the i-th split
        (0-based) gets leaf index i+1.
        """
        fname = self.config.forcedsplits_filename
        if not fname:
            return None
        import json
        from collections import deque

        from ..binning import BinType
        with open(fname) as f:
            root = json.load(f)
        inner = {orig: j for j, orig in
                 enumerate(self.train_set.used_features)}
        mappers = self.train_set.bin_mappers
        leaves: List[int] = []
        feats: List[int] = []
        thrs: List[int] = []
        q = deque()
        if isinstance(root, dict) and "feature" in root and "threshold" in root:
            q.append((root, 0))
        while q and len(leaves) < self.config.num_leaves - 1:
            node, leaf = q.popleft()
            forig = int(node["feature"])
            if forig not in inner:
                log_warning(
                    f"forced split on unused/trivial feature {forig}; "
                    "the rest of the forced-splits plan is dropped")
                break
            m = mappers[forig]
            tb = int(m.value_to_bin(
                np.array([float(node["threshold"])]))[0])
            if m.bin_type == BinType.NUMERICAL:
                tb = min(max(tb, 0), max(m.num_bin - 2, 0))
            leaves.append(leaf)
            feats.append(inner[forig])
            thrs.append(tb)
            right_leaf = len(leaves)      # i+1 for the i-th split
            for side, child_leaf in (("left", leaf), ("right", right_leaf)):
                ch = node.get(side)
                if isinstance(ch, dict) and "feature" in ch \
                        and "threshold" in ch:
                    q.append((ch, child_leaf))
        if not leaves:
            return None
        return (np.asarray(leaves, np.int32), np.asarray(feats, np.int32),
                np.asarray(thrs, np.int32))

    def _setup_distribution(self) -> None:
        """Pick the parallel mode from config.tree_learner and build the
        mesh.  reference: CreateTreeLearner (tree_learner.cpp:13-36); with
        one device every mode degenerates to serial (identical results)."""
        self._mesh = None
        self._data_axis = None
        self._feature_axis = None
        # shape-bucket ladder (ops/planner.py bucket_rows, docs/PERF.md):
        # pad the row count up to the next ladder rung so nearby dataset
        # sizes share ONE compiled training program (the jit caches key on
        # n_pad).  Padded rows ride the existing machinery — row_mask 0,
        # zero gradients, bagging always drops them — so trees are
        # unchanged; integer (quantized) accumulation makes that exact,
        # while f32 reduction trees can shift at ulp level, which is why
        # the default is accelerator-only (LGBM_TPU_SHAPE_BUCKETS
        # overrides either way).
        from ..ops.planner import bucket_rows, shape_buckets_enabled
        self._shape_buckets = shape_buckets_enabled()
        self._n_pad = (bucket_rows(self.num_data) if self._shape_buckets
                       else self.num_data)
        self._f_pad = self.train_set.binned_shape()[1]
        self._meta_dist = None
        self._row_perm = None      # [n_pad] padded-slot -> original row
        self._inv_perm = None      # [n] original row -> padded slot
        self._feat_perm = None     # [F_pad] padded feature slot -> inner
        self._col_perm = None      # [G_pad] padded column slot -> group
        tl = str(self.config.tree_learner).lower()
        aliases = {"data_parallel": "data", "feature_parallel": "feature",
                   "voting_parallel": "voting", "serial_tree_learner": "serial"}
        tl = aliases.get(tl, tl)
        if tl not in ("serial", "data", "feature", "voting"):
            raise ValueError(f"unknown tree_learner {tl!r}")
        self.tree_learner_type = tl
        self._num_slices = 1
        if tl == "serial" or jax.device_count() <= 1:
            return
        from ..parallel.learners import (DATA_AXIS, FEATURE_AXIS, make_mesh,
                                         pad_rows_to)
        ndev = jax.device_count()
        if tl == "feature" and self.config.num_machines > 1:
            # historical num_machines device cap; the data/voting branch
            # gets its shard count from mesh_plan's verdict instead
            ndev = min(ndev, self.config.num_machines)
        need_group = (self.objective is not None and
                      getattr(self.objective, "need_group", False))
        if tl in ("data", "voting"):
            # hybrid ICI x DCN mesh election (pod-scale plane): the
            # reference's num_machines / local_listen_port keys round-trip
            # through parallel/network.mesh_plan — real multi-host
            # topology > simulated slices (LGBM_TPU_NUM_SLICES) >
            # num_machines-as-slice-count > flat.  On a hybrid mesh rows
            # shard over BOTH tiers in the same linear device order as
            # the flat mesh, so electing it never changes shard contents.
            from ..parallel.learners import make_hybrid_mesh
            from ..parallel.network import mesh_plan
            mp = mesh_plan(jax.device_count(),
                           num_machines=self.config.num_machines or None,
                           local_listen_port=self.config.local_listen_port)
            if mp.hybrid:
                self._mesh = make_hybrid_mesh(mp.total_shards,
                                              num_slices=mp.num_slices)
                from ..parallel.learners import HYBRID_AXES
                self._data_axis = HYBRID_AXES
                self._num_slices = mp.num_slices
                ndev = mp.total_shards
            else:
                # the plan's flat verdict also carries the shard COUNT:
                # the historical num_machines device cap, and the
                # shrunk-world device bound of an elastic resume
                ndev = mp.total_shards
                self._mesh = make_mesh(ndev, (DATA_AXIS,))
                self._data_axis = DATA_AXIS
            if need_group:
                # ranking: whole queries per shard (query-aligned layout;
                # shape buckets don't apply — padding is query-driven)
                self._build_query_sharding(ndev)
            else:
                self._n_pad = pad_rows_to(
                    bucket_rows(self.num_data) if self._shape_buckets
                    else self.num_data, ndev)
        else:  # feature
            self._mesh = make_mesh(ndev, (FEATURE_AXIS,))
            self._feature_axis = FEATURE_AXIS
            m = self.meta.resolved()
            if m.has_bundles:
                # shard EFB GROUPS, not raw features (reference partitions
                # features after bundling, feature_parallel_tree_learner.cpp:
                # 33-52): whole bundles per shard, groups/features padded to
                # uniform per-shard counts, meta arranged shard-major
                self._build_group_sharding(ndev, m)
            else:
                F = self.train_set.binned_shape()[1]
                self._f_pad = (F + ndev - 1) // ndev * ndev
                if self._f_pad > F:
                    import dataclasses
                    pad = self._f_pad - F
                    self._meta_dist = dataclasses.replace(
                        m,
                        num_bin=np.concatenate([m.num_bin, np.ones(pad, np.int32)]),
                        missing_type=np.concatenate([m.missing_type, np.zeros(pad, np.int32)]),
                        default_bin=np.concatenate([m.default_bin, np.zeros(pad, np.int32)]),
                        most_freq_bin=np.concatenate([m.most_freq_bin, np.zeros(pad, np.int32)]),
                        is_categorical=np.concatenate([m.is_categorical, np.zeros(pad, bool)]),
                        feat_group=np.arange(self._f_pad, dtype=np.int32),
                        feat_start=np.ones(self._f_pad, np.int32),
                        num_groups=self._f_pad,
                    )
                else:
                    self._meta_dist = m

    def _build_query_sharding(self, ndev: int) -> None:
        """Row layout for distributed ranking: queries are greedily packed
        onto shards (lightest-first) and each shard is padded to the max
        shard size, so no query ever straddles a shard boundary and the
        per-query pairwise lambdas stay shard-local by construction.

        reference analogue: distributed ranking partitions rows at query
        boundaries at load time (Metadata::CheckOrPartition,
        src/io/metadata.cpp:141); the per-query loop is
        rank_objective.hpp:48-65.  Sets ``_n_pad``, ``_row_perm`` (padded
        slot -> original row, ``n`` = padding sentinel), ``_inv_perm``.
        """
        import heapq
        md = self.train_set.metadata
        if md.query_boundaries is None:
            raise RuntimeError("Ranking tasks require query information")
        qb = np.asarray(md.query_boundaries, np.int64)
        sizes = np.diff(qb)
        heap = [(0, d) for d in range(ndev)]
        heapq.heapify(heap)
        shard_queries: List[List[int]] = [[] for _ in range(ndev)]
        for q in range(len(sizes)):
            tot, d = heapq.heappop(heap)
            shard_queries[d].append(q)
            heapq.heappush(heap, (tot + int(sizes[q]), d))
        n_shard = max(1, max((int(sizes[qs].sum()) for qs in shard_queries
                              if qs), default=1))
        self._n_pad = n_shard * ndev
        n = self.num_data
        perm = np.full(self._n_pad, n, np.int64)
        for d, qs in enumerate(shard_queries):
            pos = d * n_shard
            for q in qs:
                lo, hi = int(qb[q]), int(qb[q + 1])
                perm[pos:pos + hi - lo] = np.arange(lo, hi)
                pos += hi - lo
        self._row_perm = perm
        inv = np.empty(n, np.int64)
        inv[perm[perm < n]] = np.nonzero(perm < n)[0]
        self._inv_perm = inv

    def _build_group_sharding(self, ndev: int, m) -> None:
        """Shard-major EFB layout for tree_learner=feature: pack whole
        bundles onto shards (greedy, lightest feature count first), pad
        every shard to G_shard group columns and F_shard features, and
        rewrite the meta arrays in that order with shard-LOCAL group
        indices.  Sets ``_meta_dist``, ``_f_pad``, ``_feat_perm`` (padded
        feature slot -> inner feature, sentinel = F) and ``_col_perm``
        (padded column slot -> group, sentinel = G)."""
        import dataclasses
        import heapq
        F = len(m.num_bin)
        G = m.num_groups
        feats_of: List[List[int]] = [[] for _ in range(G)]
        for f, g in enumerate(np.asarray(m.feat_group)):
            feats_of[int(g)].append(f)
        heap = [(0, d) for d in range(ndev)]
        heapq.heapify(heap)
        shard_groups: List[List[int]] = [[] for _ in range(ndev)]
        for g in sorted(range(G), key=lambda gg: -len(feats_of[gg])):
            cnt, d = heapq.heappop(heap)
            shard_groups[d].append(g)
            heapq.heappush(heap, (cnt + len(feats_of[g]), d))
        G_shard = max(1, max(len(s) for s in shard_groups))
        F_shard = max(1, max(sum(len(feats_of[g]) for g in s)
                             for s in shard_groups))
        if F_shard == G_shard:
            # FeatureMeta.has_bundles tests num_groups != num_features;
            # keep them distinct so the grower stays on the bundle path
            F_shard += 1
        G_pad, F_pad = G_shard * ndev, F_shard * ndev
        col_perm = np.full(G_pad, G, np.int64)
        feat_perm = np.full(F_pad, F, np.int64)
        feat_group_local = np.zeros(F_pad, np.int32)
        for d, gs in enumerate(shard_groups):
            for j, g in enumerate(gs):
                col_perm[d * G_shard + j] = g
            pos = d * F_shard
            for j, g in enumerate(gs):
                for f in feats_of[g]:
                    feat_perm[pos] = f
                    feat_group_local[pos] = j
                    pos += 1

        def takef(arr, fill, dtype):
            ext = np.concatenate(
                [np.asarray(arr, dtype), np.asarray([fill], dtype)])
            return ext[feat_perm]

        self._meta_dist = dataclasses.replace(
            m,
            num_bin=takef(m.num_bin, 1, np.int32),
            missing_type=takef(m.missing_type, 0, np.int32),
            default_bin=takef(m.default_bin, 0, np.int32),
            most_freq_bin=takef(m.most_freq_bin, 0, np.int32),
            is_categorical=takef(m.is_categorical, False, bool),
            feat_group=feat_group_local,
            feat_start=takef(m.feat_start, 1, np.int32),
            num_groups=G_pad,
        )
        self._f_pad = F_pad
        self._feat_perm = feat_perm
        self._col_perm = col_perm

    def _pad_rows_np(self, p: np.ndarray) -> np.ndarray:
        """Pad (and, for query-aligned layouts, permute) a per-row host
        array to the sharded row layout."""
        p = np.asarray(p, np.float32)
        if self._row_perm is not None:
            return np.concatenate([p, np.zeros(1, np.float32)])[self._row_perm]
        pad = self._n_pad - self.num_data
        return np.pad(p, (0, pad)) if pad else p

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        valid_set.construct()
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        self.valid_binned.append(jnp.asarray(
            np.ascontiguousarray(valid_set.host_binned().T)))
        K = self.num_tree_per_iteration
        vs = jnp.zeros((K, valid_set.num_data), jnp.float32)
        if valid_set.metadata.init_score is not None:
            isc = np.asarray(valid_set.metadata.init_score, np.float32)
            nv = valid_set.num_data
            vs = vs + jnp.asarray(isc.reshape(-1, nv) if isc.size == K * nv
                                  else np.broadcast_to(isc.reshape(1, nv), (K, nv)))
        self.valid_scores.append(vs)

    def set_metrics(self, train_metrics, valid_metrics_per_set) -> None:
        self.train_metrics = train_metrics
        self.valid_metrics = valid_metrics_per_set

    def _build_jit_fns(self) -> None:
        K = self.num_tree_per_iteration
        nmach = 1
        vote_k = 0
        if self._mesh is not None and self._data_axis is not None:
            from ..parallel.collectives import axis_size
            nmach = axis_size(self._mesh, self._data_axis)
            if self.tree_learner_type == "voting":
                vote_k = self.config.top_k
        # feature_fraction_bynode -> exact per-node sample count
        # (reference: ColSampler::GetCnt, col_sampler.hpp:28-33)
        F_used = len(self.train_set.used_features)
        bynode_cnt = 0
        if self.config.feature_fraction_bynode < 1.0:
            bynode_cnt = max(
                int(round(F_used * self.config.feature_fraction_bynode)),
                min(2, F_used))
        # CEGB wiring (reference: CostEfficientGradientBoosting::IsEnable +
        # Init, cost_effective_gradient_boosting.hpp:25-49): map the
        # per-ORIGINAL-feature penalty lists onto the used (inner) features
        cc = self.config
        coupled = list(cc.cegb_penalty_feature_coupled or [])
        lazy = list(cc.cegb_penalty_feature_lazy or [])
        cegb_enabled = bool(cc.cegb_penalty_split > 0.0 or coupled or lazy)
        ntf = self.train_set.num_total_features
        self._cegb_coupled_pen = None
        self._cegb_lazy_pen = None
        if cegb_enabled:
            if self._mesh is not None and self.tree_learner_type == "voting":
                # recorded design exclusion (see grower.py): exact CEGB
                # needs global per-feature candidates, which voting exists
                # to avoid materializing — data-parallel gives the same
                # result at honest cost
                raise NotImplementedError(
                    "CEGB needs global per-feature candidates; "
                    "voting-parallel exists to avoid building exactly "
                    "those — use tree_learner=data with CEGB instead")
            for name, lst in (("cegb_penalty_feature_coupled", coupled),
                              ("cegb_penalty_feature_lazy", lazy)):
                if lst and len(lst) != ntf:
                    # reference: Log::Fatal at CEGB Init
                    raise ValueError(
                        f"{name} should be the same size as feature number "
                        f"({len(lst)} vs {ntf})")
            uf = np.asarray(self.train_set.used_features, np.int64)

            def _pen_device_layout(vals):
                """Inner-feature penalties -> the grower's global feature
                order (device-slot order under feature sharding; pad slots
                get zero penalty so they can never be selected anyway)."""
                p = np.asarray(vals, np.float32)[uf]
                if self._feat_perm is not None:
                    p = np.concatenate([p, np.zeros(1, np.float32)])[
                        self._feat_perm]
                elif self._feature_axis is not None and self._f_pad > len(p):
                    p = np.concatenate(
                        [p, np.zeros(self._f_pad - len(p), np.float32)])
                return jnp.asarray(p)

            if coupled:
                self._cegb_coupled_pen = _pen_device_layout(coupled)
            if lazy:
                self._cegb_lazy_pen = _pen_device_layout(lazy)
        self._cegb_enabled = cegb_enabled
        # quantized-gradient training (use_quantized_grad): automatic f32
        # fallback with a warn-once for the combos the integer pipeline
        # does not cover (reference: quantized training is likewise gated
        # out of DART-style reweighting and constraint-coupled searches)
        quant_on = bool(cc.use_quantized_grad)
        if quant_on:
            blockers = []
            if not type(self)._quant_ok:
                blockers.append(f"boosting={self.boosting_type}")
            if cegb_enabled:
                blockers.append("CEGB")
            if cc.monotone_constraints:
                blockers.append("monotone_constraints")
            if cc.extra_trees:
                blockers.append("extra_trees (random thresholds)")
            if blockers:
                quant_on = False
                if not getattr(self, "_quant_warned", False):
                    self._quant_warned = True
                    log_warning(
                        "use_quantized_grad=true is not supported with "
                        + ", ".join(blockers)
                        + "; falling back to f32 histograms for this "
                        "booster (training proceeds unquantized)")
        self._quant_on = quant_on
        forced_plan = self._build_forced_plan()
        if forced_plan is not None and self._feat_perm is not None:
            # the grower under sharded-EFB feature layout numbers features
            # by padded DEVICE slot; the plan is built in inner numbering
            Fi = len(self.train_set.used_features)
            inv = np.zeros(Fi, np.int64)
            slot_is_real = self._feat_perm < Fi
            inv[self._feat_perm[slot_is_real]] = \
                np.nonzero(slot_is_real)[0].astype(np.int64)
            forced_plan = (forced_plan[0],
                           inv[np.asarray(forced_plan[1], np.int64)],
                           forced_plan[2])
        # fused Pallas histogram→split megakernel (ops/fused.py) context:
        # the numeric unsharded common case.  hist_method=auto elects it
        # on accelerators when the planner proves the VMEM arena fits
        # (plan_fused, below) AND a one-time compile probe verified the
        # kernel on this backend; an explicit hist_method=fused also runs
        # on CPU (interpret mode — how the tier-1 parity suite executes
        # it).  Computed BEFORE the measured-auto resolution: electing
        # fused must leave the method string "auto" for the planner, and
        # the per-kernel timing probe would be wasted work.
        meta_fused = (self._meta_dist if self._meta_dist is not None
                      else self.meta).resolved()
        fused_ctx = (
            not cegb_enabled and vote_k == 0 and self._stream is None
            and self._feature_axis is None and forced_plan is None
            and not cc.extra_trees and bynode_cnt == 0
            and not meta_fused.has_bundles)
        # categorical features, monotone constraints and data-parallel
        # sharding all ride the fused arm now (the rounds grower's
        # seam-split kernel + pick_fused_best's cat merge) — but the
        # SERIAL grower only lifted monotone, so an explicit serial
        # growth keeps its own narrower gate (grower.py applies it)
        if self.config.tpu_tree_growth == "serial" \
                and (bool(meta_fused.is_categorical.any())
                     or (self._mesh is not None
                         and self._data_axis is not None)):
            fused_ctx = False
        want_fused = fused_ctx and (
            self.config.tpu_hist_method == "fused"
            or (self.config.tpu_hist_method == "auto" and on_accelerator()
                # the serial grower's fused arm streams ALL rows per
                # split (no leaf compaction); auto only elects fused
                # where the per-LEVEL rounds grower can run it
                and self.config.tpu_tree_growth != "serial"))
        if want_fused and on_accelerator() \
                and self.config.tpu_hist_method != "fused":
            # the one-time compile/parity probe protects the AUTO
            # election only; an EXPLICIT hist_method=fused is honored
            # (it fails loudly at compile if the backend truly cannot
            # lower the kernel) — the override the probe's warning
            # advertises
            from ..ops.fused import fused_kernel_verified
            want_fused = fused_kernel_verified()
        if self.config.tpu_hist_method == "fused" and not fused_ctx \
                and not getattr(self, "_fused_warned", False):
            self._fused_warned = True
            log_warning(
                "tpu_hist_method=fused does not apply to this "
                "configuration (EFB bundles, extra_trees, per-node "
                "column sampling, CEGB, forced splits, streaming, "
                "feature/voting sharding — or categorical/data-parallel "
                "under tpu_tree_growth=serial); falling back to the "
                "staged kernel family")
        # resolve hist_method="auto" by MEASURING the kernel variants on
        # the live accelerator at the training shape (reference: the
        # GetShareStates col-vs-row timed probe, dataset.cpp:589-684);
        # CPU resolves to scatter without probing.  Deferred while a
        # fused election is pending — the planner needs the literal
        # "auto" to elect, and re-resolves below if it declines.
        hist_method = self.config.tpu_hist_method
        if hist_method == "auto" and on_accelerator() and not want_fused \
                and self._stream is None:
            # (streamed boosters skip the probe: it would allocate
            # full-scale synthetic data, and the block fold resolves the
            # kernel family itself — data/stream.py)
            from ..ops.histogram import measured_best_method
            hist_method = measured_best_method(
                self.num_data, self._binned_shape[1], self.num_bins)
        # re-derive the grower config so reset_parameter() of tree
        # hyper-parameters (lambda_l1, min_data_in_leaf, ...) takes effect
        self.grower_cfg = GrowerConfig(
            num_leaves=self.config.num_leaves,
            max_depth=self.config.max_depth,
            hp=self.config.split_hyperparams(),
            hist_method=hist_method,
            num_bins=self.num_bins,
            learning_rate=self.config.learning_rate,
            compact=self.config.tpu_compact_hist,
            round_width=self.config.tpu_round_width,
            voting_top_k=vote_k,
            num_machines=nmach,
            bynode_feature_cnt=bynode_cnt,
            num_feature_shards=(int(self._mesh.shape[self._feature_axis])
                                if self._feature_axis is not None else 1),
            cegb_tradeoff=cc.cegb_tradeoff,
            cegb_penalty_split=cc.cegb_penalty_split,
            cegb_coupled=bool(coupled),
            cegb_lazy=bool(lazy),
            n_forced=0 if forced_plan is None else len(forced_plan[0]),
            forced_exact_parity=self.config.tpu_forced_split_parity,
            quant=quant_on,
            quant_bins=cc.num_grad_quant_bins,
            quant_renew=cc.quant_train_renew_leaf,
        )
        # HBM budget plan (ops/planner.py): model per-variant peak bytes
        # for THIS shape against the device limit and pick {tile_rows,
        # record-arena hoisting, psum narrowing} at trace time.  Planned
        # with PER-SHARD rows so the same verdict governs serial and
        # sharded training (the r5 lesson: an unplanned [n*F, 3] arena
        # requested 157.7 GB against 17.2 GB of HBM).
        from ..ops.planner import apply_plan
        shard_rows = self._n_pad
        if self._mesh is not None and self._data_axis is not None:
            shard_rows = self._n_pad // max(nmach, 1)
        if self._stream is not None:
            # streamed execution: the kernels only ever see one block of
            # rows at a time, so the HBM plan (tile_rows inside a block)
            # is made at block scale
            shard_rows = int(self._stream.store.block_rows)
        # the PADDED device column count, like the device array's leading
        # axis the plan used to read (self.binned may be None when
        # streaming): G_pad under sharded-EFB layout, _f_pad under plain
        # feature sharding, the group count otherwise
        if self._col_perm is not None:
            shard_feats = len(self._col_perm)
        elif self._feature_axis is not None:
            shard_feats = int(self._f_pad)
        else:
            shard_feats = int(self._binned_shape[1])
        if self._feature_axis is not None:
            # the sharded array keeps its GLOBAL shape; each device's
            # kernels see only its feature slice
            shard_feats //= max(int(self._mesh.shape[self._feature_axis]), 1)
        # pod-scale reduction schedule (hybrid ICI x DCN mesh,
        # parallel/collectives.py): the per-tier link model elects flat vs
        # hierarchical — and records voting's DCN payload shrink — at
        # trace time; pinned mode pins one tier-ordered f32 association
        # so flat == hierarchical extends to f32 model text
        self.collective_plan = None
        if nmach > 1 and self._data_axis is not None:
            from ..ops.planner import plan_collectives
            self.collective_plan = plan_collectives(
                features=shard_feats, num_bins=self.num_bins,
                rows_global=self._n_pad, quant=quant_on,
                quant_bins=cc.num_grad_quant_bins,
                num_slices=self._num_slices,
                devices_per_slice=nmach // max(self._num_slices, 1),
                voting_k=vote_k)
            self.grower_cfg = self.grower_cfg._replace(
                num_slices=self._num_slices,
                hier_reduce=self.collective_plan.hierarchical,
                pinned_reduce=self.collective_plan.pinned)
        if want_fused and self.grower_cfg.hist_method == "auto":
            # dry-run the fused VMEM election (plan_histograms emits no
            # trace event and mutates nothing) so a decline can fall
            # back to the measured kernel BEFORE the one real apply_plan
            # — one planner.plan event, modeled on the variant that
            # actually executes, and no hist_pack ratcheting through a
            # provisional plan
            from ..ops.planner import plan_histograms
            probe_plan = plan_histograms(
                rows=shard_rows, features=shard_feats,
                num_bins=self.grower_cfg.num_bins,
                num_leaves=self.grower_cfg.num_leaves,
                quant=self.grower_cfg.quant,
                quant_bins=self.grower_cfg.quant_bins, method="auto",
                round_width=self.grower_cfg.round_width,
                machines=max(nmach, 1), fused_ok=True)
            want_fused = probe_plan.fused
        if not want_fused and self.grower_cfg.hist_method == "auto" \
                and on_accelerator() and self._stream is None:
            # the deferred timed-probe resolution (fused declined or was
            # never in play after all)
            from ..ops.histogram import measured_best_method
            self.grower_cfg = self.grower_cfg._replace(
                hist_method=measured_best_method(
                    self.num_data, self._binned_shape[1], self.num_bins))
        self.grower_cfg, self.hist_plan = apply_plan(
            self.grower_cfg, shard_rows, shard_feats, fused_ok=want_fused)
        # unified-registry training gauges (the planner.plan trace event
        # itself is emitted inside apply_plan; the bench logs the measured
        # peak next to it — docs/OBSERVABILITY.md predicted-vs-measured)
        _obs_registry.gauge("train_hist_method").set(
            self.hist_plan.variant)   # resolved variant, never "auto"
        _obs_registry.gauge("train_tile_rows").set(self.hist_plan.tile_rows)
        _obs_registry.gauge("train_hist_predicted_peak_bytes").set(
            int(self.hist_plan.predicted_peak_bytes))
        _obs_registry.gauge("train_hbm_budget_bytes").set(
            int(self.hist_plan.budget_bytes))
        # shape-bucket ladder + autotune provenance: which rung the row
        # axis landed on and whether the variant came from measurements
        # (bench_diff gates election quality on these)
        _obs_registry.gauge("train_rows_bucketed").set(int(self._n_pad))
        _obs_registry.gauge("train_shape_buckets").set(
            int(getattr(self, "_shape_buckets", False)))
        _obs_registry.gauge("train_hist_elected_by").set(
            self.hist_plan.elected_by)
        if nmach > 1:
            from ..ops.histogram import hist_payload_bytes
            _obs_registry.gauge("train_psum_payload_bytes").set(
                hist_payload_bytes(
                    shard_feats, self.num_bins,
                    rows_global=self._n_pad,
                    quant_bins=(cc.num_grad_quant_bins if quant_on
                                else None)))
        if self.collective_plan is not None:
            # the two-hop ladder's per-tier payloads (docs/OBSERVABILITY
            # .md): what one histogram sync moves over ICI and over DCN
            # under the elected schedule — trace files show the matching
            # per-tier collective.reduce spans
            _obs_registry.gauge("train_ici_payload_bytes").set(
                int(self.collective_plan.ici_bytes))
            _obs_registry.gauge("train_dcn_payload_bytes").set(
                int(self.collective_plan.dcn_bytes))
            _obs_registry.gauge("train_num_slices").set(
                int(self.collective_plan.num_slices))
            _obs_registry.gauge("train_hier_reduce").set(
                int(self.collective_plan.hierarchical))
        # planner plan summaries ride every forensic bundle's fingerprint
        # (obs/flight.py) — the ring may have rolled past the planner
        # instants by the time a long run dies
        from ..obs.flight import global_flight as _flight
        _flight.set_context(
            hist_plan=self.hist_plan.summary(),
            collective_plan=(self.collective_plan.summary()
                             if self.collective_plan is not None else None))
        if not self.hist_plan.feasible:
            log_warning(
                "HBM planner: predicted peak "
                f"{self.hist_plan.predicted_peak_bytes / 1e9:.2f} GB "
                f"exceeds the {self.hist_plan.budget_bytes / 1e9:.2f} GB "
                f"budget even at tile_rows={self.hist_plan.tile_rows}; "
                "training may OOM (set LGBM_TPU_HBM_BYTES / "
                "LGBM_TPU_TILE_ROWS to override)")
        elif self.hist_plan.degraded:
            log_info(
                "HBM planner: untiled peak "
                f"{self.hist_plan.untiled_peak_bytes / 1e9:.2f} GB > "
                f"budget {self.hist_plan.budget_bytes / 1e9:.2f} GB "
                f"({self.hist_plan.limit_source}); streaming row tiles of "
                f"{self.hist_plan.tile_rows} (predicted peak "
                f"{self.hist_plan.predicted_peak_bytes / 1e9:.2f} GB)")
        # cross-tree CEGB device state (reference keeps it in the learner),
        # indexed by the grower's GLOBAL feature id (device slots under
        # feature sharding)
        F_inner = (self._f_pad if self._feature_axis is not None
                   else len(self.train_set.used_features))
        used0 = jnp.zeros((F_inner,), bool)
        rows0 = jnp.zeros((F_inner, self._n_pad) if lazy else (1, 1), bool)
        if lazy and self._mesh is not None and self._data_axis is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rows0 = jax.device_put(
                rows0, NamedSharding(self._mesh, P(None, self._data_axis)))
        self._cegb_state = (used0, rows0)
        # per-node randomness base key (extra_trees thresholds + by-node
        # column sampling); advanced by iteration in train_one_iter
        self._node_key_base = jax.random.PRNGKey(
            (self.config.extra_trees_seed * 2654435761
             ^ self.config.feature_fraction_seed) % (2 ** 31))
        cfg = self.grower_cfg
        obj = self.objective
        n = self.num_data
        n_pad = self._n_pad
        renew_pct = obj.renew_percentile if obj is not None else None
        weight_np = (np.asarray(self.train_set.metadata.weight, np.float32)
                     if self.train_set.metadata.weight is not None else None)
        label_np = (np.asarray(self.train_set.metadata.label, np.float32)
                    if obj is not None and renew_pct is not None else None)
        # label/weight ride through the (possibly sharded) step as explicit
        # row arrays; dummies when unused (DCE'd by XLA)
        label_a = jnp.asarray(self._pad_rows_np(
            label_np if label_np is not None else np.zeros(n, np.float32)))
        weight_a = jnp.asarray(self._pad_rows_np(
            weight_np if weight_np is not None else np.ones(n, np.float32)))
        use_renew = renew_pct is not None
        mc = self.config.monotone_constraints
        if mc:
            # align per-original-feature constraints with the used (binned)
            # feature columns — trivial features are dropped at binning
            mc_full = np.zeros(self.train_set.num_total_features, np.int32)
            mc_full[:len(mc)] = np.asarray(mc, np.int32)
            mc = mc_full[self.train_set.used_features]
            if self._feat_perm is not None:
                mc = np.concatenate([mc, np.zeros(1, np.int32)])[self._feat_perm]
            elif self._feature_axis is not None and self._f_pad > len(mc):
                mc = np.concatenate(
                    [mc, np.zeros(self._f_pad - len(mc), np.int32)])
            mc = jnp.asarray(mc)
        else:
            mc = None
        meta = self._meta_dist if self._meta_dist is not None else self.meta

        cegb_on = self._cegb_enabled
        coupled_pen = self._cegb_coupled_pen
        lazy_pen = self._cegb_lazy_pen
        # growth strategy: the batched-frontier grower (grower_rounds.py)
        # produces bit-identical trees with ~log2(num_leaves) while_loop
        # steps per tree instead of num_leaves-1; modes it does not cover
        # stay on the serial grower
        growth = self.config.tpu_tree_growth
        rounds_ok = (not cegb_on and cfg.voting_top_k == 0
                     and self._feature_axis is None
                     and forced_plan is None)
        if growth in ("rounds", "fast") and not rounds_ok:
            raise ValueError(
                f"tpu_tree_growth={growth} does not support CEGB, voting, "
                "feature-parallel or forced splits; use serial or auto")
        if growth not in ("auto", "serial", "rounds", "fast"):
            raise ValueError(f"unknown tpu_tree_growth {growth!r}")
        if growth == "fast":
            cfg = self.grower_cfg = cfg._replace(rounds_relaxed=True)
        # auto: rounds only on the accelerator.  Measured (round 4, 200k x
        # 28, 255 leaves): on TPU the serial grower is bound by ~6 ms of
        # per-while-step overhead (2.6 s/tree); on CPU ops are cheap but
        # the rounds body's full-frontier vmapped search is real compute
        # (rounds 19.8 s/tree vs serial 2.4 s/tree there).
        on_accel = on_accelerator()
        use_rounds = growth in ("rounds", "fast") or (
            growth == "auto" and rounds_ok and on_accel)
        # padded-device feature slot -> inner used-feature index (sharded
        # EFB layout); trees must come back in inner feature numbering
        feat_perm_j = (jnp.asarray(self._feat_perm, jnp.int32)
                       if self._feat_perm is not None else None)
        # hoisted to locals so iter_body never closes over `self` (RF sets
        # these BEFORE its second _build_jit_fns call, so build-time
        # capture is current; RF's program is cache-ineligible anyway)
        rf_const_init = getattr(self, "_rf_renew_const_init", False)
        init_scores_c = tuple(float(s) for s in self.init_scores)

        stoch_round = bool(cc.stochastic_rounding)
        quant_bins = int(cc.num_grad_quant_bins)

        def iter_body(binned, score, row_mask, grad, hess, fmask, lr, rng,
                      label_r, weight_r, cegb_used, cegb_rows,
                      axis_name, feature_axis_name,
                      mc_arr=None, meta_args=None):
            """grad/hess: [K, rows]; fmask: [K, F] col-sample masks; lr:
            traced scalar so a learning_rates schedule never recompiles;
            rng: per-iteration PRNG key for node-level randomness;
            cegb_used/cegb_rows: cross-tree CEGB state (pass-through dummies
            when CEGB is off); mc_arr/meta_args: monotone constraints and
            per-feature bin metadata as RUNTIME inputs (shared-program
            mode) — default to the closed-over constants otherwise.
            Returns (new_score, stacked trees, leaf_ids, cegb_used,
            cegb_rows, qscales [K, 2] — per-class quantization scales,
            zeros when quantized training is off)."""
            mc_in = mc if mc_arr is None else mc_arr
            trees = []
            leaf_ids = []
            qscale_rows = []
            new_score = score
            for k in range(K):
                # quantized-gradient mode: per-round discretization with
                # stochastic rounding seeded from the SAME per-round key
                # stream the node randomness rides (so chunked and
                # per-iteration training replay identical draws); under
                # data sharding each shard folds its axis index in so the
                # rounding noise is i.i.d. across shards while the scales
                # (pmax inside quantize_gradients) stay replicated
                if quant_on:
                    qkey = jax.random.fold_in(
                        jax.random.fold_in(rng, 0x51475442), k)
                    if axis_name is not None:
                        from ..parallel.collectives import axis_index_flat
                        qkey = jax.random.fold_in(
                            qkey, axis_index_flat(axis_name))
                    quant_vals = quantize_gradients(
                        grad[k], hess[k], row_mask, quant_bins, qkey,
                        stochastic=stoch_round, axis_name=axis_name)
                    qscale_rows.append(jnp.stack([quant_vals[2],
                                                  quant_vals[3]]))
                else:
                    quant_vals = None
                if cegb_on:
                    tree, leaf_id, (cegb_used, cegb_rows) = grow_tree(
                        binned, grad[k], hess[k], row_mask, meta, cfg,
                        feature_mask=fmask[k], monotone_constraints=mc_in,
                        axis_name=axis_name,
                        feature_axis_name=feature_axis_name,
                        rng_key=jax.random.fold_in(rng, k),
                        cegb_coupled_penalty=coupled_pen,
                        cegb_lazy_penalty=lazy_pen,
                        cegb_feat_used=cegb_used,
                        cegb_used_rows=cegb_rows,
                        forced_plan=forced_plan,
                        meta_arrays=meta_args)
                elif use_rounds:
                    from ..grower_rounds import grow_tree_rounds
                    tree, leaf_id = grow_tree_rounds(
                        binned, grad[k], hess[k], row_mask, meta, cfg,
                        feature_mask=fmask[k], monotone_constraints=mc_in,
                        axis_name=axis_name,
                        rng_key=jax.random.fold_in(rng, k),
                        meta_arrays=meta_args, quant_vals=quant_vals)
                else:
                    tree, leaf_id = grow_tree(binned, grad[k], hess[k],
                                              row_mask, meta, cfg,
                                              feature_mask=fmask[k],
                                              monotone_constraints=mc_in,
                                              axis_name=axis_name,
                                              feature_axis_name=feature_axis_name,
                                              rng_key=jax.random.fold_in(rng, k),
                                              forced_plan=forced_plan,
                                              meta_arrays=meta_args,
                                              quant_vals=quant_vals)
                if feat_perm_j is not None:
                    tree = tree._replace(
                        split_feature=feat_perm_j[tree.split_feature])
                if use_renew:
                    if rf_const_init:
                        # RF renews leaf outputs against the CONSTANT init
                        # score, not the running average (reference
                        # residual_getter, rf.hpp:130-135); captured as
                        # locals — closing over `self` here would pin the
                        # booster (and its device matrix) inside the
                        # module program cache
                        residual = label_r - jnp.float32(init_scores_c[k])
                    else:
                        residual = label_r - new_score[k]
                    w = row_mask * weight_r
                    pct = leaf_percentile(leaf_id, residual, w,
                                          cfg.num_leaves, float(renew_pct))
                    if axis_name is not None:
                        # reference: distributed RenewTreeOutput averages the
                        # per-machine renewed outputs over machines that have
                        # rows in the leaf (serial_tree_learner.cpp:654-663)
                        has = jax.ops.segment_sum(
                            (w > 0).astype(jnp.float32), leaf_id,
                            num_segments=cfg.num_leaves) > 0
                        cnt = jax.lax.psum(has.astype(jnp.float32), axis_name)
                        pct = jax.lax.psum(jnp.where(has, pct, 0.0), axis_name)
                        pct = pct / jnp.maximum(cnt, 1.0)
                    active = jnp.arange(cfg.num_leaves) < tree.num_leaves
                    tree = tree._replace(
                        leaf_value=jnp.where(active, pct, tree.leaf_value))
                tree = tree._replace(
                    leaf_value=tree.leaf_value * lr,
                    internal_value=tree.internal_value * lr,
                )
                new_score = new_score.at[k].add(
                    take_from_table(tree.leaf_value, leaf_id))
                trees.append(tree)
                leaf_ids.append(leaf_id)
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)
            qscales = (jnp.stack(qscale_rows) if quant_on
                       else jnp.zeros((K, 2), jnp.float32))
            return (new_score, stacked, jnp.stack(leaf_ids), cegb_used,
                    cegb_rows, qscales)

        if self._stream is not None:
            # streamed executor (lightgbm_tpu/data/stream.py): the
            # resident per-iteration/macro programs close over a resident
            # device matrix this mode does not have — never built.  The
            # engine's chunk scheduler sees chunk_supported() False and
            # trains per-iteration; _train_one_iter_inner routes each
            # step through the StreamGrower instead of _iter_fn.
            def one_iter(*_a, **_k):
                raise RuntimeError(
                    "streamed (out-of-core) booster has no resident "
                    "iteration program; training routes through "
                    "data/stream.py")
            self._iter_fn = one_iter
            macro_core = None
        elif self._mesh is None:
            # binned rides as an explicit jit argument: a closed-over
            # device array would be captured as a program CONSTANT, and at
            # HIGGS scale (11M x 28 = 308 MB) constant-embedding bloats
            # lowering/compile.  Per-feature bin metadata, labels/weights
            # and monotone constraints ride as runtime args too, so ONE
            # traced+compiled program serves every structurally-identical
            # booster (cv folds, repeated sklearn fits) via the module
            # program cache below.
            mr = meta.resolved()
            meta_args = meta.as_runtime_arrays()
            mc_j = mc  # device array or None (None -> different pytree)
            cache_key = None
            # RF's const-init renewal reads self.init_scores at TRACE time
            # (set after build) — its program is booster-specific
            if (not cegb_on and forced_plan is None
                    and not (use_renew and rf_const_init)):
                # the trace-time env gates select program VARIANTS (the
                # compile-hang ladders flip them between attempts in one
                # process) — they must key the cache or a variant switch
                # would silently reuse the previous variant's program
                env_gates = tuple(
                    os.environ.get(k, "") for k in
                    ("LGBM_TPU_SEGHIST", "LGBM_TPU_SMALL_ROUNDS",
                     "LGBM_TPU_PACK", "LGBM_TPU_TABLE_MATMUL",
                     "LGBM_TPU_ROUTER", "LGBM_TPU_FUSED"))
                cache_key = (
                    "one_iter", K, n_pad, self.binned.shape,
                    str(self.binned.dtype), cfg, use_rounds, use_renew,
                    renew_pct, obj is None, mc is None,
                    mr.has_bundles, int(mr.max_group_bin),
                    len(mr.num_bin), int(mr.num_groups),
                    bool(mr.is_categorical.any()), env_gates,
                    stoch_round)
            shared = _shared_program(cache_key)
            if shared is None:
                def one_iter_full(binned, score, row_mask, grad, hess,
                                  fmask, lr, rng, cegb_used, cegb_rows,
                                  label_r, weight_r, mc_arr, meta_a):
                    return iter_body(binned, score, row_mask, grad, hess,
                                     fmask, lr, rng, label_r, weight_r,
                                     cegb_used, cegb_rows, None, None,
                                     mc_arr=mc_arr, meta_args=meta_a)
                shared = jax.jit(one_iter_full, donate_argnums=(1,))
                _shared_program(cache_key, shared)

            def one_iter(binned, score, row_mask, grad, hess, fmask, lr,
                         rng, cegb_used, cegb_rows, _fn=shared):
                return _fn(binned, score, row_mask, grad, hess, fmask,
                           lr, rng, cegb_used, cegb_rows,
                           label_a, weight_a, mc_j, meta_args)
            self._iter_fn = one_iter

            def macro_core(binned, score, row_mask, grad, hess, fmask, lr,
                           rng, cu, cr, label_r, weight_r):
                return iter_body(binned, score, row_mask, grad, hess,
                                 fmask, lr, rng, label_r, weight_r, cu, cr,
                                 None, None, mc_arr=mc_j,
                                 meta_args=meta_args)
        else:
            from jax.sharding import PartitionSpec as P
            ax_d, ax_f = self._data_axis, self._feature_axis

            def core(binned, score, row_mask, grad, hess, fmask, lr, rng,
                     label_r, weight_r, cegb_used, cegb_rows):
                return iter_body(binned, score, row_mask, grad, hess, fmask,
                                 lr, rng, label_r, weight_r,
                                 cegb_used, cegb_rows, ax_d, ax_f)

            row = P(ax_d)          # replicated when ax_d is None
            krow = P(None, ax_d)
            # lazy-mode used-rows bitmap is sharded with the rows
            rows_spec = krow if (cegb_on and cfg.cegb_lazy) else P()
            from ..parallel.learners import shard_map_compat
            sharded = shard_map_compat(
                core, mesh=self._mesh,
                in_specs=(P(ax_f, ax_d), krow, row, krow, krow, P(), P(),
                          P(), row, row, P(), rows_spec),
                out_specs=(krow, P(), krow, P(), rows_spec, P()),
                check_vma=False)

            def one_iter(binned, score, row_mask, grad, hess, fmask, lr,
                         rng, cegb_used, cegb_rows):
                return sharded(binned, score, row_mask, grad, hess,
                               fmask, lr, rng, label_a, weight_a,
                               cegb_used, cegb_rows)
            self._iter_fn = jax.jit(one_iter, donate_argnums=(1,))

            def macro_core(binned, score, row_mask, grad, hess, fmask, lr,
                           rng, cu, cr, label_r, weight_r):
                return sharded(binned, score, row_mask, grad, hess,
                               fmask, lr, rng, label_r, weight_r, cu, cr)
        if not hasattr(self, "_feature_rng"):  # survive jit-fn rebuilds
            self._feature_rng = np.random.RandomState(
                self.config.feature_fraction_seed)
        self._ones_fmask = None

        perm_j = (jnp.asarray(self._row_perm)
                  if self._row_perm is not None else None)
        inv_perm_j = (jnp.asarray(self._inv_perm)
                      if self._inv_perm is not None else None)

        def gradients_fn(score):
            if obj is None:
                raise RuntimeError("no objective: gradients must be provided")
            if perm_j is not None:
                # query-aligned layout: objective works in ORIGINAL row order
                s = score[:, inv_perm_j]
            else:
                s = score if n_pad == n else score[:, :n]
            s = s if K > 1 else s[0]
            g, h = obj.get_gradients(s)
            g = g.reshape(K, n)
            h = h.reshape(K, n)
            if perm_j is not None:
                zcol = jnp.zeros((K, 1), g.dtype)
                g = jnp.concatenate([g, zcol], axis=1)[:, perm_j]
                h = jnp.concatenate([h, zcol], axis=1)[:, perm_j]
            elif n_pad > n:
                g = jnp.pad(g, ((0, 0), (0, n_pad - n)))
                h = jnp.pad(h, ((0, 0), (0, n_pad - n)))
            return g, h

        self._gradients_fn = jax.jit(gradients_fn)

        # fused macro-step context (boosting/macro.py): the SAME iter_body
        # (serial or shard_map'd) and the same gradient closure, re-traced
        # inside a lax.scan chunk program; rebuilt alongside the
        # per-iteration programs so reset_parameter invalidates both
        self._macro_core = macro_core
        self._macro_grad = gradients_fn
        self._macro_ctx = {"label": label_a, "weight": weight_a}
        self._macro_chunk_jit = None
        self._macro_valid_jit = None
        self._has_forced_plan = forced_plan is not None
        if self._stream is not None:
            # (re)built with the programs so reset_parameter rebuilds
            # refresh the streamed grower's jitted pieces too
            from ..data.stream import StreamGrower
            self._stream.grower = StreamGrower(self)

        # prediction-side programs share across boosters the same way:
        # bin metadata rides as runtime args, keyed on structure only
        mrp = self.meta.resolved()
        pred_meta_args = self.meta.as_runtime_arrays()
        pred_key_tail = (len(mrp.num_bin), int(mrp.num_groups),
                         mrp.has_bundles, int(mrp.max_group_bin))

        vkey = ("valid_update", K) + pred_key_tail
        vfn = _shared_program(vkey)
        if vfn is None:
            def valid_update_full(vscore, stacked_trees, binned, meta_a):
                for k in range(K):
                    tree_k = jax.tree_util.tree_map(lambda x: x[k],
                                                    stacked_trees)
                    vscore = vscore.at[k].add(
                        predict_tree_binned(tree_k, binned, None,
                                            meta_arrays=meta_a))
                return vscore
            vfn = _shared_program(vkey, jax.jit(valid_update_full,
                                                donate_argnums=(0,)))
        self._valid_update = (
            lambda vscore, trees, binned, _f=vfn:
            _f(vscore, trees, binned, pred_meta_args))

        # the TRAIN device matrix may have permuted group columns (sharded
        # EFB layout); history-tree traversal over it needs a meta whose
        # feat_group points at the permuted column positions
        meta_train = self.meta
        if self._col_perm is not None:
            import dataclasses
            mr2 = self.meta.resolved()
            inv_col = np.zeros(mr2.num_groups, np.int32)
            valid_cols = self._col_perm < mr2.num_groups
            inv_col[self._col_perm[valid_cols]] = \
                np.nonzero(valid_cols)[0].astype(np.int32)
            meta_train = dataclasses.replace(
                mr2, feat_group=inv_col[np.asarray(mr2.feat_group)],
                num_groups=len(self._col_perm))

        tkey = ("tree_pred",) + pred_key_tail
        tfn = _shared_program(tkey)
        if tfn is None:
            tfn = _shared_program(tkey, jax.jit(
                lambda tree, binned, meta_a:
                predict_tree_binned(tree, binned, None,
                                    meta_arrays=meta_a)))
        self._tree_pred_jit = (lambda tree, binned, _f=tfn:
                               _f(tree, binned, pred_meta_args))
        if self._col_perm is not None:
            self._tree_pred_train_jit = jax.jit(
                lambda tree, binned: predict_tree_binned(tree, binned,
                                                         meta_train))
        else:
            self._tree_pred_train_jit = self._tree_pred_jit

    # --------------------------------------------------------------- training

    def _bagging_mask(self, it: int) -> jax.Array:
        """reference: GBDT::Bagging (gbdt.cpp:163-244) as a weight mask."""
        c = self.config
        n = self.num_data
        if self.boosting_type == "goss":
            raise RuntimeError("GOSS overrides _bagging_mask")
        need = (c.bagging_freq > 0 and c.bagging_fraction < 1.0)
        need_posneg = (c.pos_bagging_fraction < 1.0 or c.neg_bagging_fraction < 1.0)
        if not (need or need_posneg):
            return self._row_valid
        if it % max(c.bagging_freq, 1) != 0 and self._cur_mask is not None:
            return self._cur_mask
        if need_posneg:
            lbl = np.asarray(self.train_set.metadata.label) > 0
            u = self._rng.rand(n)
            keep = np.where(lbl, u < c.pos_bagging_fraction, u < c.neg_bagging_fraction)
        else:
            # exact count without replacement (matches reference semantics)
            cnt = int(n * c.bagging_fraction)
            idx = self._rng.choice(n, size=cnt, replace=False)
            keep = np.zeros(n, bool)
            keep[idx] = True
        self._cur_mask = jnp.asarray(
            self._pad_rows_np(keep.astype(np.float32)))
        return self._cur_mask

    _cur_mask = None

    def _feature_masks(self) -> jax.Array:
        """Per-tree column sampling (reference: ColSampler by-tree,
        src/treelearner/col_sampler.hpp:19)."""
        K = self.num_tree_per_iteration
        F = len(self.train_set.used_features)   # features, not EFB columns
        Fp = max(self._f_pad, F)                # padded for feature sharding
        frac = self.config.feature_fraction

        def place(inner_masks):   # [K, F] inner order -> [K, Fp] device order
            if self._feat_perm is not None:
                ext = np.concatenate(
                    [inner_masks, np.zeros((K, 1), np.float32)], axis=1)
                return ext[:, self._feat_perm]
            out = np.zeros((K, Fp), np.float32)
            out[:, :F] = inner_masks
            return out

        if frac >= 1.0:
            if self._ones_fmask is None:
                self._ones_fmask = jnp.asarray(
                    place(np.ones((K, F), np.float32)))
            return self._ones_fmask
        cnt = max(1, int(round(F * frac)))
        masks = np.zeros((K, F), np.float32)
        for k in range(K):
            masks[k, self._feature_rng.choice(F, size=cnt, replace=False)] = 1.0
        return jnp.asarray(place(masks))

    def _boost(self, score) -> Tuple[jax.Array, jax.Array]:
        return self._gradients_fn(score)

    def boost_from_average(self) -> None:
        """reference: GBDT::BoostFromAverage (gbdt.cpp:313)."""
        if self.iter > 0 or self.objective is None or self._init_score_added:
            return
        if not self.config.boost_from_average:
            return
        # mark done so a second call in the same iteration (e.g. from a
        # boosting subclass) cannot double-add the init score
        self._init_score_added = True
        K = self.num_tree_per_iteration
        for k in range(K):
            s = self.objective.boost_from_score(k)
            if abs(s) > K_EPSILON:
                self.init_scores[k] = s
                self.train_score = self.train_score.at[k].add(s)
                for i in range(len(self.valid_scores)):
                    self.valid_scores[i] = self.valid_scores[i].at[k].add(s)
                log_info(f"Start training from score {s:.6f}")

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration; returns True if training should STOP
        (no more splittable leaves).  reference: GBDT::TrainOneIter."""
        from ..utils.timer import global_timer
        with global_timer.section("GBDT::TrainOneIter"):
            return self._train_one_iter_inner(grad, hess)

    def _chunk_single(self) -> Optional[bool]:
        """Run ONE iteration through the fused chunk program (c=1) when
        the macro path is enabled; None = caller takes the legacy path.

        Routing per-iteration training of supported modes through the
        same runtime-trip-count loop body as multi-iteration chunks makes
        training invariant to the chunk decomposition (see
        macro.build_chunk_program) — the invariant behind byte-identical
        chunked vs. per-iteration models and chunk-agnostic
        checkpoint/resume replay.  LGBM_TPU_CHUNK=0 restores the legacy
        per-iteration program for bisection."""
        from .macro import chunk_cap, run_chunk
        if not self.chunk_supported() or chunk_cap() <= 0:
            return None
        return run_chunk(self, 1, None)

    def _train_one_iter_inner(self, grad, hess) -> bool:
        from ..utils.timer import global_timer
        if grad is None:
            single = self._chunk_single()
            if single is not None:
                return single
        K = self.num_tree_per_iteration
        n = self.num_data
        self.boost_from_average()
        if grad is None:
            with global_timer.section("GBDT::Boosting(gradients)"):
                grad, hess = self._boost(self.train_score)
        else:
            grad = np.asarray(grad, np.float32).reshape(K, n)
            hess = np.asarray(hess, np.float32).reshape(K, n)
            if self._n_pad > n:
                grad = np.stack([self._pad_rows_np(r) for r in grad])
                hess = np.stack([self._pad_rows_np(r) for r in hess])
            grad, hess = jnp.asarray(grad), jnp.asarray(hess)
        with global_timer.section("GBDT::Bagging"):
            mask = self._bagging_mask(self.iter)

        if self._stream is not None:
            return self._stream_step(grad, hess, mask)
        with global_timer.section("TreeLearner::Train(dispatch)"), \
                _span("gbdt.dispatch", iteration=self.iter):
            (self.train_score, stacked, leaf_ids, cu, cr,
             self._quant_scales) = self._iter_fn(
                self.binned, self.train_score, mask, grad, hess,
                self._feature_masks(), jnp.float32(self.shrinkage_rate),
                self._node_key(), *self._cegb_state)
            self._cegb_state = (cu, cr)
        return self._finish_iter(stacked)

    def _node_key(self):
        return jax.random.fold_in(self._node_key_base, self.iter)

    def _stream_step(self, grad, hess, mask) -> bool:
        """One boosting iteration through the out-of-core streamed
        executor (data/stream.py) — the streamed twin of the _iter_fn
        dispatch.  Identical RNG/mask draw order, identical bookkeeping
        via _finish_iter."""
        from ..utils.timer import global_timer
        with global_timer.section("TreeLearner::Train(dispatch)"), \
                _span("stream.iteration", iteration=self.iter):
            (self.train_score, stacked,
             self._quant_scales) = self._stream.grower.run_iteration(
                grad, hess, mask, jnp.float32(self.shrinkage_rate),
                self._node_key(), self._feature_masks())
        return self._finish_iter(stacked)

    # ------------------------------------------------------ fused macro-steps

    def chunk_supported(self) -> bool:
        """True when the fused multi-iteration executor (boosting/macro.py)
        can train this booster.  Paths with per-iteration host logic —
        DART drop/rollback, CEGB penalties, forced splits, custom fobj
        (objective None) — report False and the engine's chunk scheduler
        falls back to c=1 per-iteration training."""
        return (type(self)._macro_ok
                and self._stream is None     # the macro scan cannot
                # device_put host blocks mid-loop; streamed training is
                # per-iteration (and hence trivially chunk-invariant)
                and not self._cegb_enabled
                and not self._has_forced_plan
                and self.objective is not None)

    def train_chunk(self, c: int, lrs=None) -> bool:
        """Train ``c`` boosting iterations in ONE fused, score-donating
        device program (lax.scan over the same iter_body).  Bit-identical
        to ``c`` train_one_iter calls; returns True if training should
        stop (no more splittable leaves)."""
        from ..utils.timer import global_timer
        from .macro import run_chunk
        with global_timer.section("GBDT::TrainChunk"):
            return run_chunk(self, c, lrs)

    def _macro_goss_inputs(self, c: int, it0: int, lrs):
        """Per-iteration GOSS subkeys + sampling flags for a chunk; the
        base class feeds inert dummies (DCE'd by XLA)."""
        key = self._goss_rng_key
        return (jnp.zeros((c,) + key.shape, key.dtype),
                jnp.zeros((c,), bool))

    def _macro_const_grads(self):
        """RF overrides with its constant gradients; dummies otherwise."""
        z = jnp.zeros((1, 1), jnp.float32)
        return z, z

    def _chunk_valid_update(self, vscore, stacked_seq, binned, its):
        if self._macro_valid_jit is None:
            from .macro import build_chunk_valid
            self._macro_valid_jit = build_chunk_valid(self)
        return self._macro_valid_jit(vscore, stacked_seq, binned, its,
                                     np.int32(its.shape[0]))

    def _finish_chunk(self, stacked_seq, c: int, shrinks, it0: int) -> bool:
        """Chunk counterpart of _finish_iter: per-iteration bookkeeping
        from ONE stacked ``[c, ...]`` device tree bundle.  Same timer tag
        as _finish_iter — it is the same role, amortized over c."""
        from ..utils.timer import global_timer
        with global_timer.section("GBDT::FinishIter(host trees)"), \
                _span("macro.host_fetch", c=c, it0=it0):
            return self._finish_chunk_inner(stacked_seq, c, shrinks, it0)

    def _chunk_slice(self, stacked_seq, j: int):
        return jax.tree_util.tree_map(lambda x: x[j], stacked_seq)

    def _chunk_bias_fold(self, st, abs_it: int):
        """Fold the iter-0 init bias into a history slice (mirrors
        _finish_iter's handling of the saved device trees)."""
        if abs_it == 0 and any(abs(s) > K_EPSILON for s in self.init_scores):
            bias = jnp.asarray(self.init_scores, jnp.float32)[:, None]
            st = st._replace(leaf_value=st.leaf_value + bias)
        return st

    def _finish_chunk_inner(self, stacked_seq, c, shrinks, it0) -> bool:
        K = self.num_tree_per_iteration
        if self._defer_enabled():
            # bank per-iteration device slices; host conversion stays one
            # bulk transfer at _drain_pending, stop detection moves there
            # exactly as on the per-iteration deferred path
            for j in range(c):
                self._pending.append(
                    (it0 + j, shrinks[j], self._chunk_slice(stacked_seq, j)))
            if self._history_mode == "all":
                for j in range(c):
                    self.tree_history.append(self._chunk_bias_fold(
                        self._chunk_slice(stacked_seq, j), it0 + j))
            else:
                self.tree_history = [self._chunk_bias_fold(
                    self._chunk_slice(stacked_seq, c - 1), it0 + c - 1)]
            self.models_version += 1
            its = jnp.arange(it0, it0 + c, dtype=jnp.int32)
            for i in range(len(self.valid_scores)):
                self.valid_scores[i] = self._chunk_valid_update(
                    self.valid_scores[i], stacked_seq,
                    self.valid_binned[i], its)
            self.iter += c
            return False
        # eager path: ONE bulk device->host transfer for the whole chunk,
        # then the per-iteration host bookkeeping of _finish_iter_inner
        bh = jax.device_get(stacked_seq)
        stopped = False
        kept = 0
        for j in range(c):
            abs_it = it0 + j
            new_models, any_split = [], False
            for k in range(K):
                tree_k = jax.tree_util.tree_map(
                    lambda x: np.asarray(x[j][k]), bh)
                ht = tree_to_host(tree_k, self.train_set, shrinks[j])
                if ht.num_leaves > 1:
                    any_split = True
                if abs_it == 0 and abs(self.init_scores[k]) > K_EPSILON:
                    ht.add_bias(self.init_scores[k])
                new_models.append(ht)
            if not any_split:
                if abs_it == 0 and not self.models:
                    for k, ht in enumerate(new_models):
                        ht.leaf_value[:1] = self.init_scores[k]
                    self.models.extend(new_models)
                stopped = True
                break
            self.models.extend(new_models)
            for k in range(K):
                self.history_scale[len(self.models) - K + k] = 1.0
            kept = j + 1
        self.models_version += 1
        if stopped:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
        if kept:
            if self._history_mode == "all":
                for j in range(kept):
                    self.tree_history.append(self._chunk_bias_fold(
                        self._chunk_slice(stacked_seq, j), it0 + j))
            else:
                self.tree_history = [self._chunk_bias_fold(
                    self._chunk_slice(stacked_seq, kept - 1),
                    it0 + kept - 1)]
            seq_kept = (stacked_seq if kept == c else
                        jax.tree_util.tree_map(lambda x: x[:kept],
                                               stacked_seq))
            its = jnp.arange(it0, it0 + kept, dtype=jnp.int32)
            for i in range(len(self.valid_scores)):
                self.valid_scores[i] = self._chunk_valid_update(
                    self.valid_scores[i], seq_kept, self.valid_binned[i],
                    its)
        self.iter = it0 + kept
        return stopped

    @property
    def models(self) -> List[HostTree]:
        """Host trees; drains any deferred device trees first.  Returns the
        live list (callers mutate it in place: rollback, DART rescale)."""
        self._drain_pending()
        return self._models

    @models.setter
    def models(self, value) -> None:
        self._pending = []
        self._models = value

    def _defer_enabled(self) -> bool:
        if self._defer_host is None:
            env = os.environ.get("LGBT_DEFER_HOST_TREES")
            if env is not None:
                self._defer_host = env == "1" and type(self)._defer_host_ok
            else:
                # the tunneled accelerator pays ~70 ms per D2H copy; local
                # CPU copies are free and the eager path's per-iteration
                # stop check is reference-exact there
                self._defer_host = (type(self)._defer_host_ok
                                    and on_accelerator())
        return self._defer_host

    def _drain_pending(self) -> None:
        """Materialize deferred device trees as HostTrees in ONE bulk
        device->host transfer (per tree field, not per tree).

        reference semantics preserved at drain time: iteration-0 init-score
        bias (GBDT::Train, gbdt.cpp:387-405 AsConstantTree) and
        stop-on-no-splittable-leaves, which truncates the model at the
        first all-stump iteration.  Deviation (documented): iterations that
        ran AFTER such a stop already added their root-Newton-step outputs
        to train_score/valid_scores before the drain noticed; the eager
        path stops the loop instead.  Only degenerate configs (nothing
        splittable) hit this, and only on the deferred/accelerator path.
        """
        if not self._pending:
            return
        with _span("gbdt.drain_pending", pending=len(self._pending)):
            self._drain_pending_inner()

    def _drain_pending_inner(self) -> None:
        K = self.num_tree_per_iteration
        pend = self._pending
        self._pending = []
        stackeds = [st for (_it, _sr, st) in pend]
        if len(stackeds) == 1:
            hosts = [jax.device_get(stackeds[0])]
        else:
            bulk = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                          *stackeds)
            bh = jax.device_get(bulk)
            hosts = [jax.tree_util.tree_map(lambda x: x[t], bh)
                     for t in range(len(stackeds))]
        stopped_at = None
        for (abs_it, shrink, _), th in zip(pend, hosts):
            new_models, any_split = [], False
            for k in range(K):
                tree_k = jax.tree_util.tree_map(lambda x: np.asarray(x[k]),
                                                th)
                ht = tree_to_host(tree_k, self.train_set, shrink)
                if ht.num_leaves > 1:
                    any_split = True
                if abs_it == 0 and abs(self.init_scores[k]) > K_EPSILON:
                    ht.add_bias(self.init_scores[k])
                new_models.append(ht)
            if not any_split:
                if abs_it == 0 and not self._models:
                    for k, ht in enumerate(new_models):
                        ht.leaf_value[:1] = self.init_scores[k]
                    self._models.extend(new_models)
                stopped_at = abs_it
                break
            self._models.extend(new_models)
            for k in range(K):
                self.history_scale[len(self._models) - K + k] = 1.0
        self.models_version += 1
        if stopped_at is not None:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            # rewind bookkeeping to the stop point; the dropped tail's
            # history entries go with it
            dropped = self.iter - stopped_at
            if self._history_mode == "all" and dropped > 0:
                del self.tree_history[len(self.tree_history) - dropped:]
            self.iter = stopped_at

    def _finish_iter(self, stacked) -> bool:
        """Post-step bookkeeping shared by GBDT/GOSS/DART/RF: host copies of
        the (tiny) tree arrays, first-iteration bias folding, valid-score
        updates.  Returns True when training should stop."""
        from ..utils.timer import global_timer
        with global_timer.section("GBDT::FinishIter(host trees)"), \
                _span("gbdt.finish_iter", iteration=self.iter):
            return self._finish_iter_inner(stacked)

    def _finish_iter_inner(self, stacked) -> bool:
        K = self.num_tree_per_iteration
        if self._defer_enabled():
            # bank the device trees; host conversion happens in bulk at
            # _drain_pending.  Never stops eagerly — stop detection moves
            # to the drain.
            # shrinkage is recorded NOW: a reset_parameter learning-rate
            # schedule changes self.shrinkage_rate between bank and drain
            self._pending.append((self.iter, self.shrinkage_rate, stacked))
            st = stacked
            if self.iter == 0 and any(abs(s) > K_EPSILON
                                      for s in self.init_scores):
                bias = jnp.asarray(self.init_scores, jnp.float32)[:, None]
                st = st._replace(leaf_value=st.leaf_value + bias)
            if self._history_mode == "all":
                self.tree_history.append(st)
            else:
                self.tree_history = [st]
            self.models_version += 1
            for i in range(len(self.valid_scores)):
                self.valid_scores[i] = self._valid_update(
                    self.valid_scores[i], stacked, self.valid_binned[i])
            self.iter += 1
            return False
        new_models = []
        should_continue = False
        for k in range(K):
            tree_k = jax.tree_util.tree_map(lambda x: np.asarray(x[k]), stacked)
            ht = tree_to_host(tree_k, self.train_set, self.shrinkage_rate)
            if ht.num_leaves > 1:
                should_continue = True
            if self.iter == 0 and abs(self.init_scores[k]) > K_EPSILON:
                ht.add_bias(self.init_scores[k])
            new_models.append(ht)
        if not should_continue:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if self.iter == 0 and not self.models:
                # reference: first-iteration stumps are kept as CONSTANT
                # trees carrying the boost-from-average output, so the
                # model predicts the baseline (gbdt.cpp:387-405
                # AsConstantTree); later-iteration stumps are dropped
                for k, ht in enumerate(new_models):
                    ht.leaf_value[:1] = self.init_scores[k]
                self.models.extend(new_models)
                self.models_version += 1
            return True
        self.models.extend(new_models)
        self.models_version += 1

        # keep the device trees for drop/rollback re-evaluation; fold the
        # iter-0 init bias into the saved leaf values so a saved tree's
        # device output equals its HostTree counterpart's (add_bias above)
        st = stacked
        if self.iter == 0 and any(abs(s) > K_EPSILON for s in self.init_scores):
            bias = jnp.asarray(self.init_scores, jnp.float32)[:, None]
            st = st._replace(leaf_value=st.leaf_value + bias)
        if self._history_mode == "all":
            self.tree_history.append(st)
        else:
            self.tree_history = [st]
        for k in range(K):
            self.history_scale[len(self.models) - K + k] = 1.0

        for i in range(len(self.valid_scores)):
            self.valid_scores[i] = self._valid_update(
                self.valid_scores[i], stacked, self.valid_binned[i])
        self.iter += 1
        return False

    # ------------------------------------------------------------ checkpoint

    def capture_state(self) -> dict:
        """Pickle-able snapshot of EVERY mutable training-loop state:
        host trees, device score arrays, all RNG streams, bagging mask,
        device tree history.  ``restore_state`` of this dict into a
        structurally-identical booster makes the continued run replay the
        same random decisions and accumulate the same float32 sums — the
        contract behind resilience/checkpoint.py's bit-identical resume.

        Reading ``self.models`` drains any deferred device trees first,
        so the deferred-host accelerator path checkpoints correctly (at
        the cost of one bulk D2H per checkpoint)."""
        import copy as _copy
        models = [_copy.deepcopy(m) for m in self.models]
        return {
            "boosting_type": self.boosting_type,
            "iter": self.iter,
            "num_init_iteration": self.num_init_iteration,
            # the row layout this state was captured under: an ELASTIC
            # resume restores into a DIFFERENT mesh (fewer shards after a
            # slice loss — docs/RESILIENCE.md), and restore_state re-tiles
            # every per-row array through the original layout
            "n_pad": int(self._n_pad),
            "num_data": int(self.num_data),
            "row_perm": (np.asarray(self._row_perm)
                         if self._row_perm is not None else None),
            "models": models,
            "train_score": np.asarray(jax.device_get(self.train_score)),
            "valid_scores": [np.asarray(jax.device_get(v))
                             for v in self.valid_scores],
            "init_scores": list(self.init_scores),
            "init_score_added": self._init_score_added,
            "shrinkage_rate": float(self.shrinkage_rate),
            "bagging_rng": self._rng.get_state(),
            "goss_rng_key": np.asarray(jax.device_get(self._goss_rng_key)),
            "feature_rng": self._feature_rng.get_state(),
            "cur_mask": (np.asarray(jax.device_get(self._cur_mask))
                         if self._cur_mask is not None else None),
            "history_mode": self._history_mode,
            "history_scale": dict(self.history_scale),
            "tree_history": [
                jax.tree_util.tree_map(lambda x: np.asarray(
                    jax.device_get(x)), st) for st in self.tree_history],
            # cross-tree CEGB device state (per-feature used set + lazy
            # row coverage): already-charged penalties must not be charged
            # again after resume
            "cegb_state": tuple(np.asarray(jax.device_get(a))
                                for a in self._cegb_state),
            # last round's gradient-quantization scales (use_quantized_grad
            # telemetry; rides the checkpoint so a resumed run reports the
            # same payload accounting it left off with)
            "quant_scales": (np.asarray(jax.device_get(self._quant_scales))
                             if self._quant_scales is not None else None),
        }

    def restore_state(self, st: dict) -> None:
        """Inverse of ``capture_state`` into a freshly-constructed booster
        of the SAME config/dataset (engine.py builds it before calling)."""
        import copy as _copy
        if st.get("boosting_type") != self.boosting_type:
            raise ValueError(
                f"checkpoint was boosting={st.get('boosting_type')!r}, this "
                f"run is boosting={self.boosting_type!r}")
        if len(st["valid_scores"]) != len(self.valid_scores):
            raise ValueError(
                f"checkpoint has {len(st['valid_scores'])} valid sets, this "
                f"run has {len(self.valid_scores)}")
        self.iter = int(st["iter"])
        self.num_init_iteration = int(st["num_init_iteration"])
        self._pending = []
        self._models = [_copy.deepcopy(m) for m in st["models"]]
        # elastic resume (docs/RESILIENCE.md): the bundle may have been
        # captured under a DIFFERENT row layout (more shards before a
        # slice loss -> larger n_pad / different query permutation).
        # Re-tile every per-row array through the ORIGINAL row order into
        # this booster's layout; padding rows carry zeros either way, so
        # re-tiling is exact — the resumed sums start from the same f32
        # values the old world held
        if "row_perm" not in st:
            # legacy bundle (pre pod-scale): the layout keys were never
            # captured, and the pre-elastic contract was same-world
            # restore — assign directly, NEVER guess a re-tile (treating
            # "absent" as "unpermuted" would scramble a query-sharded
            # ranking resume)
            old_np, old_perm, same_layout = self._n_pad, None, True
        else:
            old_np = st.get("n_pad")
            if old_np is None:
                old_np = int(np.asarray(st["train_score"]).shape[-1])
            old_perm = st.get("row_perm")
            old_perm = np.asarray(old_perm) if old_perm is not None else None
            same_layout = (int(old_np) == self._n_pad
                           and (old_perm is None) == (self._row_perm is None)
                           and (old_perm is None
                                or np.array_equal(old_perm, self._row_perm)))

        def retile(a):
            """Old padded row layout -> this booster's, trailing axis."""
            if a is None or same_layout:
                return a
            a = np.asarray(a)
            n = self.num_data
            if old_perm is not None:
                valid = old_perm < n
                unpad = np.zeros(a.shape[:-1] + (n,), a.dtype)
                unpad[..., old_perm[valid]] = a[..., np.nonzero(valid)[0]]
            else:
                unpad = a[..., :n]
            if self._row_perm is not None:
                ext = np.concatenate(
                    [unpad, np.zeros(a.shape[:-1] + (1,), a.dtype)],
                    axis=-1)
                return ext[..., self._row_perm]
            pad = self._n_pad - n
            if pad:
                return np.concatenate(
                    [unpad, np.zeros(a.shape[:-1] + (pad,), a.dtype)],
                    axis=-1)
            return unpad

        ts = retile(st["train_score"])
        if self._mesh is not None and self._data_axis is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.train_score = jax.device_put(
                np.asarray(ts),
                NamedSharding(self._mesh, P(None, self._data_axis)))
        else:
            self.train_score = jnp.asarray(ts)
        self.valid_scores = [jnp.asarray(v) for v in st["valid_scores"]]
        self.init_scores = list(st["init_scores"])
        self._init_score_added = bool(st["init_score_added"])
        self.shrinkage_rate = float(st["shrinkage_rate"])
        self._rng.set_state(st["bagging_rng"])
        self._goss_rng_key = jnp.asarray(st["goss_rng_key"])
        self._feature_rng.set_state(st["feature_rng"])
        self._cur_mask = (jnp.asarray(retile(st["cur_mask"]))
                          if st["cur_mask"] is not None else None)
        self._history_mode = st["history_mode"]
        self.history_scale = dict(st["history_scale"])
        self.tree_history = [jax.tree_util.tree_map(jnp.asarray, t)
                             for t in st["tree_history"]]
        used0, rows0 = st["cegb_state"]
        if np.asarray(rows0).shape != (1, 1):
            rows0 = retile(rows0)
        rows0 = jnp.asarray(rows0)
        if rows0.shape != (1, 1) and self._mesh is not None \
                and self._data_axis is not None:
            # lazy-mode row bitmap is row-sharded (mirrors __init__)
            from jax.sharding import NamedSharding, PartitionSpec as P
            rows0 = jax.device_put(
                np.asarray(rows0),
                NamedSharding(self._mesh, P(None, self._data_axis)))
        self._cegb_state = (jnp.asarray(used0), rows0)
        qs = st.get("quant_scales")
        self._quant_scales = jnp.asarray(qs) if qs is not None else None
        self.models_version += 1

    def refit_leaf_values(self, leaf_preds: np.ndarray,
                          decay_rate: float) -> None:
        """Refit every tree's leaf values against THIS dataset's gradients,
        keeping tree structures fixed.

        reference: GBDT::RefitTree (gbdt.cpp:267-290) routes each row by
        ``leaf_preds`` (pred_leaf output on the new data), recomputes leaf
        sums per tree, and blends
        ``decay * old + (1 - decay) * new_output * shrinkage``
        (SerialTreeLearner::FitByExistingTree, serial_tree_learner.cpp:198-229).
        """
        K = self.num_tree_per_iteration
        n = self.num_data
        leaf_preds = np.asarray(leaf_preds)
        if leaf_preds.ndim == 1:
            leaf_preds = leaf_preds[:, None]
        if leaf_preds.shape != (n, len(self.models)):
            raise ValueError(
                f"leaf_preds shape {leaf_preds.shape} != "
                f"({n}, {len(self.models)})")
        c = self.config
        for it in range(len(self.models) // K):
            grad, hess = self._boost(self.train_score)
            if self._inv_perm is not None:
                g = np.asarray(grad)[:, self._inv_perm]
                h = np.asarray(hess)[:, self._inv_perm]
            else:
                g = np.asarray(grad)[:, :n]
                h = np.asarray(hess)[:, :n]
            for k in range(K):
                mi = it * K + k
                m = self.models[mi]
                lp = leaf_preds[:, mi].astype(np.int64)
                if lp.max(initial=0) >= m.num_leaves:
                    raise ValueError("leaf prediction out of range")
                sg = np.bincount(lp, weights=g[k], minlength=m.num_leaves)
                sh = np.bincount(lp, weights=h[k], minlength=m.num_leaves) \
                    + K_EPSILON
                reg = np.sign(sg) * np.maximum(np.abs(sg) - c.lambda_l1, 0.0)
                out = -reg / (sh + c.lambda_l2)
                if c.max_delta_step > 0:
                    out = np.clip(out, -c.max_delta_step, c.max_delta_step)
                m.leaf_value = (decay_rate * m.leaf_value
                                + (1.0 - decay_rate) * out * m.shrinkage)
                self.models_version += 1
                self.train_score = self.train_score.at[k].add(
                    jnp.asarray(self._pad_rows_np(m.leaf_value[lp])))

    def _tree_pred_device(self, model_idx: int, binned,
                          dataset: Dataset) -> jax.Array:
        """A stored tree's current score contribution over ``binned``
        (device array), via the device history when available; host
        traversal fallback for init-model trees that were never grown in
        this run.  Output rows match ``binned``'s row count."""
        K = self.num_tree_per_iteration
        it, k = divmod(model_idx, K)
        own_it = it - self.num_init_iteration
        own_total = self.iter - self.num_init_iteration
        hist_idx = (own_it if self._history_mode == "all"
                    else own_it - (own_total - len(self.tree_history)))
        if 0 <= hist_idx < len(self.tree_history):
            tree_k = jax.tree_util.tree_map(
                lambda x: x[k], self.tree_history[hist_idx])
            fn = (self._tree_pred_train_jit if binned is self.binned
                  else self._tree_pred_jit)
            out = fn(tree_k, binned)
            scale = self.history_scale.get(model_idx, 1.0)
            return out * jnp.float32(scale) if scale != 1.0 else out
        p = self.models[model_idx].predict_binned_np(
            dataset.host_binned(), dataset.feat_group, dataset.feat_start)
        if binned.shape[1] > len(p):
            p = np.pad(p, (0, binned.shape[1] - len(p)))
        return jnp.asarray(p, jnp.float32)

    def rollback_one_iter(self) -> None:
        """reference: GBDT::RollbackOneIter (gbdt.cpp:422)."""
        if self.iter <= 0:
            return
        if self._stream is not None:
            raise RuntimeError(
                "rollback_one_iter re-evaluates trees over the resident "
                "binned matrix; an out-of-core streamed booster has none "
                "(DART and rollback stay resident — LGBM_TPU_STREAM=0)")
        K = self.num_tree_per_iteration
        first = len(self.models) - K
        for k in range(K):
            self.train_score = self.train_score.at[k].add(
                -self._tree_pred_device(first + k, self.binned,
                                        self.train_set))
            for i in range(len(self.valid_scores)):
                self.valid_scores[i] = self.valid_scores[i].at[k].add(
                    -self._tree_pred_device(first + k, self.valid_binned[i],
                                            self.valid_sets[i]))
            self.history_scale.pop(first + k, None)
        del self.models[-K:]
        self.models_version += 1
        if self.tree_history:
            self.tree_history.pop()
        self.iter -= 1

    # ------------------------------------------------------------------- eval

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval("training", self.train_score, self.train_metrics,
                          self.objective)

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for i, name in enumerate(self.valid_names):
            out.extend(self._eval(name, self.valid_scores[i],
                                  self.valid_metrics[i], self.objective))
        return out

    def eval_one_valid(self, i: int) -> List[Tuple[str, str, float, bool]]:
        return self._eval(self.valid_names[i], self.valid_scores[i],
                          self.valid_metrics[i], self.objective)

    def _eval(self, dataname, score, metrics, objective):
        from ..utils.timer import global_timer
        with global_timer.section("GBDT::EvalMetrics"), \
                _span("gbdt.eval", dataset=dataname):
            return self._eval_inner(dataname, score, metrics, objective)

    def _eval_inner(self, dataname, score, metrics, objective):
        score_np = np.asarray(score)
        if dataname == "training":
            if self._inv_perm is not None:
                score_np = score_np[:, self._inv_perm]  # undo query layout
            elif score_np.shape[-1] > self.num_data:
                score_np = score_np[:, :self.num_data]  # drop pad rows
        s = score_np if self.num_tree_per_iteration > 1 else score_np[0]
        out = []
        for m in metrics:
            for (mname, val, hib) in m.eval(s, objective):
                out.append((dataname, mname, val, hib))
        return out

    # -------------------------------------------------------------- inference

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    start_iteration: int = 0) -> np.ndarray:
        """Raw scores for a raw-feature matrix (host traversal)."""
        K = self.num_tree_per_iteration
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        n = X.shape[0]
        out = np.zeros((K, n), np.float64)
        K_total = len(self.models) // K if K else 0
        stop = K_total if num_iteration < 0 else min(start_iteration + num_iteration, K_total)
        for it in range(start_iteration, stop):
            for k in range(K):
                out[k] += self.models[it * K + k].predict_np(X)
        return out if K > 1 else out[0]

    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        return self.iter

