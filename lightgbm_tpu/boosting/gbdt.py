"""GBDT training loop.

reference: src/boosting/gbdt.cpp — GBDT::Init (:42), Train (:246),
TrainOneIter (:338), Boosting (:152), Bagging (:163), BoostFromAverage
(:302), UpdateScore (:459).

TPU re-design:
- the whole per-iteration step (gradients -> bagging mask -> K tree grows ->
  leaf renewal -> shrinkage -> score update) is ONE jitted device program;
  the host only fetches the finished (tiny) tree arrays per iteration.
- bagging and GOSS are weight masks, not index subsets: shapes stay static,
  nothing is compacted (replaces is_use_subset_/bag_data_indices_ machinery,
  gbdt.cpp:163-244); excluded rows keep leaf routing so out-of-bag score
  update (gbdt.cpp:459-478) is free.
- scores live on device [K, n] f32 for train and each valid set.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset, FeatureMeta
from ..grower import GrowerConfig, TreeArrays, grow_tree, predict_tree_binned
from ..objectives import ObjectiveFunction
from ..ops.renew import leaf_percentile
from ..tree import HostTree, tree_to_host
from ..utils.log import log_info, log_warning

K_EPSILON = 1e-15


class GBDT:
    """reference: class GBDT (src/boosting/gbdt.h)."""

    boosting_type = "gbdt"

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[ObjectiveFunction]):
        self.config = config
        self.train_set = train_set.construct()
        self.objective = objective
        self.num_class = config.num_class
        self.num_tree_per_iteration = (objective.num_model_per_iteration
                                       if objective is not None else config.num_class)
        self.iter = 0
        self.models: List[HostTree] = []   # length = iter * K
        self.shrinkage_rate = config.learning_rate

        self.meta = self.train_set.feature_meta()
        self.num_data = self.train_set.num_data
        n, F = self.train_set.binned.shape
        # padded bin axis: power-of-two-ish friendly size
        self.num_bins = int(self.meta.max_num_bin)

        self.binned = jnp.asarray(self.train_set.binned)
        if objective is not None:
            objective.init(self.train_set.metadata, self.num_data)

        self.grower_cfg = GrowerConfig(
            num_leaves=config.num_leaves,
            max_depth=config.max_depth,
            hp=config.split_hyperparams(),
            hist_method=config.tpu_hist_method,
            num_bins=self.num_bins,
            learning_rate=config.learning_rate,
        )

        K = self.num_tree_per_iteration
        self.train_score = jnp.zeros((K, n), jnp.float32)
        self.init_scores = [0.0] * K
        self._init_score_added = False
        # user-provided init score (reference: score_updater has_init_score)
        if self.train_set.metadata.init_score is not None:
            isc = np.asarray(self.train_set.metadata.init_score, np.float32)
            self.train_score = self.train_score + jnp.asarray(
                isc.reshape(-1, n) if isc.size == K * n else
                np.broadcast_to(isc.reshape(1, n), (K, n)))
            self._init_score_added = True

        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self.valid_binned: List[jax.Array] = []
        self.valid_scores: List[jax.Array] = []
        self.train_metrics = []
        self.valid_metrics: List[list] = []

        self._rng = np.random.RandomState(config.bagging_seed)
        self._goss_rng_key = jax.random.PRNGKey(config.bagging_seed)
        self._build_jit_fns()

    # ------------------------------------------------------------------ setup

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        valid_set.construct()
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        self.valid_binned.append(jnp.asarray(valid_set.binned))
        K = self.num_tree_per_iteration
        vs = jnp.zeros((K, valid_set.num_data), jnp.float32)
        if valid_set.metadata.init_score is not None:
            isc = np.asarray(valid_set.metadata.init_score, np.float32)
            nv = valid_set.num_data
            vs = vs + jnp.asarray(isc.reshape(-1, nv) if isc.size == K * nv
                                  else np.broadcast_to(isc.reshape(1, nv), (K, nv)))
        self.valid_scores.append(vs)

    def set_metrics(self, train_metrics, valid_metrics_per_set) -> None:
        self.train_metrics = train_metrics
        self.valid_metrics = valid_metrics_per_set

    def _build_jit_fns(self) -> None:
        K = self.num_tree_per_iteration
        # re-derive the grower config so reset_parameter() of tree
        # hyper-parameters (lambda_l1, min_data_in_leaf, ...) takes effect
        self.grower_cfg = GrowerConfig(
            num_leaves=self.config.num_leaves,
            max_depth=self.config.max_depth,
            hp=self.config.split_hyperparams(),
            hist_method=self.config.tpu_hist_method,
            num_bins=self.num_bins,
            learning_rate=self.config.learning_rate,
            compact=self.config.tpu_compact_hist,
        )
        cfg = self.grower_cfg
        obj = self.objective
        renew_pct = obj.renew_percentile if obj is not None else None
        weight = (jnp.asarray(self.train_set.metadata.weight)
                  if self.train_set.metadata.weight is not None else None)
        label = (jnp.asarray(self.train_set.metadata.label)
                 if obj is not None and obj.renew_percentile is not None else None)
        mc = self.config.monotone_constraints
        if mc:
            # align per-original-feature constraints with the used (binned)
            # feature columns — trivial features are dropped at binning
            mc_full = np.zeros(self.train_set.num_total_features, np.int32)
            mc_full[:len(mc)] = np.asarray(mc, np.int32)
            mc = jnp.asarray(mc_full[self.train_set.used_features])
        else:
            mc = None

        def one_iter(score, row_mask, grad, hess, fmask, lr):
            """grad/hess: [K, n]; fmask: [K, F] col-sample masks; lr: traced
            scalar so a learning_rates schedule never recompiles.
            Returns (new_score, stacked trees, leaf_ids)."""
            trees = []
            leaf_ids = []
            new_score = score
            for k in range(K):
                tree, leaf_id = grow_tree(self.binned, grad[k], hess[k],
                                          row_mask, self.meta, cfg,
                                          feature_mask=fmask[k],
                                          monotone_constraints=mc)
                if renew_pct is not None:
                    residual = label - new_score[k]
                    w = row_mask if weight is None else row_mask * weight
                    pct = leaf_percentile(leaf_id, residual, w,
                                          cfg.num_leaves, float(renew_pct))
                    active = jnp.arange(cfg.num_leaves) < tree.num_leaves
                    tree = tree._replace(
                        leaf_value=jnp.where(active, pct, tree.leaf_value))
                tree = tree._replace(
                    leaf_value=tree.leaf_value * lr,
                    internal_value=tree.internal_value * lr,
                )
                new_score = new_score.at[k].add(tree.leaf_value[leaf_id])
                trees.append(tree)
                leaf_ids.append(leaf_id)
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)
            return new_score, stacked, jnp.stack(leaf_ids)

        self._iter_fn = jax.jit(one_iter, donate_argnums=(0,))
        if not hasattr(self, "_feature_rng"):  # survive jit-fn rebuilds
            self._feature_rng = np.random.RandomState(
                self.config.feature_fraction_seed)
        self._ones_fmask = None

        def gradients_fn(score):
            if obj is None:
                raise RuntimeError("no objective: gradients must be provided")
            s = score if K > 1 else score[0]
            g, h = obj.get_gradients(s)
            g = g.reshape(K, -1)
            h = h.reshape(K, -1)
            return g, h

        self._gradients_fn = jax.jit(gradients_fn)

        def valid_update(vscore, stacked_trees, binned):
            for k in range(K):
                tree_k = jax.tree_util.tree_map(lambda x: x[k], stacked_trees)
                vscore = vscore.at[k].add(
                    predict_tree_binned(tree_k, binned, self.meta))
            return vscore

        self._valid_update = jax.jit(valid_update, donate_argnums=(0,))

    # --------------------------------------------------------------- training

    def _bagging_mask(self, it: int) -> jax.Array:
        """reference: GBDT::Bagging (gbdt.cpp:163-244) as a weight mask."""
        c = self.config
        n = self.num_data
        if self.boosting_type == "goss":
            raise RuntimeError("GOSS overrides _bagging_mask")
        need = (c.bagging_freq > 0 and c.bagging_fraction < 1.0)
        need_posneg = (c.pos_bagging_fraction < 1.0 or c.neg_bagging_fraction < 1.0)
        if not (need or need_posneg):
            return jnp.ones(n, jnp.float32)
        if it % max(c.bagging_freq, 1) != 0 and self._cur_mask is not None:
            return self._cur_mask
        if need_posneg:
            lbl = np.asarray(self.train_set.metadata.label) > 0
            u = self._rng.rand(n)
            keep = np.where(lbl, u < c.pos_bagging_fraction, u < c.neg_bagging_fraction)
        else:
            # exact count without replacement (matches reference semantics)
            cnt = int(n * c.bagging_fraction)
            idx = self._rng.choice(n, size=cnt, replace=False)
            keep = np.zeros(n, bool)
            keep[idx] = True
        self._cur_mask = jnp.asarray(keep.astype(np.float32))
        return self._cur_mask

    _cur_mask = None

    def _feature_masks(self) -> jax.Array:
        """Per-tree column sampling (reference: ColSampler by-tree,
        src/treelearner/col_sampler.hpp:19)."""
        K = self.num_tree_per_iteration
        F = len(self.train_set.used_features)   # features, not EFB columns
        frac = self.config.feature_fraction
        if frac >= 1.0:
            if self._ones_fmask is None:
                self._ones_fmask = jnp.ones((K, F), jnp.float32)
            return self._ones_fmask
        cnt = max(1, int(round(F * frac)))
        masks = np.zeros((K, F), np.float32)
        for k in range(K):
            masks[k, self._feature_rng.choice(F, size=cnt, replace=False)] = 1.0
        return jnp.asarray(masks)

    def _boost(self, score) -> Tuple[jax.Array, jax.Array]:
        return self._gradients_fn(score)

    def boost_from_average(self) -> None:
        """reference: GBDT::BoostFromAverage (gbdt.cpp:313)."""
        if self.iter > 0 or self.objective is None or self._init_score_added:
            return
        if not self.config.boost_from_average:
            return
        K = self.num_tree_per_iteration
        for k in range(K):
            s = self.objective.boost_from_score(k)
            if abs(s) > K_EPSILON:
                self.init_scores[k] = s
                self.train_score = self.train_score.at[k].add(s)
                for i in range(len(self.valid_scores)):
                    self.valid_scores[i] = self.valid_scores[i].at[k].add(s)
                log_info(f"Start training from score {s:.6f}")

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration; returns True if training should STOP
        (no more splittable leaves).  reference: GBDT::TrainOneIter."""
        K = self.num_tree_per_iteration
        n = self.num_data
        self.boost_from_average()
        if grad is None:
            grad, hess = self._boost(self.train_score)
        else:
            grad = jnp.asarray(np.asarray(grad, np.float32).reshape(K, n))
            hess = jnp.asarray(np.asarray(hess, np.float32).reshape(K, n))
        mask = self._bagging_mask(self.iter)

        self.train_score, stacked, leaf_ids = self._iter_fn(
            self.train_score, mask, grad, hess, self._feature_masks(),
            jnp.float32(self.shrinkage_rate))
        return self._finish_iter(stacked)

    def _finish_iter(self, stacked) -> bool:
        """Post-step bookkeeping shared by GBDT/GOSS/DART/RF: host copies of
        the (tiny) tree arrays, first-iteration bias folding, valid-score
        updates.  Returns True when training should stop."""
        K = self.num_tree_per_iteration
        new_models = []
        should_continue = False
        for k in range(K):
            tree_k = jax.tree_util.tree_map(lambda x: np.asarray(x[k]), stacked)
            ht = tree_to_host(tree_k, self.train_set, self.shrinkage_rate)
            if ht.num_leaves > 1:
                should_continue = True
            if self.iter == 0 and abs(self.init_scores[k]) > K_EPSILON:
                ht.add_bias(self.init_scores[k])
            new_models.append(ht)
        if not should_continue:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        self.models.extend(new_models)

        for i in range(len(self.valid_scores)):
            self.valid_scores[i] = self._valid_update(
                self.valid_scores[i], stacked, self.valid_binned[i])
        self.iter += 1
        return False

    def rollback_one_iter(self) -> None:
        """reference: GBDT::RollbackOneIter (gbdt.cpp:422)."""
        if self.iter <= 0:
            return
        K = self.num_tree_per_iteration
        dropped = self.models[-K:]
        del self.models[-K:]
        # subtract the dropped trees' contributions
        for k, ht in enumerate(dropped):
            self.train_score = self.train_score.at[k].add(
                -jnp.asarray(ht.predict_binned_np(
                    self.train_set.binned, self.train_set.feat_group,
                    self.train_set.feat_start)))
        for i, vs in enumerate(self.valid_scores):
            for k, ht in enumerate(dropped):
                self.valid_scores[i] = self.valid_scores[i].at[k].add(
                    -jnp.asarray(ht.predict_binned_np(
                        self.valid_sets[i].binned, self.valid_sets[i].feat_group,
                        self.valid_sets[i].feat_start)))
        self.iter -= 1

    # ------------------------------------------------------------------- eval

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval("training", self.train_score, self.train_metrics,
                          self.objective)

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for i, name in enumerate(self.valid_names):
            out.extend(self._eval(name, self.valid_scores[i],
                                  self.valid_metrics[i], self.objective))
        return out

    def _eval(self, dataname, score, metrics, objective):
        score_np = np.asarray(score)
        s = score_np if self.num_tree_per_iteration > 1 else score_np[0]
        out = []
        for m in metrics:
            for (mname, val, hib) in m.eval(s, objective):
                out.append((dataname, mname, val, hib))
        return out

    # -------------------------------------------------------------- inference

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    start_iteration: int = 0) -> np.ndarray:
        """Raw scores for a raw-feature matrix (host traversal)."""
        K = self.num_tree_per_iteration
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        n = X.shape[0]
        out = np.zeros((K, n), np.float64)
        K_total = len(self.models) // K if K else 0
        stop = K_total if num_iteration < 0 else min(start_iteration + num_iteration, K_total)
        for it in range(start_iteration, stop):
            for k in range(K):
                out[k] += self.models[it * K + k].predict_np(X)
        return out if K > 1 else out[0]

    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        return self.iter

