"""Boosting algorithms.

reference: src/boosting/boosting.cpp CreateBoosting factory
(include/LightGBM/boosting.h:310): gbdt / dart / goss / rf.
"""

from __future__ import annotations

from ..config import Config
from .gbdt import GBDT
from .goss import GOSS


def create_boosting(config: Config, train_set, objective):
    t = config.boosting
    if t == "gbdt" or t == "gbrt":
        return GBDT(config, train_set, objective)
    if t == "goss":
        return GOSS(config, train_set, objective)
    if t == "dart":
        from .dart import DART
        return DART(config, train_set, objective)
    if t in ("rf", "random_forest"):
        from .rf import RF
        return RF(config, train_set, objective)
    raise ValueError(f"unknown boosting type {t!r}")
