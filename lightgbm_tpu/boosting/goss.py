"""GOSS: gradient-based one-side sampling.

reference: src/boosting/goss.hpp:24-132 — keep the top ``top_rate`` fraction
of rows by |grad*hess|, sample ``other_rate`` of the rest and amplify their
weight by (1-top_rate)/other_rate; no sampling during the first
1/learning_rate warm-up iterations (goss.hpp:126-131).

TPU form: pure weight mask (1 / amplified / 0) computed on device from the
current gradients — no index compaction, shapes stay static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .gbdt import GBDT


class GOSS(GBDT):
    boosting_type = "goss"

    def __init__(self, config, train_set, objective):
        super().__init__(config, train_set, objective)
        if config.bagging_freq > 0 and config.bagging_fraction < 1.0:
            raise ValueError("cannot use bagging in GOSS")
        if config.top_rate + config.other_rate > 1.0:
            raise ValueError("top_rate + other_rate cannot be larger than 1.0")

        top_rate = config.top_rate
        other_rate = config.other_rate
        n = self.num_data
        n_pad = self._n_pad
        row_valid = self._row_valid

        def goss_mask_raw(grad, hess, key, row_valid):
            # grad/hess: [K, n_pad]; sharding-pad rows (row_valid == 0) are
            # pushed below any real score so they can never enter the top set
            score = jnp.sum(jnp.abs(grad * hess), axis=0)
            score = score * row_valid - (1.0 - row_valid)
            top_k = max(1, int(top_rate * n))
            thresh = jax.lax.top_k(score, top_k)[0][-1]
            is_top = score >= thresh
            rest_p = other_rate / max(1e-12, 1.0 - top_rate)
            keep_rest = jax.random.uniform(key, (n_pad,)) < rest_p
            amp = (1.0 - top_rate) / max(other_rate, 1e-12)
            return jnp.where(is_top, 1.0,
                             jnp.where(keep_rest, amp, 0.0)) * row_valid

        # the macro-step scan body (boosting/macro.py) traces the SAME
        # function with the row mask riding as the scan input
        self._macro_goss_mask = goss_mask_raw
        self._goss_mask_fn = jax.jit(
            lambda grad, hess, key: goss_mask_raw(grad, hess, key,
                                                  row_valid))

    def _bagging_mask(self, it):
        return self._row_valid

    def train_one_iter(self, grad=None, hess=None):
        if grad is None:
            # macro path: warm-up gating and sampling ride inside the
            # chunk program (_macro_goss_inputs); keeps per-iteration and
            # chunked GOSS on the same compiled loop body
            single = self._chunk_single()
            if single is not None:
                return single
        # warm-up: no sampling for the first 1/learning_rate iterations
        warmup = 1.0 / max(self.config.learning_rate, 1e-12)
        if grad is None and self.iter >= warmup:
            self.boost_from_average()
            g, h = self._boost(self.train_score)
            self._goss_rng_key, sub = jax.random.split(self._goss_rng_key)
            mask = self._goss_mask_fn(g, h, sub)
            return self._train_with(g, h, mask)
        return super().train_one_iter(grad, hess)

    def _macro_goss_inputs(self, c, it0, lrs):
        """Per-chunk GOSS subkeys: sampling iterations consume a split of
        the stream in the exact per-iteration order; warm-up iterations
        (no sampling) leave the stream untouched and get a dummy key.
        ``lrs`` carries the per-iteration learning rate (a reset_parameter
        schedule moves the 1/lr warm-up threshold per iteration)."""
        keys, flags = [], []
        for j in range(c):
            warmup = 1.0 / max(lrs[j], 1e-12)
            if it0 + j >= warmup:
                self._goss_rng_key, sub = jax.random.split(self._goss_rng_key)
                keys.append(sub)
                flags.append(True)
            else:
                keys.append(jnp.zeros_like(self._goss_rng_key))
                flags.append(False)
        return jnp.stack(keys), jnp.asarray(np.asarray(flags))

    def _train_with(self, grad, hess, mask):
        if self._stream is not None:
            # out-of-core streamed executor (data/stream.py): same mask,
            # same RNG order, streamed tree growth
            return self._stream_step(grad, hess, mask)
        (self.train_score, stacked, leaf_ids, cu, cr,
         self._quant_scales) = self._iter_fn(
            self.binned, self.train_score, mask, grad, hess,
            self._feature_masks(), jnp.float32(self.shrinkage_rate),
            self._node_key(), *self._cegb_state)
        self._cegb_state = (cu, cr)
        return self._finish_iter(stacked)
