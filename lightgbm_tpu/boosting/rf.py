"""Random forest mode.

reference: src/boosting/rf.hpp — bagging is mandatory, no shrinkage,
gradients are computed ONCE from the constant boost-from-average scores
(Boosting override, rf.hpp:77-98), every tree carries its class's init
score as a bias (AddBias, rf.hpp:137), and train/valid scores are the
RUNNING MEAN of the trees' outputs (MultiplyScore dance, rf.hpp:140-142);
prediction averages over iterations (average_output).

Percentile-renewing objectives (L1/quantile/MAPE) renew leaf outputs
against the CONSTANT init score (reference residual_getter, rf.hpp:133);
the jitted step is rebuilt in that mode once the init scores are final.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .gbdt import GBDT, K_EPSILON
from ..tree import tree_to_host
from ..utils.log import log_warning


class RF(GBDT):
    boosting_type = "rf"
    _stream_ok = False       # const-gradient renewal + running-mean score
    #                          renorm ride the resident iteration program
    _defer_host_ok = False   # custom eager finish (averaged extension)

    def __init__(self, config, train_set, objective):
        if not (config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0):
            raise ValueError("random forest requires bagging "
                             "(bagging_freq > 0 and bagging_fraction < 1)")
        if objective is None:
            raise ValueError("RF mode does not support custom objective "
                             "functions, please use built-in objectives")
        super().__init__(config, train_set, objective)
        self.shrinkage_rate = 1.0
        K = self.num_tree_per_iteration
        # constant per-class init scores; NOT added to the score vectors —
        # they ride inside each tree as a bias (reference rf.hpp:84,137)
        if config.boost_from_average:
            self.init_scores = [objective.boost_from_score(k) for k in range(K)]
        self._init_score_added = True   # disable GBDT.boost_from_average
        # gradients once, from the constant init scores (rf.hpp:77-98)
        init_col = jnp.asarray(self.init_scores, jnp.float32)[:, None]
        score0 = jnp.broadcast_to(init_col, self.train_score.shape)
        g, h = self._gradients_fn(score0)
        self._grad, self._hess = g, h
        # percentile-renewing objectives (L1/quantile/MAPE) must renew
        # against the constant init score (reference residual_getter,
        # rf.hpp:130-135); rebuild the jitted step with that mode now that
        # init_scores are final
        self._rf_renew_const_init = True
        self._build_jit_fns()

    def _macro_const_grads(self):
        """The macro-step scan body (boosting/macro.py) uses RF's
        once-computed gradients as loop-invariant runtime inputs."""
        return self._grad, self._hess

    def _finish_chunk_inner(self, stacked_seq, c, shrinks, it0) -> bool:
        """RF chunk finish: eager averaged extension per iteration from ONE
        bulk device fetch; valid scores renormalized by the fused
        running-mean scan (macro.build_chunk_valid's rf mode)."""
        import jax
        K = self.num_tree_per_iteration
        bh = jax.device_get(stacked_seq)
        stopped = False
        kept = 0
        for j in range(c):
            new_models, any_split = [], False
            for k in range(K):
                tree_k = jax.tree_util.tree_map(
                    lambda x: np.asarray(x[j][k]), bh)
                ht = tree_to_host(tree_k, self.train_set, 1.0)
                if ht.num_leaves > 1:
                    any_split = True
                if abs(self.init_scores[k]) > K_EPSILON:
                    ht.add_bias(self.init_scores[k])
                new_models.append(ht)
            if not any_split:
                log_warning("Stopped training because there are no more "
                            "leaves that meet the split requirements")
                stopped = True
                break
            self.models.extend(new_models)
            kept = j + 1
        self.models_version += 1
        if kept:
            seq_kept = (stacked_seq if kept == c else
                        jax.tree_util.tree_map(lambda x: x[:kept],
                                               stacked_seq))
            its = jnp.arange(it0, it0 + kept, dtype=jnp.int32)
            for i in range(len(self.valid_scores)):
                self.valid_scores[i] = self._chunk_valid_update(
                    self.valid_scores[i], seq_kept, self.valid_binned[i],
                    its)
        self.iter = it0 + kept
        return stopped

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if grad is not None:
            raise ValueError("RF mode does not support custom objectives")
        single = self._chunk_single()
        if single is not None:
            return single
        it = self.iter
        mask = self._bagging_mask(it)
        # run the shared step on it*mean (so "+ tree" keeps the sum), then
        # renormalize to the running mean including the per-tree bias
        s1 = self.train_score * it
        s2, stacked, _, cu, cr, self._quant_scales = self._iter_fn(
            self.binned, s1, mask, self._grad, self._hess,
            self._feature_masks(), jnp.float32(1.0),
            self._node_key(), *self._cegb_state)
        self._cegb_state = (cu, cr)
        init_col = jnp.asarray(self.init_scores, jnp.float32)[:, None]
        self.train_score = (s2 + init_col) / (it + 1)
        return self._finish_iter(stacked)

    def _finish_iter(self, stacked) -> bool:
        K = self.num_tree_per_iteration
        it = self.iter
        import jax
        new_models = []
        should_continue = False
        for k in range(K):
            tree_k = jax.tree_util.tree_map(lambda x: np.asarray(x[k]), stacked)
            ht = tree_to_host(tree_k, self.train_set, 1.0)
            if ht.num_leaves > 1:
                should_continue = True
            if abs(self.init_scores[k]) > K_EPSILON:
                ht.add_bias(self.init_scores[k])
            new_models.append(ht)
        if not should_continue:
            log_warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        self.models.extend(new_models)
        init_col = jnp.asarray(self.init_scores, jnp.float32)[:, None]
        for i in range(len(self.valid_scores)):
            vs = self._valid_update(self.valid_scores[i] * it, stacked,
                                    self.valid_binned[i])
            self.valid_scores[i] = (vs + init_col) / (it + 1)
        self.iter += 1
        return False
