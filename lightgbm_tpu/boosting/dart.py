"""DART: dropouts meet multiple additive regression trees.

reference: src/boosting/dart.hpp — DroppingTrees (:97), Normalize (:158),
TrainOneIter (:58).  Behavioral contract reproduced:

- each iteration drops a random subset of existing trees (probability
  ``drop_rate``, at most ``max_drop``; whole dropout skipped with
  probability ``skip_drop``; non-uniform mode weights the pick by stored
  tree weight), computes gradients on the score WITHOUT the dropped trees,
  and trains the new tree with shrinkage lr/(1+k) (xgboost mode:
  lr/(lr+k)), k = number dropped;
- afterwards each dropped tree is renormalized to k/(k+1) (xgboost mode:
  k/(lr+k)) of its old weight, i.e. train and valid scores both end up
  down-shifted by (1-w) of the dropped tree's old contribution.

TPU form: the dropped trees' contributions are re-evaluated ON DEVICE from
the boosting object's stored TreeArrays history (GBDT.tree_history /
_tree_pred_device) — no host pass over the binned matrix; at HIGGS scale a
drop costs one jitted traversal instead of an 11M-row numpy walk.  The grow
step itself is the shared jitted ``one_iter``.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from .gbdt import GBDT


class DART(GBDT):
    boosting_type = "dart"
    _stream_ok = False       # drops re-evaluate saved trees over the
    #                          resident matrix — no out-of-core streaming
    _defer_host_ok = False   # per-iteration host drop & rescale of models
    _macro_ok = False        # same reason: no fused macro-steps (the chunk
    # scheduler in engine.py falls back to c=1 per-iteration training)
    _quant_ok = False        # use_quantized_grad falls back to f32 here:
    # the drop & rescale re-weights trees whose outputs carry round-local
    # quantization scales (gbdt.py warn-once explains the fallback)

    def __init__(self, config, train_set, objective):
        super().__init__(config, train_set, objective)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight: List[float] = []   # non-uniform drop weighting
        self.sum_weight = 0.0
        self._history_mode = "all"   # any this-run tree can be dropped

    # -- checkpoint -------------------------------------------------------

    def capture_state(self) -> dict:
        st = super().capture_state()
        st["drop_rng"] = self._drop_rng.get_state()
        st["tree_weight"] = list(self.tree_weight)
        st["sum_weight"] = float(self.sum_weight)
        return st

    def restore_state(self, st: dict) -> None:
        super().restore_state(st)
        self._drop_rng.set_state(st["drop_rng"])
        self.tree_weight = list(st["tree_weight"])
        self.sum_weight = float(st["sum_weight"])

    # -- helpers ----------------------------------------------------------

    def _tree_pred_train(self, model_idx: int) -> jax.Array:
        return self._tree_pred_device(model_idx, self.binned, self.train_set)

    def _tree_pred_valid(self, model_idx: int, vi: int) -> jax.Array:
        return self._tree_pred_device(model_idx, self.valid_binned[vi],
                                      self.valid_sets[vi])

    def _dropping_trees(self) -> List[int]:
        """Pick THIS-RUN iteration indices to drop (0 = first iteration
        trained in this run; init_model trees are never dropped — the
        model index of drop i is ``(num_init_iteration + i) * K``); set
        the new tree's shrinkage.  reference: dart.hpp:97-151."""
        c = self.config
        drop: List[int] = []
        if self._drop_rng.rand() >= c.skip_drop:
            drop_rate = c.drop_rate
            # only trees trained in THIS run are drop candidates
            # (reference: dart.hpp drops num_init_iteration_ + i)
            K = max(self.num_tree_per_iteration, 1)
            n_own = min(self.iter, len(self.models) // K) \
                - self.num_init_iteration
            if not c.uniform_drop and self.sum_weight > 0:
                n_own = min(n_own, len(self.tree_weight))
                inv_avg = len(self.tree_weight) / self.sum_weight
                if c.max_drop > 0:
                    drop_rate = min(drop_rate,
                                    c.max_drop * inv_avg / self.sum_weight)
                for i in range(n_own):
                    if self._drop_rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                        drop.append(i)
                        if c.max_drop > 0 and len(drop) >= c.max_drop:
                            break
            else:
                if c.max_drop > 0 and n_own > 0:
                    drop_rate = min(drop_rate, c.max_drop / n_own)
                for i in range(n_own):
                    if self._drop_rng.rand() < drop_rate:
                        drop.append(i)
                        if c.max_drop > 0 and len(drop) >= c.max_drop:
                            break
        k = len(drop)
        if not c.xgboost_dart_mode:
            self.shrinkage_rate = c.learning_rate / (1.0 + k)
        else:
            self.shrinkage_rate = (c.learning_rate if k == 0 else
                                   c.learning_rate / (c.learning_rate + k))
        return drop

    # -- training ---------------------------------------------------------

    def train_one_iter(self, grad=None, hess=None) -> bool:
        c = self.config
        K = self.num_tree_per_iteration
        # (boost_from_average happens inside super().train_one_iter —
        # calling it here too would double-add the init score at iter 0)
        drop = self._dropping_trees()
        k = len(drop)
        off = self.num_init_iteration    # drop i -> model (off + i) * K + kk

        # remove dropped trees from the train score before gradients
        # (reference: GetTrainingScore -> DroppingTrees, dart.hpp:131-137)
        drop_preds = {}
        for i in drop:
            for kk in range(K):
                p = self._tree_pred_train((off + i) * K + kk)
                drop_preds[(i, kk)] = p
                self.train_score = self.train_score.at[kk].add(-p)

        stopped = super().train_one_iter(grad, hess)
        if stopped:
            # restore the removed contributions; nothing was trained
            for (i, kk), p in drop_preds.items():
                self.train_score = self.train_score.at[kk].add(p)
            return True

        # normalize dropped trees to weight w of their old contribution
        # (reference: Normalize, dart.hpp:158-199)
        if k > 0:
            w = (k / (k + 1.0) if not c.xgboost_dart_mode
                 else k / (k + c.learning_rate))
            for (i, kk), p in drop_preds.items():
                mi = (off + i) * K + kk
                self.train_score = self.train_score.at[kk].add(
                    jnp.float32(w) * p)
                for vi in range(len(self.valid_scores)):
                    vp = self._tree_pred_valid(mi, vi)
                    self.valid_scores[vi] = self.valid_scores[vi].at[kk].add(
                        jnp.float32(-(1.0 - w)) * vp)
                self.models[mi].scale(w)
                self.models_version += 1
                self.history_scale[mi] = self.history_scale.get(mi, 1.0) * w
            if not c.uniform_drop:
                # reference Normalize: sum_weight -= tw/(k+1) (default) or
                # tw/(k+lr) (xgboost mode), then tw *= w  (dart.hpp:176,195)
                denom = (k + 1.0 if not c.xgboost_dart_mode
                         else k + c.learning_rate)
                for i in drop:
                    self.sum_weight -= self.tree_weight[i] / denom
                    self.tree_weight[i] *= w

        if not c.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False
