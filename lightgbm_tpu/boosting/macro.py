"""Fused multi-iteration boosting: ``lax.scan`` macro-steps.

The per-iteration training step (gbdt.py ``iter_body``) is one jitted
device program, but the engine still launches it once per boosting round
from Python.  On the tunneled accelerator backend the fixed per-dispatch
cost (~6 ms, measured in grower_rounds.py's motivation) dominates train
time at 100k-500k rows.  This module wraps the SAME ``iter_body`` in a
``lax.scan`` over a chunk of ``c`` iterations inside one jitted,
score-donating program, so ``num_boost_round`` trees cost
``ceil(rounds/c)`` dispatches instead of ``rounds``.

Everything the scan needs is device-resident or precomputable per chunk:

- gradients recompute from the carried score (the booster's
  ``gradients_fn`` closure, traced INSIDE the scan body);
- bagging masks are host-RNG draws -> stacked ``[c, n_pad]`` input;
- per-tree feature masks -> stacked ``[c, K, F]`` input;
- learning-rate schedules (reset_parameter) -> ``[c]`` array;
- per-iteration node keys -> stacked PRNG keys;
- GOSS masks derive from the in-scan gradients + precomputed subkeys;
- RF's running-mean renormalization rides on a ``[c]`` iteration-index
  array (``score*it`` pre / ``(score+init)/(it+1)`` post, as in rf.py).

The scan stacks per-iteration ``TreeArrays`` so the host fetches ONE
``[c, ...]`` tree bundle per chunk (feeding gbdt.py's deferred-host
drain).  Chunked training is bit-identical to per-iteration training —
the scanned program composes the same ``iter_body`` — which
tests/test_macro.py asserts byte-for-byte on saved model text.

Compile-time note: every shape in the chunk program is keyed by
``n_pad``, so with shape buckets on (``ops.planner.bucket_rows``;
docs/PERF.md "shape buckets") nearby dataset sizes land on the same
rung and REUSE one compiled chunk program instead of building a fresh
one per exact row count.

Memory: the chunk program composes ``iter_body`` over the booster's
``grower_cfg``, so the HBM budget plan (ops/planner.py ``tile_rows`` /
``hist_pack``, chosen at ``_build_jit_fns`` time with per-shard rows)
governs the fused program exactly as it governs per-iteration training —
histogram transients inside the scan stay O(tile), and tiled chunked
training is byte-identical to untiled per-iteration training
(tests/test_macro.py tiled parity rows).

Env gate: ``LGBM_TPU_CHUNK`` — unset/"on"/"auto" = default cap (32),
"0"/"off" disables, a positive integer sets the cap (1 disables fusion).
The chunk SCHEDULER (engine.py) picks the distance to the next boundary
that genuinely needs the host (eval per ``metric_freq``, snapshots,
end-of-training) and rounds down to a power of two so at most
``log2(cap)+1`` program shapes ever compile.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEFAULT_CHUNK_CAP = 32


def chunk_cap() -> int:
    """Resolve the LGBM_TPU_CHUNK env gate to a max chunk size (0 = off)."""
    env = os.environ.get("LGBM_TPU_CHUNK", "").strip().lower()
    if env in ("0", "off", "false", "no"):
        return 0
    if env in ("", "on", "true", "auto", "default"):
        return DEFAULT_CHUNK_CAP
    try:
        return max(0, int(env))
    except ValueError:
        return DEFAULT_CHUNK_CAP


def pow2_chunk(distance: int, cap: int) -> int:
    """Largest power of two <= min(distance, cap); bounds the number of
    distinct compiled chunk shapes to log2(cap)+1."""
    d = min(distance, cap)
    if d < 1:
        return 1
    c = 1
    while c * 2 <= d:
        c *= 2
    return c


def _ix(arr, j):
    return lax.dynamic_index_in_dim(arr, j, 0, keepdims=False)


def make_chunk_fn(b):
    """The UNJITTED chunk callable for booster ``b`` — the body shared by
    the solo jitted program (``build_chunk_program``) and the batched
    model-axis program (``lightgbm_tpu/multi/batch.py``), which wraps the
    SAME callable in ``jax.vmap`` over a leading booster axis.  Batched
    training composes this exact body, so batch-invariance inherits the
    chunk program's bit-parity discipline wherever the elected histogram
    variant accumulates order-invariantly (scatter / integer paths —
    docs/PERF.md "model axis").

    The loop is a ``fori_loop`` whose trip count ``n_steps`` is a RUNTIME
    scalar (always equal to the static chunk capacity ``c`` carried by the
    input shapes).  The runtime bound is load-bearing for bit-parity: with
    a static trip count XLA unrolls short loops into straight-line code,
    where XLA:CPU contracts the leaf-value-scale + gather + score-add of
    ``iter_body`` into an FMA (observed at num_class > 1; neither
    ``optimization_barrier`` nor ``--xla_allow_excess_precision=false``
    prevents it) — while loop bodies keep the two-rounding form.  A
    dynamic bound forces the SAME loop-body codegen at every chunk size,
    including c=1, which is why per-iteration training of supported modes
    also routes through this program (GBDT._chunk_single): training is
    then invariant to the chunk decomposition, the property the
    checkpoint/resume interop relies on.

    ``c`` rides in the input shapes: jax retraces per distinct chunk
    capacity, so one returned callable serves every chunk size the
    scheduler picks.
    """
    from ..grower import TreeArrays
    core = b._macro_core          # the SAME iter_body (serial or shard_map)
    grad_fn = b._macro_grad       # gradients-from-score closure (unjitted)
    kind = b.boosting_type
    goss_mask = getattr(b, "_macro_goss_mask", None)
    init_col = (jnp.asarray(b.init_scores, jnp.float32)[:, None]
                if kind == "rf" else None)
    K = b.num_tree_per_iteration
    L = b.grower_cfg.num_leaves

    def chunk(binned, score, cegb_used, cegb_rows, n_steps, xs,
              label_r, weight_r, grad_c, hess_c):
        masks, fmasks, lrs, keys, its, gkeys, gons = xs
        c = lrs.shape[0]
        tmpl = TreeArrays.empty(L)
        ys0 = jax.tree_util.tree_map(
            lambda a: jnp.zeros((c, K) + a.shape, a.dtype), tmpl)
        # per-iteration gradient-quantization scales (use_quantized_grad)
        # ride out as a stacked [c, K, 2] buffer alongside the trees —
        # the in-loop quantization recomputes them from the carried score
        # exactly as per-iteration training does (the stochastic-rounding
        # keys derive from the stacked per-round key stream `keys`)
        qss0 = jnp.zeros((c, K, 2), jnp.float32)

        def body(j, state):
            score, cu, cr, ys, qss = state
            mask = _ix(masks, j)
            it = _ix(its, j)
            if kind == "rf":
                # rf.py runs the shared step on it*mean so "+ tree" keeps
                # the sum, then renormalizes to the running mean
                g, h = grad_c, hess_c
                score_in = score * it.astype(jnp.float32)
            else:
                g, h = grad_fn(score)
                score_in = score
            if kind == "goss":
                gm = goss_mask(g, h, _ix(gkeys, j), mask)
                mask = jnp.where(_ix(gons, j), gm, mask)
            new_score, stacked, _leaf_ids, cu, cr, qsc = core(
                binned, score_in, mask, g, h, _ix(fmasks, j), _ix(lrs, j),
                _ix(keys, j), cu, cr, label_r, weight_r)
            if kind == "rf":
                new_score = (new_score + init_col) / (
                    it.astype(jnp.float32) + 1.0)
            ys = jax.tree_util.tree_map(
                lambda buf, v: lax.dynamic_update_index_in_dim(buf, v, j, 0),
                ys, stacked)
            qss = lax.dynamic_update_index_in_dim(qss, qsc, j, 0)
            return new_score, cu, cr, ys, qss

        score, cegb_used, cegb_rows, ys, qss = lax.fori_loop(
            0, n_steps, body, (score, cegb_used, cegb_rows, ys0, qss0))
        return score, cegb_used, cegb_rows, ys, qss

    return chunk


def build_chunk_program(b):
    """The solo jitted chunk program: ``make_chunk_fn`` under ``jax.jit``
    with the carried score buffer donated, like the per-iteration
    program."""
    return jax.jit(make_chunk_fn(b), donate_argnums=(1,))


def build_chunk_valid(b):
    """Fused valid-score update: one program applies a whole ``[c, ...]``
    tree bundle to a valid set (vs. one dispatch per iteration).  Same
    runtime-trip-count loop as the chunk program so RF's running-mean
    renormalization keeps identical codegen at every chunk size."""
    from ..grower import predict_tree_binned
    K = b.num_tree_per_iteration
    meta_args = b.meta.as_runtime_arrays()
    rf = b.boosting_type == "rf"
    init_col = (jnp.asarray(b.init_scores, jnp.float32)[:, None]
                if rf else None)

    def upd(vscore, stacked_seq, binned, its, n_steps):
        def body(j, vs):
            st = jax.tree_util.tree_map(lambda a: _ix(a, j), stacked_seq)
            if rf:
                itf = _ix(its, j).astype(jnp.float32)
                vs = vs * itf
            for k in range(K):
                tree_k = jax.tree_util.tree_map(lambda a: a[k], st)
                vs = vs.at[k].add(predict_tree_binned(
                    tree_k, binned, None, meta_arrays=meta_args))
            if rf:
                vs = (vs + init_col) / (itf + 1.0)
            return vs

        return lax.fori_loop(0, n_steps, body, vscore)

    return jax.jit(upd, donate_argnums=(0,))


def _stack_row_arrays(b, arrs: Sequence[jax.Array]) -> jax.Array:
    """Stack per-iteration row arrays to [c, n_pad]; under a data-sharded
    mesh the stacked input keeps the row sharding so the scan slices feed
    shard_map without a gather to one device."""
    out = jnp.stack(arrs)
    if b._mesh is not None and b._data_axis is not None:
        from ..parallel.learners import put_stacked_rows
        out = put_stacked_rows(b._mesh, b._data_axis, out)
    return out


def chunk_host_inputs(b, c: int, lrs: Optional[Sequence[float]] = None):
    """Draw booster ``b``'s per-iteration host inputs for a chunk of ``c``
    iterations starting at ``b.iter`` — bagging masks, feature masks,
    per-round node keys, the lr schedule, iteration indices and GOSS
    subkeys — in the EXACT per-iteration order, so the host RNG streams
    replay identically whether the chunk runs solo (``run_chunk``) or
    stacked along a model axis (multi/driver.py).  Returns ``(xs,
    lr_list)``; the caller is responsible for ``boost_from_average`` first
    (the draw order starts after init)."""
    it0 = b.iter
    masks: List[jax.Array] = []
    fmasks: List[jax.Array] = []
    keys: List[jax.Array] = []
    for j in range(c):
        masks.append(b._bagging_mask(it0 + j))
        fmasks.append(b._feature_masks())
        keys.append(jax.random.fold_in(b._node_key_base, it0 + j))
    if b.boosting_type == "rf":
        lr_list = [1.0] * c                   # rf.py passes literal 1.0
    elif lrs is not None:
        lr_list = [float(v) for v in lrs]
        if len(lr_list) != c:
            raise ValueError(f"got {len(lr_list)} learning rates for a "
                             f"chunk of {c} iterations")
    else:
        lr_list = [float(b.shrinkage_rate)] * c
    its = jnp.arange(it0, it0 + c, dtype=jnp.int32)
    gkeys, gon = b._macro_goss_inputs(c, it0, lr_list)
    xs = (_stack_row_arrays(b, masks), jnp.stack(fmasks),
          jnp.asarray(lr_list, jnp.float32), jnp.stack(keys), its,
          gkeys, gon)
    return xs, lr_list


def run_chunk(b, c: int, lrs: Optional[Sequence[float]] = None) -> bool:
    """Train ``c`` iterations of booster ``b`` in one fused dispatch.

    ``lrs``: per-iteration learning rates (a reset_parameter schedule
    precomputed by the engine); None = the booster's current shrinkage.
    Returns True when training stopped (no more splittable leaves, only
    detectable on the eager host path; the deferred path reports it at
    drain time exactly like per-iteration training).
    """
    if c < 1:
        raise ValueError(f"chunk size must be >= 1, got {c}")
    if not b.chunk_supported():
        raise RuntimeError(
            f"boosting={b.boosting_type!r} with this config needs "
            "per-iteration host logic; use train_one_iter (the engine's "
            "chunk scheduler falls back to c=1 automatically)")
    b.boost_from_average()
    it0 = b.iter
    xs, lr_list = chunk_host_inputs(b, c, lrs)
    grad_c, hess_c = b._macro_const_grads()

    if b._macro_chunk_jit is None:
        b._macro_chunk_jit = build_chunk_program(b)
    cu, cr = b._cegb_state
    from ..obs.metrics import global_registry as _obs_registry
    from ..obs.trace import span as _span
    from ..utils.timer import global_timer
    # chunk-size telemetry on the unified registry (obs_dump / bench
    # journal it instead of scraping logs)
    _obs_registry.counter("train_chunk_dispatches").inc()
    _obs_registry.histogram(
        "train_chunk_size",
        buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)).observe(c)
    with global_timer.section("TreeLearner::Train(dispatch)"), \
            _span("macro.dispatch", c=c, it0=it0):
        (b.train_score, cu, cr, stacked_seq, qss) = b._macro_chunk_jit(
            b.binned, b.train_score, cu, cr, np.int32(c), xs,
            b._macro_ctx["label"], b._macro_ctx["weight"], grad_c, hess_c)
    b._cegb_state = (cu, cr)
    if getattr(b, "_quant_on", False):
        b._quant_scales = qss[c - 1]   # last round's per-class scales
    return b._finish_chunk(stacked_seq, c, lr_list, it0)
