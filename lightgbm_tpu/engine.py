"""Training entry points: train() and cv().

reference: python-package/lightgbm/engine.py — train (:18) with the callback
protocol, cv (:375) with CVBooster and fold aggregation.
"""

from __future__ import annotations

import collections
import copy
import time
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster
from .config import Config
from .dataset import Dataset
from .obs.flight import global_flight as _flight
from .obs.metrics import global_registry as _obs_registry
from .obs.trace import span as _span
from .obs.watchdog import global_watchdog as _watchdog


class TrainingPaused(Exception):
    """Raised out of ``train()`` when its ``pause_control`` orders a
    pause: the full training state was evicted to a checkpoint bundle
    FIRST, so the caller resumes byte-identically later by re-calling
    ``train`` with the same arguments plus ``resume_from=e.bundle_path``
    (the PR 2 capture/restore machinery — docs/RESILIENCE.md).  Not an
    error: the engine's forensic on-exception dump does not fire."""

    def __init__(self, iteration: int, bundle_path: str):
        super().__init__(
            f"training paused at iteration {iteration}; state evicted "
            f"to {bundle_path}")
        self.iteration = int(iteration)
        self.bundle_path = bundle_path


def train(params: dict, train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model=None, feature_name="auto", categorical_feature="auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[dict] = None,
          verbose_eval: Union[bool, int] = True,
          learning_rates=None, keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          snapshot_freq: int = -1, snapshot_out: str = "model.txt",
          snapshot_keep: int = 3,
          resume_from: Optional[str] = None,
          pause_control=None) -> Booster:
    """reference: engine.py:18.

    ``snapshot_freq`` mirrors the CLI's periodic snapshots
    (gbdt.cpp:259-263) but writes CHECKPOINT BUNDLES — atomic,
    sha256-manifested, full training state — into ``<snapshot_out>.ckpt/``
    (keep-last-``snapshot_keep``) instead of bare model files a crash can
    truncate.  ``resume_from`` (a bundle file or that directory) restores
    the captured state so the continued run produces a model
    BIT-IDENTICAL to the uninterrupted one; corrupt newest bundles are
    skipped in favor of the previous verified one (docs/RESILIENCE.md).

    ``LGBM_TPU_COMPILE_CACHE=<dir>`` enables the persistent XLA
    compilation cache at engine init (docs/PERF.md): repeated trainings
    of same-shaped programs skip XLA entirely on the warm path.

    ``pause_control`` is the co-resident brownout seam
    (coresident/control.py, duck-typed): consulted at every chunk
    boundary.  ``consult(i)`` may sleep (throttle) and returns "run" or
    "pause"; ``chunk_cap()`` caps the macro-chunk so training yields the
    device between serving deadlines.  A "pause" verdict checkpoints the
    full state and raises ``TrainingPaused`` — docs/PERF.md co-residency.
    """
    from .utils.platform import enable_compile_cache
    enable_compile_cache(family="train")
    # active observability (docs/OBSERVABILITY.md): the env-gated SLO
    # sentry + metrics HTTP endpoint, and run context for any forensic
    # bundle this training might have to dump
    from .obs.http import maybe_start_from_env as _http_from_env
    from .obs.watchdog import maybe_start_from_env as _wd_from_env
    _wd_from_env()
    _http_from_env()
    params = dict(params)
    cfg = Config.from_params(params)
    if "num_iterations" in {Config.canonical_key(k) for k in params}:
        num_boost_round = cfg.num_iterations
    # the resolved round count is logged into the model file's parameters
    # section (reference train() writes params['num_iterations'])
    params["num_iterations"] = num_boost_round
    # reference: train() accepts a bare Dataset / name (engine.py:18)
    if isinstance(valid_sets, Dataset):
        valid_sets = [valid_sets]
    if isinstance(valid_names, str):
        valid_names = [valid_names]
    if fobj is not None:
        params["objective"] = "none"
    if feature_name != "auto":
        train_set._feature_name_param = feature_name
    if categorical_feature != "auto":
        train_set._categorical_feature_param = categorical_feature

    predictor = None
    init_score_offset = None
    if init_model is not None:
        predictor = init_model if isinstance(init_model, Booster) else \
            Booster(model_file=init_model, params=params)
    # raw features must be captured BEFORE construction possibly frees them
    # (reference predicts the init scores during lazy construction,
    # basic.py:840 _set_init_score_by_predictor — free_raw_data=True still
    # works for a fresh Dataset there)
    train_raw = train_set.raw_data if predictor is not None else None

    booster = Booster(params=params, train_set=train_set)

    # continued training: old model predictions become init scores
    # (reference: basic.py:840 _set_init_score_by_predictor)
    if predictor is not None:
        _apply_init_model(booster, predictor, train_set, raw=train_raw)

    train_in_valid = False
    if valid_sets:
        names_given = valid_names is not None
        valid_names = valid_names or [f"valid_{i}" for i in range(len(valid_sets))]
        added = []
        for vs, name in zip(valid_sets, valid_names):
            if vs is train_set:
                # reference: a valid set identical to the train set reports
                # the TRAINING metrics, under the passed name when one was
                # given (engine.py:175-187 is_valid_contain_train)
                train_in_valid = True
                if names_given:
                    booster.set_train_data_name(name)
                continue
            added.append((vs, vs.raw_data))
            booster.add_valid(vs, name)
        if predictor is not None:
            # valid scores must also start from the old model's predictions
            import jax.numpy as jnp
            K = booster.boosting.num_tree_per_iteration
            for i, (vs, raw) in enumerate(added):
                if raw is None:
                    raise ValueError(
                        "continued training requires free_raw_data=False "
                        "on validation Datasets")
                pred = predictor.predict(raw, raw_score=True)
                arr = (np.asarray(pred, np.float32).reshape(-1, K).T
                       if K > 1 else
                       np.asarray(pred, np.float32).reshape(1, -1))
                booster.boosting.valid_scores[i] = (
                    booster.boosting.valid_scores[i] + jnp.asarray(arr))

    cbs = set(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(
            early_stopping_rounds, cfg.first_metric_only,
            verbose=bool(verbose_eval)))
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        cbs.add(callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only,
            verbose=bool(verbose_eval)))
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        cbs.add(callback_mod.print_evaluation(verbose_eval))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))

    cbs_before = {cb for cb in cbs if getattr(cb, "before_iteration", False)}
    cbs_after = cbs - cbs_before
    cbs_before = sorted(cbs_before, key=lambda cb: getattr(cb, "order", 0))
    cbs_after = sorted(cbs_after, key=lambda cb: getattr(cb, "order", 0))

    start_iter = 0
    if resume_from is not None:
        from .resilience.checkpoint import (resolve_resume_point,
                                            restore_booster)
        ck = resolve_resume_point(resume_from)
        restore_booster(booster, ck)
        _restore_callback_states(cbs_before + cbs_after,
                                 ck.engine_state.get("callbacks", {}))
        start_iter = ck.iteration
        from .utils.log import log_info
        log_info(f"resume: restored iteration {start_iter} from "
                 f"{ck.path or resume_from}")
        # elastic resume (docs/RESILIENCE.md): the bundle records the
        # mesh it trained under; a DIFFERENT mesh here means a shrunk
        # (or regrown) world — restore_state already re-tiled the rows,
        # and the fresh planner events carry the re-planned per-shard
        # verdicts, so just make the transition visible
        old_cp = (ck.manifest or {}).get("collective_plan")
        new_cp = getattr(booster.boosting, "collective_plan", None)
        old_shape = (old_cp or {}).get("mesh_shape")
        new_shape = (list(new_cp.summary()["mesh_shape"])
                     if new_cp is not None else None)
        # only a bundle that RECORDED its mesh can evidence a transition
        # (a legacy manifest without collective_plan is not one)
        if old_cp is not None and old_shape != new_shape:
            log_info(
                f"elastic resume: bundle trained on mesh {old_shape}, "
                f"this world is {new_shape} — rows re-tiled, planner "
                "re-planned for the new per-shard shapes")

    ckpt_mgr = None
    if snapshot_freq > 0:
        from .resilience.checkpoint import CheckpointManager
        ckpt_mgr = CheckpointManager(f"{snapshot_out}.ckpt",
                                     keep_last=snapshot_keep)

    # eval cadence: the reference's OutputMetric loop evaluates every
    # ``metric_freq`` (alias output_freq) iterations; default 1 keeps the
    # historical evaluate-every-round behavior
    mf = max(int(cfg.metric_freq), 1)
    eval_possible = bool(
        (valid_sets and booster.boosting.valid_metrics)
        or feval is not None or cfg.is_provide_training_metric
        or train_in_valid)
    # early_stopping's init error moved up front: non-eval iterations no
    # longer reach the callback's init, so "no eval at all" must be
    # diagnosed here (dart disables early stopping inside the callback)
    is_dart = any(params.get(a, "") == "dart"
                  for a in ("boosting", "boosting_type", "boost"))
    has_early_stop = any(
        str(getattr(cb, "_resume_token", "")).startswith("early_stopping")
        for cb in cbs_after)
    if has_early_stop and not is_dart and not eval_possible \
            and num_boost_round > start_iter:
        raise ValueError(
            "For early stopping, at least one dataset and eval metric is "
            "required for evaluation")

    # fused macro-steps (boosting/macro.py): chunk the boosting loop into
    # lax.scan programs of c iterations each, chunks ending at the next
    # boundary that genuinely needs the host — eval (metric_freq),
    # snapshots, end of training.  Per-iteration host logic (DART, CEGB,
    # forced splits, custom fobj, non-schedule callbacks) forces c=1.
    from .boosting.macro import chunk_cap, pow2_chunk
    cap = chunk_cap()
    lr_cbs = [cb for cb in cbs_before
              if getattr(cb, "_lr_schedule", None) is not None]
    lr_lists_ok = all(
        not isinstance(cb._lr_schedule, list)
        or len(cb._lr_schedule) == num_boost_round for cb in lr_cbs)
    can_chunk = (cap > 1 and fobj is None
                 and booster.boosting.chunk_supported()
                 and len(lr_cbs) == len(cbs_before) and lr_lists_ok
                 and all(getattr(cb, "_chunk_safe", False)
                         for cb in cbs_after))

    def _lr_at(j):
        v = None
        for cb in lr_cbs:
            s = cb._lr_schedule
            v = s[j] if isinstance(s, list) else s(j)
        return float(v)

    evaluation_result_list = []
    i = start_iter
    t_loop0 = time.perf_counter()
    K_per_iter = booster.boosting.num_tree_per_iteration
    _flight.set_context(
        phase="train", num_boost_round=num_boost_round,
        start_iter=start_iter, objective=cfg.objective,
        num_leaves=cfg.num_leaves, rows=train_set.num_data)
    # the engine-loop heartbeat is stale-watched only WHILE the loop
    # runs (watchdog.py: a finished loop must never breach)
    _watchdog.watch_heartbeat(
        "engine.step", floor=_watchdog.config.trees_per_sec_floor)
    train_root = _span("engine.train", start_iter=start_iter,
                       num_boost_round=num_boost_round)
    train_root.__enter__()
    try:
        while i < num_boost_round:
            if pause_control is not None \
                    and pause_control.consult(i) == "pause":
                # evict the full training state to a bundle BEFORE
                # yielding the device: the resumed run is byte-identical
                mgr = ckpt_mgr
                if mgr is None:
                    from .resilience.checkpoint import CheckpointManager
                    mgr = CheckpointManager(f"{snapshot_out}.ckpt",
                                            keep_last=max(snapshot_keep, 1))
                path = mgr.save(
                    booster, iteration=i,
                    engine_state={"callbacks": _collect_callback_states(
                        cbs_before + cbs_after)})
                _flight.note("engine.pause", i=i, bundle=str(path))
                raise TrainingPaused(i, path)
            c = 1
            if can_chunk:
                d = num_boost_round - i
                if eval_possible:
                    d = min(d, mf - (i % mf))
                if ckpt_mgr is not None:
                    d = min(d, snapshot_freq - (i % snapshot_freq))
                c = pow2_chunk(d, cap)
                if pause_control is not None:
                    # brownout throttle: the negotiated cap shrinks the
                    # macro-chunk so the host regains control (and the
                    # batcher its deadline) sooner
                    c = pow2_chunk(c, max(int(pause_control.chunk_cap()),
                                          1))
            t_step0 = time.perf_counter()
            if c > 1:
                lrs = ([_lr_at(j) for j in range(i, i + c)] if lr_cbs else None)
                with _span("engine.step", i=i, c=c):
                    finished = booster.update_chunk(c, lrs)
                if lrs is not None:
                    # replicate the last reset_parameter side effects so the
                    # post-chunk state matches per-iteration training
                    booster.reset_parameter({"learning_rate": lrs[-1]})
                    params["learning_rate"] = lrs[-1]
                i += c
            else:
                for cb in cbs_before:
                    cb(callback_mod.CallbackEnv(booster, params, i, 0,
                                                num_boost_round, None))
                with _span("engine.step", i=i, c=1):
                    finished = booster.update(fobj=fobj)
                i += 1
            # step boundary: flight ring + live-rate gauges + heartbeat
            # (cheap host-side accounting — no device work, no numerics)
            step_s = time.perf_counter() - t_step0
            _flight.note("engine.step", i=i - c, c=c,
                         dur_us=step_s * 1e6)
            _flight.sample_metrics()
            _obs_registry.gauge("train_iter_seconds").set(
                round(step_s / max(c, 1), 6))
            live = (i - start_iter) * K_per_iter / max(
                time.perf_counter() - t_loop0, 1e-9)
            _obs_registry.gauge("train_trees_per_sec_live").set(
                round(live, 3))
            _watchdog.beat("engine.step", count=i * K_per_iter)
            j = i - 1        # last iteration trained this turn
            evaluation_result_list = []
            if eval_possible and (j + 1) % mf == 0:
                with _span("engine.eval", iteration=j):
                    if cfg.is_provide_training_metric or train_in_valid:
                        evaluation_result_list.extend(booster.eval_train(feval))
                    evaluation_result_list.extend(booster.eval_valid(feval))
                # pod telemetry at the eval boundary (obs/aggregate.py):
                # a no-op unless a pod transport is registered
                from .obs.aggregate import maybe_gather_at_eval
                maybe_gather_at_eval()
            early_stopped = False
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(booster, params, j, 0,
                                                num_boost_round, evaluation_result_list))
            except callback_mod.EarlyStopException as e:
                booster.best_iteration = e.best_iteration + 1
                for item in e.best_score:
                    booster.best_score.setdefault(item[0], collections.OrderedDict())
                    booster.best_score[item[0]][item[1]] = item[2]
                early_stopped = True
            # snapshot even on the iteration that triggered early stop
            # (reference: GBDT::Train reaches the snapshot write, gbdt.cpp:259-263)
            if ckpt_mgr is not None and (j + 1) % snapshot_freq == 0:
                ckpt_mgr.save(
                    booster, iteration=j + 1,
                    engine_state={"callbacks": _collect_callback_states(
                        cbs_before + cbs_after)})
            if early_stopped or finished:
                break
    except TrainingPaused:
        # a brownout pause is an ORDERED yield, not a failure: no
        # forensic dump (the scheduler journals the pause/resume spans)
        train_root.set(paused=True)
        raise
    except BaseException as e:
        train_root.set(error=type(e).__name__)
        # unhandled engine-loop failure: dump the forensic bundle (ring
        # + metrics + fingerprint) BEFORE the raise unwinds the process
        _flight.on_exception("engine.train", e)
        raise
    finally:
        train_root.__exit__(None, None, None)
        _watchdog.unwatch("engine.step")
    # training-loop instruments on the unified process registry
    # (docs/OBSERVABILITY.md): cheap host-side gauges, no device work
    wall = time.perf_counter() - t_loop0
    trained = i - start_iter
    if trained > 0:
        _obs_registry.counter("train_iterations_total").inc(trained)
        if wall > 0:
            _obs_registry.gauge("train_trees_per_sec").set(round(
                trained * booster.boosting.num_tree_per_iteration / wall, 3))
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
        for item in evaluation_result_list:
            booster.best_score.setdefault(item[0], collections.OrderedDict())
            booster.best_score[item[0]][item[1]] = item[2]
    return booster


def _collect_callback_states(cbs) -> dict:
    """Resumable-callback state, keyed by each callback's ``_resume_token``
    (early_stopping / record_evaluation attach one; see callback.py)."""
    out = {}
    for cb in cbs:
        tok = getattr(cb, "_resume_token", None)
        if tok is not None and hasattr(cb, "get_state"):
            out[tok] = cb.get_state()
    return out


def _restore_callback_states(cbs, states: dict) -> None:
    for cb in cbs:
        tok = getattr(cb, "_resume_token", None)
        if tok is not None and tok in states and hasattr(cb, "set_state"):
            cb.set_state(states[tok])


class InitModelCompatibilityError(ValueError):
    """The ``init_model`` cannot continue training on this train set —
    raised by name at ``train()`` entry (feature count, class count, or
    bin-mapper layout mismatch) instead of a shape failure mid-boost."""


def _validate_init_model(booster: Booster, predictor: Booster,
                         train_set: Dataset) -> None:
    """Continued training runs the old model's trees against the NEW
    training matrix; every mismatch that would otherwise surface as an
    opaque jit shape error (or silently wrong scores) is checked here.
    Covers the cross-load path too: a predictor loaded from stock
    LightGBM model text carries its feature count and class count in
    the header."""
    f_model = predictor.num_features()
    f_train = train_set.num_total_features
    if f_model != f_train:
        raise InitModelCompatibilityError(
            f"init_model was trained on {f_model} features but the "
            f"training data has {f_train}; continued training requires "
            "the same feature layout")
    k_model = max(predictor.num_tree_per_iteration, 1)
    k_train = max(booster.boosting.num_tree_per_iteration, 1)
    if k_model != k_train:
        raise InitModelCompatibilityError(
            f"init_model has {k_model} tree(s) per iteration but this "
            f"training is configured for {k_train} (num_class / "
            "objective mismatch); continued training cannot mix them")
    # an in-process predictor that retains its training Dataset also
    # pins a bin grid.  Continued training itself is grid-agnostic (the
    # old trees carry REAL thresholds, so init scores are exact on any
    # binning — the stock cross-load path relies on that), but a
    # production refresh is supposed to bin fresh rows on the DEPLOYED
    # grid (Dataset(reference=...) / lifecycle.fresh_dataset): warn by
    # name when the grids differ so a silent re-binning of the world is
    # at least a visible decision.  Shared-identity mappers (the
    # reference= path) short-circuit without comparing content.
    pts = getattr(predictor, "train_set", None)
    if pts is not None and getattr(pts, "constructed", False) \
            and train_set.bin_mappers and pts.bin_mappers \
            and pts.bin_mappers is not train_set.bin_mappers:
        same = all(a.to_dict() == b.to_dict()
                   for a, b in zip(pts.bin_mappers, train_set.bin_mappers))
        if not same:
            from .utils.log import log_warning
            log_warning(
                "continued training: the new train set's bin mappers "
                "differ from the init model's training grid — init "
                "scores stay exact (trees hold real thresholds), but "
                "fresh histograms live on a DIFFERENT grid; bin "
                "against the deployed Dataset (Dataset(reference=...) "
                "/ lifecycle.fresh_dataset) to keep one grid")


def _apply_init_model(booster: Booster, predictor: Booster, train_set: Dataset,
                      raw=None):
    _validate_init_model(booster, predictor, train_set)
    # streamed refresh (lifecycle/refresh.py): the deployed model's raw
    # scores were computed chunk-by-chunk at push time — the dataset
    # never kept raw features to re-predict from
    pre = getattr(train_set, "_init_model_raw_scores", None)
    if pre is not None:
        raw = np.asarray(pre, np.float64)
    else:
        raw = predictor.predict(raw if raw is not None
                                else _recover_raw(train_set),
                                raw_score=True)
    K = booster.boosting.num_tree_per_iteration
    import jax.numpy as jnp
    n = train_set.num_data
    isc = np.asarray(raw, np.float32).reshape(-1, K).T if K > 1 else \
        np.asarray(raw, np.float32).reshape(1, n)
    n_pad = booster.boosting._n_pad
    if n_pad > n:
        isc = np.pad(isc, ((0, 0), (0, n_pad - n)))
    booster.boosting.train_score = booster.boosting.train_score + jnp.asarray(isc)
    booster.boosting._init_score_added = True
    booster.boosting.models = list(predictor.models)
    booster.boosting.iter = len(predictor.models) // K
    # continued-training bookkeeping (reference: num_init_iteration_,
    # gbdt.cpp LoadModelFromString): DART must only drop this-run trees
    booster.boosting.num_init_iteration = len(predictor.models) // K


def _recover_raw(train_set: Dataset):
    if train_set.raw_data is not None:
        return train_set.raw_data
    raise ValueError("continued training requires free_raw_data=False on the "
                     "training Dataset")


class CVBooster:
    """reference: engine.py CVBooster."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster):
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: dict,
                  seed: int, stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data
    if folds is not None:
        if not hasattr(folds, "__iter__") and hasattr(folds, "split"):
            group = None
            if full_data.metadata.query_boundaries is not None:
                group = np.diff(full_data.metadata.query_boundaries)
            if group is not None:
                # sklearn splitters take PER-ROW group ids (reference:
                # engine.py:306 np.repeat over the group sizes)
                group = np.repeat(np.arange(len(group)), group)
            folds = folds.split(X=np.empty(num_data),
                                y=full_data.get_label(), groups=group)
        return list(folds)
    rng = np.random.RandomState(seed)
    qb = full_data.metadata.query_boundaries
    if qb is not None:
        # ranking: split whole queries across folds (reference: engine.py:301
        # GroupKFold over the flattened group array); rows of each query stay
        # contiguous and in order, as Dataset.subset() requires
        nq = len(qb) - 1
        if nfold > nq:
            raise ValueError(
                f"nfold={nfold} exceeds the number of query groups ({nq})")
        try:
            # reference: the default ranking split IS sklearn's GroupKFold
            # over per-row group ids (engine.py:301-306) — deterministic,
            # so cv(folds=GroupKFold(n)) gives identical folds
            from sklearn.model_selection import GroupKFold
            flat = np.repeat(np.arange(nq), np.diff(qb))
            return list(GroupKFold(n_splits=nfold).split(
                X=np.empty(num_data), groups=flat))
        except ImportError:
            pass
        q_idx = np.arange(nq)
        if shuffle:
            rng.shuffle(q_idx)
        q_chunks = np.array_split(q_idx, nfold)

        def rows(qs):
            qs = np.sort(qs)
            return np.concatenate([np.arange(qb[q], qb[q + 1]) for q in qs])

        return [(rows(np.concatenate([c for j, c in enumerate(q_chunks) if j != i])),
                 rows(q_chunks[i])) for i in range(nfold)]
    if stratified:
        from sklearn.model_selection import StratifiedKFold
        skf = StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                              random_state=seed if shuffle else None)
        return list(skf.split(np.empty(num_data), full_data.get_label()))
    idx = np.arange(num_data)
    if shuffle:
        rng.shuffle(idx)
    chunks = np.array_split(idx, nfold)
    return [(np.concatenate([c for j, c in enumerate(chunks) if j != i]), chunks[i])
            for i in range(nfold)]


def cv(params: dict, train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv: bool = True, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False,
       fused: bool = False) -> Dict[str, List[float]]:
    """reference: engine.py:375.

    ``fused=True`` batches the folds' per-round training steps along a
    model axis (lightgbm_tpu/multi/): every fold advances one iteration
    in ONE vmapped device dispatch instead of nfold sequential programs.
    The results dict is IDENTICAL — same keys, same mean/stdv layout,
    bit-for-bit the same values as the serial loop (tests/test_multi.py
    pins it) — because both paths run the same c=1 chunk program per
    fold; configs with per-iteration host logic (or a custom ``fobj``)
    fall back to serial stepping with a logged warning.
    """
    from .utils.platform import enable_compile_cache
    enable_compile_cache(family="train")
    params = dict(params)
    if fobj is not None:
        # custom objective: no built-in objective, hence no default metric
        # (reference cv sets objective to none, engine.py:485)
        params["objective"] = "none"
    if metrics is not None:
        # the metrics ARG overwrites every metric alias in params
        # (reference cv pops all _ConfigAliases 'metric' keys first)
        for k in [k for k in params if Config.canonical_key(k) == "metric"]:
            params.pop(k)
        params["metric"] = metrics
    cfg = Config.from_params(params)
    if cfg.objective in ("binary",) or cfg.objective.startswith("multiclass"):
        pass
    else:
        stratified = False

    folds_idx = _make_n_folds(train_set, folds, nfold, params, seed,
                              stratified, shuffle)
    cvbooster = CVBooster()
    results = collections.defaultdict(list)

    boosters = []
    for (tr_idx, te_idx) in folds_idx:
        tr = train_set.subset(tr_idx, params)
        te = train_set.subset(te_idx, params)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, dict(params))
        bst = Booster(params=params, train_set=tr)
        bst.add_valid(te, "valid")
        boosters.append(bst)
        cvbooster._append(bst)

    cbs = set(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds,
                                            cfg.first_metric_only, verbose=False))
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.add(callback_mod.print_evaluation(verbose_eval, show_stdv))
    cbs = sorted(cbs, key=lambda cb: getattr(cb, "order", 0))

    from .multi.driver import CVStepper
    stepper = CVStepper(boosters, fused, fobj)
    for i in range(num_boost_round):
        agg: Dict[str, List[float]] = collections.defaultdict(list)
        # advance EVERY fold first (batched across folds when fused),
        # then evaluate — folds are independent, so the reordering vs
        # the reference's update-then-eval-per-fold changes nothing
        stepper.step()
        for bst in boosters:
            # reference cv names the train split 'train' (engine.py:353)
            res = ([("train", mn, v, h)
                    for (_, mn, v, h) in bst.eval_train(feval)]
                   if eval_train_metric else []) + bst.eval_valid(feval)
            for (dname, mname, val, hib) in res:
                agg[(dname if eval_train_metric else "valid", mname, hib)].append(val)
        evaluation_result_list = [
            ("cv_agg", f"{d} {m}" if eval_train_metric else m,
             float(np.mean(v)), h, float(np.std(v)))
            for (d, m, h), v in agg.items()]
        for (_, m, mean, _, std) in evaluation_result_list:
            results[m + "-mean"].append(mean)
            results[m + "-stdv"].append(std)
        try:
            for cb in cbs:
                cb(callback_mod.CallbackEnv(cvbooster, params, i, 0,
                                            num_boost_round, evaluation_result_list))
        except callback_mod.EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for k in results:
                results[k] = results[k][:cvbooster.best_iteration]
            break
    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out


def serve(model, config=None, **overrides):
    """Construct a serving.Server from a Booster or a model-file path.

    The module-level twin of ``Booster.serve`` (docs/SERVING.md) so a
    deployment can go file -> server in one call::

        server = lgb.serve("model.txt", max_batch_rows=512)
    """
    from .serving import Server
    return Server(Server._as_booster(model), config=config, **overrides)
