"""LightGBM-TPU: a TPU-native gradient boosting framework.

A from-scratch re-design of LightGBM (reference: Crissman/LightGBM v2.3.2)
for TPU hardware: the binned feature matrix lives in HBM, per-leaf
grad/hess histograms and the split-gain scan are fused XLA programs on the
MXU/VPU, and the distributed tree learners run over `jax.lax.psum`-style
collectives on the ICI mesh instead of sockets/MPI.

Public API mirrors the reference Python package (lightgbm):
Dataset, Booster, train, cv, sklearn-style estimators, callbacks, plotting.
"""

from . import compat  # noqa: F401  (optional-dependency flags)
from .basic import Booster, LightGBMError
from .callback import (EarlyStopException, early_stopping, print_evaluation,
                       record_evaluation, reset_parameter)
from .config import Config
from .dataset import Dataset
from . import serving  # noqa: F401  (in-process inference server)
from . import fleet  # noqa: F401  (multi-model serving fleet)
from . import lifecycle  # noqa: F401  (guarded model lifecycle)
from .engine import CVBooster, InitModelCompatibilityError, cv, serve, train
from .fleet import Fleet, PodFleet
from .lifecycle import LifecycleController
from . import multi  # noqa: F401  (batched multi-booster training)
from .multi import expand_param_grid, train_many
from . import coresident  # noqa: F401  (co-resident train+serve)

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "Config", "LightGBMError", "train", "cv",
    "CVBooster", "early_stopping", "print_evaluation", "record_evaluation",
    "reset_parameter", "EarlyStopException", "serve", "serving",
    "fleet", "Fleet", "PodFleet", "lifecycle", "LifecycleController",
    "InitModelCompatibilityError", "multi", "train_many",
    "expand_param_grid", "coresident",
]

try:  # sklearn API is optional at import time
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,  # noqa: F401
                          LGBMRegressor)
    __all__ += ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass

try:
    from .plotting import (create_tree_digraph,  # noqa: F401
                           plot_importance, plot_metric,
                           plot_split_value_histogram, plot_tree)
    __all__ += ["create_tree_digraph", "plot_importance", "plot_metric",
                "plot_tree", "plot_split_value_histogram"]
except ImportError:  # pragma: no cover
    pass
