"""Production serving fleet: multi-model registry with planner-driven
shared-HBM eviction, AOT cold start, and opt-in low-precision inference
(docs/SERVING.md fleet section).

Quick start::

    fleet = lightgbm_tpu.Fleet(max_batch_rows=512)
    fleet.add_model("ranker", "ranker.txt", weight=3.0,
                    deadline_class="interactive")
    fleet.add_model("scorer", booster, precision="bf16",
                    accuracy_budget=1e-2)
    scores = fleet.predict("ranker", X)        # or .submit() -> Future
    fleet.export_aot()                         # compile-free replicas
    print(fleet.prometheus_text())             # model="..."-labelled
    fleet.close()

Pod scale (docs/SERVING.md multi-device section; docs/RESILIENCE.md
failover section)::

    pod = lightgbm_tpu.PodFleet(devices=4)
    pod.add_model("ranker", booster, weight=3.0,
                  deadline_class="interactive")
    scores = pod.predict("ranker", X)   # health-routed, hedged, replicated
    pod.kill_device(2)                  # a replan, not an outage

Module map: ``registry`` (Fleet front door: weighted admission, deadline
classes, residency replans), ``topology`` (multi-device placement
planner: replicate hot models, partition the cold tail), ``router``
(PodFleet: health-scored routing, hedged retries, brownout degradation,
device-loss failover), ``aot`` (jax.export serialize/restore of
bucket programs under LGBM_TPU_COMPILE_CACHE/serving), ``lowprec``
(bf16/int8 forest quantization + the accuracy-budget measurement).
The single-model building blocks stay in ``lightgbm_tpu.serving``.
"""

from .aot import AOTStore, aot_dir_from_env
from .lowprec import measure_accuracy_delta, quantize_forest
from .registry import (DEFAULT_DEADLINE_CLASSES, Fleet, FleetConfig,
                       FleetEntry)
from .router import PodFleet, RouterConfig
from .topology import (DeviceSpec, TopologyPlan, plan_devices,
                       plan_topology)

__all__ = [
    "Fleet", "FleetConfig", "FleetEntry", "DEFAULT_DEADLINE_CLASSES",
    "PodFleet", "RouterConfig", "DeviceSpec", "TopologyPlan",
    "plan_devices", "plan_topology",
    "AOTStore", "aot_dir_from_env", "quantize_forest",
    "measure_accuracy_delta",
]
