"""Opt-in low-precision inference: quantize a forest at hot-swap time.

The fixed-point GBDT accelerator literature ("Booster: An Accelerator
for Gradient Boosting Decision Trees", arXiv 2011.02022) shows tree
THRESHOLDS and LEAF VALUES tolerate aggressive narrowing: routing only
needs enough threshold precision to keep rows on the same side of each
split, and leaf sums average out rounding.  This module does the model
surgery: ``quantize_forest`` rounds a ``StackedForest``'s numeric
thresholds and leaf values onto a bf16 or per-tree-int8 grid, producing
a NEW forest the serving registry treats like any other model —
distinct digest, its own compiled programs, host path and device path
bit-identical to each other (every grid value is exactly
f32-representable, so DeviceForest's f32 round-down is the identity).

What low precision buys the fleet: the device threshold array shrinks
2x (bf16) / 4x (int8 codes + one f32 scale per tree), and the leaf
array never uploads at all (serving gathers leaves on the host), so the
shared-HBM residency election (ops/planner.plan_fleet) can keep more
models resident.  What it costs: raw scores drift from the
full-precision model — which is why the serving registry measures the
drift on a probe batch at admission/swap time against a caller-declared
``accuracy_budget`` and QUARANTINES the model when it exceeds it
(serving/registry.py, riding the PR 2 probe-batch machinery).  Raw-score
bit-parity with ``Booster.predict(raw_score=True)`` remains the DEFAULT:
nothing here runs unless a model opts in with ``precision=``.

Deliberately a leaf module: numpy + ml_dtypes only, no jax, no serving
imports — predict.py and serving/registry.py import it lazily.
"""

from __future__ import annotations

import copy

import numpy as np

PRECISIONS = ("f32", "bf16", "int8")


def bf16_round(a: np.ndarray) -> np.ndarray:
    """Round float64 values to the nearest bfloat16, returned as float64
    (every bf16 value is exactly f32- and f64-representable)."""
    import ml_dtypes
    return a.astype(ml_dtypes.bfloat16).astype(np.float64)


def int8_rows(a: np.ndarray, skip=None):
    """Per-row symmetric int8 quantization of a [T, N] float64 array.

    Returns ``(q, scale, deq)``: int8 codes, per-row f32 scale, and the
    dequantized float64 grid ``f32(q * scale)``.  Entries where ``skip``
    is True (non-finite padding, categorical bitset indices) get code 0
    and keep their original value in ``deq``.  The scale and the
    dequantization are computed in float32 so a device kernel doing
    ``q.astype(f32) * scale`` reproduces ``deq`` bit-exactly.
    """
    a = np.asarray(a, np.float64)
    if skip is None:
        skip = ~np.isfinite(a)
    else:
        skip = np.asarray(skip, bool) | ~np.isfinite(a)
    live = np.where(skip, 0.0, a)
    mag = np.abs(live).max(axis=1)                        # [T]
    scale = np.where(mag > 0, mag, 1.0).astype(np.float32) / np.float32(127)
    q = np.clip(np.round(live / scale[:, None].astype(np.float64)),
                -127, 127).astype(np.int8)
    q = np.where(skip, np.int8(0), q)
    deq = (q.astype(np.float32) * scale[:, None]).astype(np.float64)
    deq = np.where(skip, a, deq)
    return q, scale, deq


def quantize_forest(forest, precision: str):
    """Shallow-copy ``forest`` with thresholds + leaf values moved onto
    the ``precision`` grid ("bf16" | "int8").

    Categorical split nodes keep their thresholds verbatim — there the
    "threshold" is a bitset INDEX (predict.py), and rounding an index
    corrupts routing rather than merely perturbing it.  Non-finite
    entries (the +inf padding of unused node slots) are preserved too.
    int8 forests additionally carry ``threshold_q`` / ``threshold_scale``
    / ``threshold_skip`` so ``DeviceForest(precision="int8")`` can store
    the codes on device and dequantize in-kernel to the exact same grid.
    """
    if precision == "f32":
        return forest
    if precision not in PRECISIONS:
        raise ValueError(f"unknown serving precision {precision!r}; "
                         f"expected one of {PRECISIONS}")
    qf = copy.copy(forest)
    thr_skip = ~np.isfinite(forest.threshold) | forest.is_cat
    if precision == "bf16":
        qf.threshold = np.where(thr_skip, forest.threshold,
                                bf16_round(forest.threshold))
        qf.leaf_value = bf16_round(forest.leaf_value)
    else:
        q, scale, deq = int8_rows(forest.threshold, skip=thr_skip)
        qf.threshold = deq
        qf.threshold_q = q
        qf.threshold_scale = scale
        qf.threshold_skip = thr_skip
        _, _, qf.leaf_value = int8_rows(forest.leaf_value)
    return qf


def forest_precision_bytes(forest, precision: str) -> dict:
    """Rough host-side accounting of what the grid move saves on device:
    {threshold_bytes, leaf_bytes} at the given precision vs f32 — the
    planner's ``predict_forest_bytes`` is the authoritative (padded)
    model; this is the human-readable smoke/bench twin."""
    T, I = forest.threshold.shape
    L = forest.leaf_value.shape[1]
    thr_item = {"f32": 4, "bf16": 2, "int8": 1}[precision]
    return {
        "threshold_bytes": T * I * thr_item + (T * 4 if precision == "int8"
                                               else 0),
        "threshold_bytes_f32": T * I * 4,
        # low-precision serving gathers leaves on the host: no device copy
        "leaf_bytes": 0 if precision != "f32" else T * L * 4,
        "leaf_bytes_f32": T * L * 4,
    }


def measure_accuracy_delta(full_forest, lp_forest, X: np.ndarray,
                           num_class: int = 1) -> float:
    """max |raw_lp - raw_full| over the probe rows ``X`` — the number the
    serving registry compares against ``accuracy_budget`` and journals
    as ``lowprec_accuracy_delta``.  Uses the host path on both forests:
    for f32-precision probes it is bit-identical to what the device
    serves, and it needs no compile."""
    X = np.asarray(X, np.float64)
    ref = full_forest.predict_raw(X, num_class=num_class)
    got = lp_forest.predict_raw(X, num_class=num_class)
    return float(np.max(np.abs(got - ref))) if ref.size else 0.0
