"""Multi-device serving topology: replicate hot models for throughput,
partition the cold tail for capacity.

The PR 9 fleet packs N models into ONE device's HBM; the north-star
traffic ("millions of users", ROADMAP item 5) needs N devices — and the
moment serving spans devices the planning question changes shape: not
"which models stay resident" but "which DEVICE hosts which REPLICA of
which model".  ``plan_topology`` grows ``ops.planner.plan_fleet`` into
that placement planner:

* **devices** come from the PR 10 mesh-plan seam
  (``parallel.network.mesh_plan``): the same priority order that
  partitions training shards into DCN slices assigns each serving
  device a slice id, so the router (fleet/router.py) knows which
  replica pairs are one ICI hop apart and which cost a DCN crossing —
  PV-Tree's elect-before-you-ship rule (arXiv 1611.01276) applied to
  request routing: keep traffic device-local, spill across the slow
  tier only when a replica is sick or saturated.
* **placement** is a two-pass greedy election charged with the SAME
  per-replica cost model the single-device residency election uses
  (``ops.planner.fleet_replica_bytes`` — the loads can never disagree
  with the verdicts).  Pass 1 partitions: every model, hottest first
  (``weight / (1 + age_s)``), gets its PRIMARY replica on the
  least-loaded device that admits it.  Pass 2 replicates: while
  devices have room, the model with the highest *marginal* heat
  (priority / current replica count) gains a replica on a device not
  yet hosting it — hot models spread across the pod first, the cold
  tail stays singly-placed for capacity, and with ample budget every
  model lands everywhere.
* **per-device residency** is then exactly ``plan_fleet`` run on each
  device's assigned replicas against its own budget — eviction,
  bucket election, host-path fallback all carry over verbatim.

Replicas serve BIT-IDENTICAL raw scores (same forest, same program
construction), which is the load-bearing fact of the whole tier: the
router's hedged retries and failover re-dispatch are correctness-free
by construction, so availability engineering never risks wrong answers.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

from ..ops.planner import (HEADROOM, FleetPlan, fleet_replica_bytes,
                           hbm_limit_bytes, plan_fleet)


class DeviceSpec(NamedTuple):
    """One serving device of the pod: its id, the DCN slice it lives in
    (same slice = ICI-local, different slice = a DCN crossing), and its
    HBM budget (None = the measured/env device limit)."""

    device_id: int
    slice_id: int
    hbm_budget_bytes: Optional[int] = None


def plan_devices(n_devices: int,
                 budget_bytes_per_device: Optional[int] = None
                 ) -> Tuple[DeviceSpec, ...]:
    """Describe ``n_devices`` serving devices through the mesh-plan seam
    (``parallel.network.mesh_plan``): device ``i`` belongs to slice
    ``i // devices_per_slice``, exactly the row-major device order the
    training mesh uses, so a serving pod and a training pod agree about
    which devices share ICI."""
    from ..parallel.network import mesh_plan
    n = max(int(n_devices), 1)
    mp = mesh_plan(n)
    per = max(int(mp.devices_per_slice), 1) if mp.hybrid else n
    return tuple(DeviceSpec(i, i // per, budget_bytes_per_device)
                 for i in range(n))


class ReplicaPlacement(NamedTuple):
    """One (model, device) replica assignment."""

    name: str
    device_id: int
    primary: bool               # the model's home replica (pass 1)


class TopologyPlan(NamedTuple):
    """Placement verdict for a multi-device serving fleet.

    ``feasible`` means every model won at least one replica; an
    unplaced model is NOT unservable — the router degrades it to the
    bit-identical host path — but it is a capacity signal the operator
    should see.  ``device_plans`` carries each device's own
    ``FleetPlan`` residency election over exactly the replicas placed
    there."""

    devices: Tuple[DeviceSpec, ...]
    placements: Tuple[ReplicaPlacement, ...]
    replicas: Dict[str, Tuple[int, ...]]    # name -> device ids, primary 1st
    device_plans: Dict[int, FleetPlan]      # device_id -> residency plan
    device_load_bytes: Dict[int, int]       # placed replica bytes
    budget_bytes: int                       # per-device budget (headroomed)
    unplaced: Tuple[str, ...]
    feasible: bool

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / flight fingerprints."""
        return {
            "devices": [
                {"device": d.device_id, "slice": d.slice_id,
                 "load_bytes": self.device_load_bytes.get(d.device_id, 0),
                 "models": sorted(p.name for p in self.placements
                                  if p.device_id == d.device_id)}
                for d in self.devices
            ],
            "replicas": {n: list(ids)
                         for n, ids in sorted(self.replicas.items())},
            "budget_bytes": self.budget_bytes,
            "unplaced": list(self.unplaced),
            "feasible": self.feasible,
        }


def plan_topology(models, devices, accel: Optional[bool] = None,
                  max_replicas: Optional[int] = None,
                  ledgers: Optional[Dict[int, "ResidencyLedger"]] = None
                  ) -> TopologyPlan:
    """Elect replica placement for ``models`` (``FleetModelShape`` list)
    over ``devices`` (``DeviceSpec`` list) — module docstring for the
    election; deterministic for identical inputs (ties break on the
    lower device id / earlier model).

    ``ledgers`` maps device ids to co-residency ledgers
    (``ops.planner.ResidencyLedger``): a device with a ledger is planned
    against the ledger's REMAINING budget (bytes an in-flight training
    refresh has leased are not available for replica placement), and its
    per-device residency election runs through ``plan_fleet(ledger=)``
    so the two verdicts agree."""
    models = list(models)
    devices = tuple(sorted(devices, key=lambda d: d.device_id))
    if not devices:
        raise ValueError("plan_topology needs at least one device")
    cap = min(max_replicas or len(devices), len(devices))
    ledgers = ledgers or {}

    default_limit = None
    limits: Dict[int, int] = {}
    budgets: Dict[int, int] = {}
    for d in devices:
        lg = ledgers.get(d.device_id)
        if lg is not None:
            # the ledger already applied HEADROOM once; its remainder IS
            # the placement budget for this device
            limits[d.device_id] = int(lg.limit_bytes)
            budgets[d.device_id] = int(lg.available_bytes())
            continue
        limit = d.hbm_budget_bytes
        if limit is None:
            if default_limit is None:
                default_limit = hbm_limit_bytes()[0]
            limit = default_limit
        # plan_fleet applies HEADROOM to the RAW limit itself: hand it
        # the same limit (not budget/HEADROOM, whose int round-trip can
        # land a byte short) so the placement admission and the
        # per-device residency election can never disagree
        limits[d.device_id] = int(limit)
        budgets[d.device_id] = int(limit * HEADROOM)

    costs = {}          # name -> (admit_bytes, load_bytes)
    prio = {}
    for m in models:
        fb, prog = fleet_replica_bytes(m, accel)
        costs[m.name] = (fb + prog[min(prog)], fb + sum(prog.values()))
        prio[m.name] = m.weight / (1.0 + max(m.age_s, 0.0))

    load: Dict[int, int] = {d.device_id: 0 for d in devices}
    hosted: Dict[int, set] = {d.device_id: set() for d in devices}
    placements: List[ReplicaPlacement] = []
    replicas: Dict[str, List[int]] = {m.name: [] for m in models}

    def admit(name: str, primary: bool) -> bool:
        """Least-loaded device not hosting ``name`` that fits one more
        replica; False when none admits."""
        admit_b, load_b = costs[name]
        cands = [d.device_id for d in devices
                 if name not in hosted[d.device_id]
                 and load[d.device_id] + admit_b <= budgets[d.device_id]]
        if not cands:
            return False
        did = min(cands, key=lambda i: (load[i], i))
        load[did] += min(load_b, budgets[did] - load[did])
        hosted[did].add(name)
        placements.append(ReplicaPlacement(name, did, primary))
        replicas[name].append(did)
        return True

    # pass 1 — partition: primaries, hottest first
    order = sorted(range(len(models)),
                   key=lambda i: (-prio[models[i].name], i))
    unplaced = []
    for i in order:
        if not admit(models[i].name, primary=True):
            unplaced.append(models[i].name)

    # pass 2 — replicate by marginal heat until nothing more fits
    while True:
        cands = [(prio[m.name] / len(replicas[m.name]), -i, m.name)
                 for i, m in enumerate(models)
                 if 0 < len(replicas[m.name]) < cap]
        placed_one = False
        for _heat, _i, name in sorted(cands, reverse=True):
            if admit(name, primary=False):
                placed_one = True
                break
        if not placed_one:
            break

    shapes = {m.name: m for m in models}
    device_plans = {}
    for d in devices:
        placed = [shapes[p.name] for p in placements
                  if p.device_id == d.device_id]
        lg = ledgers.get(d.device_id)
        if lg is not None:
            device_plans[d.device_id] = plan_fleet(
                placed, accel=accel, ledger=lg)
        else:
            device_plans[d.device_id] = plan_fleet(
                placed, budget_bytes=limits[d.device_id], accel=accel)

    return TopologyPlan(
        devices=devices, placements=tuple(placements),
        replicas={n: tuple(ids) for n, ids in replicas.items()},
        device_plans=device_plans, device_load_bytes=dict(load),
        budget_bytes=max(budgets.values()),
        unplaced=tuple(unplaced), feasible=not unplaced)
