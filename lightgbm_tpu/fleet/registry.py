"""Multi-model serving fleet: one front door over N hot-swappable models
sharing a single device's HBM.

PR 1's ``Server`` is one model per instance; the ROADMAP fleet item asks
for the "millions of users" shape — many models behind one admission
policy, sharing the accelerator without OOMing it.  ``Fleet`` composes
the existing pieces instead of reinventing them: each named model gets
its OWN ``Server`` (bucket ladder, micro-batcher, program LRU, hot-swap
— every single-model invariant carries over verbatim), and the fleet
layers three policies on top:

* **Shared-HBM residency** (ops/planner.plan_fleet): the planner models
  every model's device-resident bytes (forest arrays + warmed bucket
  programs) against the measured HBM limit and elects which models stay
  device-resident; the rest are EVICTED — their device arrays and
  compiled programs released — and serve through the bit-identical host
  path until a replan readmits them.  Cold models degrade to host
  latency; nothing ever OOMs or stops serving.
* **Weighted admission / SLO-aware shedding**: one fleet-wide queue-row
  budget.  Under the budget every request is admitted; over it, a model
  is only admitted up to its weight's share — heavy traffic to one model
  sheds ITS overflow (typed ``QueueFull``), never its neighbors'
  protected share.  Deadline classes give each model a default deadline
  (the existing batcher already rejects expired work at pop time), so an
  "interactive" model's queue cannot silently grow unbounded latency.
* **AOT cold start** (fleet/aot.py): ``export_aot`` serializes every
  resident bucket program; a fresh replica pointed at the same store
  warms by DESERIALIZING — its first request runs with zero compile
  events.

Per-model observability rides the unified registry (obs/metrics.py)
with ``model="<name>"`` labels, so one Prometheus scrape shows the whole
fleet.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant
from ..obs.watchdog import beat as _beat
from ..ops.planner import FleetModelShape, FleetPlan, plan_fleet
from ..serving.errors import ModelNotFound, QueueFull, ServerClosed
from ..serving.metrics import MetricsRegistry
from ..serving.server import Server, ServingConfig

# deadline classes: per-model default deadline when a request names none
# (None = no deadline).  Values are milliseconds.
DEFAULT_DEADLINE_CLASSES = {
    "interactive": 50.0,
    "standard": 250.0,
    "batch": None,
}


@dataclass
class FleetConfig:
    """Fleet-wide knobs; per-model Server knobs ride ``add_model``."""

    max_queue_rows: int = 1 << 16       # fleet-wide admission budget
    hbm_budget_bytes: Optional[int] = None   # None = planner-measured limit
    aot_dir: Optional[str] = None       # None = LGBM_TPU_COMPILE_CACHE/serving
    backend: str = "device"             # default per-model backend
    min_bucket_rows: int = 8            # default per-model ladder
    max_batch_rows: int = 1024
    batch_window_ms: float = 2.0
    max_programs: int = 64
    replan_every: int = 256             # admissions between auto replans
    deadline_classes: Dict[str, Optional[float]] = field(
        default_factory=lambda: dict(DEFAULT_DEADLINE_CLASSES))

    def __post_init__(self):
        if self.backend not in ("device", "host"):
            raise ValueError(f"unknown fleet backend {self.backend!r}")


class FleetEntry:
    """One registered model: its server plus the fleet-side policy state."""

    __slots__ = ("name", "server", "weight", "deadline_class", "precision",
                 "resident", "resident_buckets", "last_used")

    def __init__(self, name: str, server: Server, weight: float,
                 deadline_class: str, precision: str):
        self.name = name
        self.server = server
        self.weight = weight
        self.deadline_class = deadline_class
        self.precision = precision
        self.resident = server.config.backend == "device"
        self.resident_buckets = tuple(server.ladder.buckets)
        self.last_used = time.monotonic()

    @property
    def model(self):
        return self.server.models.active

    def queued_rows(self) -> int:
        return self.server._batcher.queued_rows()


class Fleet:
    """Multi-model registry + planner-driven residency + weighted front
    door (module docstring; docs/SERVING.md fleet section)."""

    def __init__(self, config: Optional[FleetConfig] = None, **overrides):
        if config is None:
            config = FleetConfig(**overrides)
        elif overrides:
            raise ValueError("pass either config or keyword overrides")
        self.config = config
        self.metrics = MetricsRegistry()
        self._entries: Dict[str, FleetEntry] = {}   # guarded-by: _lock
        self._lock = threading.Lock()       # entry map + counters (cheap ops)
        self._replan_lock = threading.Lock()    # serializes plan application
        self._admissions = 0                        # guarded-by: _lock
        self._closed = False
        self._plan: Optional[FleetPlan] = None      # guarded-by: _lock
        self._obs_component = _obs_registry.attach_child(
            "fleet", self.metrics)

    # ------------------------------------------------------------ registry

    def models(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def entry(self, name: str) -> FleetEntry:
        with self._lock:
            e = self._entries.get(name)
        if e is None:
            raise ModelNotFound(
                f"fleet has no model {name!r}; registered: "
                f"{self.models()}")
        return e

    def add_model(self, name: str, booster_or_path, weight: float = 1.0,
                  deadline_class: str = "standard",
                  precision: str = "f32",
                  accuracy_budget: Optional[float] = None,
                  probe_X=None, replan: bool = True,
                  **server_overrides) -> FleetEntry:
        """Register ``booster_or_path`` under ``name`` and replan
        residency.

        ``precision`` opts the model into bf16/int8 serving held to
        ``accuracy_budget`` on a probe batch — a candidate over its
        budget raises ``LowPrecisionQuarantined`` and is NOT registered.
        ``weight`` scales both its admission share and its residency
        priority; ``deadline_class`` names its default deadline
        (config.deadline_classes)."""
        if self._closed:
            raise ServerClosed("fleet is shut down")
        if deadline_class not in self.config.deadline_classes:
            raise ValueError(
                f"unknown deadline class {deadline_class!r}; configured: "
                f"{sorted(self.config.deadline_classes)}")
        if weight <= 0:
            raise ValueError("model weight must be positive")
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered; use "
                                 "swap_model to replace it")
        cfg = dict(
            backend=self.config.backend,
            min_bucket_rows=self.config.min_bucket_rows,
            max_batch_rows=self.config.max_batch_rows,
            batch_window_ms=self.config.batch_window_ms,
            max_programs=self.config.max_programs,
            # each server gets the WHOLE fleet budget: the fleet-level
            # weighted check is the binding one under contention
            max_queue_rows=self.config.max_queue_rows,
            precision=precision, accuracy_budget=accuracy_budget,
            probe_X=probe_X, aot_dir=self.config.aot_dir)
        cfg.update(server_overrides)
        booster = Server._as_booster(booster_or_path)
        server = Server(booster, ServingConfig(**cfg))   # may quarantine
        entry = FleetEntry(name, server, weight, deadline_class, precision)
        with self._lock:
            if name in self._entries:       # lost a registration race
                server.close(drain=False)
                raise ValueError(f"model {name!r} already registered")
            self._entries[name] = entry
        m = self.metrics
        m.counter("fleet_models_added").inc()
        m.gauge("model_weight", labels={"model": name}).set(weight)
        m.gauge("model_digest", labels={"model": name}).set(
            entry.model.digest)
        m.gauge("model_precision", labels={"model": name}).set(precision)
        if entry.precision != "f32":
            m.gauge("lowprec_accuracy_delta", labels={"model": name}).set(
                server.metrics.gauge("lowprec_accuracy_delta").value)
        if replan:
            self.replan()
        return entry

    def remove_model(self, name: str, drain: bool = True,
                     timeout: Optional[float] = None) -> None:
        """Unregister ``name``: DRAIN it, then replan — never race a
        replan in flight.  ``replan`` applies residency under
        ``_replan_lock`` while reading each entry's server; closing one
        mid-apply would restore/drop device arrays on a dying server
        (and an eviction landing between the pop and the close could
        resurrect its programs).  Holding the same lock makes removal
        atomic with respect to plan application: a concurrent replan
        sees the entry either fully alive or fully gone.  ``timeout``
        bounds the batcher-thread join (the pod router passes one so a
        wedged-but-not-yet-dead device can never freeze a replan)."""
        e = self.entry(name)
        with self._replan_lock:
            with self._lock:
                self._entries.pop(name, None)
            e.server.close(drain=drain, timeout=timeout)
        self.metrics.counter("fleet_models_removed").inc()
        self.replan()

    def set_weight(self, name: str, weight: float) -> None:
        """Re-weight one fleet member (admission share + residency
        priority) and replan — the lifecycle canary ramp drives this at
        every step (lightgbm_tpu/lifecycle/)."""
        if weight <= 0:
            raise ValueError("model weight must be positive")
        e = self.entry(name)
        e.weight = float(weight)
        self.metrics.gauge("model_weight", labels={"model": name}).set(
            float(weight))
        self.replan()

    def swap_model(self, name: str, booster_or_path, **kw):
        """Hot-swap one fleet member (Server.swap_model semantics: warm,
        probe, quarantine, atomic flip) and replan residency for the new
        shape."""
        e = self.entry(name)
        out = e.server.swap_model(booster_or_path, **kw)
        self.metrics.gauge("model_digest", labels={"model": name}).set(
            e.model.digest)
        self.replan()
        return out

    # ------------------------------------------------------------- serving

    def _class_deadline(self, entry: FleetEntry) -> Optional[float]:
        return self.config.deadline_classes.get(entry.deadline_class)

    def _admit(self, entry: FleetEntry, n: int) -> None:
        """Weighted admission: under the fleet budget everyone is
        admitted; over it, a model may only occupy its weight's share of
        the queue — overflow traffic to one model sheds ITS requests
        (typed QueueFull), never a lighter model's protected share."""
        with self._lock:
            live = list(self._entries.values())
        total = sum(e.queued_rows() for e in live)
        cap = self.config.max_queue_rows
        if total + n <= cap:
            return
        wsum = sum(e.weight for e in live) or 1.0
        share = entry.weight / wsum * cap
        if entry.queued_rows() + n <= share:
            return
        self.metrics.counter("fleet_shed_total",
                             labels={"model": entry.name}).inc()
        raise QueueFull(
            f"fleet queue at {total} rows (cap {cap}); model "
            f"{entry.name!r} is over its weighted share of "
            f"{share:.0f} rows — shed")

    def submit(self, name: str, X, deadline_ms: Optional[float] = None):
        """Enqueue a predict request for model ``name``; returns the
        Future.  ``deadline_ms`` defaults to the model's deadline class;
        sheds with ``QueueFull`` when the model exceeds its weighted
        share of a contended fleet queue."""
        if self._closed:
            raise ServerClosed("fleet is shut down")
        entry = self.entry(name)
        entry.last_used = time.monotonic()
        X = np.asarray(X)
        n = X.shape[0] if X.ndim >= 2 else 1
        self._admit(entry, n)
        if deadline_ms is None:
            deadline_ms = self._class_deadline(entry)
        m = self.metrics
        m.counter("fleet_requests_total", labels={"model": name}).inc()
        _beat("fleet.submit")
        t0 = time.monotonic()
        fut = entry.server.submit(X, deadline_ms=deadline_ms)
        hist = m.histogram("request_latency_ms", labels={"model": name})

        def _record(f):
            try:
                if f.cancelled() or f.exception() is not None:
                    return
            except Exception:      # cancelled between the two checks
                return
            hist.observe((time.monotonic() - t0) * 1e3)

        fut.add_done_callback(_record)
        with self._lock:        # plain += from N submit threads loses
            self._admissions += 1      # updates and can skip the trigger
            due = (self.config.replan_every > 0
                   and self._admissions % self.config.replan_every == 0)
        if due:
            self.replan()
        return fut

    def predict(self, name: str, X, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous submit + wait (Server.predict semantics)."""
        fut = self.submit(name, X, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout)
        except FuturesTimeoutError:
            fut.cancel()
            raise

    # ----------------------------------------------------------- residency

    def _shapes(self) -> list:
        now = time.monotonic()
        shapes = []
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            f = e.model.forest
            shapes.append(FleetModelShape(
                name=e.name,
                num_trees=f.num_trees,
                nodes_dim=f.split_feature.shape[1],
                leaves_dim=f.leaf_value.shape[1],
                features=e.model.num_features,
                num_class=e.model.num_class,
                buckets=tuple(e.server.ladder.buckets),
                weight=e.weight,
                age_s=max(now - e.last_used, 0.0),
                precision=e.precision,
                cat_words=(f.cat_words.size if f.has_cat else 0)))
        return shapes

    def replan(self) -> FleetPlan:
        """Re-run the shared-HBM residency election and apply it: evict
        device arrays + compiled programs of models the plan demotes,
        restore models it readmits.  Cheap enough to call per-swap and
        every ``replan_every`` admissions."""
        plan = plan_fleet(self._shapes(),
                          budget_bytes=self.config.hbm_budget_bytes)
        # apply OUTSIDE self._lock: restore_device is a full device upload
        # and must not stall the submit path's admission check.  Programs
        # read the device pointer at call time, so flipping residency
        # mid-flight is safe; _replan_lock keeps two replans from
        # interleaving their drop/restore sequences.
        with self._replan_lock:
            for mp in plan.models:
                with self._lock:
                    e = self._entries.get(mp.name)
                if e is None or e.server.config.backend != "device":
                    continue
                am = e.model
                if mp.resident and am.device_forest is None:
                    am.restore_device()
                    e.server.programs.evict_model(am.digest)
                    self.metrics.counter(
                        "fleet_restores", labels={"model": mp.name}).inc()
                elif not mp.resident and am.device_forest is not None:
                    am.drop_device()
                    e.server.programs.evict_model(am.digest)
                    self.metrics.counter(
                        "fleet_evictions", labels={"model": mp.name}).inc()
                e.resident = mp.resident
                e.resident_buckets = mp.resident_buckets
                self.metrics.gauge(
                    "model_resident", labels={"model": mp.name}).set(
                    int(mp.resident))
            with self._lock:
                self._plan = plan
        m = self.metrics
        m.gauge("fleet_models").set(len(plan.models))
        m.gauge("fleet_resident_bytes").set(plan.total_resident_bytes)
        m.gauge("fleet_budget_bytes").set(plan.budget_bytes)
        m.gauge("fleet_evicted_models").set(len(plan.evicted))
        _instant("fleet.plan", **plan.summary())
        # the instant above also feeds the flight ring (trace.py tee);
        # the fingerprint additionally carries the CURRENT plan so a
        # bundle shows residency state even after the ring rolled over
        from ..obs.flight import global_flight
        global_flight.set_context(fleet_plan=plan.summary())
        return plan

    @property
    def plan(self) -> Optional[FleetPlan]:
        return self._plan

    def warm(self) -> int:
        """Pre-compile (or AOT-restore) every RESIDENT model's resident
        buckets so first requests pay no compile; returns buckets
        warmed."""
        n = 0
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if e.resident and e.resident_buckets:
                n += e.server.warm(e.resident_buckets)
            elif e.resident:
                n += e.server.warm()
        return n

    # ------------------------------------------------------------- AOT

    def export_aot(self, path: Optional[str] = None) -> int:
        """Serialize every device-resident model's resident bucket
        programs into the AOT store (fleet/aot.py) so a fresh replica
        cold-starts compile-free; returns entries written."""
        n = 0
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if e.model.device_forest is None:
                continue
            buckets = e.resident_buckets or tuple(e.server.ladder.buckets)
            n += e.server.export_aot(path=path, buckets=buckets)
        self.metrics.counter("fleet_aot_exports").inc(n)
        return n

    # ----------------------------------------------------------- lifecycle

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        if self._closed:
            return
        self._closed = True
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            e.server.close(drain=drain, timeout=timeout)
        _obs_registry.detach_child(self._obs_component)

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # ------------------------------------------------------------- metrics

    def metrics_dict(self) -> dict:
        """Fleet-level instruments plus every member server's snapshot
        under ``servers.<name>`` (each server's own layout unchanged)."""
        out = self.metrics.to_dict()
        with self._lock:
            entries = dict(self._entries)
        out["servers"] = {n: e.server.metrics_dict()
                          for n, e in sorted(entries.items())}
        return out

    def prometheus_text(self, prefix: str = "lgbt_fleet") -> str:
        """Fleet instruments (``model=\"name\"``-labelled) + per-server
        exposition under ``<prefix>_server_<name>``."""
        parts = [self.metrics.to_prometheus(prefix=prefix)]
        with self._lock:
            entries = dict(self._entries)
        for n, e in sorted(entries.items()):
            parts.append(e.server.prometheus_text(
                prefix=f"{prefix}_server_{n}"))
        return "".join(parts)
