"""AOT-serialized serving programs: compile-free cold start.

PR 5 gave training a persistent XLA compile cache behind
``LGBM_TPU_COMPILE_CACHE``; this module extends the same cache directory
to SERVING buckets.  ``AOTStore.export_device_forest`` serializes each
(model digest, bucket) routing program with ``jax.export`` — the traced,
lowered StableHLO with the forest arrays baked in as constants — into
``<cache>/serving/``; a fresh replica then builds its bucket programs by
DESERIALIZING instead of re-tracing, and the backend compile of the
restored module rides the persistent compile cache, so the replica's
first request pays neither a trace nor a fresh XLA compile.  The program
registry counts restored programs as ``aot_program_loads`` instead of
``compile_events`` — "first request with zero compile events" is the
cold-start acceptance bar (tools/fleet_smoke.py, tests/test_fleet.py).

Only the LEAF-ROUTING half of a serving program is exported (the
device-side ``DeviceForest._leaves``): the float64 leaf gather stays on
the host via the shared ``predict.gather_leaf_sum`` epilogue, which is
what keeps an AOT-restored replica bit-identical to the live-compiled
one.  Everything here fails SOFT: a corrupt, foreign-platform, or
version-skewed entry is a cache MISS (the program compiles normally),
never a serving failure.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ..utils.log import log_warning

AOT_VERSION = 1
_SUBDIR = "serving"


def aot_dir_from_env() -> Optional[str]:
    """``LGBM_TPU_COMPILE_CACHE=<dir>`` -> ``<dir>/serving``, or None
    when the persistent cache is disabled (same off-switch spellings as
    ``utils.platform.enable_compile_cache``)."""
    d = os.environ.get("LGBM_TPU_COMPILE_CACHE", "").strip()
    if not d or d.lower() in ("0", "off", "none"):
        return None
    return os.path.join(d, _SUBDIR)


class AOTStore:
    """Directory of serialized serving programs, keyed
    ``(model digest, bucket_rows)``.

    One entry is two atomic sibling files (utils.file_io.write_atomic):
    ``<digest>-b<bucket>.bin`` — the ``jax.export`` blob — and
    ``<digest>-b<bucket>.json`` — {version, platforms, jax} metadata
    checked BEFORE the expensive deserialize so a foreign-platform or
    version-skewed blob is rejected cheaply.
    """

    def __init__(self, root: str):
        self.root = str(root)

    # ------------------------------------------------------------- layout

    def _base(self, digest: str, bucket_rows: int) -> str:
        return os.path.join(self.root, f"{digest}-b{int(bucket_rows)}")

    def entries(self) -> list:
        """Sorted [(digest, bucket_rows)] of complete entries on disk."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in names:
            if not n.endswith(".json"):
                continue
            stem = n[:-len(".json")]
            digest, sep, b = stem.rpartition("-b")
            if not sep or not b.isdigit():
                continue
            if os.path.exists(os.path.join(self.root, stem + ".bin")):
                out.append((digest, int(b)))
        return sorted(out)

    def buckets_for(self, digest: str) -> list:
        return sorted(b for d, b in self.entries() if d == digest)

    # -------------------------------------------------------------- export

    def save_leaves(self, digest: str, bucket_rows: int, exported) -> str:
        """Serialize one exported routing program; returns the blob path."""
        import jax

        from ..utils.file_io import write_atomic
        base = self._base(digest, bucket_rows)
        write_atomic(base + ".bin", exported.serialize())
        write_atomic(base + ".json", json.dumps({
            "version": AOT_VERSION,
            "digest": digest,
            "bucket_rows": int(bucket_rows),
            "platforms": [p.lower() for p in exported.platforms],
            "jax": jax.__version__,
        }, indent=1, sort_keys=True))
        return base + ".bin"

    def export_device_forest(self, device_forest, features: int,
                             buckets, digest: str) -> int:
        """Export ``device_forest``'s routing program for every bucket in
        ``buckets``; returns the number of entries written."""
        import jax
        import jax.numpy as jnp
        from jax import export as jax_export
        # a fused-variant forest keeps a fixed-trip fori twin as its
        # export arm (bit-identical leaves, serializes cleanly — Pallas
        # kernels do not); plain variants export their own jit
        fn = getattr(device_forest, "_leaves_export",
                     device_forest._leaves_jit)
        n = 0
        for b in sorted({int(b) for b in buckets}):
            exp = jax_export.export(fn)(
                jax.ShapeDtypeStruct((b, int(features)), jnp.float32))
            self.save_leaves(digest, b, exp)
            n += 1
        return n

    # ------------------------------------------------------------- restore

    def load_leaves(self, digest: str, bucket_rows: int):
        """Deserialize the (digest, bucket) routing program into a
        jit-wrapped callable ``[bucket, F] f32 -> [T, bucket] i32``, or
        None on ANY miss/mismatch/corruption — the caller compiles
        normally, serving never fails on a bad cache entry."""
        base = self._base(digest, bucket_rows)
        try:
            with open(base + ".json") as fh:
                meta = json.load(fh)
            if meta.get("version") != AOT_VERSION:
                return None
            import jax
            if jax.default_backend().lower() not in meta.get("platforms", []):
                return None
            with open(base + ".bin", "rb") as fh:
                blob = fh.read()
            from jax import export as jax_export
            exported = jax_export.deserialize(bytearray(blob))
            # one jit wrapper per restored program: the executable is
            # cached across calls exactly like a live-compiled bucket
            return jax.jit(exported.call)
        except FileNotFoundError:
            return None
        except Exception as e:  # noqa: BLE001 — any corruption is a miss
            log_warning(
                f"AOT serving cache entry {os.path.basename(base)} "
                f"unusable ({type(e).__name__}: {str(e)[:120]}); "
                "recompiling this bucket")
            return None


def make_aot_program(store: "AOTStore", model, bucket_rows: int):
    """Build a serving program for ``(model, bucket)`` from the AOT
    store, or None on miss.  The returned callable matches
    ``CompiledModel.make_program``'s contract ([bucket, F] f64 padded
    batch -> [K, bucket] f64 raw scores) and is tagged ``aot=True`` so
    the program registry counts it as a restore, not a compile."""
    fn = store.load_leaves(model.digest, bucket_rows)
    if fn is None:
        return None
    from ..predict import gather_leaf_sum
    forest = model.forest
    K = model.num_class

    def run(Xpad: np.ndarray) -> np.ndarray:
        leaves = np.asarray(fn(np.asarray(Xpad, np.float32)))
        return gather_leaf_sum(forest, leaves, K)

    run.aot = True
    return run


def make_bulk_program(device_forest, features: int, block_rows: int,
                      digest: str, store: Optional["AOTStore"] = None):
    """Fixed-shape routing program for the bulk scorer (data/score.py):
    ``[block_rows, F] f32 -> [T, block_rows] i32`` leaves, at the bulk
    pipeline's ONE block-sized bucket.

    Tries the AOT store first (compile-free start, same bit-parity story
    as serving buckets); on a miss it exports the bucket so the NEXT run
    — a resumed crash included — restores instead of re-tracing, and
    serves this run with the freshly restored program.  Export is
    best-effort: any failure falls back to the live jit, never fails the
    scoring run.  Returns ``(callable, source)``, source in
    {"aot", "jit"}.
    """
    if store is not None:
        fn = store.load_leaves(digest, block_rows)
        if fn is not None:
            return fn, "aot"
        try:
            os.makedirs(store.root, exist_ok=True)
            store.export_device_forest(device_forest, features,
                                       [block_rows], digest)
            fn = store.load_leaves(digest, block_rows)
            if fn is not None:
                return fn, "aot"
        except Exception as e:  # noqa: BLE001 — export is best-effort
            log_warning(f"bulk AOT export failed ({type(e).__name__}: "
                        f"{str(e)[:120]}); scoring with the live jit")
    return getattr(device_forest, "_leaves_export",
                   device_forest._leaves_jit), "jit"
