"""Fault-aware pod router: replicated multi-device serving with
health-checked failover, hedged retries, and brownout degradation.

``PodFleet`` is the multi-device front door (docs/SERVING.md
multi-device section; docs/RESILIENCE.md failover section): N simulated
serving devices, each running its OWN single-device ``Fleet`` (weighted
admission, shared-HBM residency, per-device AOT cache — every PR 9
invariant carries over verbatim), with the placement planner
(fleet/topology.py) deciding which device hosts which replica and this
router deciding which replica serves which request.

The moment serving spans devices the dominant risk flips from
throughput to AVAILABILITY, and the router's whole design leans on one
fact: replicas serve BIT-IDENTICAL raw scores, so retrying, hedging,
and failing over are correctness-free — the only question is where the
bytes run, never what they say.

* **health-scored routing** — every replica is scored from the PR 11
  watchdog's signals: its batcher's liveness-beat staleness (a wedged
  device stops beating within ~0.1 s), its request-latency p99 vs the
  configured ceiling, and its windowed error / non-finite rate.  A
  replica that goes stale for ``dead_strikes`` consecutive health
  sweeps is declared DEAD and its device drained; degraded replicas
  are routed around, not killed.
* **device-local dispatch, DCN-aware spillover** — requests go to the
  model's primary replica first; when it is sick or saturated they
  spill to a same-slice replica (one ICI hop) before a cross-slice one
  (a DCN crossing), PV-Tree's elect-before-you-ship rule applied to
  routing (``fleet_spillover_total{tier="ici"|"dcn"}``).
* **hedged retries** — an interactive-class request that has not
  completed by its hedge deadline (``hedge_ms``, else
  ``hedge_fraction`` of its deadline budget) is duplicated onto a
  second replica; the first completion wins.  Bit-identical replicas
  make the duplicate free of consistency questions; the deadline
  budget makes it free of retry storms.
* **brownout degradation** — instead of cliff-edge ``QueueFull``,
  pressure on a model's replica set degrades in tiers: shed the batch
  class (typed), prefer its low-precision twin where an
  ``accuracy_budget`` admitted one, and finally serve through the
  bit-identical host path in the caller's thread — slower answers
  beat no answers, and the caller-thread cost IS the backpressure.
* **failover** — a lost device (chaos ``device`` site: wedge / error /
  vanish; or ``kill_device``) is drained: routing stops, its in-flight
  requests are RE-DISPATCHED to surviving replicas (not failed), a
  forensic flight bundle is dumped, and the next replan tick re-plans
  the topology over the survivors so every model regains its replica
  count — a replan, not an outage.

Availability is a first-class number: per-model
``fleet_completed_total`` / ``fleet_failed_total`` counters feed the
watchdog's ``LIGHTGBM_TPU_SLO_AVAILABILITY`` floor, and typed
shed/expired outcomes are never counted as failures.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant
from ..obs.watchdog import global_watchdog, histogram_p99_ms
from ..ops.planner import FleetModelShape
from ..serving.batcher import BucketLadder
from ..serving.errors import (DeadlineExceeded, DeviceLost, ModelNotFound,
                              QueueFull, ServerClosed, ServingError)
from ..serving.metrics import MetricsRegistry
from ..serving.registry import CompiledModel
from ..serving.server import Server
from .registry import DEFAULT_DEADLINE_CLASSES, Fleet, FleetConfig
from .topology import DeviceSpec, TopologyPlan, plan_devices, plan_topology

# router-retriable failures: the replica (or its device) is the problem,
# not the request — a surviving replica serves the same bits
_RETRIABLE = (DeviceLost, ServerClosed, OSError)


@dataclass
class RouterConfig:
    """Routing / health / brownout knobs; defaults are serving-sane and
    every threshold is a plain float a test can pin."""

    # hedging: interactive-class requests duplicate onto a second
    # replica after hedge_ms (else hedge_fraction of the deadline)
    hedge_ms: Optional[float] = None
    hedge_fraction: float = 0.5
    hedge_classes: tuple = ("interactive",)
    # health scoring (fed by the watchdog; module docstring)
    stale_beat_s: float = 5.0           # beat older than this = a strike
    dead_strikes: int = 3               # consecutive strikes = device dead
    p99_ceiling_ms: Optional[float] = None      # degraded above this
    error_window_s: float = 30.0
    error_rate_degraded: float = 0.25   # window error share -> degraded
    health_interval_s: float = 0.5      # health-sweep thread period
    # spillover / brownout pressure thresholds (queued / queue capacity
    # over a model's live replica set)
    saturation: float = 0.60            # spill off a loaded primary
    brownout_shed: float = 0.75         # tier >= 1: shed batch class
    brownout_lowprec: float = 0.85      # tier >= 2: prefer lowprec twin
    brownout_host: float = 0.95         # tier >= 3: host-path fallback


class ReplicaHealth:
    """Windowed health state of one replica; scored on demand from the
    watchdog beat age, the replica's latency histogram, and the
    outcome window this object accumulates."""

    __slots__ = ("beat_name", "_window", "_lock", "strikes", "dead",
                 "degraded", "score")

    def __init__(self, beat_name: str):
        self.beat_name = beat_name
        self._window: deque = deque(maxlen=256)   # guarded-by: _lock
        self._lock = threading.Lock()
        self.strikes = 0
        self.dead = False
        self.degraded = False
        self.score = 1.0

    def record(self, ok: bool) -> None:
        with self._lock:
            self._window.append((time.monotonic(), bool(ok)))

    def error_rate(self, now: float, window_s: float) -> float:
        with self._lock:
            recent = [ok for ts, ok in self._window if now - ts <= window_s]
        if not recent:
            return 0.0
        return 1.0 - sum(recent) / len(recent)

    def assess(self, server, cfg: RouterConfig,
               now: Optional[float] = None) -> float:
        """Recompute ``score``/``degraded``/``strikes`` from the three
        watchdog-fed signals; the caller (the router's health sweep)
        declares death from the strike count."""
        now = time.monotonic() if now is None else now
        score = 1.0
        age = global_watchdog.beat_age(self.beat_name, now)
        if age is not None and age > cfg.stale_beat_s:
            self.strikes += 1
            score = 0.0
        else:
            self.strikes = 0
        degraded = False
        if cfg.p99_ceiling_ms is not None:
            p99 = histogram_p99_ms(
                server.metrics.histogram("request_latency_ms"))
            if p99 is not None and p99 > cfg.p99_ceiling_ms:
                degraded = True
                score = min(score, 0.5)
        if self.error_rate(now, cfg.error_window_s) \
                >= cfg.error_rate_degraded:
            degraded = True
            score = min(score, 0.5)
        self.degraded = degraded
        self.score = 0.0 if self.dead else score
        return self.score


class Replica:
    """One (model, device) serving replica: the device fleet entry it
    lives in, its health state, and the routed requests currently
    riding it (the re-dispatch set when its device dies)."""

    __slots__ = ("name", "inner_name", "device_id", "slice_id", "fleet",
                 "lowprec", "health", "inflight", "primary")

    def __init__(self, name: str, inner_name: str, device_id: int,
                 slice_id: int, dev_fleet: Fleet, lowprec: bool,
                 primary: bool):
        self.name = name
        self.inner_name = inner_name
        self.device_id = device_id
        self.slice_id = slice_id
        self.fleet = dev_fleet
        self.lowprec = lowprec
        self.primary = primary
        self.health = ReplicaHealth(f"fleet.d{device_id}.{inner_name}")
        self.inflight: set = set()      # GIL-atomic add/discard; snapshots
        #                                 via list() (re-dispatch on death)

    @property
    def server(self) -> Server:
        return self.fleet.entry(self.inner_name).server

    def fill(self) -> float:
        """Queue pressure of this replica in [0, 1].  A replica whose
        entry vanished mid-read (a replan dropped it between the table
        snapshot and this call) reads as fully saturated — the router
        routes around it and the next sweep forgets it."""
        try:
            s = self.server
        except (ModelNotFound, ServerClosed):
            return 1.0
        cap = max(s.config.max_queue_rows, 1)
        return s._batcher.queued_rows() / cap


class _ModelSpec:
    """Everything the pod needs to (re)place one model."""

    __slots__ = ("name", "booster", "weight", "deadline_class",
                 "precision", "accuracy_budget", "probe_X",
                 "brownout_precision", "overrides", "host_model",
                 "buckets")

    def __init__(self, name, booster, weight, deadline_class, precision,
                 accuracy_budget, probe_X, brownout_precision, overrides,
                 buckets):
        self.name = name
        self.booster = booster
        self.weight = weight
        self.deadline_class = deadline_class
        self.precision = precision
        self.accuracy_budget = accuracy_budget
        self.probe_X = probe_X
        self.brownout_precision = brownout_precision
        self.overrides = overrides
        self.buckets = buckets
        # the always-there fallback: host-path serving is bit-identical
        # to the device path, so "every replica is gone" degrades to
        # latency, never to unavailability
        self.host_model = CompiledModel(booster, backend="host",
                                        precision=precision)

    @property
    def model(self) -> CompiledModel:
        # loadgen and smoke tools read entry(name).model.num_features /
        # .num_class — same surface as a single-device FleetEntry
        return self.host_model

    def shape(self) -> FleetModelShape:
        f = self.host_model.forest
        return FleetModelShape(
            name=self.name, num_trees=f.num_trees,
            nodes_dim=f.split_feature.shape[1],
            leaves_dim=f.leaf_value.shape[1],
            features=self.host_model.num_features,
            num_class=self.host_model.num_class,
            buckets=self.buckets, weight=self.weight,
            age_s=0.0, precision=self.precision,
            cat_words=(f.cat_words.size if f.has_cat else 0))


class _RoutedRequest:
    """One pod-level request: the outer future the caller holds, the
    devices already tried, and the settle-once accounting that makes
    hedges / failover re-dispatches race-free (whichever attempt
    finishes first wins; the rest are ignored)."""

    __slots__ = ("name", "X", "cls", "deadline_end", "future", "tried",
                 "hedge_timer", "t0", "_lock", "_settled",
                 "prefer_lowprec")

    def __init__(self, name: str, X: np.ndarray, cls: str,
                 deadline_ms: Optional[float], prefer_lowprec: bool):
        self.name = name
        self.X = X
        self.cls = cls
        self.t0 = time.monotonic()
        self.deadline_end = (self.t0 + deadline_ms / 1e3
                             if deadline_ms is not None else None)
        self.future: Future = Future()
        self.tried: set = set()
        self.hedge_timer: Optional[threading.Timer] = None
        self.prefer_lowprec = prefer_lowprec
        self._lock = threading.Lock()
        self._settled = False           # guarded-by: _lock

    def remaining_ms(self) -> Optional[float]:
        if self.deadline_end is None:
            return None
        return (self.deadline_end - time.monotonic()) * 1e3

    def settled(self) -> bool:
        with self._lock:
            if not self._settled and self.future.cancelled():
                self._settled = True
            return self._settled

    def _claim(self) -> bool:
        with self._lock:
            if self._settled:
                return False
            self._settled = True
        return True

    def settle_result(self, result) -> bool:
        if not self._claim():
            return False
        t = self.hedge_timer
        if t is not None:
            t.cancel()
        try:
            self.future.set_result(result)
            return True
        except InvalidStateError:       # cancelled under our feet
            return False

    def settle_failure(self, exc: Exception) -> bool:
        if not self._claim():
            return False
        t = self.hedge_timer
        if t is not None:
            t.cancel()
        try:
            self.future.set_exception(exc)
            return True
        except InvalidStateError:
            return False


class PodFleet:
    """Replicated multi-device serving fleet behind one fault-aware
    router (module docstring; docs/SERVING.md multi-device section).

    Drop-in surface for ``Fleet`` callers: ``add_model`` / ``submit`` /
    ``predict`` / ``swap_model`` / ``remove_model`` / ``warm`` /
    ``export_aot`` / ``close`` plus ``entry(name)`` for the loadgen
    drivers.  ``devices=N`` stands up N per-device ``Fleet`` instances
    whose slice layout follows the PR 10 mesh-plan seam; ``chaos``
    attaches a ``resilience.faults.ChaosRegistry`` whose ``device``
    fault site can wedge / error / vanish any device mid-run."""

    def __init__(self, devices: int = 2,
                 device_budget_bytes: Optional[int] = None,
                 router: Optional[RouterConfig] = None,
                 chaos=None, aot_dir: Optional[str] = None,
                 **fleet_overrides):
        self.router = router or RouterConfig()
        self.chaos = chaos
        self.metrics = MetricsRegistry()
        self._aot_dir = aot_dir
        self._devices: Tuple[DeviceSpec, ...] = plan_devices(
            devices, device_budget_bytes)
        self._slice_of = {d.device_id: d.slice_id for d in self._devices}
        self._fleet_overrides = dict(fleet_overrides)
        self.deadline_classes = dict(
            self._fleet_overrides.pop("deadline_classes", None)
            or DEFAULT_DEADLINE_CLASSES)
        self._device_fleets: Dict[int, Fleet] = {}  # guarded-by: _table_lock
        for d in self._devices:
            self._device_fleets[d.device_id] = self._make_device_fleet(d)
        self._specs: Dict[str, _ModelSpec] = {}     # guarded-by: _table_lock
        self._replicas: Dict[str, List[Replica]] = {}  # guarded-by: _table_lock
        self._dead: set = set()                     # guarded-by: _table_lock
        self._device_lost_listeners: list = []      # guarded-by: _table_lock
        self._topology: Optional[TopologyPlan] = None  # guarded-by: _table_lock
        self._admissions = 0                        # guarded-by: _table_lock
        self._replan_every = int(
            self._fleet_overrides.get("replan_every", 256))
        self._closed = False
        self._table_lock = threading.Lock()
        self._replan_lock = threading.Lock()    # serializes plan application
        self._obs_component = _obs_registry.attach_child(
            "pod_fleet", self.metrics)
        self.metrics.gauge("fleet_live_devices").set(len(self._devices))
        # retry-path host fallbacks run here, never on the batcher or
        # drain thread that observed the failure (a full host-path
        # predict on a device's batcher thread would stall every queued
        # batch on that device); bounded, so a fallback storm queues
        # instead of spawning unbounded threads
        self._fallback_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="lgbt-pod-hostfb")
        self._health_stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="lgbt-pod-health", daemon=True)
        self._health_thread.start()

    # ----------------------------------------------------------- plumbing

    def _make_device_fleet(self, d: DeviceSpec) -> Fleet:
        aot = (os.path.join(self._aot_dir, f"dev{d.device_id}")
               if self._aot_dir else None)
        cfg = dict(self._fleet_overrides)
        cfg.setdefault("hbm_budget_bytes", d.hbm_budget_bytes)
        cfg.setdefault("aot_dir", aot)
        cfg.setdefault("deadline_classes", dict(self.deadline_classes))
        return Fleet(FleetConfig(**cfg))

    def entry(self, name: str) -> _ModelSpec:
        with self._table_lock:
            spec = self._specs.get(name)
        if spec is None:
            raise ModelNotFound(
                f"pod fleet has no model {name!r}; registered: "
                f"{self.models()}")
        return spec

    def models(self) -> list:
        with self._table_lock:
            return sorted(self._specs)

    @property
    def topology(self) -> Optional[TopologyPlan]:
        return self._topology

    def live_devices(self) -> list:
        with self._table_lock:
            return [d.device_id for d in self._devices
                    if d.device_id not in self._dead]

    def latency_histograms(self) -> dict:
        """``{(model, device_id): request_latency_ms Histogram}`` for
        every live full-precision replica — the co-resident scheduler's
        brownout guards watch these (coresident/scheduler.py).  Replicas
        whose entry vanished mid-read are skipped, like ``fill()``."""
        with self._table_lock:
            reps = [(name, r) for name, rs in self._replicas.items()
                    for r in rs
                    if not r.lowprec and r.device_id not in self._dead]
        out = {}
        for name, r in reps:
            try:
                hist = r.server.metrics.histogram("request_latency_ms")
            except (ModelNotFound, ServerClosed):
                continue
            out[(name, r.device_id)] = hist
        return out

    # ----------------------------------------------------------- registry

    def add_model(self, name: str, booster_or_path, weight: float = 1.0,
                  deadline_class: str = "standard", precision: str = "f32",
                  accuracy_budget: Optional[float] = None,
                  probe_X=None, brownout_precision: Optional[str] = None,
                  **server_overrides) -> _ModelSpec:
        """Register ``booster_or_path`` pod-wide: the topology planner
        places its replicas, every placed device fleet gets an entry.
        ``brownout_precision`` ("bf16"/"int8") additionally registers a
        low-precision twin wherever the base model lands — admitted only
        under a declared ``accuracy_budget`` — which tier-2 brownout
        prefers under pressure."""
        if self._closed:
            raise ServerClosed("pod fleet is shut down")
        if deadline_class not in self.deadline_classes:
            raise ValueError(
                f"unknown deadline class {deadline_class!r}; configured: "
                f"{sorted(self.deadline_classes)}")
        if weight <= 0:
            raise ValueError("model weight must be positive")
        if brownout_precision is not None and accuracy_budget is None:
            raise ValueError(
                "brownout_precision needs accuracy_budget: an unbudgeted "
                "lowprec twin could serve arbitrarily wrong scores")
        booster = Server._as_booster(booster_or_path)
        ladder = BucketLadder(
            self._fleet_overrides.get("min_bucket_rows", 8),
            self._fleet_overrides.get("max_batch_rows", 1024))
        spec = _ModelSpec(name, booster, float(weight), deadline_class,
                          precision, accuracy_budget, probe_X,
                          brownout_precision, dict(server_overrides),
                          tuple(ladder.buckets))
        with self._table_lock:
            if name in self._specs:
                raise ValueError(f"model {name!r} already registered; "
                                 "use swap_model to replace it")
            self._specs[name] = spec
        c = self.metrics.counter("fleet_completed_total",
                                 labels={"model": name})
        fcnt = self.metrics.counter("fleet_failed_total",
                                    labels={"model": name})
        global_watchdog.watch_availability(
            name, lambda c=c, f=fcnt: (c.value, f.value))
        try:
            self.replan()
        except ServingError:
            # a base replica that cannot serve (quarantined probe, over
            # its accuracy budget) fails the REGISTRATION, exactly like
            # the single-device Fleet: no spec, no replicas, no watch
            with self._table_lock:
                self._specs.pop(name, None)
                leftovers = self._replicas.pop(name, [])
            global_watchdog.unwatch_availability(name)
            for r in leftovers:
                try:
                    r.fleet.remove_model(r.inner_name, drain=False)
                except ModelNotFound:
                    pass
            raise
        return spec

    def swap_model(self, name: str, booster_or_path, **kw):
        """Hot-swap every replica of ``name`` (per-device Server swap
        semantics: warm, probe, quarantine, atomic flip).  Low-precision
        twins re-quantize and re-probe their accuracy budget against the
        NEW model; a twin that no longer fits its budget is dropped to
        the f32 path (a lost optimization, never a serving failure)."""
        spec = self.entry(name)
        booster = Server._as_booster(booster_or_path)
        from ..serving.errors import SwapQuarantined
        # under _replan_lock: a replan racing the rolling flip would
        # read spec.booster and could place a replica serving the OLD
        # model next to already-swapped siblings — a persistent bit
        # divergence the hedging/failover design cannot tolerate.  The
        # spec flips FIRST so any replan after the lock releases places
        # the new model only.
        with self._replan_lock:
            spec.booster = booster
            spec.host_model = CompiledModel(booster, backend="host",
                                            precision=spec.precision)
            with self._table_lock:
                replicas = list(self._replicas.get(name, ()))
            for r in replicas:
                if not r.lowprec:
                    r.fleet.swap_model(r.inner_name, booster, **kw)
        for r in replicas:
            if r.lowprec:
                try:
                    r.fleet.swap_model(r.inner_name, booster, **kw)
                except SwapQuarantined as e:
                    from ..utils.log import log_warning
                    log_warning(
                        f"pod fleet: lowprec twin {r.inner_name!r} on "
                        f"device {r.device_id} quarantined against the "
                        f"new model and dropped: {e}")
                    self._drop_replica(name, r.device_id, lowprec=True)

    def remove_model(self, name: str, drain: bool = True) -> None:
        """Unregister ``name`` pod-wide.  The routing table entry is
        removed FIRST (no new dispatch can pick a dying replica), then
        in-flight routed requests drain, then each device fleet removes
        its entry — a replan racing this sees either the full replica
        set or none of it, never a half-closed server."""
        with self._replan_lock:     # a concurrent replan must not re-place
            with self._table_lock:  # or restore what we are removing
                spec = self._specs.pop(name, None)
                replicas = self._replicas.pop(name, [])
            if spec is None:
                raise ModelNotFound(f"pod fleet has no model {name!r}")
            global_watchdog.unwatch_availability(name)
            for r in replicas:
                for req in list(r.inflight):
                    try:
                        req.future.result(timeout=5.0)
                    except Exception:  # noqa: BLE001 — outcome is theirs
                        pass
            for r in replicas:
                try:
                    r.fleet.remove_model(r.inner_name, drain=drain,
                                         timeout=5.0)
                except ModelNotFound:
                    pass
        self.metrics.counter("fleet_models_removed").inc()

    # ----------------------------------------------------------- topology

    def replan(self) -> TopologyPlan:
        """Re-run the placement election over the LIVE devices and apply
        the diff: place missing replicas, drain dropped ones, let each
        device fleet re-elect its own residency.  Called on add/remove,
        every ``replan_every`` admissions, and on device loss — the
        existing tick IS the recovery path."""
        with self._replan_lock:
            with self._table_lock:
                live = [d for d in self._devices
                        if d.device_id not in self._dead]
                specs = dict(self._specs)
                current = {(n, r.device_id, r.lowprec)
                           for n, rs in self._replicas.items() for r in rs}
            if not live:
                raise DeviceLost("every serving device is gone; the pod "
                                 "fleet serves host-path only")
            plan = plan_topology([s.shape() for s in specs.values()], live)
            wanted = set()
            for pname, dids in plan.replicas.items():
                spec = specs[pname]
                for did in dids:
                    wanted.add((pname, did, False))
                    if spec.brownout_precision is not None:
                        wanted.add((pname, did, True))
            for key in sorted(wanted - current):
                self._place_replica(specs[key[0]], key[1], lowprec=key[2])
            for key in sorted(current - wanted):
                self._drop_replica(*key)
            with self._table_lock:
                self._topology = plan
                for pname, dids in plan.replicas.items():
                    rs = self._replicas.get(pname, [])
                    order = {d: i for i, d in enumerate(dids)}
                    rs.sort(key=lambda r: (order.get(r.device_id, 99),
                                           r.lowprec))
                    for r in rs:
                        r.primary = (not r.lowprec
                                     and bool(dids)
                                     and r.device_id == dids[0])
        self.metrics.counter("fleet_replans_total").inc()
        self.metrics.gauge("fleet_live_devices").set(len(live))
        _instant("fleet.topology", **plan.summary())
        from ..obs.flight import global_flight
        global_flight.set_context(fleet_topology=plan.summary())
        return plan

    def _place_replica(self, spec: _ModelSpec, device_id: int,
                       lowprec: bool) -> None:
        with self._table_lock:
            dev_fleet = self._device_fleets.get(device_id)
        if dev_fleet is None:
            return
        inner = spec.name + ("!lp" if lowprec else "")
        precision = (spec.brownout_precision if lowprec
                     else spec.precision)
        try:
            dev_fleet.add_model(
                inner, spec.booster, weight=spec.weight,
                deadline_class=spec.deadline_class, precision=precision,
                # the declared budget guards EVERY low-precision serving
                # path — a lowprec twin AND a base model registered with
                # precision="bf16"/"int8" (same quarantine a
                # single-device Fleet would apply)
                accuracy_budget=(spec.accuracy_budget
                                 if precision != "f32" else None),
                probe_X=spec.probe_X,
                heartbeat_name=f"fleet.d{device_id}.{inner}",
                **spec.overrides)
        except ServingError as e:
            # a quarantined lowprec TWIN (over its budget) is a skipped
            # OPTIMIZATION, never a failed placement; a base replica
            # that cannot serve (e.g. a low-precision base model over
            # its declared budget) must surface exactly as the
            # single-device Fleet would raise it
            if not lowprec:
                raise
            from ..utils.log import log_warning
            log_warning(f"pod fleet: lowprec twin {inner!r} on device "
                        f"{device_id} not placed: {e}")
            return
        entry = dev_fleet.entry(inner)
        if self.chaos is not None:
            b = entry.server._batcher
            b.run_batch = self.chaos.wrap_device_batch(
                device_id, b.run_batch)
        rep = Replica(spec.name, inner, device_id,
                      self._slice_of[device_id], dev_fleet, lowprec,
                      primary=False)
        with self._table_lock:
            self._replicas.setdefault(spec.name, []).append(rep)
        self.metrics.gauge("replica_health", labels={
            "model": spec.name, "device": device_id}).set(1.0)

    def _drop_replica(self, name: str, device_id: int,
                      lowprec: bool) -> None:
        with self._table_lock:
            rs = self._replicas.get(name, [])
            victim = next((r for r in rs if r.device_id == device_id
                           and r.lowprec == lowprec), None)
            if victim is not None:
                rs.remove(victim)
        if victim is None:
            return
        for req in list(victim.inflight):
            if not req.settled():
                self._route_and_dispatch(req)
        try:
            # bounded join: this can run under _replan_lock, and a
            # wedged-but-not-yet-dead batcher (chaos wedge before the
            # health sweep strikes out) must not freeze every replan
            victim.fleet.remove_model(victim.inner_name, drain=True,
                                      timeout=2.0)
        except ModelNotFound:
            pass

    # ------------------------------------------------------------ serving

    def _pressure(self, name: str) -> float:
        with self._table_lock:
            rs = [r for r in self._replicas.get(name, ())
                  if r.device_id not in self._dead]
        if not rs:
            return 1.0
        return sum(r.fill() for r in rs) / len(rs)

    def _tier(self, name: str) -> int:
        p = self._pressure(name)
        cfg = self.router
        tier = (3 if p >= cfg.brownout_host else
                2 if p >= cfg.brownout_lowprec else
                1 if p >= cfg.brownout_shed else 0)
        self.metrics.gauge("fleet_brownout_tier",
                           labels={"model": name}).set(tier)
        return tier

    def _pick(self, req: _RoutedRequest) -> Optional[Replica]:
        """Elect the next replica for ``req``: device-local first, then
        same-slice (ICI), then cross-slice (DCN, counted as spillover);
        dead/downed/tried replicas never, degraded and saturated ones
        only when nothing better lives."""
        cfg = self.router
        with self._table_lock:
            rs = [r for r in self._replicas.get(req.name, ())
                  if r.device_id not in self._dead
                  and r.device_id not in req.tried
                  and not r.health.dead]
        if self.chaos is not None:
            rs = [r for r in rs
                  if self.chaos.device_down(r.device_id) is None]
        if req.prefer_lowprec and any(r.lowprec for r in rs):
            rs = [r for r in rs if r.lowprec]
        else:
            rs = [r for r in rs if not r.lowprec]
        if not rs:
            return None
        primary = next((r for r in rs if r.primary), rs[0])
        # one fill() read per replica per pick: each read takes the
        # device fleet's entry lock, so the sort key must not re-read
        fills = {id(r): r.fill() for r in rs}

        def group(r: Replica) -> int:
            if r.device_id == primary.device_id:
                return 0
            return 1 if r.slice_id == primary.slice_id else 2

        best = min(rs, key=lambda r: (
            group(r), r.health.degraded,
            fills[id(r)] >= cfg.saturation, fills[id(r)], r.device_id))
        g = group(best)
        if g > 0:
            self.metrics.counter(
                "fleet_spillover_total",
                labels={"tier": "ici" if g == 1 else "dcn"}).inc()
        return best

    def submit(self, name: str, X, deadline_ms: Optional[float] = None,
               request_class: Optional[str] = None) -> Future:
        """Route one predict request; returns the pod-level Future.
        Typed outcomes: ``QueueFull`` (brownout shed / every replica
        over its share), ``DeadlineExceeded`` (budget spent in queue).
        Replica failures are the ROUTER's problem — retried, hedged, or
        degraded to the host path, not surfaced."""
        if self._closed:
            raise ServerClosed("pod fleet is shut down")
        spec = self.entry(name)
        cls = request_class or spec.deadline_class
        tier = self._tier(name)
        if tier >= 1 and cls == "batch":
            self.metrics.counter("fleet_brownout_shed_total",
                                 labels={"model": name}).inc()
            raise QueueFull(
                f"brownout tier {tier}: batch-class request to {name!r} "
                "shed to protect interactive traffic")
        if deadline_ms is None:
            deadline_ms = self.deadline_classes.get(cls)
        X = np.array(X, np.float64, order="C")
        if X.ndim == 1:
            X = X[None, :]
        req = _RoutedRequest(name, X, cls, deadline_ms,
                             prefer_lowprec=tier >= 2)
        self.metrics.counter("fleet_requests_total",
                             labels={"model": name}).inc()
        fut = req.future
        fut.add_done_callback(lambda f: self._account(name, f))
        self._maybe_hedge_later(req)
        if tier >= 3:
            self._host_fallback(req, spec, sync=True)
        else:
            self._route_and_dispatch(req, sync=True)
        with self._table_lock:  # plain += from N submit threads loses
            self._admissions += 1      # updates and skips the tick
            due = (self._replan_every > 0
                   and self._admissions % self._replan_every == 0)
        if due:
            self.replan()
        return fut

    def predict(self, name: str, X, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                request_class: Optional[str] = None) -> np.ndarray:
        fut = self.submit(name, X, deadline_ms=deadline_ms,
                          request_class=request_class)
        try:
            return fut.result(timeout)
        except FuturesTimeoutError:
            fut.cancel()
            raise

    def _account(self, name: str, f: Future) -> None:
        m = self.metrics
        try:
            if f.cancelled():
                # a caller-cancelled request (predict wait timeout) is
                # work the pod failed to settle in time — invisible to
                # typed outcomes, so it MUST count against availability
                # or a hang-style failure never breaches the SLO
                m.counter("fleet_cancelled_total",
                          labels={"model": name}).inc()
                m.counter("fleet_failed_total",
                          labels={"model": name}).inc()
                return
            exc = f.exception()
        except Exception:  # noqa: BLE001
            return
        if exc is None:
            m.counter("fleet_completed_total",
                      labels={"model": name}).inc()
        elif isinstance(exc, QueueFull):
            m.counter("fleet_shed_total", labels={"model": name}).inc()
        elif isinstance(exc, DeadlineExceeded):
            m.counter("fleet_expired_total", labels={"model": name}).inc()
        else:
            m.counter("fleet_failed_total", labels={"model": name}).inc()

    # ----------------------------------------------------------- dispatch

    def _route_and_dispatch(self, req: _RoutedRequest,
                            hedged: bool = False,
                            sync: bool = False) -> None:
        if req.settled():
            return
        rem = req.remaining_ms()
        if rem is not None and rem <= 0:
            req.settle_failure(DeadlineExceeded(
                f"deadline budget spent after trying devices "
                f"{sorted(req.tried)}"))
            return
        replica = self._pick(req)
        if replica is None:
            # this can run inside a Future done-callback, where a raise
            # would be swallowed and the outer future never settle: a
            # model removed mid-flight must FAIL the request typed
            try:
                spec = self.entry(req.name)
            except ModelNotFound as e:
                req.settle_failure(e)
                return
            self._host_fallback(req, spec, sync=sync)
            return
        self._dispatch(req, replica, hedged=hedged)

    def _dispatch(self, req: _RoutedRequest, replica: Replica,
                  hedged: bool) -> None:
        req.tried.add(replica.device_id)
        try:
            inner = replica.fleet.submit(replica.inner_name, req.X,
                                         deadline_ms=req.remaining_ms())
        except (QueueFull, ModelNotFound):
            # ModelNotFound: a replan dropped this replica between the
            # table snapshot and the submit — the device is fine, the
            # request is routable; try the next replica, never surface
            # a non-typed failure for a transient placement move
            self._route_and_dispatch(req, hedged=hedged)
            return
        except _RETRIABLE as e:
            self._replica_failed(req, replica, e, hedged)
            return
        replica.inflight.add(req)
        inner.add_done_callback(
            lambda f: self._on_done(req, replica, f, hedged))

    def _on_done(self, req: _RoutedRequest, replica: Replica, f: Future,
                 hedged: bool) -> None:
        replica.inflight.discard(req)
        if req.settled():
            return
        try:
            if f.cancelled():
                return
            exc = f.exception()
        except Exception:  # noqa: BLE001 — cancelled between the checks
            return
        if exc is None:
            out = np.asarray(f.result())
            if not np.isfinite(out).all():
                self.metrics.counter("fleet_nonfinite_total",
                                     labels={"model": req.name}).inc()
                self._replica_failed(req, replica, ServingError(
                    f"replica on device {replica.device_id} returned "
                    "non-finite scores"), hedged)
                return
            replica.health.record(True)
            if req.settle_result(f.result()) and hedged:
                self.metrics.counter("fleet_hedge_wins_total",
                                     labels={"model": req.name}).inc()
            return
        if isinstance(exc, DeadlineExceeded):
            req.settle_failure(exc)
            return
        if isinstance(exc, QueueFull):
            self._route_and_dispatch(req, hedged=hedged)
            return
        if isinstance(exc, _RETRIABLE):
            self._replica_failed(req, replica, exc, hedged)
            return
        replica.health.record(False)
        req.settle_failure(exc)

    def _replica_failed(self, req: _RoutedRequest, replica: Replica,
                        exc: Exception, hedged: bool) -> None:
        replica.health.record(False)
        if isinstance(exc, DeviceLost):
            self._device_lost(replica.device_id, str(exc))
        self.metrics.counter("fleet_failover_redispatch_total",
                             labels={"model": req.name}).inc()
        self._route_and_dispatch(req, hedged=hedged)

    def _host_fallback(self, req: _RoutedRequest, spec: _ModelSpec,
                       sync: bool = True) -> None:
        """Last-resort availability through the bit-identical host path.
        ``sync`` (the submit-time tier-3 brownout) computes in the
        CALLER's thread — the latency is the backpressure; retry paths
        (which run on batcher / drain / timer threads that must not
        stall) hand the compute to the bounded fallback pool."""
        self.metrics.counter("fleet_host_fallback_total",
                             labels={"model": req.name}).inc()
        if not sync:
            try:
                self._fallback_pool.submit(self._host_fallback_run,
                                           req, spec)
                return
            except RuntimeError:    # pool shut down mid-close: inline
                pass
        self._host_fallback_run(req, spec)

    def _host_fallback_run(self, req: _RoutedRequest,
                           spec: _ModelSpec) -> None:
        try:
            K = spec.host_model.num_class
            raw = spec.host_model.forest.predict_raw(req.X, num_class=K)
            raw = spec.host_model.scale_raw(np.asarray(raw, np.float64))
            req.settle_result(raw[0] if K == 1 else raw.T)
        except Exception as e:  # noqa: BLE001 — surface, nothing left
            req.settle_failure(e)

    # ------------------------------------------------------------ hedging

    def _maybe_hedge_later(self, req: _RoutedRequest) -> None:
        cfg = self.router
        if req.cls not in cfg.hedge_classes:
            return
        if cfg.hedge_ms is not None:
            delay = cfg.hedge_ms / 1e3
        elif req.deadline_end is not None:
            delay = max(req.deadline_end - req.t0, 0.0) \
                * cfg.hedge_fraction
        else:
            return

        def fire():
            if req.settled():
                return
            self.metrics.counter("fleet_hedges_total",
                                 labels={"model": req.name}).inc()
            self._route_and_dispatch(req, hedged=True)

        t = threading.Timer(delay, fire)
        t.daemon = True
        req.hedge_timer = t
        t.start()

    # ------------------------------------------------------------- health

    def check_health(self, now: Optional[float] = None) -> dict:
        """One synchronous health sweep over every live replica (the
        sentry thread calls this every ``health_interval_s``; tests call
        it directly).  Returns {(model, device): score}."""
        cfg = self.router
        with self._table_lock:
            replicas = [r for rs in self._replicas.values() for r in rs
                        if r.device_id not in self._dead]
        scores = {}
        doomed = set()
        for r in replicas:
            try:
                score = r.health.assess(r.server, cfg, now)
            except ModelNotFound:       # mid-drop: next sweep is clean
                continue
            scores[(r.name, r.device_id)] = score
            self.metrics.gauge("replica_health", labels={
                "model": r.name, "device": r.device_id}).set(score)
            if r.health.strikes >= cfg.dead_strikes:
                doomed.add(r.device_id)
            if self.chaos is not None and \
                    self.chaos.device_down(r.device_id) == "vanish":
                doomed.add(r.device_id)
        for did in doomed:
            self._device_lost(did, "health: stale heartbeat")
        return scores

    def _health_loop(self) -> None:
        while not self._health_stop.wait(self.router.health_interval_s):
            try:
                self.check_health()
            except Exception:  # noqa: BLE001 — the sweep never dies
                pass

    # ------------------------------------------------------------ failover

    def kill_device(self, device_id: int,
                    reason: str = "operator kill") -> None:
        """Declare ``device_id`` dead NOW (drills, orchestration): drain
        it, re-dispatch its in-flight requests, replan the topology."""
        self._device_lost(device_id, reason, wait=True)

    def add_device_lost_listener(self, fn) -> None:
        """Register ``fn(device_id, reason, recovered)`` to run after a
        lost device's drain settles (serving replan done or abandoned).
        The co-resident scheduler hooks here so a device loss shrinks
        the TRAINING world in the same coordinated replan that drained
        the serving replicas (coresident/scheduler.py).  Exceptions are
        swallowed: a broken hook never blocks the drain."""
        with self._table_lock:
            if fn not in self._device_lost_listeners:
                self._device_lost_listeners.append(fn)

    def remove_device_lost_listener(self, fn) -> None:
        with self._table_lock:
            if fn in self._device_lost_listeners:
                self._device_lost_listeners.remove(fn)

    def _notify_device_lost(self, device_id: int, reason: str,
                            recovered: bool) -> None:
        with self._table_lock:
            listeners = list(self._device_lost_listeners)
        for fn in listeners:
            try:
                fn(device_id, reason, recovered)
            except Exception:  # noqa: BLE001 — hooks never block the drain
                pass

    def _device_lost(self, device_id: int, reason: str,
                     wait: bool = False) -> None:
        with self._table_lock:
            if device_id in self._dead:
                return
            self._dead.add(device_id)
        self.metrics.counter("fleet_devices_lost_total").inc()
        # the drain runs off-thread: a DeviceLost often surfaces INSIDE
        # the dying device's own batcher thread, which must not try to
        # join itself through Fleet.close
        t = threading.Thread(target=self._drain_device,
                             args=(device_id, reason),
                             name=f"lgbt-pod-drain-{device_id}",
                             daemon=True)
        t.start()
        if wait:
            t.join()

    def _drain_device(self, device_id: int, reason: str) -> None:
        with self._table_lock:
            victims = [r for rs in self._replicas.values() for r in rs
                       if r.device_id == device_id]
            for name in list(self._replicas):
                self._replicas[name] = [
                    r for r in self._replicas[name]
                    if r.device_id != device_id]
            dev_fleet = self._device_fleets.get(device_id)
        for r in victims:
            r.health.dead = True
            self.metrics.gauge("replica_health", labels={
                "model": r.name, "device": device_id}).set(0.0)
        redispatched = 0
        for r in victims:
            for req in list(r.inflight):
                r.inflight.discard(req)
                if not req.settled():
                    redispatched += 1
                    self.metrics.counter(
                        "fleet_failover_redispatch_total",
                        labels={"model": req.name}).inc()
                    self._route_and_dispatch(req)
        if dev_fleet is not None:
            try:
                dev_fleet.close(drain=False, timeout=1.0)
            except Exception:  # noqa: BLE001 — a wedged batcher must not
                pass           # block the drain of everyone else
        from ..obs.flight import global_flight
        global_flight.dump("fleet:device_lost", extra={
            "device": device_id, "reason": reason,
            "redispatched_inflight": redispatched,
            "models": sorted({r.name for r in victims})})
        _instant("fleet.failover", device=device_id, reason=reason,
                 redispatched=redispatched)
        try:
            plan = self.replan()
        except DeviceLost:
            # every device gone: host-path-only from here
            self._notify_device_lost(device_id, reason, recovered=False)
            return
        except ServingError as e:  # a replacement replica quarantined:
            from ..utils.log import log_warning   # recovery is partial,
            log_warning(                          # the drain lives on
                f"pod fleet: replan after losing device {device_id} "
                f"failed: {e}")
            self._notify_device_lost(device_id, reason, recovered=False)
            return
        # the acceptance bar: the FIRST replan after a loss restores
        # every model's replica coverage — recovery within one tick
        with self._table_lock:
            ok = all(len(plan.replicas.get(n, ())) > 0
                     for n in self._specs)
        self.metrics.gauge("fleet_recovered_one_tick").set(int(ok))
        self._notify_device_lost(device_id, reason, recovered=bool(ok))

    # ----------------------------------------------------------- warm/aot

    def warm(self) -> int:
        n = 0
        with self._table_lock:
            fleets = [f for d, f in self._device_fleets.items()
                      if d not in self._dead]
        for f in fleets:
            n += f.warm()
        return n

    def export_aot(self, path: Optional[str] = None) -> int:
        """Per-device AOT export: each device fleet serializes into its
        OWN subdirectory (``dev<id>/``) so a replacement device restores
        exactly the programs its residency plan warmed."""
        base = path or self._aot_dir
        if base is None:
            raise ServingError("no AOT directory configured: pass path= "
                               "or construct with aot_dir=")
        n = 0
        with self._table_lock:
            items = [(d, f) for d, f in self._device_fleets.items()
                     if d not in self._dead]
        for did, f in items:
            n += f.export_aot(os.path.join(base, f"dev{did}"))
        return n

    # ---------------------------------------------------------- lifecycle

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        if self._closed:
            return
        self._closed = True
        self._health_stop.set()
        self._health_thread.join(timeout=2.0)
        self._fallback_pool.shutdown(wait=False)
        with self._table_lock:
            names = sorted(self._specs)
            fleets = list(self._device_fleets.values())
        for name in names:
            global_watchdog.unwatch_availability(name)
        for f in fleets:
            try:
                f.close(drain=drain, timeout=timeout)
            except Exception:  # noqa: BLE001 — close everything we can
                pass
        _obs_registry.detach_child(self._obs_component)

    def __enter__(self) -> "PodFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # ------------------------------------------------------------ metrics

    def metrics_dict(self) -> dict:
        out = self.metrics.to_dict()
        with self._table_lock:
            fleets = {d: f for d, f in self._device_fleets.items()
                      if d not in self._dead}
        out["devices"] = {str(d): f.metrics_dict()
                          for d, f in sorted(fleets.items())}
        return out

    def availability(self, name: str) -> Optional[float]:
        """Cumulative availability of ``name``: completed / (completed +
        non-typed failed); None before any outcome.  Typed shed/expired
        are excluded — they are correct overload behavior."""
        c = self.metrics.counter("fleet_completed_total",
                                 labels={"model": name}).value
        f = self.metrics.counter("fleet_failed_total",
                                 labels={"model": name}).value
        if c + f <= 0:
            return None
        return c / (c + f)

    def prometheus_text(self, prefix: str = "lgbt_pod") -> str:
        parts = [self.metrics.to_prometheus(prefix=prefix)]
        with self._table_lock:
            fleets = {d: f for d, f in self._device_fleets.items()
                      if d not in self._dead}
        for d, f in sorted(fleets.items()):
            parts.append(f.prometheus_text(prefix=f"{prefix}_dev{d}"))
        return "".join(parts)
