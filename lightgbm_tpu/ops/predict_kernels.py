"""Inference traversal kernels: the predict path's kernel family.

Training got a kernel war (histogram families, the fused megakernel);
this module gives the inference hot path the same treatment.  Three
variants share ONE decision-step expression so routing parity across
them is by construction, not by test luck:

``while``   the legacy ``lax.while_loop`` node chase (predict.py) — a
            per-step ``jnp.any`` convergence sync and a dynamic trip
            count that AOT export cannot serialize.  Kept as the
            fallback arm.
``fori``    the same [T, nc] depth-stepping state advanced for a STATIC
            ``forest.max_depth`` trips.  Rows that reach a leaf freeze
            (the step is idempotent on negative node ids), so the extra
            trips are no-ops — and the fixed trip count drops the
            convergence sync and AOT-exports cleanly (fleet/aot.py).
``fused``   a Pallas kernel that streams rows tile-by-tile (the PR 5
            ``tile_rows`` regime): the [T, tile] node state lives in
            VMEM for the whole descent, the forest arrays stay resident
            across grid steps (their block index never moves, so Pallas
            skips the re-DMA), and — when leaf values are on device —
            per-class raw scores are accumulated in-kernel in a pinned
            iteration-major order so only a [K, tile] block leaves HBM.

All three carry the full routing contract: categorical bitsets, the
three missing-value types, and every threshold precision
(f32/bf16/int8 via fleet/lowprec.py) — the fused kernel consumes a
precomputed full [T, I] f32 threshold plane that is elementwise
identical to ``DeviceForest._thr_at``'s per-gather dequantization.

Off accelerators the Pallas kernel runs in interpret mode (the
ops/fused.py convention), which executes the very jnp expressions the
other variants use — so CPU tier-1 parity tests are meaningful.  On
real accelerators a one-time per-backend probe compares fused leaf
indices against the while_loop arm and demotes to ``fori`` on any
mismatch or compile failure (the ``take_from_table`` precedent).
"""

from __future__ import annotations

import warnings

import numpy as np

PREDICT_VARIANTS = ("while", "fori", "fused")

# matches predict.py's kZeroThreshold (feature_group.h)
_K_ZERO = 1e-35

# row-tile ladder the planner's VMEM model elects from
FUSED_TILE_LADDER = (2048, 1024, 512, 256, 128)


def _interp(interpret):
    """Pallas interpret-mode default: real kernel on accelerators,
    interpreted everywhere else (the ops/fused.py convention)."""
    if interpret is None:
        from .histogram import on_accelerator
        return not on_accelerator()
    return bool(interpret)


# ----------------------------------------------------------------------
# the shared decision step
# ----------------------------------------------------------------------

def decide_step(node, Xc, sf, thr, left, right, mt, dl, has_cat,
                ic=None, co=None, cn=None, cw=None):
    """One depth step of the [T', nc] node chase, written once.

    ``node`` < 0 marks a frozen row (two's-complement leaf id); the
    returned state keeps frozen entries untouched, so the step is
    idempotent and any trip count >= the true depth is exact.  All
    operand planes are FULL [T', I] arrays (thresholds already in f32)
    — the jnp variants pass the DeviceForest arrays through unchanged
    and the Pallas kernel passes its VMEM-resident blocks, so every
    variant evaluates literally this expression.
    """
    import jax.numpy as jnp

    T, nc = node.shape
    from jax import lax
    rows = lax.broadcasted_iota(jnp.int32, (T, nc), 1)
    tid2 = lax.broadcasted_iota(jnp.int32, (T, nc), 0)
    nd = jnp.maximum(node, 0)
    fval = Xc[rows, sf[tid2, nd]]
    th = thr[tid2, nd]
    m = mt[tid2, nd]
    nan = jnp.isnan(fval)
    fz = jnp.where(nan & (m != 2), 0.0, fval)
    is_missing = ((m == 1) & (jnp.abs(fz) <= _K_ZERO)) | ((m == 2) & nan)
    gl = jnp.where(is_missing, dl[tid2, nd] != 0, fz <= th)
    if has_cat:
        # truncate toward zero (reference static_cast<int> semantics)
        iv = jnp.fix(jnp.where(nan, -1.0, fval)).astype(jnp.int32)
        nw = cn[tid2, nd]
        valid = (iv >= 0) & (iv < nw * 32)
        ivc = jnp.clip(iv, 0, None)
        widx = co[tid2, nd] + jnp.minimum(ivc // 32, jnp.maximum(nw - 1, 0))
        inset = (cw[0, widx] >> (ivc % 32).astype(jnp.uint32)) & 1
        gl = jnp.where(ic[tid2, nd] != 0, valid & (inset == 1), gl)
    nxt = jnp.where(gl, left[tid2, nd], right[tid2, nd])
    return jnp.where(node < 0, node, nxt)


def full_threshold_f32(dev) -> "np.ndarray":
    """The complete [T, I] f32 threshold plane for ``dev``, elementwise
    identical to what ``DeviceForest._thr_at`` gathers: bf16 widens,
    int8 dequantizes (q * per-tree scale) with the sparse fix-mask
    correction for non-quantized nodes.  Dequantization is elementwise,
    so precomputing the plane cannot change a single routing bit."""
    import jax.numpy as jnp
    if dev.precision == "bf16":
        return dev.threshold.astype(jnp.float32)
    if dev.precision == "int8":
        thr = dev.threshold.astype(jnp.float32) * dev._thr_scale
        return jnp.where(dev._thr_fix_mask, dev._thr_fix, thr)
    return dev.threshold


def kernel_args(dev) -> dict:
    """The fused kernel's operand planes for ``dev``, cached on the
    instance: int32 copies of the routing arrays (Mosaic has no i64 or
    1-bit lanes), the precomputed f32 threshold plane, and the bitset
    words lifted to a 2D [1, W] block."""
    cached = dev.__dict__.get("_fused_kernel_args")
    if cached is not None:
        return cached
    import jax
    import jax.numpy as jnp
    # never build (and cache!) these under an active trace — the planes
    # must be concrete device arrays, not leaked tracers
    with jax.ensure_compile_time_eval():
        args = {
            "sf": dev.split_feature.astype(jnp.int32),
            "thr": full_threshold_f32(dev),
            "left": dev.left.astype(jnp.int32),
            "right": dev.right.astype(jnp.int32),
            "mt": dev.missing_type.astype(jnp.int32),
            "dl": dev.default_left.astype(jnp.int32),
            "ic": dev.is_cat.astype(jnp.int32),
            "co": dev.cat_offset.astype(jnp.int32),
            "cn": dev.cat_nwords.astype(jnp.int32),
            "cw": dev.cat_words.reshape(1, -1),
        }
    dev.__dict__["_fused_kernel_args"] = args
    return args


# ----------------------------------------------------------------------
# jnp variants
# ----------------------------------------------------------------------

def _dev_planes(dev):
    import jax.numpy as jnp
    return dict(sf=dev.split_feature, thr=full_threshold_f32(dev),
                left=dev.left, right=dev.right, mt=dev.missing_type,
                dl=dev.default_left.astype(jnp.int32),
                has_cat=dev.forest.has_cat,
                ic=dev.is_cat.astype(jnp.int32),
                co=dev.cat_offset.astype(jnp.int32), cn=dev.cat_nwords,
                cw=dev.cat_words.reshape(1, -1))


def leaves_while(dev, Xc):
    """[nc, F] f32 -> leaf index [T, nc] under ``lax.while_loop`` —
    the legacy arm, one shared step expression."""
    import jax.numpy as jnp
    from jax import lax
    planes = _dev_planes(dev)
    T = dev.forest.num_trees
    node = lax.while_loop(
        lambda nd: jnp.any(nd >= 0),
        lambda nd: decide_step(nd, Xc, **planes),
        jnp.zeros((T, Xc.shape[0]), jnp.int32))
    return ~node


def leaves_fori(dev, Xc):
    """[nc, F] f32 -> leaf index [T, nc] in exactly ``max_depth`` fixed
    trips — no convergence sync, AOT-export-clean (the trip count is a
    trace-time constant; ``StackedForest.max_depth`` counts decisions on
    the deepest root-to-leaf path, so it is exactly sufficient)."""
    import jax.numpy as jnp
    from jax import lax
    planes = _dev_planes(dev)
    T = dev.forest.num_trees
    node = lax.fori_loop(
        0, max(int(dev.forest.max_depth), 1),
        lambda _, nd: decide_step(nd, Xc, **planes),
        jnp.zeros((T, Xc.shape[0]), jnp.int32))
    return ~node


# ----------------------------------------------------------------------
# the fused Pallas kernel
# ----------------------------------------------------------------------

def _traverse_kernel(depth, has_cat, num_class, emit_scores):
    """Kernel body factory.  One grid step owns one row tile: descend
    all trees to their leaves with the node state held in VMEM, then
    either write the [T, tile] leaf ids or gather+accumulate the
    [K, tile] raw scores in pinned iteration-major order."""
    import jax.numpy as jnp
    from jax import lax

    def kernel(x_ref, sf_ref, thr_ref, left_ref, right_ref, mt_ref,
               dl_ref, ic_ref, co_ref, cn_ref, cw_ref, *rest):
        lv_ref, out_ref = rest if emit_scores else (None, rest[0])
        X = x_ref[...]
        T = sf_ref.shape[0]
        tile = X.shape[0]
        planes = dict(
            sf=sf_ref[...], thr=thr_ref[...], left=left_ref[...],
            right=right_ref[...], mt=mt_ref[...], dl=dl_ref[...],
            has_cat=has_cat, ic=ic_ref[...], co=co_ref[...],
            cn=cn_ref[...], cw=cw_ref[...])
        node = lax.fori_loop(
            0, depth, lambda _, nd: decide_step(nd, X, **planes),
            jnp.zeros((T, tile), jnp.int32))
        leaves = ~node
        if not emit_scores:
            out_ref[...] = leaves
            return
        tid2 = lax.broadcasted_iota(jnp.int32, (T, tile), 0)
        lv = lv_ref[...][tid2, leaves]                   # [T, tile] f32
        K = max(num_class, 1)
        lv3 = lv.reshape(T // K, K, tile)
        # pinned tree order: sequential iteration-major accumulation,
        # bit-stable run to run (jnp.sum may re-associate)
        out_ref[...] = lax.fori_loop(
            0, T // K, lambda i, acc: acc + lv3[i],
            jnp.zeros((K, tile), jnp.float32))

    return kernel


def fused_traverse(dev, Xpad, tile_rows: int = 512, num_class: int = 1,
                   emit_scores: bool = False, interpret=None):
    """Fused tile-streaming traversal of ``Xpad`` [n, F] f32.

    Returns leaf indices [T, n] i32, or raw scores [K, n] f32 when
    ``emit_scores`` (requires device leaf values).  Rows are padded up
    to a whole number of tiles and the pad columns sliced off; a padded
    all-zero row routes like any ordinary row, it just gets discarded.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if emit_scores and dev.leaf_value is None:
        raise ValueError("fused score accumulation needs device leaf "
                         "values (routing_only forest)")
    args = kernel_args(dev)
    n, F = Xpad.shape
    T = dev.forest.num_trees
    K = max(num_class, 1)
    tile = max(min(int(tile_rows), max(n, 1)), 8)
    ntiles = max(-(-n // tile), 1)
    npad = ntiles * tile
    X = jnp.asarray(Xpad, jnp.float32)
    if npad != n:
        X = jnp.pad(X, ((0, npad - n), (0, 0)))

    def _full(a):
        return pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)

    operands = [X] + [args[k] for k in
                      ("sf", "thr", "left", "right", "mt", "dl",
                       "ic", "co", "cn", "cw")]
    in_specs = [pl.BlockSpec((tile, F), lambda i: (i, 0))] + \
        [_full(a) for a in operands[1:]]
    if emit_scores:
        operands.append(dev.leaf_value)
        in_specs.append(_full(dev.leaf_value))
        out_shape = jax.ShapeDtypeStruct((K, npad), jnp.float32)
        out_specs = pl.BlockSpec((K, tile), lambda i: (0, i))
    else:
        out_shape = jax.ShapeDtypeStruct((T, npad), jnp.int32)
        out_specs = pl.BlockSpec((T, tile), lambda i: (0, i))
    kernel = _traverse_kernel(max(int(dev.forest.max_depth), 1),
                              dev.forest.has_cat, K, emit_scores)
    out = pl.pallas_call(
        kernel, grid=(ntiles,), in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=_interp(interpret))(*operands)
    return out[:, :n]


# ----------------------------------------------------------------------
# per-backend verification probe (the take_from_table precedent)
# ----------------------------------------------------------------------

_FUSED_PREDICT_PROBE: dict = {}


def fused_predict_verified(dev) -> bool:
    """One-time per (backend, precision, cat) verdict: the fused kernel
    must reproduce the while_loop arm's leaf indices BIT-exactly on a
    probe batch covering zeros, NaNs and sign extremes, or it is demoted
    (the caller falls back to ``fori``).  Off accelerators the kernel
    interprets as the same jnp math, so the answer is trivially yes."""
    import jax
    backend = jax.default_backend()
    if backend not in ("tpu", "axon"):
        return True
    key = (backend, dev.precision, bool(dev.forest.has_cat))
    ok = _FUSED_PREDICT_PROBE.get(key)
    if ok is None:
        try:
            F = int(np.asarray(dev.split_feature).max(initial=0)) + 1
            rng = np.random.RandomState(7)
            X = rng.standard_normal((16, F)).astype(np.float32) * 10.0
            X[0] = 0.0
            X[1] = np.nan
            X[2] = -1e30
            X[3] = 1e30
            X[4, ::2] = np.nan
            ref = np.asarray(jax.jit(dev._leaves)(X))
            got = np.asarray(fused_traverse(dev, X, tile_rows=8))
            ok = bool(np.array_equal(ref, got))
            if not ok:
                warnings.warn(
                    "fused predict kernel demoted: leaf indices diverged "
                    f"from the while_loop arm on backend {backend!r}")
        except Exception as e:                      # compile/lowering loss
            warnings.warn("fused predict kernel failed its probe on "
                          f"backend {backend!r} ({e}); demoting to fori")
            ok = False
        _FUSED_PREDICT_PROBE[key] = ok
    return bool(ok)
