"""Fused Pallas histogram→split megakernel: one HBM pass per level.

Why this module exists (ROADMAP item 2, bench telemetry): the staged
pipeline runs histogram build and split-gain scan as SEPARATE device
programs with the materialized ``[L, ch, F, B]`` histogram round-tripping
through HBM between them — ``mfu_histogram_lower_bound`` pinned at
~0.0005 even in the best sorted-arena run (0.852 s/tree at 1M×28).  The
GPU prior art (shared-memory histograms: Wen et al., arXiv 1706.08359;
XGBoost GPU, arXiv 1806.11248) accumulates bins in fast on-chip memory
and scans gains before ever writing back; this is the TPU/Pallas shape
of that move:

- Grid = (feature blocks, row tiles), row axis fastest.  Each binned
  row tile streams HBM→VMEM ONCE per level (Pallas' block pipeline
  double-buffers the tile DMA against compute automatically — the
  planner's ``fused_vmem_bytes`` model charges 2× tile bytes for it).
- Per-leaf grad/hess bins accumulate into a VMEM scratch arena in the
  slot-expanded MXU formulation (``segment_histogram_expanded``'s
  one-hot ⊗ slot-mask matmul — the quantized 2×64-slot layout fills one
  s8 MXU tile exactly), so the arena never leaves the chip between the
  build and the scan.
- After the last tile, STILL IN-KERNEL: sibling-subtraction children
  derive their histograms from the parent arena carried alongside the
  scratch (``sibling = parent − smaller``), the quantized arena is
  rescaled (``quant_rescale_hist``'s formulas, kept in lockstep), and
  the per-feature cumulative-sum gain scan runs — BOTH missing-direction
  sweeps, the L1/L2 thresholds, the monotone clamp when constraints
  ride along — via ``ops.split.numeric_feature_scan``, the SAME function
  the staged pipeline calls, so fused == staged per-feature-best tuples
  are bit-identical by construction given bit-identical histograms
  (exactly the case for the integer family: int32 accumulation is
  associative).
- Writeback per level is the tiny ``[children, F]`` per-feature-best
  tuple set (gain, bin, direction, left sums) plus the one smaller-child
  histogram the growers' subtraction cache needs — the staged pipeline's
  extra hist-cache read for the scan (and the sibling's write+read) never
  happens.  ``hist_scan_traffic_bytes`` is the accounting twin.

**The collective seam** (sharded training): gains are NOT summable
across data shards, but the smaller-child histograms are — so the
megakernel splits into ``fused_frontier_accumulate`` (the accumulate
half, emitting the LOCAL ``[K, ch, F, B]`` arena straight from VMEM)
→ one tiered ``psum``/``psum_int_tiered`` of exactly those hists over
ICI/DCN (``parallel/collectives.py``) → ``fused_sibling_scan`` (the
epilogue half: sibling-derive + rescale + gain scan on the REDUCED
arena).  Both halves run the verbatim code paths of the combined
kernel (``_accumulate_tile`` / ``_derive_and_scan``), so sharded fused
== sharded staged stays bit-identical for the integer family, and the
staged ``[L, ch, F, B]`` HBM scan round-trip disappears from the
data-parallel path too — only hists cross the wire.

Scope: numeric AND categorical features (per-category stats are the
same segment reduction — the kernel accumulates every column and the
growers override the in-kernel numeric tuples on categorical columns
with the shared ``feature_best_splits`` cat scan via
``pick_fused_best``'s merge), with or without monotone constraints
(the constraint vector rides as a fourth meta row and the per-child
output bounds as a ``[2, NC]`` input into the in-kernel scan).  The
growers still gate the fused arm off for EFB bundles and per-node
randomness (extra_trees / by-node column sampling), falling back to
the staged family; ``hist_method=auto`` elects fused only when
``ops.planner.plan_fused`` proves the VMEM arena fits.  "One HBM pass
per LEVEL" is the rounds grower's contract (one kernel per frontier
round); the serial grower's fused arm streams the full matrix once per
SPLIT with no leaf compaction — it exists for mode completeness and
the parity suite, so ``auto`` only elects fused where the rounds
grower runs (explicit ``hist_method=fused`` still honors a forced
``tpu_tree_growth=serial``).

Off-accelerator the whole family runs under
``pl.pallas_call(..., interpret=True)`` so tier-1's ``JAX_PLATFORMS=cpu``
pytest run executes the kernels instead of skipping them.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .histogram import _pad_rows, on_accelerator, resolve_tile_rows
from .split import (K_MIN_SCORE, MAX_CAT_WORDS, NumericFeatureBest,
                    PerFeatureBest, SplitHyperparams, SplitResult,
                    numeric_feature_scan, quant_rescale_hist)

# row-tile (VMEM block) and feature-block defaults; the planner's
# plan_fused() picks per-shape values against the VMEM budget
_DEF_BLOCK_ROWS = 512
_DEF_FEAT_TILE = 8


def _interp(interpret: Optional[bool]) -> bool:
    return (not on_accelerator()) if interpret is None else bool(interpret)


def hist_scan_traffic_bytes(num_candidates: int, num_features: int,
                            num_bins: int, quant: bool = False) -> int:
    """Per-level HBM bytes the fused kernel does NOT move vs staged.

    Staged, per round of K candidates: the split scan re-reads both
    children's histograms (2K·ch·F·B cells) and the sibling histograms
    are written+read through the cache (K·ch·F·B each way).  Fused scans
    in VMEM and derives siblings in-kernel, so exactly this term drops;
    ``tools/hist_probe.py --fused`` journals it next to the measured
    ``bytes_accessed`` delta.  The SHARDED seam keeps the same drop: the
    psum moves only the ``[K, ch, F, B]`` smaller-child arena the staged
    sharded arm already moves, while the scan re-read + sibling
    write/read still never touch HBM."""
    ch = 2 if quant else 3
    cell = ch * num_features * num_bins * 4
    return num_candidates * cell * 4          # 2K scan reads + K write + K read


def _derive_and_scan(small, sums_k, meta_rows, hp,
                     parent=None, s_is_left_vec=None, scales=None,
                     mono=None, bounds=None):
    """The megakernel epilogue body, shared VERBATIM by the combined
    kernel and ``fused_sibling_scan`` (the post-collective half of the
    sharded seam) so their tuples cannot diverge.

    ``small`` [K, ch, Ft, B]; ``parent`` None | [K, ch, Ft, B];
    ``s_is_left_vec`` None | [K] i32; ``sums_k`` [3, NC];
    ``meta_rows`` (num_bin, missing, default) [Ft] rows; ``mono``
    None | [Ft] i32; ``bounds`` None | ([NC], [NC]) per-child output
    clamp.  Returns ``NumericFeatureBest`` [NC, Ft]."""
    if parent is not None:
        s_is_left = (s_is_left_vec != 0)[:, None, None, None]
        h_left = jnp.where(s_is_left, small, parent - small)
        h_right = parent - h_left
        ch_hist = jnp.concatenate([h_left, h_right], axis=0)
    else:
        ch_hist = small
    sg, sh, cnt = sums_k[0], sums_k[1], sums_k[2]
    if scales is not None:
        # the SHARED rescale body (batched over children; its default
        # count factor reads the block's FIRST feature — any feature's
        # bins partition the child's rows, so the integer total equals
        # the staged feature-0 total bit-for-bit)
        hist3 = quant_rescale_hist(ch_hist, scales[0], scales[1], cnt)
    else:
        hist3 = ch_hist
    return numeric_feature_scan(
        hist3, sg, sh, cnt, meta_rows[0], meta_rows[1], meta_rows[2], hp,
        monotone_constraints=mono, leaf_output_bounds=bounds)


def _fused_call(
    binned_t: jax.Array,          # [F, n] uint8/uint16 feature-major
    vals_t: jax.Array,            # f32 [3, n] (g,h,1)*w  |  int8 [2, n]
    slot: jax.Array,              # [n] i32 in [0, K]; K = dropped
    num_slots: int,
    num_bins: int,
    child_sums: Optional[jax.Array],  # [3, NC] f32 (sum_g, sum_h, count)
    meta_vecs: Optional[tuple],   # (num_bin, missing_type, default_bin) [F]
    hp: Optional[SplitHyperparams],
    small_left: Optional[jax.Array] = None,   # [K] bool (with parent)
    parent_hist: Optional[jax.Array] = None,  # [K, ch, F, B]
    quant_scales: Optional[tuple] = None,     # (g_scale, h_scale) traced
    monotone_constraints: Optional[jax.Array] = None,  # [F] i32
    child_bounds: Optional[tuple] = None,     # ([NC], [NC]) output clamp
    feat_tile: Optional[int] = None,
    block_rows: Optional[int] = None,
    tile_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
    with_scan: bool = True,
):
    """One megakernel invocation; returns ``(slot_hist [K, ch, F, B],
    NumericFeatureBest [NC, F])`` with NC = 2K (parent mode: children are
    [left 0..K-1, right K..2K-1]) or K (leaf mode: the slot histograms
    themselves are scanned).  ``with_scan=False`` drops the epilogue and
    its inputs entirely — the accumulate half of the collective seam —
    and returns only the histogram."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    quant = vals_t.dtype == jnp.int8
    ch = int(vals_t.shape[0])
    acc_dtype = jnp.int32 if quant else jnp.float32
    F, n = binned_t.shape
    K = int(num_slots)
    B = int(num_bins)
    with_parent = parent_hist is not None
    NC = 2 * K if with_parent else K
    if with_scan and quant and quant_scales is None:
        raise ValueError("quantized fused kernel needs quant_scales")
    has_mono = with_scan and monotone_constraints is not None
    has_bounds = with_scan and child_bounds is not None

    if feat_tile is None or block_rows is None:
        from .planner import plan_fused
        fp = plan_fused(K, B, quant, with_parent=with_parent)
        if feat_tile is None:
            feat_tile = fp["feat_tile"] if fp else 1
        if block_rows is None:
            block_rows = fp["block_rows"] if fp else 128
    Ft = max(1, min(int(feat_tile), F))
    # tile_rows (the planner's row-tile budget) CAPS the VMEM block like
    # the staged family's _tile_block: peak per-step bytes track the tile
    T = resolve_tile_rows(tile_rows, n)
    C = int(block_rows)
    if T is not None:
        C = min(C, max(128, _pad_rows(T, 128)))
    C = max(128, C)

    n_pad = _pad_rows(n, C)
    F_pad = _pad_rows(F, Ft)
    bt = binned_t
    if n_pad != n or F_pad != F:
        bt = jnp.pad(bt, ((0, F_pad - F), (0, n_pad - n)))
    vt = jnp.pad(vals_t, ((0, 0), (0, n_pad - n))) if n_pad != n else vals_t
    st = jnp.pad(slot.astype(jnp.int32), (0, n_pad - n),
                 constant_values=K)[None, :]               # [1, n_pad]
    nf_blocks = F_pad // Ft
    nt = n_pad // C

    in_arrays = [bt, vt, st]
    in_specs = [
        pl.BlockSpec((Ft, C), lambda j, i: (j, i)),
        pl.BlockSpec((ch, C), lambda j, i: (0, i)),
        pl.BlockSpec((1, C), lambda j, i: (0, i)),
    ]
    if with_parent:
        in_arrays.append(parent_hist.astype(acc_dtype))
        in_specs.append(pl.BlockSpec((K, ch, Ft, B),
                                     lambda j, i: (0, 0, j, 0)))
        in_arrays.append(small_left.astype(jnp.int32)[None, :])  # [1, K]
        in_specs.append(pl.BlockSpec((1, K), lambda j, i: (0, 0)))
    if with_scan:
        num_bin_v, missing_v, default_v = meta_vecs
        meta_rows = [jnp.asarray(num_bin_v, jnp.int32),
                     jnp.asarray(missing_v, jnp.int32),
                     jnp.asarray(default_v, jnp.int32)]
        if has_mono:
            meta_rows.append(jnp.asarray(monotone_constraints, jnp.int32))
        meta = jnp.stack(meta_rows)                        # [3|4, F]
        if F_pad != F:
            # padded features: num_bin 0 -> every bin invalid -> gain -inf
            meta = jnp.pad(meta, ((0, 0), (0, F_pad - F)))
        R = int(meta.shape[0])
        sums = jnp.asarray(child_sums, jnp.float32)        # [3, NC]
        in_arrays.append(sums)
        in_specs.append(pl.BlockSpec((3, NC), lambda j, i: (0, 0)))
        in_arrays.append(meta)
        in_specs.append(pl.BlockSpec((R, Ft), lambda j, i: (0, j)))
        if quant:
            in_arrays.append(
                jnp.stack([jnp.asarray(quant_scales[0], jnp.float32),
                           jnp.asarray(quant_scales[1],
                                       jnp.float32)])[None, :])
            in_specs.append(pl.BlockSpec((1, 2), lambda j, i: (0, 0)))
        if has_bounds:
            in_arrays.append(jnp.stack(
                [jnp.asarray(child_bounds[0], jnp.float32),
                 jnp.asarray(child_bounds[1], jnp.float32)]))   # [2, NC]
            in_specs.append(pl.BlockSpec((2, NC), lambda j, i: (0, 0)))

    def kernel(*refs):
        it = iter(refs)
        b_ref = next(it)
        v_ref = next(it)
        s_ref = next(it)
        p_ref = next(it) if with_parent else None
        sl_ref = next(it) if with_parent else None
        sum_ref = next(it) if with_scan else None
        m_ref = next(it) if with_scan else None
        sc_ref = next(it) if with_scan and quant else None
        bd_ref = next(it) if has_bounds else None
        hist_ref = next(it)
        if with_scan:
            gn_ref = next(it)
            th_ref = next(it)
            dl_ref = next(it)
            lg_ref = next(it)
            lh_ref = next(it)
            lc_ref = next(it)
        acc = next(it)

        i = pl.program_id(1)

        @pl.when(i == 0)
        def _init():
            acc[...] = jnp.zeros_like(acc)

        _accumulate_tile(acc, b_ref, v_ref, s_ref, K, Ft, B, ch, quant)

        # ---- epilogue after the last tile: derive + scan in VMEM ----
        @pl.when(i == nt - 1)
        def _epilogue():
            small = acc[...].reshape(ch, K, Ft, B).transpose(1, 0, 2, 3)
            hist_ref[...] = small
            if with_scan:
                res = _derive_and_scan(
                    small, sum_ref[...],
                    (m_ref[0, :], m_ref[1, :], m_ref[2, :]), hp,
                    parent=p_ref[...] if with_parent else None,
                    s_is_left_vec=sl_ref[0, :] if with_parent else None,
                    scales=(sc_ref[0, 0], sc_ref[0, 1]) if quant else None,
                    mono=m_ref[3, :] if has_mono else None,
                    bounds=(bd_ref[0, :], bd_ref[1, :]) if has_bounds
                    else None)
                gn_ref[...] = res.gain
                th_ref[...] = res.threshold
                dl_ref[...] = res.default_left.astype(jnp.int32)
                lg_ref[...] = res.left_sum_grad
                lh_ref[...] = res.left_sum_hess
                lc_ref[...] = res.left_count

    hist_spec = pl.BlockSpec((K, ch, Ft, B), lambda j, i: (0, 0, j, 0))
    hist_shape = jax.ShapeDtypeStruct((K, ch, F_pad, B), acc_dtype)
    tuple_spec = pl.BlockSpec((NC, Ft), lambda j, i: (0, j))
    if with_scan:
        out_specs = [hist_spec] + [tuple_spec] * 6
        out_shape = [
            hist_shape,
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),   # gain
            jax.ShapeDtypeStruct((NC, F_pad), jnp.int32),     # threshold
            jax.ShapeDtypeStruct((NC, F_pad), jnp.int32),     # default_left
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),   # left_sum_grad
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),   # left_sum_hess
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),   # left_count
        ]
    else:
        out_specs = [hist_spec]
        out_shape = [hist_shape]
    out = pl.pallas_call(
        kernel,
        grid=(nf_blocks, nt),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((ch * K, Ft * B), acc_dtype)],
        interpret=_interp(interpret),
    )(*in_arrays)
    if not with_scan:
        return out[0][:, :, :F, :]
    hist, gain, thr, dl, lgs, lhs_, lcs = out
    best = NumericFeatureBest(
        gain=gain[:, :F], threshold=thr[:, :F],
        default_left=dl[:, :F].astype(bool),
        left_sum_grad=lgs[:, :F], left_sum_hess=lhs_[:, :F],
        left_count=lcs[:, :F])
    return hist[:, :, :F, :], best


def _accumulate_tile(acc, b_ref, v_ref, s_ref, K, Ft, B, ch, quant):
    """One row tile of the slot-expanded one-hot matmul, accumulated
    into the VMEM arena — the accumulate half of the megakernel, shared
    verbatim by the combined kernel and ``fused_frontier_accumulate``."""
    blk = b_ref[...].astype(jnp.int32)                 # [Ft, C]
    C = blk.shape[1]
    sl = s_ref[0, :]                                   # [C]
    iota_s = lax.broadcasted_iota(jnp.int32, (K, C), 0)
    oh_s = sl[None, :] == iota_s                       # [K, C]
    v = v_ref[...]                                     # [ch, C]
    iota_b = lax.broadcasted_iota(jnp.int32, (C, Ft, B), 2)
    ohb = blk.T[:, :, None] == iota_b                  # [C, Ft, B]
    if quant:
        lhs = (v[:, None, :] * oh_s[None].astype(jnp.int8)
               ).reshape(ch * K, C)
        part = lax.dot(lhs, ohb.astype(jnp.int8).reshape(C, Ft * B),
                       preferred_element_type=jnp.int32)
    else:
        lhs = (v[:, None, :] * oh_s[None].astype(jnp.float32)
               ).reshape(ch * K, C)
        part = lax.dot(lhs, ohb.astype(jnp.float32).reshape(C, Ft * B),
                       precision=lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
    acc[...] += part


def fused_frontier_accumulate(
    binned_t: jax.Array,
    vals_t: jax.Array,
    slot: jax.Array,
    num_slots: int,
    num_bins: int,
    feat_tile: Optional[int] = None,
    block_rows: Optional[int] = None,
    tile_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """The accumulate HALF of the collective seam: build the K
    smaller-child (or slot) histograms in the VMEM arena and emit them —
    no scan, no parent.  Returns ``hist [K, ch, F, B]`` (int32 when
    ``vals_t`` is int8, f32 otherwise).

    Sharded training runs THIS program per shard, reduces exactly its
    output over the data axes (``psum_int_tiered`` / tiered ``psum``),
    then hands the reduced arena to ``fused_sibling_scan`` — gains stay
    local, only hists cross the wire.  One program also serves every
    frontier level AND the root (slot 0 = all member rows): the shared
    frontier program of the compile-time ladder (docs/PERF.md)."""
    return _fused_call(
        binned_t, vals_t, slot, num_slots, num_bins, None, None, None,
        feat_tile=feat_tile, block_rows=block_rows, tile_rows=tile_rows,
        interpret=interpret, with_scan=False)


def fused_sibling_scan(
    small_hist: jax.Array,         # [K, ch, F, B] REDUCED smaller-child hists
    child_sums: jax.Array,         # [3, NC] (NC = 2K parent mode, K leaf)
    num_bin: jax.Array,
    missing_type: jax.Array,
    default_bin: jax.Array,
    hp: SplitHyperparams,
    small_left: Optional[jax.Array] = None,   # [K] bool (parent mode)
    parent_hist: Optional[jax.Array] = None,  # [K, ch, F, B]
    quant_scales: Optional[tuple] = None,
    monotone_constraints: Optional[jax.Array] = None,  # [F] i32
    child_bounds: Optional[tuple] = None,     # ([NC], [NC]) output clamp
    feat_tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> NumericFeatureBest:
    """The scan HALF of the collective seam: sibling-derive + rescale +
    gain scan over the ALREADY-REDUCED arena, one feature block per grid
    step, all in VMEM.  The body is ``_derive_and_scan`` — the verbatim
    epilogue of the combined kernel — so seam-split tuples equal combined
    tuples bit-for-bit given equal histograms."""
    from jax.experimental import pallas as pl

    quant = jnp.issubdtype(small_hist.dtype, jnp.integer)
    if quant and quant_scales is None:
        raise ValueError("quantized fused sibling scan needs quant_scales")
    K, ch, F, B = (int(d) for d in small_hist.shape)
    with_parent = parent_hist is not None
    NC = 2 * K if with_parent else K
    has_mono = monotone_constraints is not None
    has_bounds = child_bounds is not None
    if feat_tile is None:
        from .planner import plan_fused
        fp = plan_fused(K, B, bool(quant), with_parent=with_parent)
        feat_tile = fp["feat_tile"] if fp else 1
    Ft = max(1, min(int(feat_tile), F))
    F_pad = _pad_rows(F, Ft)
    acc_dtype = jnp.int32 if quant else jnp.float32

    small = small_hist.astype(acc_dtype)
    if F_pad != F:
        small = jnp.pad(small, ((0, 0), (0, 0), (0, F_pad - F), (0, 0)))
    meta_rows = [jnp.asarray(num_bin, jnp.int32),
                 jnp.asarray(missing_type, jnp.int32),
                 jnp.asarray(default_bin, jnp.int32)]
    if has_mono:
        meta_rows.append(jnp.asarray(monotone_constraints, jnp.int32))
    meta = jnp.stack(meta_rows)
    if F_pad != F:
        meta = jnp.pad(meta, ((0, 0), (0, F_pad - F)))
    R = int(meta.shape[0])

    in_arrays = [small]
    in_specs = [pl.BlockSpec((K, ch, Ft, B), lambda j: (0, 0, j, 0))]
    if with_parent:
        parent = parent_hist.astype(acc_dtype)
        if F_pad != F:
            parent = jnp.pad(parent,
                             ((0, 0), (0, 0), (0, F_pad - F), (0, 0)))
        in_arrays.append(parent)
        in_specs.append(pl.BlockSpec((K, ch, Ft, B), lambda j: (0, 0, j, 0)))
        in_arrays.append(small_left.astype(jnp.int32)[None, :])
        in_specs.append(pl.BlockSpec((1, K), lambda j: (0, 0)))
    in_arrays.append(jnp.asarray(child_sums, jnp.float32))
    in_specs.append(pl.BlockSpec((3, NC), lambda j: (0, 0)))
    in_arrays.append(meta)
    in_specs.append(pl.BlockSpec((R, Ft), lambda j: (0, j)))
    if quant:
        in_arrays.append(
            jnp.stack([jnp.asarray(quant_scales[0], jnp.float32),
                       jnp.asarray(quant_scales[1], jnp.float32)])[None, :])
        in_specs.append(pl.BlockSpec((1, 2), lambda j: (0, 0)))
    if has_bounds:
        in_arrays.append(jnp.stack(
            [jnp.asarray(child_bounds[0], jnp.float32),
             jnp.asarray(child_bounds[1], jnp.float32)]))
        in_specs.append(pl.BlockSpec((2, NC), lambda j: (0, 0)))

    def kernel(*refs):
        it = iter(refs)
        sm_ref = next(it)
        p_ref = next(it) if with_parent else None
        sl_ref = next(it) if with_parent else None
        sum_ref = next(it)
        m_ref = next(it)
        sc_ref = next(it) if quant else None
        bd_ref = next(it) if has_bounds else None
        gn_ref = next(it)
        th_ref = next(it)
        dl_ref = next(it)
        lg_ref = next(it)
        lh_ref = next(it)
        lc_ref = next(it)

        res = _derive_and_scan(
            sm_ref[...], sum_ref[...],
            (m_ref[0, :], m_ref[1, :], m_ref[2, :]), hp,
            parent=p_ref[...] if with_parent else None,
            s_is_left_vec=sl_ref[0, :] if with_parent else None,
            scales=(sc_ref[0, 0], sc_ref[0, 1]) if quant else None,
            mono=m_ref[3, :] if has_mono else None,
            bounds=(bd_ref[0, :], bd_ref[1, :]) if has_bounds else None)
        gn_ref[...] = res.gain
        th_ref[...] = res.threshold
        dl_ref[...] = res.default_left.astype(jnp.int32)
        lg_ref[...] = res.left_sum_grad
        lh_ref[...] = res.left_sum_hess
        lc_ref[...] = res.left_count

    tuple_spec = pl.BlockSpec((NC, Ft), lambda j: (0, j))
    out = pl.pallas_call(
        kernel,
        grid=(F_pad // Ft,),
        in_specs=in_specs,
        out_specs=[tuple_spec] * 6,
        out_shape=[
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),
            jax.ShapeDtypeStruct((NC, F_pad), jnp.int32),
            jax.ShapeDtypeStruct((NC, F_pad), jnp.int32),
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),
            jax.ShapeDtypeStruct((NC, F_pad), jnp.float32),
        ],
        interpret=_interp(interpret),
    )(*in_arrays)
    gain, thr, dl, lgs, lhs_, lcs = out
    return NumericFeatureBest(
        gain=gain[:, :F], threshold=thr[:, :F],
        default_left=dl[:, :F].astype(bool),
        left_sum_grad=lgs[:, :F], left_sum_hess=lhs_[:, :F],
        left_count=lcs[:, :F])


def fused_segment_splits(
    binned_t: jax.Array,
    vals_t: jax.Array,
    slot: jax.Array,
    num_slots: int,
    num_bins: int,
    slot_sums: jax.Array,          # [3, K] per-slot (sum_g, sum_h, count)
    num_bin: jax.Array,
    missing_type: jax.Array,
    default_bin: jax.Array,
    hp: SplitHyperparams,
    quant_scales: Optional[tuple] = None,
    monotone_constraints: Optional[jax.Array] = None,
    child_bounds: Optional[tuple] = None,
    feat_tile: Optional[int] = None,
    block_rows: Optional[int] = None,
    tile_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Leaf mode: build K slot histograms AND their per-feature-best
    numeric splits in one pass.  Returns ``(hist [K, ch, F, B],
    NumericFeatureBest [K, F])`` — the staged equivalent is
    ``segment_histogram*`` + (rescale +) ``feature_best_splits`` with the
    full histogram round-tripping through HBM in between."""
    return _fused_call(
        binned_t, vals_t, slot, num_slots, num_bins, slot_sums,
        (num_bin, missing_type, default_bin), hp,
        quant_scales=quant_scales,
        monotone_constraints=monotone_constraints,
        child_bounds=child_bounds, feat_tile=feat_tile,
        block_rows=block_rows, tile_rows=tile_rows, interpret=interpret)


def fused_frontier_splits(
    binned_t: jax.Array,
    vals_t: jax.Array,
    slot: jax.Array,               # [n] i32: candidate rank of the row's
                                   # SMALLER child, K = dropped
    num_slots: int,                # K (the frontier width)
    num_bins: int,
    child_sums: jax.Array,         # [3, 2K] (left children, right children)
    small_left: jax.Array,         # [K] bool: smaller child is the LEFT one
    parent_hist: jax.Array,        # [K, ch, F, B] candidates' parent hists
    num_bin: jax.Array,
    missing_type: jax.Array,
    default_bin: jax.Array,
    hp: SplitHyperparams,
    quant_scales: Optional[tuple] = None,
    monotone_constraints: Optional[jax.Array] = None,
    child_bounds: Optional[tuple] = None,
    feat_tile: Optional[int] = None,
    block_rows: Optional[int] = None,
    tile_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Frontier mode (the growers' per-level call): accumulate the K
    smaller-child histograms in VMEM, derive each sibling from the parent
    arena in-kernel, scan BOTH children, and write back the smaller-child
    histograms (the subtraction cache's input) plus ``[2K, F]``
    per-feature-best tuples — one streamed pass over the binned matrix
    per level."""
    return _fused_call(
        binned_t, vals_t, slot, num_slots, num_bins, child_sums,
        (num_bin, missing_type, default_bin), hp,
        small_left=small_left, parent_hist=parent_hist,
        quant_scales=quant_scales,
        monotone_constraints=monotone_constraints,
        child_bounds=child_bounds, feat_tile=feat_tile,
        block_rows=block_rows, tile_rows=tile_rows, interpret=interpret)


def pick_fused_best(best: NumericFeatureBest, sum_grad, sum_hess, num_data,
                    feature_mask: Optional[jax.Array] = None,
                    cat_best: Optional[PerFeatureBest] = None,
                    cat_idx=None) -> SplitResult:
    """argmax over features of fused per-feature-best tuples — the
    numeric twin of ``ops.split.pick_best_feature`` (ties -> smaller
    feature index), vectorized over the leading children axis.  The
    feature mask applies here (outside the kernel): masking gains after
    the scan is exactly what ``feature_best_splits`` does inside.

    Categorical merge (the lifted gate): the kernel accumulates EVERY
    column — per-category stats are the same segment reduction — but its
    in-kernel NUMERIC scan is meaningless on categorical columns, so the
    growers run the shared ``feature_best_splits`` cat scan on just the
    categorical slice of the derived child histograms and pass it here as
    ``cat_best`` (fields [..., Fc]) with the static column indices
    ``cat_idx``.  Scattering those tuples over the numeric ones before
    the argmax reproduces ``feature_best_splits``' own
    ``jnp.where(is_categorical, cat, numeric)`` merge and
    ``pick_best_feature``'s tie order exactly."""
    gain = best.gain
    thr = best.threshold
    dl = best.default_left
    blg_f = best.left_sum_grad
    blh_f = best.left_sum_hess
    blc_f = best.left_count
    F = gain.shape[-1]
    is_cat = jnp.zeros(gain.shape, bool)
    bitset = jnp.zeros(gain.shape + (MAX_CAT_WORDS,), jnp.uint32)
    if cat_best is not None:
        ci = jnp.asarray(cat_idx, jnp.int32)
        gain = gain.at[..., ci].set(cat_best.gain)
        thr = thr.at[..., ci].set(cat_best.threshold.astype(thr.dtype))
        dl = dl.at[..., ci].set(cat_best.default_left.astype(dl.dtype))
        blg_f = blg_f.at[..., ci].set(cat_best.left_sum_grad)
        blh_f = blh_f.at[..., ci].set(cat_best.left_sum_hess)
        blc_f = blc_f.at[..., ci].set(cat_best.left_count)
        is_cat = is_cat.at[..., ci].set(cat_best.is_categorical)
        bitset = bitset.at[..., ci, :].set(cat_best.cat_bitset)
    if feature_mask is not None:
        gain = jnp.where(feature_mask.astype(bool), gain, K_MIN_SCORE)
    f = jnp.argmax(gain, axis=-1).astype(jnp.int32)

    def sel(a):
        return jnp.take_along_axis(a, f[..., None], -1)[..., 0]

    blg = sel(blg_f)
    blh = sel(blh_f)
    blc = sel(blc_f)
    return SplitResult(
        gain=sel(gain), feature=f,
        threshold=sel(thr),
        default_left=sel(dl),
        left_sum_grad=blg, left_sum_hess=blh, left_count=blc,
        right_sum_grad=jnp.asarray(sum_grad) - blg,
        right_sum_hess=jnp.asarray(sum_hess) - blh,
        right_count=jnp.asarray(num_data).astype(jnp.float32) - blc,
        is_categorical=sel(is_cat),
        cat_bitset=jnp.take_along_axis(
            bitset, f[..., None, None],
            -2)[..., 0, :] if cat_best is not None else
        jnp.zeros(f.shape + (MAX_CAT_WORDS,), jnp.uint32))


# one-time per-backend verdict: does the fused megakernel COMPILE AND
# AGREE with the staged pipeline on this backend?  {backend_name: bool}
_FUSED_PROBE: dict = {}


def fused_kernel_verified() -> bool:
    """Compile + run the fused kernel at a tiny shape on the live backend
    and check its tuples against the staged scan.

    The scan epilogue leans on ops (cumsum, argmax, take_along_axis)
    whose Pallas/Mosaic lowering varies by backend and jax version; a
    backend where any of them fails must NOT be elected by
    ``hist_method=auto`` — it falls back to the staged family instead of
    crashing the trace (same pattern as histogram.py's
    ``_table_matmul_verified``).  Off-accelerator (interpret mode) the
    kernel is plain jax — verified trivially."""
    backend = jax.default_backend()
    ok = _FUSED_PROBE.get(backend)
    if ok is not None:
        return ok
    if not on_accelerator():
        _FUSED_PROBE[backend] = True
        return True
    try:
        rng = np.random.RandomState(0)
        F, n, B, K = 4, 256, 8, 2
        binned = jnp.asarray(rng.randint(0, B - 1, (F, n)), jnp.uint8)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        h = jnp.abs(g) + 0.1
        vals = jnp.stack([g, h, jnp.ones_like(g)])
        slot = jnp.asarray(rng.randint(0, K + 1, n), jnp.int32)
        sums = []
        for k in range(K):
            m = np.asarray(slot) == k
            sums.append([float(np.asarray(g)[m].sum()),
                         float(np.asarray(h)[m].sum()), float(m.sum())])
        sums = jnp.asarray(np.asarray(sums).T, jnp.float32)
        nb = jnp.full((F,), B, jnp.int32)
        zero = jnp.zeros((F,), jnp.int32)
        hp = SplitHyperparams(min_data_in_leaf=1)
        hist, best = jax.jit(
            lambda b, v, s, su: fused_segment_splits(
                b, v, s, K, B, su, nb, zero, zero, hp,
                feat_tile=2, block_rows=128))(binned, vals, slot, sums)
        # BOTH halves of the kernel are checked: the accumulated
        # histograms against the staged scatter segment pass (a Mosaic
        # mis-lowering of the slot-expanded dot would be internally
        # consistent with the in-kernel scan, so scan parity alone
        # cannot catch it), and the scan against the shared body
        from .histogram import segment_histogram
        ref_hist = segment_histogram(binned, g, h, jnp.ones_like(g),
                                     slot, K, B)
        ok = bool(np.allclose(np.asarray(hist), np.asarray(ref_hist),
                              rtol=1e-4, atol=1e-3))
        ref = numeric_feature_scan(hist.astype(jnp.float32), sums[0],
                                   sums[1], sums[2], nb, zero, zero, hp)
        ok = ok and bool(np.allclose(np.asarray(best.gain),
                                     np.asarray(ref.gain), equal_nan=True))
        # the seam halves ride the same backend verdict: accumulate-only
        # must reproduce the combined kernel's arena, and the standalone
        # scan the combined kernel's tuples
        acc_only = jax.jit(
            lambda b, v, s: fused_frontier_accumulate(
                b, v, s, K, B, feat_tile=2, block_rows=128))(
                    binned, vals, slot)
        ok = ok and bool(np.allclose(np.asarray(acc_only),
                                     np.asarray(hist), rtol=1e-4,
                                     atol=1e-3))
        scan_only = jax.jit(
            lambda hh, su: fused_sibling_scan(
                hh, su, nb, zero, zero, hp, feat_tile=2))(hist, sums)
        ok = ok and bool(np.allclose(np.asarray(scan_only.gain),
                                     np.asarray(best.gain),
                                     equal_nan=True))
    except Exception:
        ok = False
    _FUSED_PROBE[backend] = ok
    if not ok:
        import warnings
        warnings.warn(
            f"fused histogram→split megakernel is unavailable on backend "
            f"{jax.default_backend()!r}; hist_method=auto falls back to "
            "the staged kernel family (set tpu_hist_method explicitly to "
            "override)")
    return ok


def fused_enabled_env() -> bool:
    """LGBM_TPU_FUSED=0 drops the fused arm (compile-cost bisect hook,
    mirroring LGBM_TPU_SEGHIST / LGBM_TPU_ROUTER)."""
    return os.environ.get("LGBM_TPU_FUSED") != "0"


def shared_frontier_enabled() -> bool:
    """LGBM_TPU_SHARED_FRONTIER=0 turns off the shared frontier program
    (the sharded fused root riding the SAME ``fused_frontier_accumulate``
    program as every level — slot 0 = all member rows — so one Mosaic
    kernel serves root + levels and the compile ladder shrinks by one
    program; docs/PERF.md "shared frontier programs")."""
    return os.environ.get("LGBM_TPU_SHARED_FRONTIER") != "0"
