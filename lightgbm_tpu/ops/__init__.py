"""Device-side compute ops: histograms, split search, leaf renewal."""
