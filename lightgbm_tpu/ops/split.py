"""Vectorized best-split search over histograms.

TPU-native replacement for LightGBM's per-feature threshold scan
(reference: src/treelearner/feature_histogram.hpp:782
FindBestThresholdSequentially and the dispatch at :157-200).  Instead of a
sequential scan with a template zoo, both missing-direction variants are
evaluated for EVERY (feature, threshold) cell at once on the VPU:
prefix-sums along the bin axis + a masked argmax.  Semantics preserved:

- gain  = GetLeafGain(GL,HL) + GetLeafGain(GR,HR) with L1 thresholding
  (feature_histogram.hpp:669-780), compared against
  parent_gain + min_gain_to_split.
- missing direction: the missing mass (NaN bin ``num_bin-1`` for
  MissingType::NaN, the zero/default bin for MissingType::Zero) is excluded
  from the threshold prefix and assigned to the default side; both
  directions are scanned, reverse (missing->left) winning ties — matching
  the reference's scan composition order (reverse runs first, later scans
  must be strictly better).
- epsilons: child hessians get +kEpsilon, parent +2*kEpsilon
  (feature_histogram.hpp:91, :796).

Deliberate deviation: min_data_in_leaf uses EXACT per-bin counts (third
histogram channel) rather than the reference's hessian-estimated counts
(``Common::RoundInt(hess * cnt_factor)``, feature_histogram.hpp:813); exact
counts are free here and strictly more faithful to the parameter's meaning.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..binning import MissingType

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf


class SplitHyperparams(NamedTuple):
    """Static split hyper-parameters (trace-time constants)."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    max_delta_step: float = 0.0
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    extra_trees: bool = False


class SplitResult(NamedTuple):
    """Per-leaf best split; all fields [*] or scalar, f32/i32/bool."""

    gain: jax.Array          # shifted gain (already minus parent gain & min_gain)
    feature: jax.Array       # i32
    threshold: jax.Array     # i32 bin threshold (numerical) or category set size
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array    # f32 (exact count)
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    is_categorical: jax.Array  # bool
    cat_bitset: jax.Array    # [MAX_CAT_WORDS] u32: categories (bins) going LEFT


MAX_CAT_WORDS = 8  # supports bitsets over up to 256 bins


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """reference: ThresholdL1 (feature_histogram.hpp:661)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g: jax.Array, h: jax.Array, l1: float, l2: float) -> jax.Array:
    """reference: GetLeafGain (feature_histogram.hpp:712)."""
    sg = threshold_l1(g, l1)
    return (sg * sg) / (h + l2)


def leaf_output(g: jax.Array, h: jax.Array, l1: float, l2: float,
                max_delta_step: float = 0.0) -> jax.Array:
    """reference: CalculateSplittedLeafOutput (feature_histogram.hpp:669)."""
    out = -threshold_l1(g, l1) / (h + l2)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def leaf_gain_given_output(g: jax.Array, h: jax.Array, l1: float, l2: float,
                           out: jax.Array) -> jax.Array:
    """Gain of a leaf forced to emit ``out`` (e.g. clamped by monotone
    bounds).  reference: GetLeafGainGivenOutput (feature_histogram.hpp:760)."""
    sg = threshold_l1(g, l1)
    return -(2.0 * sg * out + (h + l2) * out * out)


class PerFeatureBest(NamedTuple):
    """Per-feature best split candidates (all arrays [F])."""

    gain: jax.Array
    threshold: jax.Array
    default_left: jax.Array
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    is_categorical: jax.Array
    cat_bitset: jax.Array     # [F, MAX_CAT_WORDS]


class NumericFeatureBest(NamedTuple):
    """Per-feature best NUMERIC split candidates ([..., F] arrays).

    ``gain`` is already shifted by the leaf's ``parent_gain +
    min_gain_to_split`` (same convention as ``PerFeatureBest.gain``)."""

    gain: jax.Array
    threshold: jax.Array     # i32 bin threshold
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array


def numeric_feature_scan(
    hist: jax.Array,            # [..., 3, F, B] (grad, hess, count leading)
    sum_grad: jax.Array,        # [...] leaf totals (broadcast against hist)
    sum_hess: jax.Array,
    num_data: jax.Array,
    num_bin: jax.Array,         # [F] i32 static-shaped per-feature bin counts
    missing_type: jax.Array,    # [F] i32
    default_bin: jax.Array,     # [F] i32
    hp: SplitHyperparams,
    monotone_constraints: Optional[jax.Array] = None,  # [F] i32 in {-1,0,1}
    leaf_output_bounds: Optional[tuple] = None,        # (min, max) scalars
    rand_t_u: Optional[jax.Array] = None,  # [F] uniforms: extra-trees random
                                           # thresholds (one per feature)
) -> NumericFeatureBest:
    """The numeric-feature threshold scan of ``feature_best_splits``,
    extracted as ONE shared body: prefix-sums along the bin axis, both
    missing-direction sweeps, L1/L2-thresholded gains, masked argmax.

    Shared verbatim by the staged pipeline (``feature_best_splits`` below)
    and by the fused Pallas megakernel's in-kernel epilogue
    (``ops/fused.py``), so the two pipelines' per-feature-best tuples are
    bit-identical BY CONSTRUCTION given bit-identical histograms — the
    seam the fused == staged parity suite pins.  Supports arbitrary
    leading batch axes on ``hist`` / the scalar totals (the fused kernel
    scans a whole frontier of children at once); every op is written
    batch-agnostic (negative axes, ``broadcasted_iota``) and produces
    values bit-identical to the historical unbatched code.
    """
    F, B = hist.shape[-2], hist.shape[-1]
    bins = lax.broadcasted_iota(jnp.int32, (F, B), 1)           # [F, B]

    num_data = jnp.asarray(num_data).astype(jnp.float32)
    sum_grad = jnp.asarray(sum_grad)
    sum_hess = jnp.asarray(sum_hess)
    parent_gain = leaf_gain(sum_grad, sum_hess + 2 * K_EPSILON,
                            hp.lambda_l1, hp.lambda_l2)         # [...]
    min_gain_shift = parent_gain + hp.min_gain_to_split
    mgs = min_gain_shift[..., None, None]

    # missing bin per feature: NaN bin = num_bin-1, Zero bin = default_bin.
    # Features WITHOUT a dedicated missing direction (missing_type None, or
    # num_bin <= 2 — the reference's dispatch guard) run the plain scan
    # with the missing bin treated as an ordinary bin
    # (feature_histogram.hpp:96-258: the two-direction template is only
    # instantiated for num_bin > 2 with missing handling).
    has_missing_dir = (missing_type != MissingType.NONE) & (num_bin > 2)
    miss_bin = jnp.where(
        missing_type == MissingType.NAN, num_bin - 1,
        jnp.where(missing_type == MissingType.ZERO, default_bin, -1),
    )  # [F]; -1 = no missing handling
    miss_bin = jnp.where(has_missing_dir, miss_bin, -1)
    is_missing_bin = bins == miss_bin[:, None]                  # [F, B]
    valid_bin = bins < num_bin[:, None]                         # [F, B]

    drop = is_missing_bin | ~valid_bin                          # [F, B]
    hist_nm = jnp.where(drop, 0.0, hist)                        # [..., 3, F, B]
    prefix = jnp.cumsum(hist_nm, axis=-1)
    miss = jnp.where(is_missing_bin, hist, 0.0).sum(axis=-1)    # [..., 3, F]

    total_g = sum_grad[..., None, None]
    total_h = (sum_hess + 2 * K_EPSILON)[..., None, None]
    nd = num_data[..., None, None]

    def eval_dir(missing_left: jax.Array):
        # left sums at threshold t (non-missing bins <= t, missing by dir)
        lg = prefix[..., 0, :, :] + jnp.where(missing_left,
                                              miss[..., 0, :, None], 0.0)
        lh = prefix[..., 1, :, :] + jnp.where(missing_left,
                                              miss[..., 1, :, None], 0.0) \
            + K_EPSILON
        lc = prefix[..., 2, :, :] + jnp.where(missing_left,
                                              miss[..., 2, :, None], 0.0)
        rg = total_g - lg
        rh = total_h - lh
        rc = nd - lc
        ok = (
            (lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf)
            & (lh >= hp.min_sum_hessian_in_leaf)
            & (rh >= hp.min_sum_hessian_in_leaf)
        )
        if monotone_constraints is None:
            gain = leaf_gain(lg, lh, hp.lambda_l1, hp.lambda_l2) + \
                leaf_gain(rg, rh, hp.lambda_l1, hp.lambda_l2)
        else:
            # monotone mode (reference: GetSplitGains USE_MC,
            # feature_histogram.hpp:714-747): child outputs are clamped
            # to the leaf's propagated bounds, the gain is computed FROM
            # the clamped outputs, and the split is rejected when the
            # clamped outputs violate the feature's constraint direction.
            lo = leaf_output(lg, lh, hp.lambda_l1, hp.lambda_l2,
                             hp.max_delta_step)
            ro = leaf_output(rg, rh, hp.lambda_l1, hp.lambda_l2,
                             hp.max_delta_step)
            if leaf_output_bounds is not None:
                lob = jnp.asarray(leaf_output_bounds[0])[..., None, None]
                upb = jnp.asarray(leaf_output_bounds[1])[..., None, None]
                lo = jnp.clip(lo, lob, upb)
                ro = jnp.clip(ro, lob, upb)
            mc = monotone_constraints[:, None]
            bad = ((mc > 0) & (lo > ro)) | ((mc < 0) & (lo < ro))
            gain = leaf_gain_given_output(lg, lh, hp.lambda_l1,
                                          hp.lambda_l2, lo) + \
                leaf_gain_given_output(rg, rh, hp.lambda_l1, hp.lambda_l2, ro)
            gain = jnp.where(bad, K_MIN_SCORE, gain)
        gain = jnp.where(ok & (gain > mgs), gain, K_MIN_SCORE)
        return gain, (lg, lh - K_EPSILON, lc)

    # valid thresholds: t in [0, num_bin-2], t not the missing bin when Zero
    # thresholds stop one short of the last scannable bin; with a dedicated
    # NaN bin the last REAL bin is num_bin-2, so t <= num_bin-3 (reference
    # scan bound: num_bin - 2 - NA_AS_MISSING, feature_histogram.hpp:782+)
    na_dir = has_missing_dir & (missing_type == MissingType.NAN)
    t_valid = (bins <
               (num_bin - 1 - na_dir.astype(jnp.int32))[:, None]) & valid_bin
    t_valid &= ~((missing_type[:, None] == MissingType.ZERO) & is_missing_bin)
    if rand_t_u is not None:
        rand_t = jnp.floor(
            rand_t_u * jnp.maximum(num_bin - 1, 1).astype(jnp.float32)
        ).astype(jnp.int32)
        t_valid &= bins == rand_t[:, None]

    gain_r, left_r = eval_dir(jnp.zeros((F, 1), dtype=bool))   # missing -> R
    gain_l, left_l = eval_dir(jnp.ones((F, 1), dtype=bool))    # missing -> L
    gain_r = jnp.where(t_valid, gain_r, K_MIN_SCORE)
    gain_l = jnp.where(t_valid, gain_l, K_MIN_SCORE)
    # features without missing handling: reference runs the REVERSE scan only
    # (missing mass is zero so directions agree); default_left = True there.
    gain_r = jnp.where(has_missing_dir[:, None], gain_r, K_MIN_SCORE)

    # reverse (missing->left) wins ties; within a direction larger threshold
    # wins for reverse, smaller for forward (reference iteration order).
    def argmax_last(x):
        rev = x[..., ::-1]
        idx = jnp.argmax(rev, axis=-1)
        t = x.shape[-1] - 1 - idx
        return t, jnp.take_along_axis(x, t[..., None], -1)[..., 0]

    t_l, g_l = argmax_last(gain_l)                 # [..., F]
    t_r_idx = jnp.argmax(gain_r, axis=-1)
    g_r = jnp.take_along_axis(gain_r, t_r_idx[..., None], -1)[..., 0]
    use_left = g_l >= g_r                          # ties -> missing-left
    num_gain = jnp.where(use_left, g_l, g_r)
    num_thr = jnp.where(use_left, t_l, t_r_idx).astype(jnp.int32)

    def pick(a, b):
        return jnp.where(
            use_left,
            jnp.take_along_axis(a, t_l[..., None], -1)[..., 0],
            jnp.take_along_axis(b, t_r_idx[..., None], -1)[..., 0])

    num_lg = pick(left_l[0], left_r[0])
    num_lh = pick(left_l[1], left_r[1])
    num_lc = pick(left_l[2], left_r[2])
    # plain-scan features: the reference emits default_left=false for
    # NaN-type (so NaN-bin rows follow the ordinary bin comparison at the
    # partition) and default_left=true otherwise (feature_histogram.hpp:
    # 89,200)
    num_dl = jnp.where(has_missing_dir, use_left,
                       missing_type != MissingType.NAN)
    num_gain = jnp.where(jnp.isfinite(num_gain),
                         num_gain - min_gain_shift[..., None], K_MIN_SCORE)
    return NumericFeatureBest(
        gain=num_gain, threshold=num_thr, default_left=num_dl,
        left_sum_grad=num_lg, left_sum_hess=num_lh, left_count=num_lc)


def feature_best_splits(
    hist: jax.Array,            # [3, F, B] (grad, hess, count leading)
    sum_grad: jax.Array,        # scalar: leaf totals
    sum_hess: jax.Array,
    num_data: jax.Array,        # scalar f32/i32: leaf row count
    num_bin: jax.Array,         # [F] i32 static-shaped per-feature bin counts
    missing_type: jax.Array,    # [F] i32
    default_bin: jax.Array,     # [F] i32
    is_categorical: jax.Array,  # [F] bool
    hp: SplitHyperparams,
    feature_mask: Optional[jax.Array] = None,  # [F] f32/bool col-sampling mask
    monotone_constraints: Optional[jax.Array] = None,  # [F] i32 in {-1,0,1}
    leaf_output_bounds: Optional[tuple] = None,        # (min, max) scalars
    has_categorical: bool = False,             # static: any categorical feature
    extra_rand_u: Optional[jax.Array] = None,  # [F, 2] uniforms: extra-trees
    gain_penalty: Optional[jax.Array] = None,  # [F] CEGB gain penalty
) -> PerFeatureBest:
    """Best split PER FEATURE of one leaf. Fully vectorized [F, B].

    The split into per-feature candidates + global argmax (see
    ``best_split_for_leaf``) mirrors the reference's two stages and is the
    seam the voting-parallel learner needs: local per-feature gains drive
    the vote (voting_parallel_tree_learner.cpp:264-305) before any
    histogram is exchanged.

    extra_trees (reference: USE_RAND dispatch, feature_histogram.hpp:96-127):
    when ``hp.extra_trees`` and ``extra_rand_u`` is given, each feature
    evaluates exactly ONE random threshold (numerical: a random bin in
    [0, num_bin-2]; categorical: a random one-hot category / sorted-scan
    position) instead of the full scan.
    """
    _, F, B = hist.shape
    bins = jnp.arange(B, dtype=jnp.int32)
    use_rand = hp.extra_trees and extra_rand_u is not None

    num_data = num_data.astype(jnp.float32)
    valid_bin = bins[None, :] < num_bin[:, None]                    # [F, B]

    # ---- numerical features ------------------------------------------------
    # the shared scan body (also the fused Pallas megakernel's in-kernel
    # epilogue, ops/fused.py — ONE implementation so the staged and fused
    # per-feature-best tuples can never drift); returns SHIFTED gains
    nf = numeric_feature_scan(
        hist, sum_grad, sum_hess, num_data, num_bin, missing_type,
        default_bin, hp, monotone_constraints=monotone_constraints,
        leaf_output_bounds=leaf_output_bounds,
        rand_t_u=(extra_rand_u[:, 0] if use_rand else None))
    num_gain, num_thr, num_dl = nf.gain, nf.threshold, nf.default_left
    num_lg, num_lh, num_lc = (nf.left_sum_grad, nf.left_sum_hess,
                              nf.left_count)

    # ---- categorical features ---------------------------------------------
    cat = _best_categorical(
        hist, sum_grad, sum_hess, num_data, num_bin, valid_bin, hp,
        rand_u=(extra_rand_u[:, 1] if use_rand else None),
        missing_type=missing_type,
    ) if has_categorical else None

    # each feature's gain is shifted by ITS OWN parent gain (categorical
    # uses l2+cat_l2, reference feature_histogram.hpp:268-276) so the
    # cross-feature argmax compares the same quantity the reference does
    # (the numeric gains come back from the scan already shifted)
    if cat is not None:
        c_gain, c_thr, c_lg, c_lh, c_lc, c_bitset = cat
        feat_gain = jnp.where(is_categorical, c_gain, num_gain)
        feat_thr = jnp.where(is_categorical, c_thr, num_thr)
        feat_lg = jnp.where(is_categorical, c_lg, num_lg)
        feat_lh = jnp.where(is_categorical, c_lh, num_lh)
        feat_lc = jnp.where(is_categorical, c_lc, num_lc)
        feat_dl = jnp.where(is_categorical, False, num_dl)
        bitsets = c_bitset                     # [F, W]
    else:
        feat_gain, feat_thr = num_gain, num_thr
        feat_lg, feat_lh, feat_lc, feat_dl = num_lg, num_lh, num_lc, num_dl
        bitsets = jnp.zeros((F, MAX_CAT_WORDS), dtype=jnp.uint32)

    if gain_penalty is not None:
        # CEGB (reference: CostEfficientGradientBoosting::DetlaGain,
        # cost_effective_gradient_boosting.hpp:50 — subtracted from the
        # shifted split gain before the cross-feature argmax)
        feat_gain = jnp.where(jnp.isfinite(feat_gain),
                              feat_gain - gain_penalty, K_MIN_SCORE)
    if feature_mask is not None:
        feat_gain = jnp.where(feature_mask.astype(bool), feat_gain, K_MIN_SCORE)

    return PerFeatureBest(
        gain=feat_gain,
        threshold=feat_thr,
        default_left=feat_dl,
        left_sum_grad=feat_lg,
        left_sum_hess=feat_lh,
        left_count=feat_lc,
        is_categorical=is_categorical,
        cat_bitset=bitsets,
    )


def best_split_for_leaf(
    hist: jax.Array,
    sum_grad: jax.Array,
    sum_hess: jax.Array,
    num_data: jax.Array,
    num_bin: jax.Array,
    missing_type: jax.Array,
    default_bin: jax.Array,
    is_categorical: jax.Array,
    hp: SplitHyperparams,
    feature_mask: Optional[jax.Array] = None,
    monotone_constraints: Optional[jax.Array] = None,
    leaf_output_bounds: Optional[tuple] = None,
    has_categorical: bool = False,
    extra_rand_u: Optional[jax.Array] = None,
    gain_penalty: Optional[jax.Array] = None,
) -> SplitResult:
    """Best split over all features of one leaf (see feature_best_splits)."""
    pf = feature_best_splits(
        hist, sum_grad, sum_hess, num_data, num_bin, missing_type,
        default_bin, is_categorical, hp, feature_mask=feature_mask,
        monotone_constraints=monotone_constraints,
        leaf_output_bounds=leaf_output_bounds,
        has_categorical=has_categorical, extra_rand_u=extra_rand_u,
        gain_penalty=gain_penalty)
    return pick_best_feature(pf, sum_grad, sum_hess, num_data)


def pick_best_feature(pf: PerFeatureBest, sum_grad, sum_hess,
                      num_data) -> SplitResult:
    """argmax over features; ties -> smaller feature index (reference:
    SplitInfo::operator> tie-break, split_info.hpp:126-155)."""
    best_f = jnp.argmax(pf.gain).astype(jnp.int32)
    bg = pf.gain[best_f]
    blg, blh, blc = (pf.left_sum_grad[best_f], pf.left_sum_hess[best_f],
                     pf.left_count[best_f])
    return SplitResult(
        gain=bg,
        feature=best_f,
        threshold=pf.threshold[best_f],
        default_left=pf.default_left[best_f],
        left_sum_grad=blg,
        left_sum_hess=blh,
        left_count=blc,
        right_sum_grad=sum_grad - blg,
        right_sum_hess=sum_hess - blh,
        right_count=num_data - blc,
        is_categorical=pf.is_categorical[best_f],
        cat_bitset=pf.cat_bitset[best_f],
    )


def _best_categorical(hist, sum_grad, sum_hess, num_data, num_bin, valid_bin,
                      hp, rand_u=None, missing_type=None):
    """Categorical split search, vectorized over features.

    reference: FindBestThresholdCategoricalInner (feature_histogram.hpp:259-460).
    One-hot mode for small cardinality (num_bin <= max_cat_to_onehot): best
    single category vs rest.  Otherwise: sort categories by
    sum_grad/(sum_hess + cat_smooth) and scan prefixes from both ends, at most
    max_cat_threshold categories on the smaller side; lambda_l2 += cat_l2.
    Returns per-feature (gain, n_left_cats, left sums, bitset of bins LEFT).
    """
    _, F, B = hist.shape
    l2 = hp.lambda_l2 + hp.cat_l2
    g, h, c = hist[0], hist[1], hist[2]
    total_g, total_h = sum_grad, sum_hess + 2 * K_EPSILON
    parent_gain = leaf_gain(sum_grad, total_h, hp.lambda_l1, l2)
    min_gain_shift = parent_gain + hp.min_gain_to_split

    # --- one-hot mode: each category k vs rest
    lg, lh, lc = g, h + K_EPSILON, c
    rg, rh, rc = total_g - lg, total_h - lh, num_data - lc
    ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf)
          & (lh >= hp.min_sum_hessian_in_leaf) & (rh >= hp.min_sum_hessian_in_leaf)
          & valid_bin)
    onehot_gain = leaf_gain(lg, lh, hp.lambda_l1, l2) + leaf_gain(rg, rh, hp.lambda_l1, l2)
    onehot_gain = jnp.where(ok & (onehot_gain > min_gain_shift), onehot_gain, K_MIN_SCORE)
    if rand_u is not None:
        rand_cat = jnp.floor(rand_u * num_bin.astype(jnp.float32)).astype(jnp.int32)
        onehot_gain = jnp.where(
            jnp.arange(B, dtype=jnp.int32)[None, :] == rand_cat[:, None],
            onehot_gain, K_MIN_SCORE)
    oh_k = jnp.argmax(onehot_gain, axis=1)                        # [F]
    oh_gain = jnp.take_along_axis(onehot_gain, oh_k[:, None], 1)[:, 0]

    # --- sorted many-vs-many
    # order by g/(h + cat_smooth); categories with small count excluded
    usable = valid_bin & (c >= max(1, hp.min_data_per_group // 4))
    ratio = jnp.where(usable, g / (h + hp.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1)                            # ascending; unusable last
    sg = jnp.take_along_axis(g, order, 1)
    sh = jnp.take_along_axis(h, order, 1)
    sc = jnp.take_along_axis(c, order, 1)
    s_usable = jnp.take_along_axis(usable, order, 1)
    sg = jnp.where(s_usable, sg, 0.0)
    sh = jnp.where(s_usable, sh, 0.0)
    sc = jnp.where(s_usable, sc, 0.0)
    pg, ph, pc = jnp.cumsum(sg, 1), jnp.cumsum(sh, 1), jnp.cumsum(sc, 1)
    k_idx = jnp.arange(B, dtype=jnp.int32)[None, :]
    max_k = jnp.minimum(hp.max_cat_threshold, B)
    n_usable = jnp.sum(s_usable, axis=1).astype(jnp.int32)[:, None]  # [F, 1]

    def scan_dir(from_low: bool):
        if from_low:
            clg, clh, clc = pg, ph + K_EPSILON, pc
            # left set = sorted[0..k]: size k+1 bounded by max_cat_threshold
            size_ok = k_idx < max_k
        else:
            clg = pg[:, -1:] - pg
            clh = ph[:, -1:] - ph + K_EPSILON
            clc = pc[:, -1:] - pc
            # left set = sorted[k+1..]: bound the SUFFIX size, not k itself
            left_size = n_usable - 1 - k_idx
            size_ok = (left_size <= max_k) & (left_size >= 1)
        crg, crh, crc = total_g - clg, total_h - clh, num_data - clc
        okd = ((clc >= hp.min_data_in_leaf) & (crc >= hp.min_data_in_leaf)
               & (clh >= hp.min_sum_hessian_in_leaf) & (crh >= hp.min_sum_hessian_in_leaf)
               & size_ok)
        gn = leaf_gain(clg, clh, hp.lambda_l1, l2) + leaf_gain(crg, crh, hp.lambda_l1, l2)
        gn = jnp.where(okd & (gn > min_gain_shift), gn, K_MIN_SCORE)
        if rand_u is not None:
            rand_pos = jnp.floor(
                rand_u * n_usable[:, 0].astype(jnp.float32)).astype(jnp.int32)
            gn = jnp.where(k_idx == rand_pos[:, None], gn, K_MIN_SCORE)
        kk = jnp.argmax(gn, axis=1)
        return jnp.take_along_axis(gn, kk[:, None], 1)[:, 0], kk, (clg, clh - K_EPSILON, clc)

    lo_gain, lo_k, lo_sums = scan_dir(True)
    hi_gain, hi_k, hi_sums = scan_dir(False)
    use_lo = lo_gain >= hi_gain
    mm_gain = jnp.where(use_lo, lo_gain, hi_gain)
    mm_k = jnp.where(use_lo, lo_k, hi_k)
    mm_lg = jnp.where(use_lo, jnp.take_along_axis(lo_sums[0], lo_k[:, None], 1)[:, 0],
                      jnp.take_along_axis(hi_sums[0], hi_k[:, None], 1)[:, 0])
    mm_lh = jnp.where(use_lo, jnp.take_along_axis(lo_sums[1], lo_k[:, None], 1)[:, 0],
                      jnp.take_along_axis(hi_sums[1], hi_k[:, None], 1)[:, 0])
    mm_lc = jnp.where(use_lo, jnp.take_along_axis(lo_sums[2], lo_k[:, None], 1)[:, 0],
                      jnp.take_along_axis(hi_sums[2], hi_k[:, None], 1)[:, 0])

    is_onehot = num_bin <= hp.max_cat_to_onehot
    cat_gain = jnp.where(is_onehot, oh_gain, mm_gain)
    cat_gain = jnp.where(jnp.isfinite(cat_gain), cat_gain - min_gain_shift,
                         K_MIN_SCORE)
    cat_lg = jnp.where(is_onehot, jnp.take_along_axis(lg, oh_k[:, None], 1)[:, 0], mm_lg)
    cat_lh = jnp.where(is_onehot,
                       jnp.take_along_axis(lh, oh_k[:, None], 1)[:, 0] - K_EPSILON, mm_lh)
    cat_lc = jnp.where(is_onehot, jnp.take_along_axis(lc, oh_k[:, None], 1)[:, 0], mm_lc)

    # bitset of bins going LEFT
    # one-hot: {oh_k}; many-vs-many low side: sorted[0..k]; high: sorted[k+1..]
    in_left_sorted_lo = k_idx <= mm_k[:, None]
    in_left_sorted = jnp.where(use_lo[:, None], in_left_sorted_lo,
                               (k_idx > mm_k[:, None]) & s_usable)
    member = jnp.zeros((F, B), dtype=bool)
    member = member.at[jnp.arange(F)[:, None], order].set(in_left_sorted & s_usable)
    member_oh = k_idx == oh_k[:, None]
    member = jnp.where(is_onehot[:, None], member_oh, member)
    # normalize: the NaN category (bin num_bin-1 when the feature has one,
    # i.e. missing_type NaN) must never sit in the stored goes-LEFT set —
    # prediction routes NaN right when it is not listed (the reference
    # never emits -1 in a categorical threshold).  Swapping sides keeps
    # the identical partition: new left = old right.
    if missing_type is not None:
        is_nan_bin = (k_idx == (num_bin - 1)[:, None]) & \
            (missing_type == MissingType.NAN)[:, None]
        nan_left = jnp.any(member & is_nan_bin, axis=1)
        member = jnp.where(nan_left[:, None],
                           valid_bin & ~member & ~is_nan_bin, member)
        cat_lg = jnp.where(nan_left, sum_grad - cat_lg, cat_lg)
        cat_lh = jnp.where(nan_left, sum_hess - cat_lh, cat_lh)
        cat_lc = jnp.where(nan_left, num_data - cat_lc, cat_lc)
    word = (jnp.arange(B, dtype=jnp.uint32) // 32)
    bitpos = (jnp.arange(B, dtype=jnp.uint32) % 32)
    bit = jnp.where(member, jnp.uint32(1) << bitpos[None, :], jnp.uint32(0))
    bitset = jnp.zeros((F, MAX_CAT_WORDS), dtype=jnp.uint32)
    bitset = bitset.at[:, word].add(bit)  # each word gets OR'd via add (bits disjoint)

    return cat_gain, mm_k.astype(jnp.int32), cat_lg, cat_lh, cat_lc, bitset


# ======================================================================
# Quantized-gradient training (use_quantized_grad) rescaling
# ======================================================================


def quant_rescale_hist(hist_int: jax.Array, g_scale, h_scale, num_data,
                       cnt_factor=None) -> jax.Array:
    """[2, F, B] (or [2, G, Bg]) integer histogram -> the [3, F, B] f32
    histogram every split kernel above consumes.

    reference: the quantized-training split path converts int32/int64
    bin sums to double before the gain math
    (feature_histogram.hpp GET_GRAD/GET_HESS int-hist specializations);
    here the rescale runs in jnp.float64 — true f64 under
    ``jax_enable_x64``, f32 otherwise — then lands in f32 for the
    vectorized scan.  Per-bin COUNTS are estimated from the hessian
    channel with the leaf's count factor
    (``Common::RoundInt(sum_hess * cnt_factor)``,
    feature_histogram.hpp:813): the count channel is deliberately NOT
    accumulated in quantized mode — dropping it is what shrinks the
    integer histogram to 2 channels and the data-parallel psum payload
    with it (ops/histogram.py ``hist_payload_bytes``).

    ``cnt_factor`` defaults to ``num_data / hess_int_total`` with the
    total read from axis-0 feature/group 0, whose bins partition the
    leaf's rows (every row has exactly one bin per feature).  Voting's
    local-candidate pass overrides it with the globally-derived factor
    (grower.py ``leaf_best_voting``).

    Accepts arbitrary leading batch axes on ``hist_int`` (with
    ``num_data``/``cnt_factor`` broadcastable to them) — the fused
    megakernel's epilogue rescales a whole frontier of children through
    THIS body (ops/fused.py), so the staged and fused rescales can never
    drift; the batched ops are elementwise and bit-identical to the
    historical unbatched code.
    """
    # true f64 only when the session enabled x64 (requesting f64 under
    # the default x64-off config would just warn and truncate to f32)
    wide = jnp.float64 if jax.config.x64_enabled else jnp.float32
    hi = hist_int.astype(wide)
    g = hi[..., 0, :, :] * jnp.asarray(g_scale, wide)
    h = hi[..., 1, :, :] * jnp.asarray(h_scale, wide)
    if cnt_factor is None:
        tot = jnp.sum(hist_int[..., 1, 0, :], axis=-1).astype(jnp.float32)
        cnt_factor = num_data / jnp.maximum(tot, 1.0)
    cf = jnp.asarray(cnt_factor, wide)
    c = jnp.round(hi[..., 1, :, :] * cf[..., None, None])
    return jnp.stack([g, h, c], axis=-3).astype(jnp.float32)
