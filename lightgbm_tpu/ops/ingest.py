"""Device-side ingest: the fused bucketize+pack binning kernel.

Training (histogram families, the fused megakernel) and inference
(predict_kernels.py) both run on kernels; this module moves the last
unkernelized hot path — the second binning pass of ``Dataset.construct``
— onto the accelerator.  One Pallas kernel owns one row tile: the
per-feature bin-boundary tables stay VMEM-resident across grid steps
(their block index never moves, the predict-kernel trick), each f32 row
tile is bucketized with a vectorized searchsorted-equivalent, and the
EFB group fold packs the per-feature bins straight into the [tile, G]
output block — raw floats cross HBM once and the binned matrix comes
back, nothing in between.

Bit-parity contract (tests/test_ingest.py, tools/ingest_probe.py): the
device matrix is BYTE-identical to the host ``BinMapper.value_to_bin``
+ ``Dataset._bin_block`` path.  Three constructions make that exact
rather than approximate:

- **directed-rounded boundaries**: the host compares the widened-f64
  value against f64 upper bounds (``searchsorted(ub, v, "left")`` ==
  count of ``ub < v``).  For f32 inputs, ``ub < v`` is equivalent to
  ``round_toward_neg_inf_f32(ub) < v`` — there is no f32 strictly
  between a bound and its round-down — so the kernel compares in pure
  f32 against a pre-rounded table and loses nothing.  Consequence: the
  device path applies ONLY to dense float32 raw input; float64 and
  sparse inputs take the host oracle.
- **the host fold, verbatim**: bundle members fold in ascending
  used-feature order with ``col = where(bin != 0, start + bin - 1,
  col)``; the host's singleton special case (``feat_start == 1``,
  group size 1) is the same fold evaluated from zero, so one rule
  covers every group byte-for-byte, including the reference's
  observable last-writer-wins conflict semantics.
- **categorical truncation**: ``int(v)`` truncates toward zero
  (``jnp.fix``), NaN and >= 2^31 magnitudes map to "no category"
  (the host's int64 cast of such values can never match an int32
  category code either), and a match requires ``iv >= 0`` exactly as
  the host lookup does.

The host NumPy path is the never-deleted fallback AND the parity
oracle: before the first committed device block of a dataset, a salted
probe (first rows + zeros / NaN / sign extremes / non-category codes)
is binned both ways and compared byte-for-byte; any mismatch — or any
kernel exception — demotes that dataset to the host path with a
warning (``fused_predict_verified`` precedent: never wrong bytes).
``LGBM_TPU_INGEST_KERNEL`` pins the arm for bisection; off accelerators
the kernel interprets as the same jnp math, so CPU parity tests are
meaningful.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import NamedTuple, Optional, Tuple

import numpy as np

INGEST_VARIANTS = ("kernel", "host")

# |v| >= 2^31 cannot equal any int32 categorical code; the host's int64
# cast of such a value cannot match one either, so "no category" is
# parity-exact (f32 has no integers between 2^31 and this boundary)
_CAT_HUGE = np.float32(2147483648.0)


class IngestUnsupported(ValueError):
    """This dataset's binning recipe cannot run on device (the caller
    falls back to the host oracle — never an error for users)."""


def _interp(interpret):
    """Pallas interpret-mode default (the ops/fused.py convention)."""
    if interpret is None:
        from .histogram import on_accelerator
        return not on_accelerator()
    return bool(interpret)


class FeatureSpec(NamedTuple):
    """Static per-used-feature binning recipe (Python ints — closed over
    by the kernel factory, so they are trace-time constants)."""

    column: int          # raw matrix column
    group: int           # EFB output column
    start: int           # feat_start offset inside the merged column
    is_cat: bool
    num_bin: int
    row: int             # row in the bounds (numerical) / cats table
    nan_as_last: bool    # numerical MissingType.NAN: NaN -> num_bin - 1


class IngestTables(NamedTuple):
    """Everything the kernel needs, host-side: the directed-rounded f32
    boundary table, the int32 category-code table, and the per-feature
    static specs."""

    specs: Tuple[FeatureSpec, ...]
    bounds: np.ndarray       # f32 [max(Fnum,1), Bmax], +inf padded
    cats: np.ndarray         # i32 [max(Fcat,1), Cmax], -2 padded
    num_features: int        # raw matrix width the kernel consumes
    num_groups: int
    out_dtype: np.dtype      # uint8 | uint16 (the group dtype)


def round_bounds_f32(ub: np.ndarray) -> np.ndarray:
    """f64 upper bounds -> the largest f32 <= each bound (round toward
    -inf), the table the kernel's pure-f32 compare is exact against."""
    ub = np.asarray(ub, np.float64)
    with np.errstate(over="ignore"):     # f32-overflow -> inf IS the
        ub32 = ub.astype(np.float32)     # round-up case handled below
        over = ub32.astype(np.float64) > ub      # round-to-nearest went UP
        ub32[over] = np.nextafter(ub32[over], np.float32(-np.inf))
    return ub32


def build_ingest_tables(ds) -> IngestTables:
    """Compile a constructed-or-fitting Dataset's bin mappers + EFB
    layout into device tables.  Raises ``IngestUnsupported`` when the
    recipe cannot be represented (categorical codes outside int32)."""
    from ..binning import BinType, MissingType

    specs = []
    brows = []
    crows = []
    for j, f in enumerate(ds.used_features):
        m = ds.bin_mappers[f]
        g = int(ds.feat_group[j])
        start = int(ds.feat_start[j])
        if m.bin_type == BinType.CATEGORICAL:
            cats = np.asarray(m.bin_2_categorical, dtype=np.int64)
            if cats.size and (cats.max() >= 2 ** 31
                              or cats.min() < -2 ** 31):
                raise IngestUnsupported(
                    f"feature {f}: categorical codes exceed int32")
            specs.append(FeatureSpec(int(f), g, start, True,
                                     int(m.num_bin), len(crows), False))
            crows.append(cats.astype(np.int32))
        else:
            r = m.num_bin - 1
            if m.missing_type == MissingType.NAN:
                r -= 1
            specs.append(FeatureSpec(
                int(f), g, start, False, int(m.num_bin), len(brows),
                m.missing_type == MissingType.NAN))
            brows.append(round_bounds_f32(
                np.asarray(m.bin_upper_bound)[:max(r, 0)]))
    bmax = max([len(b) for b in brows] + [1])
    cmax = max([len(c) for c in crows] + [1])
    bounds = np.full((max(len(brows), 1), bmax), np.inf, np.float32)
    for i, b in enumerate(brows):
        bounds[i, :len(b)] = b
    cats_t = np.full((max(len(crows), 1), cmax), -2, np.int32)
    for i, c in enumerate(crows):
        cats_t[i, :len(c)] = c
    dtype = np.dtype(np.uint8 if ds.max_group_bin <= 256 else np.uint16)
    return IngestTables(tuple(specs), bounds, cats_t,
                        int(ds.num_total_features), int(ds.num_groups),
                        dtype)


# ----------------------------------------------------------------------
# the fused bucketize+pack kernel
# ----------------------------------------------------------------------

def _ingest_kernel(specs, num_groups, cats_width):
    """Kernel body factory.  One grid step owns one row tile: bucketize
    every used feature of the [tile, F] f32 block against the resident
    boundary/category tables, fold each EFB group's members in the
    host's exact order, and write the [tile, G] packed block.  The
    feature loop is unrolled at trace time (``specs`` are Python
    constants), so each feature compiles to a broadcast compare +
    row-sum — the vectorized searchsorted."""
    import jax.numpy as jnp

    def kernel(x_ref, bounds_ref, cats_ref, out_ref):
        X = x_ref[...]                              # [tile, F] f32
        tile = X.shape[0]
        carange = jnp.arange(cats_width, dtype=jnp.int32)
        cols = [jnp.zeros((tile,), jnp.int32) for _ in range(num_groups)]
        for s in specs:
            v = X[:, s.column]
            nan = v != v
            if s.is_cat:
                nan_bin = s.num_bin - 1
                miss = nan | (jnp.abs(v) >= _CAT_HUGE)
                iv = jnp.fix(jnp.where(miss, jnp.float32(-1.0), v)
                             ).astype(jnp.int32)
                hit = ((iv[:, None] == cats_ref[s.row, :][None, :])
                       & (iv[:, None] >= 0))
                # at most one code matches: the sum IS the select
                bins = jnp.sum(
                    jnp.where(hit, carange[None, :] - nan_bin, 0),
                    axis=1) + nan_bin
            else:
                fz = jnp.where(nan, jnp.float32(0.0), v)
                bins = jnp.sum(
                    (bounds_ref[s.row, :][None, :] < fz[:, None]
                     ).astype(jnp.int32), axis=1)
                if s.nan_as_last:
                    bins = jnp.where(nan, s.num_bin - 1, bins)
            # the host fold, verbatim (singletons are the start==1 case)
            cols[s.group] = jnp.where(bins != 0, s.start + bins - 1,
                                      cols[s.group])
        out_ref[...] = jnp.stack(cols, axis=1)

    return kernel


class DeviceBinner:
    """A compiled bucketize+pack program for one dataset's tables.

    ``__call__`` takes a [rows, F] f32 block (host or device) and
    returns the [rows, G] binned block in the group dtype, on device.
    Rows pad up to whole tiles and slice back off; jit caches one
    program per padded shape (full chunks share one, the ragged tail
    adds one)."""

    def __init__(self, tables: IngestTables, tile_rows: int = 1024,
                 interpret=None):
        import jax
        import jax.numpy as jnp

        self.tables = tables
        self.tile_rows = max(int(tile_rows), 8)
        self.interpret = _interp(interpret)
        self._bounds = jnp.asarray(tables.bounds)
        self._cats = jnp.asarray(tables.cats)
        self._kernel = _ingest_kernel(tables.specs, tables.num_groups,
                                      tables.cats.shape[1])
        self._call = jax.jit(self._run)

    def _run(self, X):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        n, F = X.shape
        G = int(self.tables.num_groups)
        tile = min(self.tile_rows, max(int(n), 8))
        ntiles = max(-(-n // tile), 1)
        npad = ntiles * tile
        if npad != n:
            X = jnp.pad(X, ((0, npad - n), (0, 0)))

        def _full(a):
            return pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)

        out = pl.pallas_call(
            self._kernel, grid=(ntiles,),
            in_specs=[pl.BlockSpec((tile, F), lambda i: (i, 0)),
                      _full(self._bounds), _full(self._cats)],
            out_specs=pl.BlockSpec((tile, G), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((npad, G), jnp.int32),
            interpret=self.interpret)(X, self._bounds, self._cats)
        return out[:n].astype(self.tables.out_dtype)

    def __call__(self, X):
        import jax.numpy as jnp
        if X.shape[1] != self.tables.num_features:
            raise ValueError(
                f"ingest kernel built for {self.tables.num_features} "
                f"features, got a block of {X.shape[1]}")
        return self._call(jnp.asarray(X, jnp.float32))


# ----------------------------------------------------------------------
# parity probe + the ingest story
# ----------------------------------------------------------------------

def salt_rows(width: int, like: Optional[np.ndarray] = None) -> np.ndarray:
    """Edge-case rows every parity check must cover: zeros, all-NaN,
    sign extremes, non-integer positives, negative and huge codes."""
    salt = np.zeros((6, width), np.float32)
    salt[1, :] = np.nan
    salt[2, :] = -np.float32(1e30)
    salt[3, :] = np.float32(1e30)
    salt[4, :] = np.float32(2.5)
    salt[5, :] = np.float32(-1.0)
    if like is not None and len(like):
        # a real row with alternating NaN: missing routing inside data
        extra = np.array(like[:1], np.float32)
        extra[0, ::2] = np.nan
        salt = np.concatenate([salt, extra])
    return salt


def parity_probe(binner: DeviceBinner, ds, raw_head: np.ndarray) -> bool:
    """Byte-compare device vs host binning on a salted head sample.
    True == the kernel may commit blocks for this dataset."""
    probe = np.concatenate([
        np.asarray(raw_head[:512], np.float32),
        salt_rows(raw_head.shape[1], raw_head)])
    ref = np.zeros((probe.shape[0], ds.num_groups),
                   binner.tables.out_dtype)
    with np.errstate(invalid="ignore"):   # host int64 cast of the salted
        ds._bin_block(probe.astype(np.float64), None, ref)  # 1e30 rows
    got = np.asarray(binner(probe))
    return bool(np.array_equal(ref, got))


# last construct's election + outcome, for obs/diagnose.py's
# input-bound verdict (mirrors the planner's _AUTOTUNE_LAST story)
_INGEST_LAST: dict = {}
_INGEST_LAST_LOCK = threading.Lock()


def record_ingest_story(**kw) -> None:
    with _INGEST_LAST_LOCK:
        _INGEST_LAST.clear()
        _INGEST_LAST.update(kw, ts=time.time())


def ingest_last() -> dict:
    with _INGEST_LAST_LOCK:
        return dict(_INGEST_LAST)


def demote(reason: str, warn: bool = True, **kw) -> None:
    """Record a host fallback and say why (the bisect gate's evidence)."""
    record_ingest_story(path="host", reason=reason, **kw)
    if warn:
        warnings.warn(f"device ingest demoted to host binning: {reason}")
