"""HBM budget planner: pick histogram execution parameters at trace time.

The r5 bench died in compile with an HBM OOM — a lane-padded
``f32[308000000, 3]`` whole-dataset record arena (157.7 GB requested vs
17.2 GB HBM) — because every kernel materialized O(n*F) intermediates
and nothing MODELED whether they fit.  This module is the model: it
predicts per-variant peak HBM bytes for the histogram pipeline
(device binned matrix, carried scores/gradients, per-tree hist cache
including TPU lane padding, per-pass transients, pack/sort arenas,
cross-device psum payloads) against the device's reported HBM limit and
picks, at trace time:

- ``tile_rows`` — the row-tile size every kernel in ops/histogram.py
  streams through (power of two; 0 = untiled).  Peak transient HBM
  becomes O(tile), not O(n*F);
- whether the whole-dataset ``pack_cols_u32`` record arena may be
  hoisted (``use_pack``) or records must be assembled per tile inside
  the kernel loops;
- the psum payload width for quantized histograms (``narrow_int16`` —
  the record of ``ops.histogram.quant_psum_narrow``'s static bound).

The same plan governs serial and sharded training: the GBDT layer plans
with PER-SHARD rows and threads the result through ``GrowerConfig``
(tile_rows / hist_pack), so the serial grower, the batched-frontier
grower, the fused macro-chunk program and the data-/voting-parallel
learners all execute under one verdict.  bench.py gates its >=10M-row
stage on ``feasible`` and journals the chosen tile instead of crashing.

Env overrides:
- ``LGBM_TPU_TILE_ROWS``: force a tile size (``0``/``off`` forces
  untiled; a positive integer forces that many rows per tile).
- ``LGBM_TPU_HBM_BYTES``: override the device HBM limit (useful off-TPU
  and in tests, which plan against a fake memory model).

Related work: bounding device memory by streaming row chunks through a
fixed-footprint histogram kernel is the GPU GBDT move (Wen et al.,
arXiv:1706.08359; Ou, arXiv:1806.11248 — gradient-based sketching to
bound device memory); here the bound is a *planner verdict* instead of
an operator-tuned chunk count.
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import NamedTuple, Optional

# default assumed HBM when the backend reports nothing (one v5e-class
# chip; r5 measured 17.2 GB reported — stay conservative)
DEFAULT_HBM_BYTES = 16 * (1 << 30)
# fraction of the limit a plan may claim: XLA needs slack for fusion
# temps, the program image, and collectives' staging buffers
HEADROOM = 0.85
# smallest tile the planner will degrade to (a histogram pass over fewer
# rows is dominated by fixed per-pass overhead)
MIN_TILE_ROWS = 1 << 16
_DEFAULT_BLOCK_ROWS = 4096

# on-chip vector memory per core (v5e-class ~16 MiB; LGBM_TPU_VMEM_BYTES
# overrides) and the fraction the fused megakernel's arena may claim —
# Mosaic needs slack for the pipeline's double-buffered tile windows and
# spills
DEFAULT_VMEM_BYTES = 16 << 20
VMEM_HEADROOM = 0.7

# host-RSS side of the two-level budget (out-of-core streaming,
# lightgbm_tpu/data/): fraction of the host limit training may claim —
# the OS, the Python runtime and JAX's own host allocations need the rest
HOST_HEADROOM = 0.8
DEFAULT_HOST_BYTES = 8 * (1 << 30)
# smallest streamed row block the stream planner will degrade to; a
# device_put + histogram pass over fewer rows is dominated by dispatch
# overhead (tests force smaller via LGBM_TPU_STREAM_BLOCK_ROWS)
MIN_STREAM_BLOCK_ROWS = 1 << 16
MAX_STREAM_BLOCK_ROWS = 1 << 24


def _pad(x: int, m: int) -> int:
    return -(-int(x) // m) * m


def _arr(minor: int, second: int, itemsize: int, accel: bool,
         leading: int = 1) -> int:
    """Bytes of an array whose two minor dims are (second, minor).

    On accelerators the two minor-most dims tile to (sublanes, 128) with
    sublanes scaling inversely with itemsize — (8, 128) for 4-byte,
    (16, 128) for 2-byte, (32, 128) for 1-byte (ops/histogram.py LAYOUT
    DOCTRINE).  Off-accelerator: dense.
    """
    if not accel:
        return leading * second * minor * itemsize
    sub = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    return leading * _pad(second, sub) * _pad(minor, 128) * itemsize


class HistPlan(NamedTuple):
    """Trace-time histogram execution plan (see module docstring)."""

    tile_rows: int              # 0 = untiled
    use_pack: bool              # whole-dataset u32 record arena allowed
    variant: str                # resolved histogram kernel family
    quant: bool
    narrow_int16: bool          # quantized psum payload narrowed
    predicted_peak_bytes: int   # at the chosen tile
    untiled_peak_bytes: int     # what the unplanned pipeline would take
    budget_bytes: int           # limit * HEADROOM
    limit_bytes: int
    limit_source: str           # "memory_stats" | "env" | "default"
    feasible: bool              # predicted peak fits the budget
    degraded: bool              # tiling was forced by the budget
    fused: bool = False         # fused Pallas megakernel elected
    fused_feat_tile: int = 0    # features per VMEM arena block
    fused_block_rows: int = 0   # rows per double-buffered tile DMA
    fused_vmem_bytes: int = 0   # predicted VMEM arena bytes at that shape
    vmem_limit_bytes: int = 0   # VMEM limit the fused election ran against
    elected_by: str = "analytic"   # "analytic" | "measured" (autotuner)
    measured_variant: str = ""  # store's best for this bucket ("" = cold)
    autotune_key: str = ""      # shape-bucket key the election ran under

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / telemetry."""
        return {
            "tile_rows": self.tile_rows,
            "use_pack": self.use_pack,
            "variant": self.variant,
            "quant": self.quant,
            "narrow_int16": self.narrow_int16,
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "untiled_peak_bytes": self.untiled_peak_bytes,
            "budget_bytes": self.budget_bytes,
            "hbm_limit_bytes": self.limit_bytes,
            "limit_source": self.limit_source,
            "feasible": self.feasible,
            "degraded": self.degraded,
            "fused": self.fused,
            "fused_feat_tile": self.fused_feat_tile,
            "fused_block_rows": self.fused_block_rows,
            "fused_vmem_bytes": self.fused_vmem_bytes,
            "vmem_limit_bytes": self.vmem_limit_bytes,
            "elected_by": self.elected_by,
            "measured_variant": self.measured_variant,
            "autotune_key": self.autotune_key,
        }


def hbm_limit_bytes() -> tuple:
    """(limit_bytes, source) for the active device.

    Priority: ``LGBM_TPU_HBM_BYTES`` env (tests / fake memory models) >
    the device allocator's reported ``bytes_limit`` > the conservative
    default.  Never raises — planning must work before/without a
    backend.
    """
    env = os.environ.get("LGBM_TPU_HBM_BYTES", "").strip()
    if env:
        try:
            return max(int(float(env)), 1), "env"
        except ValueError:
            pass
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit, "memory_stats"
    except Exception:
        pass
    return DEFAULT_HBM_BYTES, "default"


def vmem_limit_bytes() -> int:
    """VMEM per core for the fused megakernel's arena election
    (``LGBM_TPU_VMEM_BYTES`` overrides; tests plan against fakes)."""
    env = os.environ.get("LGBM_TPU_VMEM_BYTES", "").strip()
    if env:
        try:
            return max(int(float(env)), 1)
        except ValueError:
            pass
    return DEFAULT_VMEM_BYTES


def fused_vmem_bytes(num_slots: int, num_bins: int, feat_tile: int,
                     block_rows: int, quant: bool = False,
                     with_parent: bool = True) -> int:
    """Predicted VMEM bytes of one fused-megakernel step (ops/fused.py).

    Resident across the row loop: the [ch·K, Ft·B] accumulator arena,
    the parent block and the double-buffered input tile windows; the
    epilogue additionally materializes the 2K children (+ their rescale/
    prefix transients) and the tiny tuple blocks.  Deliberately simple —
    the right ORDER for the fits/doesn't verdict, like
    ``predict_peak_bytes``."""
    K = max(int(num_slots), 1)
    B = max(int(num_bins), 2)
    Ft = max(int(feat_tile), 1)
    C = max(int(block_rows), 128)
    ch = 2 if quant else 3
    nc = 2 * K if with_parent else K
    acc = ch * K * Ft * B * 4
    parent = K * ch * Ft * B * 4 if with_parent else 0
    small_out = K * ch * Ft * B * 4
    # epilogue: children + one prefix/rescale transient of the same shape
    children = 2 * nc * 3 * Ft * B * 4
    # double-buffered tile DMA windows: binned (1B), vals (<=4B), slot,
    # plus the one-hot operand the dot consumes
    tiles = 2 * (Ft * C + ch * C * 4 + C * 4)
    onehot = C * Ft * B * (1 if quant else 4)
    tuples = 6 * nc * Ft * 4
    return acc + parent + small_out + children + tiles + onehot + tuples


def plan_fused(num_slots: int, num_bins: int, quant: bool = False,
               with_parent: bool = True,
               vmem_bytes: Optional[int] = None) -> Optional[dict]:
    """Pick {feat_tile, block_rows} for the fused megakernel, or None
    when no shape fits the VMEM budget (the staged family then keeps the
    level).  Preference order: widest feature block first (fewer grid
    columns, better MXU occupancy), then the larger row tile."""
    limit = int(vmem_bytes if vmem_bytes is not None else vmem_limit_bytes())
    budget = int(limit * VMEM_HEADROOM)
    for ft in (8, 4, 2, 1):
        for c in (512, 256, 128):
            need = fused_vmem_bytes(num_slots, num_bins, ft, c, quant,
                                    with_parent)
            if need <= budget:
                return {"feat_tile": ft, "block_rows": c,
                        "vmem_bytes": need, "vmem_limit_bytes": limit}
    return None


def predict_peak_bytes(
    rows: int,                  # per-shard row count the kernels see
    features: int,              # device column count (groups under EFB)
    num_bins: int,              # padded bin axis B
    num_leaves: int = 31,
    num_class: int = 1,
    quant: bool = False,
    variant: str = "scatter",   # resolved kernel family name
    tile_rows: int = 0,         # 0 = untiled
    use_pack: bool = True,
    round_width: int = 128,
    machines: int = 1,
    accel: Optional[bool] = None,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> tuple:
    """(peak_bytes, breakdown dict) for one training step's histogram
    pipeline on one device.

    A deliberately simple sum of the dominant allocations — resident
    state plus the largest per-pass transient — NOT an XLA simulator.
    Accuracy target: the right ORDER for the feasibility verdict (the
    r5 failure was off by 9x, not 10%).
    """
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    n = max(int(rows), 1)
    F = max(int(features), 1)
    B = max(int(num_bins), 2)
    L = max(int(num_leaves), 2)
    K = max(int(num_class), 1)
    S = max(int(round_width), 1)
    T = n if tile_rows <= 0 else min(int(tile_rows), n)
    C = min(block_rows, _pad(T, 128))
    ch = 2 if quant else 3          # histogram channels
    hitem = 4                       # i32 / f32 cells

    b = {}
    bin_item = 1 if B <= 256 else 2
    # resident: the device binned matrix (feature-major [F, n]) and one
    # transformation copy (pad / compaction gather of the same shape)
    b["binned"] = _arr(n, F, bin_item, accel) * 2
    # carried scores (donated in+out) + per-class grad/hess f32 rows
    b["scores"] = 2 * K * _arr(n, 1, 4, accel)
    b["grads"] = 2 * K * _arr(n, 1, 4, accel)
    if quant:
        b["grads"] += 2 * K * _arr(n, 1, 1, accel)      # int8 gq/hq
    # per-tree histogram cache [L, ch, F, B] + the round's segment
    # output [S, ch, F, B]
    b["hist_cache"] = L * ch * _arr(B, F, hitem, accel)
    b["seg_hist"] = (S + 1) * ch * _arr(B, F, hitem, accel)
    # sorted-arena fixed state: u32 sort keys (key + sorted + order)
    if variant in ("sorted", "matmul", "matmul_int8"):
        b["sort_keys"] = 3 * _arr(n, 1, 4, accel)
    # whole-dataset fused record arena (pack_cols_u32): Wb+3 u32 words
    # per row (Wb+1 quantized)
    if use_pack:
        wb = (F + 3) // 4
        b["pack_arena"] = _arr(n, wb + (1 if quant else 3), 4, accel)

    # dominant per-pass transient, by kernel family
    if variant.startswith("scatter"):
        # the r5 OOM shape: [T*F, ch] update buffer (lane-padded on
        # accel) + [T, F] i32 flat indices
        b["scatter_updates"] = _arr(ch, T * F, hitem, accel)
        b["scatter_index"] = _arr(F, T, 4, accel)
    elif variant == "pallas":
        # VPU kernel: the accumulator and tile windows live in VMEM; HBM
        # transients are just the padded vals copy and the (small)
        # blocked output already counted in seg_hist/hist_cache
        b["vals_pad"] = _arr(n, ch, 4, accel)
    elif variant == "fused":
        # fused megakernel (ops/fused.py): the arena and one-hot operands
        # are VMEM-resident (modeled by fused_vmem_bytes, a SEPARATE
        # budget); HBM sees the streamed tiles, the smaller-child hist
        # writeback (seg_hist above) and the tiny tuple outputs — the
        # [L,ch,F,B] scan round-trip term is exactly what this variant
        # deletes
        b["vals_pad"] = _arr(n, ch, 4, accel)
        b["fused_tuples"] = 6 * _arr(F, 2 * S, 4, accel)
    elif variant.startswith("matmul"):
        onehot_item = 1 if (quant or variant == "matmul") else 4
        if variant == "matmul" and not quant:
            onehot_item = 2                      # bf16 one-hot
        b["onehot"] = _arr(B * F, C, onehot_item, accel)
        b["vals_pad"] = _arr(n, ch, 4, accel)    # padded vals copy
    else:                                        # sorted / expanded
        b["onehot"] = _arr(B * F, C, 1 if quant else 2, accel)
        if tile_rows <= 0:
            # hoisted whole-arena record gather
            wb = (F + 3) // 4
            width = (wb + (1 if quant else 3)) if use_pack else (F + 3)
            b["arena_gather"] = _arr(n, width, 4, accel)
        else:
            wb = (F + 3) // 4
            width = (wb + (1 if quant else 3)) if use_pack else (F + 3)
            b["arena_gather"] = _arr(C, width, 4, accel)
    # cross-device histogram reduction staging
    if machines > 1:
        from .histogram import hist_payload_bytes
        b["psum"] = 2 * hist_payload_bytes(
            F, B, rows_global=n * machines,
            quant_bins=None if not quant else 64) * S

    return sum(b.values()), b


def _resolved_variant(method: str, quant: bool) -> str:
    from .histogram import resolve_hist_method, use_sorted_seghist
    # "fused" models at the staged family here; fused election is a
    # separate verdict in plan_histograms (VMEM budget, plan_fused)
    m = resolve_hist_method("auto" if method == "fused" else method,
                            quantized=quant)
    # the segment passes dominate peak; their dispatch follows
    # use_sorted_seghist, not the point-histogram method — a forced
    # "pallas" POINT kernel still runs sorted-arena segment passes on
    # accelerators, so the peak model must keep those terms
    if use_sorted_seghist():
        return "sorted"
    return m


def _tile_override():
    """LGBM_TPU_TILE_ROWS: None = unset, 0 = force untiled, >0 = force."""
    v = os.environ.get("LGBM_TPU_TILE_ROWS", "").strip().lower()
    if not v:
        return None
    if v in ("0", "off", "none", "false"):
        return 0
    try:
        return max(int(v), 1)
    except ValueError:
        return None


# ======================================================================
# Compile-time war, part 1: shape-bucket ladders.  Every distinct row
# count is a distinct XLA program, so a pipeline of nearby dataset sizes
# recompiles everything from scratch each time.  Padding training rows
# up to a coarse ladder rung (the serving-bucket trick from predict,
# applied to training) makes nearby sizes share ONE compiled program;
# padded rows ride the existing row_mask machinery (mask 0, zero
# grad/hess) so sums and counts are untouched.
# ======================================================================

# smallest ladder rung: below this, compile time dwarfs any pad waste,
# so every tiny fit shares a single program shape
MIN_BUCKET_ROWS = 4096


def shape_buckets_enabled() -> bool:
    """LGBM_TPU_SHAPE_BUCKETS: "0" off, "1" on, unset = accelerators
    only.  CPU defaults OFF so golden-model tests keep exact row counts
    (f32 reduction trees change with padding; quantized paths do not)."""
    v = os.environ.get("LGBM_TPU_SHAPE_BUCKETS", "").strip().lower()
    if v in ("0", "off", "false", "no"):
        return False
    if v in ("1", "on", "true", "yes"):
        return True
    from .histogram import on_accelerator
    return on_accelerator()


def bucket_rows(n: int) -> int:
    """Smallest ladder rung >= n; rungs are {2^k, 1.5 * 2^k}.

    Two rungs per octave bounds pad waste at 50% (just past a power of
    two) while keeping the distinct-program count logarithmic in the
    row-count range.
    """
    n = max(int(n), 1)
    if n <= MIN_BUCKET_ROWS:
        return MIN_BUCKET_ROWS
    base = 1 << (n.bit_length() - 1)        # 2^k <= n
    for rung in (base, base + (base >> 1), base << 1):
        if rung >= n:
            return rung
    return base << 1                        # unreachable


# ======================================================================
# Compile-time war, part 2 — measured election: the autotuner.  The
# analytic models above answer "does it fit"; only a stopwatch answers
# "which variant is FASTEST here".  tools/hist_probe.py and bench record
# measured sec/level per (shape-bucket, variant) from
# obs.devprof.measure_program into an atomic JSON store beside the
# persistent compile cache; plan_histograms then elects the kernel
# variant (and the fused kernel's {feat_tile, block_rows}) from
# measurements when they exist, keeping the analytic model as the
# cold-start fallback.  A corrupt, stale or version-mismatched store is
# ALWAYS a miss, never a crash.
# ======================================================================

AUTOTUNE_STORE_VERSION = 1
_AUTOTUNE_STORE_FILE = "hist_timings.json"
# election outcomes since process start (or last reset):
#   hit  = a valid measurement keyed this shape and drove the election
#   miss = no usable measurement (cold start / stale name / bad context)
#   flip = a hit elected a DIFFERENT variant than the analytic model
_AUTOTUNE_STATS = {"hits": 0, "misses": 0, "flips": 0}
# the most recent election's full story — obs/diagnose.py feeds the
# kernel-underutilized verdict its concrete cure from here
_AUTOTUNE_LAST: dict = {}
_AUTOTUNE_LOCK = threading.Lock()


def autotune_enabled() -> bool:
    """LGBM_TPU_AUTOTUNE != "0" (default on; measurements only steer an
    election when the store actually holds some)."""
    return os.environ.get("LGBM_TPU_AUTOTUNE", "").strip().lower() \
        not in ("0", "off", "false", "no")


def autotune_dir():
    """Directory of the measured-timings store, or None (analytic-only).

    ``LGBM_TPU_AUTOTUNE_DIR`` wins; otherwise an ``autotune/`` sibling
    inside the persistent compile-cache dir — the measurements describe
    the same machine the cached programs were compiled for, so they
    share a home and a lifetime.
    """
    d = os.environ.get("LGBM_TPU_AUTOTUNE_DIR", "").strip()
    if d:
        return None if d.lower() in ("0", "off", "none") else d
    cc = os.environ.get("LGBM_TPU_COMPILE_CACHE", "").strip() \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR", "").strip()
    if cc and cc.lower() not in ("0", "off", "none"):
        return os.path.join(cc, "autotune")
    return None


def shape_bucket_key(rows: int, features: int, num_bins: int,
                     quant: bool, round_width: int) -> str:
    """Store key: the shape-bucket a measurement generalizes over.

    Rows go through ``bucket_rows`` so a 1.05M-row run reuses the
    1M-bucket measurement — exact-shape keys would never warm up.
    """
    return (f"r{bucket_rows(rows)}-f{int(features)}-b{int(num_bins)}"
            f"-q{int(bool(quant))}-w{int(round_width)}")


def _autotune_path(path=None):
    d = path or autotune_dir()
    return os.path.join(d, _AUTOTUNE_STORE_FILE) if d else None


def _load_autotune_store(path=None) -> dict:
    """{key: {variant: {"seconds": s, "params": {...}}}} — {} on ANY
    problem: missing file, corrupt JSON, wrong version, wrong shape."""
    p = _autotune_path(path)
    if not p:
        return {}
    try:
        with open(p, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) \
                or doc.get("version") != AUTOTUNE_STORE_VERSION:
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}
    except Exception:
        return {}


def record_timing(rows: int, features: int, num_bins: int, quant: bool,
                  round_width: int, variant: str, seconds: float,
                  params=None, path=None):
    """Bank one measured (shape-bucket, variant) timing; returns the
    store file path, or None when no store dir is configured.

    Read-merge-write under the process lock, landed via
    ``file_io.write_atomic`` so a crashed writer can never leave a torn
    store for the next election to trip over.
    """
    p = _autotune_path(path)
    if not p:
        return None
    from ..utils.file_io import write_atomic
    key = shape_bucket_key(rows, features, num_bins, quant, round_width)
    with _AUTOTUNE_LOCK:
        entries = _load_autotune_store(path)
        slot = dict(entries.get(key) or {})
        slot[str(variant)] = {"seconds": float(seconds),
                              "params": dict(params or {})}
        entries[key] = slot
        write_atomic(p, json.dumps(
            {"version": AUTOTUNE_STORE_VERSION, "entries": entries},
            indent=1, sort_keys=True))
    return p


def measured_election(rows, features, num_bins, quant, round_width,
                      path=None):
    """Fastest measured variant for this shape-bucket, or None (cold).

    Returns {"key", "variant", "seconds", "params"}; a malformed entry
    inside an otherwise-good slot is skipped, not fatal.
    """
    key = shape_bucket_key(rows, features, num_bins, quant, round_width)
    slot = _load_autotune_store(path).get(key)
    if not isinstance(slot, dict):
        return None
    best_v, best = None, None
    for v, rec in slot.items():
        try:
            s = float(rec["seconds"])
        except Exception:
            continue
        if s > 0 and (best is None or s < best["seconds"]):
            params = rec.get("params")
            best_v = str(v)
            best = {"seconds": s,
                    "params": params if isinstance(params, dict) else {}}
    if best_v is None:
        return None
    return {"key": key, "variant": best_v, **best}


def autotune_counters(reset: bool = False) -> dict:
    """Election-outcome counters {hits, misses, flips} since last reset."""
    with _AUTOTUNE_LOCK:
        out = dict(_AUTOTUNE_STATS)
        if reset:
            for k in _AUTOTUNE_STATS:
                _AUTOTUNE_STATS[k] = 0
    return out


def autotune_last() -> dict:
    """The most recent election's story (diagnose's cure feed)."""
    with _AUTOTUNE_LOCK:
        return dict(_AUTOTUNE_LAST)


def _adoptable_methods(quant: bool):
    """Measured staged variants plan_histograms may promote directly to
    ``hist_method`` (must be names resolve_hist_method accepts for the
    family; dispatch-level names like "sorted" steer via the family
    verdict "staged" instead)."""
    if quant:
        return ("matmul_int8", "scatter_int")
    return ("matmul", "matmul_f32", "scatter", "pallas")


def plan_histograms(
    rows: int,
    features: int,
    num_bins: int,
    num_leaves: int = 31,
    num_class: int = 1,
    quant: bool = False,
    quant_bins: int = 4,
    method: str = "auto",
    round_width: int = 128,
    machines: int = 1,
    budget_bytes: Optional[int] = None,   # tests: fake memory model
    accel: Optional[bool] = None,
    fused_ok: bool = False,               # caller-verified fused context
    vmem_bytes: Optional[int] = None,     # tests: fake VMEM model
    ledger: Optional["ResidencyLedger"] = None,   # co-resident budget
) -> HistPlan:
    """Choose {tile_rows, use_pack, psum narrowing} for a training shape.

    Search: untiled first (fastest dispatch); if its predicted peak
    exceeds the budget, walk tile_rows down through powers of two until
    the prediction fits (records un-hoisted — ``use_pack=False`` — the
    moment tiling engages, so no whole-dataset record arena is ever
    materialized in tiled mode).  ``feasible=False`` means even
    MIN_TILE_ROWS does not fit: the caller should refuse to launch the
    shape rather than hand XLA a guaranteed OOM.

    ``fused_ok=True`` (the caller proved the semantic context applies:
    numeric features, no bundles/monotone/per-node randomness, unsharded
    axes — GBDT._build_jit_fns) lets ``method`` "auto"/"fused" elect the
    fused Pallas histogram→split megakernel (ops/fused.py): elected ONLY
    when ``plan_fused`` proves its VMEM arena fits, so the staged family
    remains the fallback arm and an explicit ``hist_method=fused`` that
    does not fit degrades to staged instead of OOMing VMEM.
    """
    from .fused import fused_enabled_env
    from .histogram import quant_psum_narrow

    if budget_bytes is not None:
        limit, source = int(budget_bytes), "caller"
        budget = int(limit * HEADROOM)
    elif ledger is not None:
        # co-resident planning: the budget is what the ledger has LEFT
        # (already post-HEADROOM — the ledger applied it once to the
        # device limit; re-applying here would double-charge)
        limit, source = int(ledger.limit_bytes), "ledger"
        budget = int(ledger.available_bytes())
    else:
        limit, source = hbm_limit_bytes()
        # HEADROOM applies to EVERY limit source (caller-supplied fake
        # memory models included) so tests exercise the shipped rule
        budget = int(limit * HEADROOM)
    fp = None
    if fused_ok and method in ("auto", "fused") and fused_enabled_env():
        # the frontier never exceeds num_leaves - 1 candidates, so the
        # arena is sized by the EFFECTIVE round width (grower KCAP)
        kcap = max(min(int(round_width), int(num_leaves) - 1), 1)
        fp = plan_fused(kcap, num_bins, quant, with_parent=True,
                        vmem_bytes=vmem_bytes)
    variant = "fused" if fp is not None else _resolved_variant(method, quant)
    analytic_variant = variant
    elected_by, measured_variant, autotune_key = "analytic", "", ""
    if autotune_enabled() and method == "auto":
        # measured election: adopt the store's fastest variant for this
        # shape-bucket when it is valid IN CONTEXT — fused only if the
        # VMEM election ran and passed, staged names only within the
        # right kernel family; anything else is a stale name → a miss.
        autotune_key = shape_bucket_key(rows, features, num_bins, quant,
                                        round_width)
        m = measured_election(rows, features, num_bins, quant, round_width)
        adopted = False
        if m is not None:
            measured_variant = m["variant"]
            if measured_variant == "fused":
                if fp is not None:
                    adopted = True
                    ft = int(m["params"].get("feat_tile") or 0)
                    br = int(m["params"].get("block_rows") or 0)
                    if ft > 0 and br > 0:
                        # measured {feat_tile, block_rows} override the
                        # analytic walk — but only if they still fit the
                        # VMEM model (a store written on a bigger core
                        # must not OOM this one)
                        kcap = max(min(int(round_width),
                                       int(num_leaves) - 1), 1)
                        need = fused_vmem_bytes(kcap, num_bins, ft, br,
                                                quant, True)
                        lim = int(vmem_bytes if vmem_bytes is not None
                                  else vmem_limit_bytes())
                        if need <= int(lim * VMEM_HEADROOM):
                            fp = {"feat_tile": ft, "block_rows": br,
                                  "vmem_bytes": need,
                                  "vmem_limit_bytes": lim}
            elif measured_variant == "staged":
                # family-level verdict: the staged arm measured faster
                # than the fused kernel here — decline fused even when
                # its arena fits
                adopted = True
                fp = None
                variant = _resolved_variant("auto", quant)
            elif measured_variant in _adoptable_methods(quant):
                adopted = True
                fp = None
                variant = measured_variant
        elected = "fused" if fp is not None else variant
        with _AUTOTUNE_LOCK:
            if adopted:
                _AUTOTUNE_STATS["hits"] += 1
                elected_by = "measured"
                if elected != analytic_variant:
                    _AUTOTUNE_STATS["flips"] += 1
            else:
                _AUTOTUNE_STATS["misses"] += 1
            _AUTOTUNE_LAST.clear()
            _AUTOTUNE_LAST.update(
                key=autotune_key, analytic_variant=analytic_variant,
                measured_variant=measured_variant or None,
                measured_seconds=(m or {}).get("seconds"),
                elected_by=elected_by, elected_variant=elected)
    narrow = bool(quant and quant_psum_narrow(rows * machines, quant_bins))
    # the fused grower never hoists the pack_cols_u32 record arena (it
    # gathers nothing), so its plan must not charge — or report — it
    pack_cap = variant != "fused"

    def peak(tile, pack):
        return predict_peak_bytes(
            rows, features, num_bins, num_leaves, num_class, quant,
            variant, tile, pack and pack_cap, round_width, machines,
            accel)[0]

    untiled_peak = peak(0, True)
    forced = _tile_override()

    def mk(tile, pack, degraded):
        pack = pack and pack_cap
        p = peak(tile, pack)
        return HistPlan(
            tile_rows=tile, use_pack=pack, variant=variant, quant=quant,
            narrow_int16=narrow, predicted_peak_bytes=p,
            untiled_peak_bytes=untiled_peak, budget_bytes=budget,
            limit_bytes=limit, limit_source=source,
            feasible=p <= budget, degraded=degraded,
            fused=fp is not None,
            fused_feat_tile=fp["feat_tile"] if fp else 0,
            fused_block_rows=fp["block_rows"] if fp else 0,
            fused_vmem_bytes=fp["vmem_bytes"] if fp else 0,
            vmem_limit_bytes=fp["vmem_limit_bytes"] if fp else 0,
            elected_by=elected_by, measured_variant=measured_variant,
            autotune_key=autotune_key)

    if forced is not None:
        if forced == 0 or forced >= rows:
            return mk(0, True, False)
        return mk(int(forced), False, False)

    if untiled_peak <= budget:
        return mk(0, True, False)

    # degrade: largest power-of-two tile whose prediction fits
    tile = 1 << max(int(rows - 1).bit_length() - 1, 0)
    tile = max(tile, MIN_TILE_ROWS)
    while tile > MIN_TILE_ROWS and peak(tile, False) > budget:
        tile //= 2
    return mk(tile, False, True)


def apply_plan(cfg, rows: int, features: int, accel: Optional[bool] = None,
               fused_ok: bool = False):
    """Thread a plan into a ``GrowerConfig``; returns (cfg, plan).

    Shared by the GBDT layer (per-shard rows) and the standalone
    parallel learners so every path trains under the same verdict.
    ``fused_ok`` carries the caller's semantic-applicability verdict for
    the fused megakernel; when the plan elects it, ``hist_method`` flips
    to "fused" and the kernel's {feat_tile, block_rows} ride along — and
    when an EXPLICIT hist_method="fused" fails the VMEM election, the
    config degrades to the staged auto family instead of OOMing.
    """
    plan = plan_histograms(
        rows=rows, features=features, num_bins=cfg.num_bins,
        num_leaves=cfg.num_leaves, quant=cfg.quant,
        quant_bins=cfg.quant_bins, method=cfg.hist_method,
        round_width=cfg.round_width, machines=max(cfg.num_machines, 1),
        accel=accel, fused_ok=fused_ok)
    # first-class predicted-peak event (docs/OBSERVABILITY.md): the bench
    # logs the allocator's MEASURED peak next to it, so memory-model
    # drift is visible per run on the same timeline
    from ..obs.trace import instant
    instant("planner.plan", rows=rows, features=features, **plan.summary())
    cfg = cfg._replace(tile_rows=plan.tile_rows,
                       hist_pack=cfg.hist_pack and plan.use_pack)
    if plan.fused:
        cfg = cfg._replace(hist_method="fused",
                           fused_feat_tile=plan.fused_feat_tile,
                           fused_block_rows=plan.fused_block_rows)
    elif cfg.hist_method == "fused":
        from .fused import fused_enabled_env
        if fused_ok and fused_enabled_env():
            # the VMEM election actually ran and declined; the env-gate
            # (LGBM_TPU_FUSED=0) and context rejections are explained by
            # their own channels (the bisect operator / GBDT's gate
            # warning / make_sharded_grower's note)
            from ..utils.log import log_warning
            log_warning(
                "hist_method=fused: the fused megakernel's VMEM arena "
                f"does not fit at round_width={cfg.round_width}, "
                f"num_bins={cfg.num_bins} "
                f"(limit {vmem_limit_bytes()} bytes; LGBM_TPU_VMEM_BYTES "
                "overrides); falling back to the staged kernel family")
        cfg = cfg._replace(hist_method="auto")
    elif (plan.elected_by == "measured" and cfg.hist_method == "auto"
          and plan.variant in _adoptable_methods(cfg.quant)):
        # measured election of a staged POINT kernel: promote it so the
        # dispatch sites run what the stopwatch picked, not what "auto"
        # resolves to ("staged"/"sorted" family verdicts stay on auto)
        cfg = cfg._replace(hist_method=plan.variant)
    return cfg, plan


# ======================================================================
# Model-axis (batched multi-booster) memory model: lightgbm_tpu/multi/
# trains B boosters in ONE vmapped chunk program.  Per-lane state — the
# carried scores, gradients, per-tree hist cache, per-pass transients —
# scales ×B; the binned matrix does NOT in shared-data mode (every lane
# indexes one device matrix, in_axes=None) and DOES in stacked-data mode
# (CV folds upload per-lane matrices along the lane axis).  plan_model_batch
# elects the largest lane-chunk Bc <= B whose predicted peak fits the
# budget; the driver degrades to ceil(B / Bc) sequential dispatch groups
# when HBM says no.  LGBM_TPU_MODEL_BATCH: "" = planner-elected, "0"/"off"
# = force sequential (Bc=1), N = cap Bc.
# ======================================================================


def _model_batch_override():
    """LGBM_TPU_MODEL_BATCH: None = planner-elected, 1 = batching off,
    N = cap the elected lane chunk."""
    v = os.environ.get("LGBM_TPU_MODEL_BATCH", "").strip().lower()
    if not v:
        return None
    if v in ("0", "off", "false", "none", "no"):
        return 1
    try:
        return max(int(v), 1)
    except ValueError:
        return None


class ModelBatchPlan(NamedTuple):
    """Lane-chunk verdict for one batched multi-booster group."""

    b_total: int                # boosters in the group
    b_chunk: int                # lanes per device dispatch
    num_dispatch_groups: int    # ceil(b_total / b_chunk)
    stacked: bool               # binned matrix scales with Bc
    per_lane_bytes: int         # what ONE extra lane costs
    shared_bytes: int           # lane-independent residency (shared binned)
    predicted_peak_bytes: int   # at the elected b_chunk
    budget_bytes: int
    limit_bytes: int
    limit_source: str           # "memory_stats" | "env" | "default" | "caller"
    feasible: bool              # even Bc=1 fits the budget
    degraded: bool              # budget forced Bc < b_total
    forced: bool                # LGBM_TPU_MODEL_BATCH capped the election

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / telemetry."""
        return {
            "b_total": self.b_total,
            "b_chunk": self.b_chunk,
            "num_dispatch_groups": self.num_dispatch_groups,
            "stacked": self.stacked,
            "per_lane_bytes": self.per_lane_bytes,
            "shared_bytes": self.shared_bytes,
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "budget_bytes": self.budget_bytes,
            "hbm_limit_bytes": self.limit_bytes,
            "limit_source": self.limit_source,
            "feasible": self.feasible,
            "degraded": self.degraded,
            "forced": self.forced,
        }


def plan_model_batch(
    b_total: int,
    rows: int,
    features: int,
    num_bins: int,
    num_leaves: int = 31,
    num_class: int = 1,
    quant: bool = False,
    method: str = "auto",
    round_width: int = 128,
    machines: int = 1,
    stacked: bool = False,
    tile_rows: int = 0,
    use_pack: bool = True,
    budget_bytes: Optional[int] = None,   # tests: fake memory model
    accel: Optional[bool] = None,
    ledger: Optional["ResidencyLedger"] = None,   # co-resident budget
) -> ModelBatchPlan:
    """Elect the lane chunk for a B-booster batched training group.

    Memory model: ``total(Bc) = shared + Bc * per_lane`` where ``shared``
    is the binned matrix (plus its transformation copy) in shared-data
    mode and zero in stacked mode, and ``per_lane`` is everything else in
    ``predict_peak_bytes``'s breakdown (scores, gradients, hist cache,
    per-pass transients — all of which vmap replicates along the lane
    axis) plus, in stacked mode, the lane's own binned matrix.  Walk Bc
    down from B until the prediction fits; ``feasible=False`` means even
    one lane does not fit (same contract as ``plan_histograms``: refuse,
    don't OOM).
    """
    B = max(int(b_total), 1)
    if budget_bytes is not None:
        limit, source = int(budget_bytes), "caller"
        budget = int(limit * HEADROOM)
    elif ledger is not None:
        limit, source = int(ledger.limit_bytes), "ledger"
        budget = int(ledger.available_bytes())   # already post-HEADROOM
    else:
        limit, source = hbm_limit_bytes()
        budget = int(limit * HEADROOM)
    variant = _resolved_variant(method, quant)
    solo_peak, bd = predict_peak_bytes(
        rows, features, num_bins, num_leaves, num_class, quant, variant,
        tile_rows, use_pack, round_width, machines, accel)
    binned = bd["binned"]
    shared = 0 if stacked else binned
    per_lane = solo_peak - binned + (binned if stacked else 0)
    forced_cap = _model_batch_override()
    cap = B if forced_cap is None else min(B, forced_cap)

    def total(bc):
        return shared + bc * per_lane

    bc = cap
    while bc > 1 and total(bc) > budget:
        bc -= 1
    plan = ModelBatchPlan(
        b_total=B, b_chunk=bc, num_dispatch_groups=-(-B // bc),
        stacked=bool(stacked), per_lane_bytes=int(per_lane),
        shared_bytes=int(shared), predicted_peak_bytes=int(total(bc)),
        budget_bytes=budget, limit_bytes=limit, limit_source=source,
        feasible=total(1) <= budget,
        degraded=bc < B and (forced_cap is None or bc < cap),
        forced=forced_cap is not None)
    from ..obs.trace import instant
    instant("planner.model_batch", rows=rows, features=features,
            **plan.summary())
    return plan


# ======================================================================
# Per-tier collective link model: the hybrid ("dcn", "ici") mesh's
# reduction-schedule election (parallel/collectives.py).
#
# A TPU pod moves histogram payloads over TWO transports with a ~10-50x
# bandwidth gap: the intra-slice ICI torus and the cross-host DCN
# (PAPER.md §2.6).  ``plan_collectives`` models one histogram reduction
# under each schedule — flat (one psum over every data axis; the full
# payload effectively crosses the slow tier once per PARTICIPATING
# DEVICE, un-preaggregated), hierarchical (psum over ICI first, so DCN
# runs between num_slices pre-reduced participants), and voting
# (PV-Tree: only the top-k elected features' columns ever cross DCN) —
# and elects the cheapest.  Deliberately simple, like every model in
# this module: the right ORDER for the schedule verdict, not an XLA
# collective simulator.
# ======================================================================

# per-tier link bandwidths the election runs against (GB/s); order-of-
# magnitude figures for a v5e-class slice (ICI torus per-chip) vs a
# 50 Gbps-class host NIC.  LGBM_TPU_ICI_GBPS / LGBM_TPU_DCN_GBPS override
# (tests plan against fakes; operators against their fabric)
DEFAULT_ICI_GBPS = 100.0
DEFAULT_DCN_GBPS = 6.25


def _env_gbps(name: str, default: float) -> float:
    v = os.environ.get(name, "").strip()
    if v:
        try:
            return max(float(v), 1e-6)
        except ValueError:
            pass
    return default


def _hier_override():
    """LGBM_TPU_HIER_REDUCE: None = planner-elected, True/False forced."""
    v = os.environ.get("LGBM_TPU_HIER_REDUCE", "").strip().lower()
    if v in ("1", "on", "true", "yes", "force"):
        return True
    if v in ("0", "off", "false", "no"):
        return False
    return None


def pinned_reduce_env() -> bool:
    """LGBM_TPU_PINNED_REDUCE=1: deterministic tier-ordered f32 sums
    (parallel/collectives.py pinned mode) — the determinism knob behind
    the f32 flat==hierarchical model-text parity claim."""
    return os.environ.get("LGBM_TPU_PINNED_REDUCE", "").strip().lower() \
        in ("1", "on", "true", "yes")


class CollectivePlan(NamedTuple):
    """Reduction-schedule verdict for one histogram psum (see section
    docstring).  Byte fields are PER REDUCTION: what one [ch, F, B]
    histogram sync moves across each tier."""

    num_slices: int             # DCN participants (1 = single tier)
    devices_per_slice: int      # ICI participants per slice
    total_shards: int
    hierarchical: bool          # ICI-first tiered schedule elected
    pinned: bool                # deterministic tier-ordered f32 sums
    voting_k: int               # >0: only k elected features cross DCN
    payload_bytes: int          # one full-histogram psum payload
    ici_bytes: int              # bytes crossing the fast tier / device
    dcn_bytes: int              # bytes crossing the slow tier / slice
    flat_dcn_bytes: int         # what the FLAT schedule would move there
    est_flat_us: float          # modeled reduction time per schedule
    est_hier_us: float
    ici_gbps: float
    dcn_gbps: float
    elected: str                # "single" | "flat" | "hierarchical"
    #                             | "hierarchical+voting"

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / checkpoint manifests
        (the MULTICHIP journal's {mesh_shape, ici_bytes, dcn_bytes,
        hierarchy_elected, voting_k} fields read from here)."""
        return {
            "mesh_shape": [self.num_slices, self.devices_per_slice],
            "num_slices": self.num_slices,
            "total_shards": self.total_shards,
            "hierarchy_elected": self.hierarchical,
            "pinned": self.pinned,
            "voting_k": self.voting_k,
            "payload_bytes": self.payload_bytes,
            "ici_bytes": self.ici_bytes,
            "dcn_bytes": self.dcn_bytes,
            "flat_dcn_bytes": self.flat_dcn_bytes,
            "est_flat_us": round(self.est_flat_us, 3),
            "est_hier_us": round(self.est_hier_us, 3),
            "ici_gbps": self.ici_gbps,
            "dcn_gbps": self.dcn_gbps,
            "elected": self.elected,
        }


def plan_collectives(
    features: int,
    num_bins: int,
    rows_global: int,
    quant: bool = False,
    quant_bins: int = 4,
    num_slices: int = 1,
    devices_per_slice: int = 1,
    voting_k: int = 0,
    pinned: Optional[bool] = None,
    ici_gbps: Optional[float] = None,     # tests: fake link model
    dcn_gbps: Optional[float] = None,
) -> CollectivePlan:
    """Elect the reduction schedule for a (possibly hybrid) data mesh.

    ``features == 0`` plans shape-free (a nominal unit payload): the
    standalone learners elect a schedule before the traced shapes are
    known, and only the byte ACCOUNTING needs the real feature count.
    ``voting_k`` caps at ``features`` when both are known.  The verdict
    is journaled as a ``planner.plan_collectives`` trace instant, the
    twin of ``planner.plan`` (docs/OBSERVABILITY.md).
    """
    from .histogram import hist_payload_bytes

    s = max(int(num_slices), 1)
    d = max(int(devices_per_slice), 1)
    F = max(int(features), 0)
    k = min(int(voting_k), F) if (voting_k and F) else int(voting_k or 0)
    ici_bw = ici_gbps if ici_gbps is not None else _env_gbps(
        "LGBM_TPU_ICI_GBPS", DEFAULT_ICI_GBPS)
    dcn_bw = dcn_gbps if dcn_gbps is not None else _env_gbps(
        "LGBM_TPU_DCN_GBPS", DEFAULT_DCN_GBPS)
    payload = hist_payload_bytes(
        F or 1, max(int(num_bins), 2), rows_global=rows_global,
        quant_bins=(quant_bins if quant else None))
    # what crosses the slow tier per reduction: pre-aggregated full
    # payload (hierarchical data-parallel), the elected columns only
    # (voting), or the payload from every device of a slice (flat — no
    # pre-aggregation before the slow hop)
    # unknown feature count (shape-free planning) models NO voting
    # saving — a conservative ratio of 1.0 keeps the election and the
    # journaled DCN bytes honest until the real F is known
    vote_ratio = (k / F) if (k and F) else 1.0
    dcn_hier = int(payload * (vote_ratio if k else 1.0))
    if k:
        # the vote itself: [k] gains f32 + [k] indices i32, gathered
        # across slices — tiny next to histogram columns, but accounted
        dcn_hier += 8 * max(k, 1) * s
    flat_dcn = payload * d if s > 1 else 0
    us = 1e6 / 1e9   # bytes/GBps -> microseconds
    est_flat = (flat_dcn / dcn_bw + payload / ici_bw) * us if s > 1 \
        else (payload / ici_bw) * us
    est_hier = (payload / ici_bw + dcn_hier / dcn_bw) * us
    forced = _hier_override()
    if s <= 1:
        hier = False
        elected = "single" if d <= 1 else "flat"
    elif forced is not None:
        hier = forced
        elected = ("hierarchical+voting" if (hier and k) else
                   "hierarchical" if hier else "flat")
    else:
        hier = est_hier <= est_flat
        elected = ("hierarchical+voting" if (hier and k) else
                   "hierarchical" if hier else "flat")
    pin = pinned_reduce_env() if pinned is None else bool(pinned)
    plan = CollectivePlan(
        num_slices=s, devices_per_slice=d, total_shards=s * d,
        hierarchical=hier, pinned=pin, voting_k=k,
        payload_bytes=int(payload),
        ici_bytes=int(payload) if s * d > 1 else 0,
        dcn_bytes=int(dcn_hier if hier else flat_dcn) if s > 1 else 0,
        flat_dcn_bytes=int(flat_dcn),
        est_flat_us=float(est_flat), est_hier_us=float(est_hier),
        ici_gbps=float(ici_bw), dcn_gbps=float(dcn_bw), elected=elected)
    from ..obs.trace import instant
    instant("planner.plan_collectives", features=F, **plan.summary())
    return plan


# ======================================================================
# Two-level (device HBM + host RSS) budget: out-of-core streaming verdict
#
# PR 5's plan above made the *transients* O(tile); the binned matrix
# itself was still fully resident on BOTH memories, so dataset scale was
# capped by whichever is smaller.  ``plan_stream`` generalizes the model:
# it predicts the resident peaks on each memory, and when either budget
# is blown it elects ROW-BLOCK STREAMING (lightgbm_tpu/data/): the
# binned matrix lives in a checksummed spill store on disk, the host
# holds O(block) windows, and the device sees one double-buffered block
# at a time while the per-row vectors (scores/gradients/leaf routing)
# stay device-resident.  External-memory execution with block-compressed
# feature pages is the XGBoost external-memory lineage (arXiv
# 1806.11248); the one-pass-per-level feature-block access pattern is
# arXiv 1706.08359's.
# ======================================================================


def host_limit_bytes() -> tuple:
    """(limit_bytes, source) for the host-RSS side of the budget.

    Priority: ``LGBM_TPU_HOST_BYTES`` env (tests / fake memory models) >
    /proc/meminfo MemAvailable (what this process may still claim) > the
    conservative default.  Never raises.
    """
    env = os.environ.get("LGBM_TPU_HOST_BYTES", "").strip()
    if env:
        try:
            return max(int(float(env)), 1), "env"
        except ValueError:
            pass
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    kb = int(line.split()[1])
                    if kb > 0:
                        return kb * 1024, "meminfo"
    except OSError:
        pass
    return DEFAULT_HOST_BYTES, "default"


def predict_host_peak_bytes(rows: int, groups: int, bin_item: int = 1,
                            block_rows: int = 0) -> tuple:
    """(peak_bytes, breakdown) of the HOST side of one training run.

    ``block_rows == 0`` models the resident loader: the full [n, G]
    binned matrix plus one chunk of float64 binning scratch and the
    per-row metadata.  ``block_rows > 0`` models the streaming loader:
    three block windows (the spill writer's buffer + the pump's two
    double-buffered read windows) replace the matrix.  Deliberately
    simple — the right ORDER for the fits/doesn't verdict, like
    ``predict_peak_bytes``.
    """
    n = max(int(rows), 1)
    G = max(int(groups), 1)
    b = {}
    # label f32 + weight f32 + score fetches f32 + leaf routing i32 hosted
    # transiently by checkpoints: ~16 bytes/row of per-row metadata
    b["row_meta"] = 16 * n
    if block_rows <= 0:
        b["binned"] = n * G * bin_item
        # one float64 column of binning scratch per worker (dataset.py
        # _bin_block: 8 workers max)
        b["bin_scratch"] = 8 * 8 * n
    else:
        C = int(block_rows)
        b["block_windows"] = 3 * C * G * bin_item
        b["bin_scratch"] = 8 * 8 * C
    return sum(b.values()), b


class StreamPlan(NamedTuple):
    """Two-level budget verdict (see module section docstring)."""

    stream: bool                       # row-block streaming elected
    block_rows: int                    # rows per streamed block (0 = resident)
    num_blocks: int
    resident_device_ok: bool           # full residency fits the HBM budget
    resident_host_ok: bool             # full residency fits the RSS budget
    predicted_device_peak_bytes: int   # for the chosen mode
    predicted_host_peak_bytes: int     # for the chosen mode
    device_budget_bytes: int
    host_budget_bytes: int
    host_limit_bytes: int
    host_limit_source: str             # "env" | "meminfo" | "default"
    feasible: bool                     # the chosen mode fits BOTH budgets
    reason: str                        # why streaming was/wasn't elected

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / checkpoint provenance."""
        return {
            "stream": self.stream,
            "block_rows": self.block_rows,
            "num_blocks": self.num_blocks,
            "resident_device_ok": self.resident_device_ok,
            "resident_host_ok": self.resident_host_ok,
            "predicted_device_peak_bytes": self.predicted_device_peak_bytes,
            "predicted_host_peak_bytes": self.predicted_host_peak_bytes,
            "device_budget_bytes": self.device_budget_bytes,
            "host_budget_bytes": self.host_budget_bytes,
            "host_limit_bytes": self.host_limit_bytes,
            "host_limit_source": self.host_limit_source,
            "feasible": self.feasible,
            "reason": self.reason,
        }


def _stream_override():
    """LGBM_TPU_STREAM: None = auto (budget-elected), True = force
    streaming, False = never stream."""
    v = os.environ.get("LGBM_TPU_STREAM", "").strip().lower()
    if v in ("1", "on", "force", "true", "yes"):
        return True
    if v in ("0", "off", "false", "no", "none"):
        return False
    return None


def _stream_block_override():
    v = os.environ.get("LGBM_TPU_STREAM_BLOCK_ROWS", "").strip()
    if not v:
        return None
    try:
        return max(int(float(v)), 128)
    except ValueError:
        return None


def predict_stream_device_peak_bytes(
        rows: int, features: int, num_bins: int, block_rows: int,
        num_leaves: int = 31, num_class: int = 1, quant: bool = False,
        variant: str = "scatter", tile_rows: int = 0,
        round_width: int = 128, accel: Optional[bool] = None) -> int:
    """Device peak of one STREAMED training step: the resident model with
    the whole-matrix terms replaced by two device block windows plus the
    per-row routing vectors the streamed grower keeps resident."""
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    n = max(int(rows), 1)
    C = min(max(int(block_rows), 1), n)
    bin_item = 1 if num_bins <= 256 else 2
    # model the per-pass transients at block scale: the kernels only ever
    # see C rows at a time
    peak, b = predict_peak_bytes(
        C, features, num_bins, num_leaves, num_class, quant, variant,
        min(tile_rows, C) if tile_rows else 0, False, round_width,
        1, accel)
    peak -= b["binned"]                      # no resident matrix
    peak -= b["scores"] + b["grads"]         # re-added at full n below
    dev = peak
    dev += 2 * _arr(C, max(int(features), 1), bin_item, accel)  # 2 windows
    K = max(int(num_class), 1)
    dev += 2 * K * _arr(n, 1, 4, accel)      # scores (donated in+out)
    dev += 2 * K * _arr(n, 1, 4, accel)      # grad/hess rows
    if quant:
        dev += 2 * K * _arr(n, 1, 1, accel)
    # leaf_id i32 + goes-left bool + candidate-rank i32 + row mask f32
    dev += _arr(n, 1, 4, accel) * 3 + _arr(n, 1, 1, accel)
    return int(dev)


# ======================================================================
# Serving-fleet residency budget: multi-model shared-HBM election
#
# The serving tier (lightgbm_tpu/fleet/) keeps N models' device routing
# arrays (DeviceForest) plus their per-bucket compiled programs resident
# in the SAME HBM the training plans above budget.  ``plan_fleet``
# applies the training planner's discipline to the fleet: model the
# per-model resident bytes, elect which models (and which of their
# ladder buckets) stay device-resident under the budget, and mark the
# rest EVICTED — an evicted model keeps serving through the bit-identical
# host path instead of OOMing the chip.  Low-precision models
# (bf16/int8 thresholds, host-gathered leaves — the fixed-point GBDT
# accelerator direction of arXiv 2011.02022) charge proportionally less,
# so opting a model into low precision buys residency for its neighbors.
# ======================================================================


def predict_forest_bytes(num_trees: int, nodes_dim: int, leaves_dim: int,
                         precision: str = "f32", cat_words: int = 0,
                         accel: Optional[bool] = None,
                         routing_only: bool = False) -> int:
    """Resident device bytes of ONE model's DeviceForest arrays.

    ``nodes_dim``/``leaves_dim`` are the padded [T, I]/[T, L] axes of the
    stacked forest (predict.py).  ``precision`` prices the threshold
    array (f32 = 4, bf16 = 2, int8 = 1 byte + a per-tree f32 dequant
    scale); ``routing_only`` drops the leaf-value array (low-precision
    serving gathers leaves on the host, so it never uploads them).
    Deliberately simple — the right ORDER for the residency election,
    like ``predict_peak_bytes``.
    """
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    T = max(int(num_trees), 1)
    I = max(int(nodes_dim), 1)
    L = max(int(leaves_dim), 1)
    thr_item = {"f32": 4, "bf16": 2, "int8": 1}.get(precision, 4)
    b = 3 * _arr(I, T, 4, accel)            # split_feature, left, right i32
    b += _arr(I, T, thr_item, accel)        # thresholds
    b += 2 * _arr(I, T, 1, accel)           # is_cat, default_left bool
    b += _arr(I, T, 4, accel)               # missing_type i32
    if precision == "int8":
        b += _arr(1, T, 4, accel)           # per-tree dequant scale f32
    if not routing_only:
        b += _arr(L, T, 4, accel)           # leaf_value f32
    if cat_words > 0:
        b += 2 * _arr(I, T, 8, accel) + _arr(int(cat_words), 1, 4, accel)
    return int(b)


def predict_program_bytes(num_trees: int, bucket_rows: int, features: int,
                          accel: Optional[bool] = None) -> int:
    """Transient device bytes of one bucket-shaped serving program
    invocation: the padded [bucket, F] f32 input, the [T, bucket]
    traversal state (node + gathered attrs live across the while-loop
    step) and the leaf-index output.  This is what the residency
    election charges per WARMED bucket — the executable itself is small
    next to its activations."""
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    T = max(int(num_trees), 1)
    C = max(int(bucket_rows), 1)
    F = max(int(features), 1)
    b = _arr(F, C, 4, accel)                # input batch f32
    b += 4 * _arr(C, T, 4, accel)           # node/next/fval/threshold state
    b += _arr(C, T, 4, accel)               # leaves out i32
    return int(b)


def fleet_replica_bytes(m: "FleetModelShape",
                        accel: Optional[bool] = None):
    """Device cost of ONE replica of ``m``: ``(forest_bytes,
    {bucket: program_bytes})`` — the unit the single-device residency
    election (``plan_fleet``) and the multi-device placement planner
    (``fleet/topology.plan_topology``) both charge, so a topology's
    per-device loads and each device's own residency verdicts can never
    disagree about what a replica costs."""
    fb = predict_forest_bytes(
        m.num_trees, m.nodes_dim, m.leaves_dim, m.precision,
        m.cat_words, accel, routing_only=m.precision != "f32")
    ladder = sorted(set(int(b) for b in m.buckets)) or [8]
    prog = {b: predict_program_bytes(m.num_trees, b, m.features, accel)
            for b in ladder}
    return fb, prog


class FleetModelShape(NamedTuple):
    """One serving model's shape as the fleet election sees it."""

    name: str
    num_trees: int
    nodes_dim: int              # padded internal-node axis I
    leaves_dim: int             # padded leaf axis L
    features: int
    num_class: int = 1
    buckets: tuple = ()         # the model's bucket ladder (row counts)
    weight: float = 1.0         # admission weight (fleet config)
    age_s: float = 0.0          # seconds since last request (0 = hot)
    precision: str = "f32"      # "f32" | "bf16" | "int8"
    cat_words: int = 0


class FleetModelPlan(NamedTuple):
    """Residency verdict for one model."""

    name: str
    resident: bool              # device forest stays in HBM
    resident_buckets: tuple     # buckets whose programs stay warm
    forest_bytes: int           # charged when resident
    program_bytes: int          # charged for the resident buckets
    priority: float             # weight / (1 + age): the election key


class FleetPlan(NamedTuple):
    """Shared-HBM residency plan for a serving fleet (see section
    docstring).  Always servable: eviction falls back to the host path,
    so ``feasible`` is about DEVICE residency, not about serving."""

    models: tuple               # FleetModelPlan per input model, input order
    total_resident_bytes: int
    budget_bytes: int
    limit_bytes: int
    limit_source: str           # "memory_stats" | "env" | "default" | "caller"
    evicted: tuple              # names of non-resident models
    pressure: float             # wanted-resident bytes / budget
    feasible: bool              # every model got device residency

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / telemetry."""
        return {
            "models": [
                {"name": m.name, "resident": m.resident,
                 "resident_buckets": list(m.resident_buckets),
                 "forest_bytes": m.forest_bytes,
                 "program_bytes": m.program_bytes,
                 "priority": round(m.priority, 6)}
                for m in self.models
            ],
            "total_resident_bytes": self.total_resident_bytes,
            "budget_bytes": self.budget_bytes,
            "hbm_limit_bytes": self.limit_bytes,
            "limit_source": self.limit_source,
            "evicted": list(self.evicted),
            "pressure": round(self.pressure, 4),
            "feasible": self.feasible,
        }


def plan_fleet(models, budget_bytes: Optional[int] = None,
               accel: Optional[bool] = None,
               ledger: Optional["ResidencyLedger"] = None) -> FleetPlan:
    """Elect per-model device residency for a serving fleet.

    Greedy by priority ``weight / (1 + age_s)`` — hot, heavily-weighted
    models first.  A model is admitted when its forest plus at least its
    smallest bucket's program fit the remaining budget; further buckets
    are admitted smallest-first (the cheapest warm shapes give the most
    service per byte).  Models that do not fit are EVICTED: their device
    arrays and compiled programs are released and they serve through the
    bit-identical host path until a replan readmits them.  ``HEADROOM``
    applies to every limit source, exactly like ``plan_histograms``.
    """
    if budget_bytes is not None:
        limit, source = int(budget_bytes), "caller"
        budget = int(limit * HEADROOM)
    elif ledger is not None:
        # serving election against the ledger's REMAINING budget: bytes
        # already leased (e.g. by an in-flight training refresh) are not
        # available for model residency
        limit, source = int(ledger.limit_bytes), "ledger"
        budget = int(ledger.available_bytes())
    else:
        limit, source = hbm_limit_bytes()
        budget = int(limit * HEADROOM)
    models = list(models)
    order = sorted(
        range(len(models)),
        key=lambda i: (-(models[i].weight / (1.0 + max(models[i].age_s, 0.0))),
                       i))
    plans: dict = {}
    used = 0
    wanted = 0
    for i in order:
        m = models[i]
        prio = m.weight / (1.0 + max(m.age_s, 0.0))
        fb, prog = fleet_replica_bytes(m, accel)
        ladder = sorted(prog)
        wanted += fb + sum(prog.values())
        if used + fb + prog[ladder[0]] > budget:
            plans[i] = FleetModelPlan(m.name, False, (), fb, 0, prio)
            continue
        used += fb
        taken, pb = [], 0
        for b in ladder:
            if used + prog[b] <= budget:
                taken.append(b)
                used += prog[b]
                pb += prog[b]
        plans[i] = FleetModelPlan(m.name, True, tuple(taken), fb, pb, prio)
    ordered = tuple(plans[i] for i in range(len(models)))
    evicted = tuple(p.name for p in ordered if not p.resident)
    return FleetPlan(
        models=ordered, total_resident_bytes=used, budget_bytes=budget,
        limit_bytes=limit, limit_source=source, evicted=evicted,
        pressure=(wanted / budget) if budget > 0 else float("inf"),
        feasible=not evicted)


def plan_stream(
    rows: int,
    features: int,               # device column count (groups under EFB)
    num_bins: int,
    num_leaves: int = 31,
    num_class: int = 1,
    quant: bool = False,
    method: str = "auto",
    round_width: int = 128,
    tile_rows: int = 0,          # the hist plan's tile (block aligns to it)
    device_budget_bytes: Optional[int] = None,   # tests: fake memory model
    host_budget_bytes: Optional[int] = None,     # tests: fake memory model
    accel: Optional[bool] = None,
    ledger: Optional["ResidencyLedger"] = None,  # co-resident budget
) -> StreamPlan:
    """Choose resident vs row-block-streamed execution for a shape.

    Streaming is elected when full residency blows EITHER budget (device
    HBM via ``predict_peak_bytes``'s model, host RSS via
    ``predict_host_peak_bytes``) and a block size exists whose streamed
    peaks fit BOTH.  Block search: largest power of two first (fewer
    dispatches), aligned up to a multiple of the hist plan's ``tile_rows``
    so the streamed fold partitions rows exactly like the resident tiled
    kernels (the f32 matmul family's bit-parity needs the alignment; the
    scatter family is partition-free).  ``feasible=False`` means even
    MIN_STREAM_BLOCK_ROWS does not fit — refuse to launch rather than
    OOM either memory.

    Env: ``LGBM_TPU_STREAM`` (1 = force streaming, 0 = never),
    ``LGBM_TPU_STREAM_BLOCK_ROWS`` (force the block size),
    ``LGBM_TPU_HOST_BYTES`` (host limit override).
    """
    n = max(int(rows), 1)
    variant = _resolved_variant(method, quant)
    if device_budget_bytes is not None:
        dev_budget = int(device_budget_bytes * HEADROOM)
    elif ledger is not None:
        dev_budget = int(ledger.available_bytes())   # already post-HEADROOM
    else:
        dev_budget = int(hbm_limit_bytes()[0] * HEADROOM)
    if host_budget_bytes is not None:
        host_limit, host_src = int(host_budget_bytes), "caller"
    else:
        host_limit, host_src = host_limit_bytes()
    host_budget = int(host_limit * HOST_HEADROOM)
    bin_item = 1 if num_bins <= 256 else 2

    resident_dev = predict_peak_bytes(
        n, features, num_bins, num_leaves, num_class, quant, variant,
        tile_rows, tile_rows <= 0, round_width, 1, accel)[0]
    resident_host = predict_host_peak_bytes(n, features, bin_item)[0]
    dev_ok = resident_dev <= dev_budget
    host_ok = resident_host <= host_budget

    forced = _stream_override()
    want = forced if forced is not None else not (dev_ok and host_ok)

    def mk(stream, block, reason, dev_peak, host_peak):
        nb = 0 if block <= 0 else -(-n // block)
        return StreamPlan(
            stream=stream, block_rows=block, num_blocks=nb,
            resident_device_ok=dev_ok, resident_host_ok=host_ok,
            predicted_device_peak_bytes=int(dev_peak),
            predicted_host_peak_bytes=int(host_peak),
            device_budget_bytes=dev_budget, host_budget_bytes=host_budget,
            host_limit_bytes=host_limit, host_limit_source=host_src,
            feasible=(dev_peak <= dev_budget and host_peak <= host_budget),
            reason=reason)

    if not want:
        reason = ("disabled by LGBM_TPU_STREAM=0" if forced is False
                  else "resident fits both budgets")
        return mk(False, 0, reason, resident_dev, resident_host)

    def peaks(block):
        return (predict_stream_device_peak_bytes(
                    n, features, num_bins, block, num_leaves, num_class,
                    quant, variant, tile_rows, round_width, accel),
                predict_host_peak_bytes(n, features, bin_item, block)[0])

    def align(block):
        if tile_rows > 0 and block > tile_rows:
            return block // tile_rows * tile_rows
        return block

    reason = ("forced by LGBM_TPU_STREAM=1" if forced else
              ("device+host" if not dev_ok and not host_ok else
               "device" if not dev_ok else "host") + " budget exceeded")
    b_forced = _stream_block_override()
    if b_forced is not None:
        block = min(b_forced, n)
        dp, hp = peaks(block)
        return mk(True, block, reason + " (block forced)", dp, hp)
    block = MAX_STREAM_BLOCK_ROWS
    while block > MIN_STREAM_BLOCK_ROWS:
        if align(block) < n:        # a single-block "stream" is resident
            dp, hp = peaks(align(block))
            if dp <= dev_budget and hp <= host_budget:
                return mk(True, align(block), reason, dp, hp)
        block //= 2
    block = align(min(MIN_STREAM_BLOCK_ROWS, n))
    dp, hp = peaks(block)
    return mk(True, block, reason, dp, hp)


# ======================================================================
# Residency ledger: ONE per-device HBM budget both planes lease from.
#
# Every planner above models its OWN plane's peak against a budget it
# assumes it owns — which is exactly how co-resident train+serve on one
# pod over-commits and dies as a compile-OOM.  ``ResidencyLedger`` is
# the arbitration layer: one post-HEADROOM budget per device, explicit
# leases (who, which plane, how many bytes, preemptible?), and a
# ``ledger=`` seam on ``plan_histograms`` / ``plan_model_batch`` /
# ``plan_stream`` / ``plan_fleet`` (and ``fleet.topology.plan_topology``)
# that makes each planner elect against the ledger's REMAINING bytes.
# The degradation order falls out of the existing planners: a training
# refresh planned against the remainder degrades its tile size first
# (plan_histograms' tile walk), and only an explicit ``preempt`` ever
# touches serving residency.  Infeasible co-residency is a loud
# ``LedgerError`` carrying the lease table — never an XLA OOM.  Every
# ledger event is journaled as a ``planner.ledger`` trace instant and
# mirrored to ``ledger_*`` gauges (docs/OBSERVABILITY.md).
# ======================================================================


class LedgerError(RuntimeError):
    """A lease request exceeds the ledger's remaining budget — the loud
    co-residency verdict (refuse, don't OOM).  The message carries the
    full lease table so the operator sees WHO holds the HBM."""


class Lease(NamedTuple):
    """One admitted residency claim."""

    lease_id: int
    owner: str                  # e.g. "fleet:ranker" / "refresh:ranker"
    plane: str                  # "serving" | "train"
    nbytes: int
    preemptible: bool           # preempt() may evict it


class ResidencyLedger:
    """Per-device HBM budget shared by the serving and training planes.

    Thread-safe: the serving fleet's replan thread and the co-resident
    training scheduler lease/release concurrently.  The ledger applies
    ``HEADROOM`` ONCE to the device limit; planners handed a ledger use
    ``available_bytes()`` directly (already post-HEADROOM), so the slack
    is never double-charged.
    """

    def __init__(self, limit_bytes: Optional[int] = None):
        if limit_bytes is not None:
            limit, source = max(int(limit_bytes), 1), "caller"
        else:
            limit, source = hbm_limit_bytes()
        self.limit_bytes = limit
        self.limit_source = source
        self.budget_bytes = int(limit * HEADROOM)
        self._lock = threading.Lock()
        self._leases = {}       # guarded-by: _lock
        self._next_id = 1       # guarded-by: _lock

    # -- accounting --------------------------------------------------

    def leased_bytes(self, plane: Optional[str] = None) -> int:
        with self._lock:
            return sum(l.nbytes for l in self._leases.values()
                       if plane is None or l.plane == plane)

    def available_bytes(self) -> int:
        """Remaining post-HEADROOM budget — what a co-resident planner
        may claim without over-committing the device."""
        return max(self.budget_bytes - self.leased_bytes(), 0)

    def train_limit_bytes(self, lease: Optional[Lease] = None) -> int:
        """The remainder expressed as a LIMIT (pre-HEADROOM), for code
        paths that re-apply HEADROOM themselves (``LGBM_TPU_HBM_BYTES``
        consumers).  Int-floored so re-applying HEADROOM lands <= the
        actual remainder.  ``lease`` adds a held training lease back in:
        the training plane's envelope is its own lease plus the slack."""
        grant = self.available_bytes()
        if lease is not None:
            with self._lock:
                if lease.lease_id in self._leases:
                    grant += lease.nbytes
        return max(int(grant / HEADROOM), 1)

    def table(self) -> list:
        """The lease table, JSON-friendly (flight bundles / doctor
        evidence / LedgerError messages)."""
        with self._lock:
            leases = sorted(self._leases.values())
        return [{"lease_id": l.lease_id, "owner": l.owner,
                 "plane": l.plane, "bytes": l.nbytes,
                 "preemptible": l.preemptible} for l in leases]

    def summary(self) -> dict:
        """JSON-friendly totals for journals / telemetry."""
        with self._lock:
            leased = sum(l.nbytes for l in self._leases.values())
            by_plane: dict = {}
            for l in self._leases.values():
                by_plane[l.plane] = by_plane.get(l.plane, 0) + l.nbytes
            count = len(self._leases)
        return {"limit_bytes": self.limit_bytes,
                "limit_source": self.limit_source,
                "budget_bytes": self.budget_bytes,
                "leased_bytes": leased,
                "available_bytes": max(self.budget_bytes - leased, 0),
                "num_leases": count,
                "leased_by_plane": by_plane}

    # -- lease lifecycle ---------------------------------------------

    def lease(self, owner: str, nbytes: int, plane: str = "train",
              preemptible: bool = False) -> Lease:
        """Admit a residency claim or raise ``LedgerError`` loudly."""
        need = max(int(nbytes), 0)
        with self._lock:
            leased = sum(l.nbytes for l in self._leases.values())
            if leased + need > self.budget_bytes:
                denied = True
                granted = None
            else:
                denied = False
                granted = Lease(self._next_id, str(owner), str(plane),
                                need, bool(preemptible))
                self._leases[granted.lease_id] = granted
                self._next_id += 1
        if denied:
            self._emit("deny", owner=str(owner), plane=str(plane),
                       bytes=need)
            raise LedgerError(
                f"residency ledger: lease '{owner}' ({plane}) wants "
                f"{need} bytes but only {self.available_bytes()} of the "
                f"{self.budget_bytes}-byte budget remain "
                f"(limit {self.limit_bytes}, source "
                f"{self.limit_source}); held leases: {self.table()}")
        self._emit("lease", owner=granted.owner, plane=granted.plane,
                   bytes=granted.nbytes, lease_id=granted.lease_id)
        return granted

    def try_lease(self, owner: str, nbytes: int, plane: str = "train",
                  preemptible: bool = False) -> Optional[Lease]:
        """``lease`` that returns None instead of raising."""
        try:
            return self.lease(owner, nbytes, plane, preemptible)
        except LedgerError:
            return None

    def release(self, lease) -> None:
        """Return a lease's bytes to the budget (idempotent)."""
        lid = getattr(lease, "lease_id", lease)
        with self._lock:
            gone = self._leases.pop(lid, None)
        if gone is not None:
            self._emit("release", owner=gone.owner, plane=gone.plane,
                       bytes=gone.nbytes, lease_id=gone.lease_id)

    def preempt(self, plane: str = "train") -> int:
        """Evict every preemptible lease of ``plane``; returns the bytes
        freed.  The co-resident scheduler marks training leases
        preemptible, so a serving-side replan under pressure preempts
        training residency — never the other way around (degrade tile
        before degrading serving residency)."""
        with self._lock:
            victims = [l for l in self._leases.values()
                       if l.plane == plane and l.preemptible]
            for v in victims:
                del self._leases[v.lease_id]
        freed = sum(v.nbytes for v in victims)
        if victims:
            self._emit("preempt", plane=plane, freed_bytes=freed,
                       victims=[v.owner for v in victims])
        return freed

    @contextmanager
    def train_env(self, lease: Optional[Lease] = None):
        """Pin ``LGBM_TPU_HBM_BYTES`` to the training plane's envelope
        so every planner reached INSIDE ``engine.train`` (hist, stream,
        model-batch) plans against remaining-HBM-plus-own-lease instead
        of the whole device."""
        key = "LGBM_TPU_HBM_BYTES"
        prev = os.environ.get(key)
        os.environ[key] = str(self.train_limit_bytes(lease))
        try:
            yield self
        finally:
            if prev is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prev

    # -- telemetry ---------------------------------------------------

    def _emit(self, event: str, **extra) -> None:
        s = self.summary()
        from ..obs.trace import instant
        instant("planner.ledger", event=event, **extra, **s)
        from ..obs.metrics import global_registry
        global_registry.gauge("ledger_budget_bytes").set(s["budget_bytes"])
        global_registry.gauge("ledger_available_bytes").set(
            s["available_bytes"])
        for plane in ("serving", "train"):
            global_registry.gauge(
                "ledger_leased_bytes", labels={"plane": plane}).set(
                    s["leased_by_plane"].get(plane, 0))


# the process's co-residency ledger, when a coresident.Scheduler (or an
# operator) installed one — the diagnose layer reads it for the
# contention verdict's lease-table evidence
_active_ledger: Optional[ResidencyLedger] = None
_active_ledger_lock = threading.Lock()


def set_active_ledger(ledger: Optional[ResidencyLedger]):
    """Install ``ledger`` as the process's co-residency ledger; returns
    the previous one (restore it when tearing down a scheduler)."""
    global _active_ledger
    with _active_ledger_lock:
        prev = _active_ledger
        _active_ledger = ledger
    return prev


def active_ledger() -> Optional[ResidencyLedger]:
    return _active_ledger


# ======================================================================
# Inference kernel + chunk election: plan_predict.  The predict path's
# analogue of plan_histograms — byte models answer "does it fit", the
# measured-timings store (a new "p-..." key namespace in the SAME
# hist_timings.json) answers "which traversal variant is fastest", and
# LGBM_TPU_PREDICT_KERNEL is the bisect gate over the whole election.
# ======================================================================

PREDICT_VARIANTS = ("while", "fori", "fused")
# largest device chunk the election will reach for (a ladder rung; the
# per-call chunk still shrinks to bucket_rows(n) for small batches)
MAX_PREDICT_CHUNK_ROWS = 1 << 20
# fused-traversal row-tile ladder (widest VMEM-resident tile first)
FUSED_PREDICT_TILES = (2048, 1024, 512, 256, 128)


def _predict_kernel_override():
    """LGBM_TPU_PREDICT_KERNEL: pin the traversal variant, bypassing
    measured and analytic election (the bisect gate)."""
    v = os.environ.get("LGBM_TPU_PREDICT_KERNEL", "").strip().lower()
    return v if v in PREDICT_VARIANTS else None


def _predict_chunk_override():
    """LGBM_TPU_PREDICT_CHUNK: pin the predict chunk size."""
    v = os.environ.get("LGBM_TPU_PREDICT_CHUNK", "").strip()
    if not v:
        return None
    try:
        n = int(float(v))
    except ValueError:
        return None
    return max(n, 8) if n > 0 else None


def predict_bucket_key(rows: int, features: int, num_trees: int,
                       num_class: int, precision: str) -> str:
    """Store key of the predict autotune family — prefixed "p-" so it
    can never collide with histogram shape-bucket keys in the shared
    store file."""
    return (f"p-r{bucket_rows(max(int(rows), 1))}-f{int(features)}"
            f"-t{int(num_trees)}-k{max(int(num_class), 1)}-{precision}")


def record_predict_timing(rows, features, num_trees, num_class, precision,
                          variant, seconds, params=None, path=None):
    """Bank one measured (predict shape-bucket, variant) timing in the
    shared store; returns the store path or None (no store dir).  Same
    read-merge-write-atomic discipline as ``record_timing``."""
    p = _autotune_path(path)
    if not p:
        return None
    from ..utils.file_io import write_atomic
    key = predict_bucket_key(rows, features, num_trees, num_class, precision)
    with _AUTOTUNE_LOCK:
        entries = _load_autotune_store(path)
        slot = dict(entries.get(key) or {})
        slot[str(variant)] = {"seconds": float(seconds),
                              "params": dict(params or {})}
        entries[key] = slot
        write_atomic(p, json.dumps(
            {"version": AUTOTUNE_STORE_VERSION, "entries": entries},
            indent=1, sort_keys=True))
    return p


def measured_predict_election(rows, features, num_trees, num_class,
                              precision, path=None):
    """Fastest measured traversal variant for this predict bucket, or
    None (cold).  Unknown variant names (a store written by a future
    version) are skipped, not adopted."""
    key = predict_bucket_key(rows, features, num_trees, num_class, precision)
    slot = _load_autotune_store(path).get(key)
    if not isinstance(slot, dict):
        return None
    best_v, best = None, None
    for v, rec in slot.items():
        if str(v) not in PREDICT_VARIANTS:
            continue
        try:
            s = float(rec["seconds"])
        except Exception:
            continue
        if s > 0 and (best is None or s < best["seconds"]):
            params = rec.get("params")
            best_v = str(v)
            best = {"seconds": s,
                    "params": params if isinstance(params, dict) else {}}
    if best_v is None:
        return None
    return {"key": key, "variant": best_v, **best}


def predict_fused_vmem_bytes(num_trees: int, nodes_dim: int, features: int,
                             tile_rows: int, cat_words: int = 0,
                             leaves_dim: int = 0, num_class: int = 1,
                             emit_scores: bool = False) -> int:
    """Predicted VMEM bytes of one fused-traversal grid step
    (ops/predict_kernels.py): the nine resident [T, I] forest planes +
    bitset words, the double-buffered [tile, F] input window, the
    [T, tile] node state with its gather transients, and the output
    block (leaf plane, or the [K, tile] score block plus the resident
    leaf-value plane in score mode).  Deliberately simple — the right
    ORDER for the fits/doesn't verdict, like ``fused_vmem_bytes``."""
    T = max(int(num_trees), 1)
    I = max(int(nodes_dim), 1)
    F = max(int(features), 1)
    C = max(int(tile_rows), 8)
    K = max(int(num_class), 1)
    planes = 9 * T * I * 4 + max(int(cat_words), 1) * 4
    x = 2 * C * F * 4
    state = 6 * T * C * 4
    if emit_scores:
        out = K * C * 4 + T * max(int(leaves_dim), 1) * 4
    else:
        out = T * C * 4
    return planes + x + state + out


def plan_predict_fused_tile(num_trees, nodes_dim, features, cat_words=0,
                            leaves_dim=0, num_class=1, emit_scores=False,
                            vmem_bytes=None):
    """Largest fused row tile whose VMEM prediction fits, or None when
    no ladder rung does (the election then stays on ``fori``)."""
    limit = int(vmem_bytes if vmem_bytes is not None else vmem_limit_bytes())
    budget = int(limit * VMEM_HEADROOM)
    for c in FUSED_PREDICT_TILES:
        need = predict_fused_vmem_bytes(num_trees, nodes_dim, features, c,
                                        cat_words, leaves_dim, num_class,
                                        emit_scores)
        if need <= budget:
            return {"tile_rows": c, "vmem_bytes": need,
                    "vmem_limit_bytes": limit}
    return None


def elect_predict_chunk(num_trees, nodes_dim, leaves_dim, features,
                        precision="f32", cat_words=0, routing_only=False,
                        accel=None, budget=None) -> int:
    """Largest ladder rung whose forest + per-chunk activation bytes fit
    the HBM budget, replacing ``DeviceForest``'s historical hard-coded
    ``1 << 16``.  ``LGBM_TPU_PREDICT_CHUNK`` pins it outright."""
    o = _predict_chunk_override()
    if o:
        return o
    if budget is None:
        limit, _ = hbm_limit_bytes()
        budget = int(limit * HEADROOM)
    fb = predict_forest_bytes(num_trees, nodes_dim, leaves_dim, precision,
                              cat_words, accel, routing_only)
    best = MIN_BUCKET_ROWS
    c = MIN_BUCKET_ROWS
    while c <= MAX_PREDICT_CHUNK_ROWS:
        if fb + predict_program_bytes(num_trees, c, features,
                                      accel) > budget:
            break
        best = c
        c = bucket_rows(c + 1)
    return int(best)


def elect_csr_chunk(features: int) -> int:
    """Host-memory-aware CSR densification chunk for
    ``predict.predict_csr_chunked``: the dense f64 chunk (plus its
    densify + result transients, ~3x) may claim a quarter of the host
    budget.  ``LGBM_TPU_PREDICT_CHUNK`` pins it outright."""
    o = _predict_chunk_override()
    if o:
        return o
    limit, _ = host_limit_bytes()
    budget = int(limit * HOST_HEADROOM) // 4
    per_row = max(int(features), 1) * 8 * 3
    return int(min(max(budget // per_row, 1 << 12), 1 << 20))


class PredictPlan(NamedTuple):
    """plan_predict's verdict: traversal variant, fused row tile, device
    chunk, and the byte story the election ran under."""

    variant: str                # "while" | "fori" | "fused"
    tile_rows: int              # fused VMEM row tile (0 = not fused)
    chunk_rows: int             # elected device chunk (a ladder rung)
    forest_bytes: int
    program_bytes: int          # activations at chunk_rows
    predicted_peak_bytes: int
    budget_bytes: int
    limit_bytes: int
    limit_source: str
    feasible: bool
    elected_by: str             # "env" | "measured" | "analytic"
    measured_variant: str = ""  # store's best for this bucket ("" = cold)
    autotune_key: str = ""      # predict-bucket key the election ran under

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / telemetry."""
        return {
            "variant": self.variant,
            "tile_rows": self.tile_rows,
            "chunk_rows": self.chunk_rows,
            "forest_bytes": self.forest_bytes,
            "program_bytes": self.program_bytes,
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "budget_bytes": self.budget_bytes,
            "hbm_limit_bytes": self.limit_bytes,
            "limit_source": self.limit_source,
            "feasible": self.feasible,
            "elected_by": self.elected_by,
            "measured_variant": self.measured_variant,
            "autotune_key": self.autotune_key,
        }


def plan_predict(num_trees: int, nodes_dim: int, leaves_dim: int,
                 features: int, rows: int = 0, num_class: int = 1,
                 precision: str = "f32", cat_words: int = 0,
                 routing_only: bool = False, ledger=None,
                 accel: Optional[bool] = None,
                 vmem_bytes: Optional[int] = None) -> PredictPlan:
    """Elect {variant, tile_rows, chunk_rows} for one model's predict
    path.

    Budget: the ledger's remaining bytes when one is leased against
    (serving co-residency, PR 17), else HEADROOM x the device limit.
    Variant: ``LGBM_TPU_PREDICT_KERNEL`` > the measured predict family
    > analytic (fused on accelerators when its VMEM tile fits, fori
    everywhere else — the while arm is never elected, only pinned).
    """
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    limit, source = hbm_limit_bytes()
    if ledger is not None:
        # ledger budgets are already post-HEADROOM (applied once at the
        # ledger's limit — see plan_histograms' co-resident arm)
        limit, source = int(ledger.limit_bytes), "ledger"
        budget = int(ledger.available_bytes())
    else:
        budget = int(limit * HEADROOM)
    chunk = elect_predict_chunk(num_trees, nodes_dim, leaves_dim, features,
                                precision, cat_words, routing_only,
                                accel=accel, budget=budget)
    if rows:
        chunk = min(chunk, bucket_rows(rows))
    fb = predict_forest_bytes(num_trees, nodes_dim, leaves_dim, precision,
                              cat_words, accel, routing_only)
    pb = predict_program_bytes(num_trees, chunk, features, accel)
    ft = plan_predict_fused_tile(num_trees, nodes_dim, features, cat_words,
                                 leaves_dim, num_class,
                                 emit_scores=not routing_only,
                                 vmem_bytes=vmem_bytes)
    analytic = "fused" if (accel and ft is not None) else "fori"
    variant, elected_by = analytic, "analytic"
    measured_variant, autotune_key = "", ""
    if autotune_enabled():
        autotune_key = predict_bucket_key(rows or chunk, features,
                                          num_trees, num_class, precision)
        m = measured_predict_election(rows or chunk, features, num_trees,
                                      num_class, precision)
        with _AUTOTUNE_LOCK:
            if m is not None:
                measured_variant = m["variant"]
                variant, elected_by = measured_variant, "measured"
                _AUTOTUNE_STATS["hits"] += 1
                if variant != analytic:
                    _AUTOTUNE_STATS["flips"] += 1
            else:
                _AUTOTUNE_STATS["misses"] += 1
    o = _predict_kernel_override()
    if o is not None:
        variant, elected_by = o, "env"
    if variant == "fused" and ft is None and elected_by != "env":
        # a measured "fused" from a bigger core must not OOM this one
        variant = "fori"
    tile = (ft["tile_rows"] if ft is not None else FUSED_PREDICT_TILES[-1]) \
        if variant == "fused" else 0
    peak = fb + pb
    return PredictPlan(
        variant=variant, tile_rows=tile, chunk_rows=chunk,
        forest_bytes=fb, program_bytes=pb, predicted_peak_bytes=peak,
        budget_bytes=budget, limit_bytes=limit, limit_source=source,
        feasible=peak <= budget, elected_by=elected_by,
        measured_variant=measured_variant, autotune_key=autotune_key)


# ======================================================================
# Ingest kernel + chunk election: plan_ingest.  The binning pass's
# analogue of plan_predict — byte models answer "what chunk fits the
# ledger remainder", the measured-timings store (an "i-..." key
# namespace in the SAME hist_timings.json) answers "kernel or host",
# and LGBM_TPU_INGEST_KERNEL is the bisect gate over the election.
# ======================================================================

INGEST_VARIANTS = ("kernel", "host")
# largest device ingest chunk the election reaches for (a ladder rung)
MAX_INGEST_CHUNK_ROWS = 1 << 21
# bucketize+pack row-tile ladder (widest VMEM-resident tile first)
INGEST_TILES = (2048, 1024, 512, 256)
# past this width the unrolled per-feature kernel stops being the
# analytic default (compile time grows with the feature loop); the env
# pin and the measured store can still elect it
MAX_INGEST_KERNEL_FEATURES = 1024


def _ingest_kernel_override():
    """LGBM_TPU_INGEST_KERNEL: pin the binning arm ("kernel" | "host"),
    bypassing measured and analytic election (the bisect gate)."""
    v = os.environ.get("LGBM_TPU_INGEST_KERNEL", "").strip().lower()
    return v if v in INGEST_VARIANTS else None


def _ingest_chunk_override():
    """LGBM_TPU_INGEST_CHUNK: pin the device ingest chunk size."""
    v = os.environ.get("LGBM_TPU_INGEST_CHUNK", "").strip()
    if not v:
        return None
    try:
        n = int(float(v))
    except ValueError:
        return None
    return max(n, 8) if n > 0 else None


def ingest_bucket_key(rows: int, features: int, num_groups: int,
                      item_bytes: int) -> str:
    """Store key of the ingest autotune family — prefixed "i-" so it
    can never collide with the histogram or predict namespaces."""
    return (f"i-r{bucket_rows(max(int(rows), 1))}-f{int(features)}"
            f"-g{int(num_groups)}-u{max(int(item_bytes), 1)}")


def record_ingest_timing(rows, features, num_groups, item_bytes,
                         variant, seconds, params=None, path=None):
    """Bank one measured (ingest shape-bucket, variant) timing in the
    shared store; returns the store path or None (no store dir)."""
    p = _autotune_path(path)
    if not p:
        return None
    from ..utils.file_io import write_atomic
    key = ingest_bucket_key(rows, features, num_groups, item_bytes)
    with _AUTOTUNE_LOCK:
        entries = _load_autotune_store(path)
        slot = dict(entries.get(key) or {})
        slot[str(variant)] = {"seconds": float(seconds),
                              "params": dict(params or {})}
        entries[key] = slot
        write_atomic(p, json.dumps(
            {"version": AUTOTUNE_STORE_VERSION, "entries": entries},
            indent=1, sort_keys=True))
    return p


def measured_ingest_election(rows, features, num_groups, item_bytes,
                             path=None):
    """Fastest measured ingest arm for this shape bucket, or None
    (cold).  Unknown variant names are skipped, not adopted."""
    key = ingest_bucket_key(rows, features, num_groups, item_bytes)
    slot = _load_autotune_store(path).get(key)
    if not isinstance(slot, dict):
        return None
    best_v, best = None, None
    for v, rec in slot.items():
        if str(v) not in INGEST_VARIANTS:
            continue
        try:
            s = float(rec["seconds"])
        except Exception:
            continue
        if s > 0 and (best is None or s < best["seconds"]):
            params = rec.get("params")
            best_v = str(v)
            best = {"seconds": s,
                    "params": params if isinstance(params, dict) else {}}
    if best_v is None:
        return None
    return {"key": key, "variant": best_v, **best}


def ingest_vmem_bytes(features: int, tile_rows: int, bounds_width: int,
                      cats_width: int, num_groups: int) -> int:
    """Predicted VMEM bytes of one bucketize+pack grid step
    (ops/ingest.py): the double-buffered [tile, F] f32 input window,
    the resident boundary + category tables, the [tile, G] i32 output
    block, and the broadcast compare plane (two transient copies).
    Deliberately simple — the right ORDER for fits/doesn't."""
    F = max(int(features), 1)
    C = max(int(tile_rows), 8)
    G = max(int(num_groups), 1)
    W = max(int(bounds_width), int(cats_width), 1)
    x = 2 * C * F * 4
    tables = F * (max(int(bounds_width), 1) + max(int(cats_width), 1)) * 4
    out = C * G * 4
    transients = 2 * C * W * 4
    return x + tables + out + transients


def plan_ingest_tile(features, bounds_width, cats_width, num_groups,
                     vmem_bytes=None):
    """Largest ingest row tile whose VMEM prediction fits, or None when
    no ladder rung does (the election then stays on host)."""
    limit = int(vmem_bytes if vmem_bytes is not None else vmem_limit_bytes())
    budget = int(limit * VMEM_HEADROOM)
    for c in INGEST_TILES:
        need = ingest_vmem_bytes(features, c, bounds_width, cats_width,
                                 num_groups)
        if need <= budget:
            return {"tile_rows": c, "vmem_bytes": need,
                    "vmem_limit_bytes": limit}
    return None


def ingest_chunk_bytes(chunk_rows: int, features: int, num_groups: int,
                       item_bytes: int) -> int:
    """Device bytes of one in-flight ingest chunk: the double-buffered
    raw f32 block (the pump keeps chunk t+1 in flight while t bins),
    the i32 kernel output, and its cast to the group dtype."""
    c = max(int(chunk_rows), 1)
    return c * (2 * max(int(features), 1) * 4
                + max(int(num_groups), 1) * (4 + max(int(item_bytes), 1)))


def elect_ingest_chunk(features: int, num_groups: int, item_bytes: int,
                       budget: Optional[int] = None) -> int:
    """Largest ladder rung whose in-flight chunk bytes fit the budget —
    how 11M rows bin without a single 157 GB device_put.
    ``LGBM_TPU_INGEST_CHUNK`` pins it outright."""
    o = _ingest_chunk_override()
    if o:
        return o
    if budget is None:
        limit, _ = hbm_limit_bytes()
        budget = int(limit * HEADROOM)
    best = MIN_BUCKET_ROWS
    c = MIN_BUCKET_ROWS
    while c <= MAX_INGEST_CHUNK_ROWS:
        if ingest_chunk_bytes(c, features, num_groups, item_bytes) > budget:
            break
        best = c
        c = bucket_rows(c + 1)
    return int(best)


class IngestPlan(NamedTuple):
    """plan_ingest's verdict: binning arm, VMEM row tile, device chunk,
    and the byte story the election ran under."""

    variant: str                # "kernel" | "host"
    tile_rows: int              # kernel VMEM row tile (0 = host)
    chunk_rows: int             # elected device chunk (a ladder rung)
    chunk_bytes: int            # in-flight bytes at chunk_rows
    budget_bytes: int
    limit_bytes: int
    limit_source: str
    feasible: bool
    elected_by: str             # "env" | "measured" | "analytic"
    measured_variant: str = ""  # store's best for this bucket ("" = cold)
    autotune_key: str = ""      # ingest-bucket key the election ran under

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / telemetry."""
        return {
            "variant": self.variant,
            "tile_rows": self.tile_rows,
            "chunk_rows": self.chunk_rows,
            "chunk_bytes": self.chunk_bytes,
            "budget_bytes": self.budget_bytes,
            "hbm_limit_bytes": self.limit_bytes,
            "limit_source": self.limit_source,
            "feasible": self.feasible,
            "elected_by": self.elected_by,
            "measured_variant": self.measured_variant,
            "autotune_key": self.autotune_key,
        }


def plan_ingest(rows: int, features: int, num_groups: int,
                item_bytes: int = 1, bounds_width: int = 1,
                cats_width: int = 1, ledger=None,
                accel: Optional[bool] = None,
                vmem_bytes: Optional[int] = None) -> IngestPlan:
    """Elect {variant, tile_rows, chunk_rows} for one dataset's binning
    pass.

    Budget: the ledger's remaining bytes when one is leased against
    (co-residency, PR 17), else HEADROOM x the device limit.  Variant:
    ``LGBM_TPU_INGEST_KERNEL`` > the measured "i-..." family > analytic
    (kernel on accelerators when its VMEM tile fits and the feature
    width is kernel-sized, host everywhere else).
    """
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    limit, source = hbm_limit_bytes()
    if ledger is not None:
        # ledger budgets are already post-HEADROOM (plan_predict's rule)
        limit, source = int(ledger.limit_bytes), "ledger"
        budget = int(ledger.available_bytes())
    else:
        budget = int(limit * HEADROOM)
    chunk = elect_ingest_chunk(features, num_groups, item_bytes,
                               budget=budget)
    if rows:
        chunk = min(chunk, bucket_rows(rows))
    tile = plan_ingest_tile(features, bounds_width, cats_width, num_groups,
                            vmem_bytes=vmem_bytes)
    analytic = "kernel" if (accel and tile is not None
                            and features <= MAX_INGEST_KERNEL_FEATURES) \
        else "host"
    variant, elected_by = analytic, "analytic"
    measured_variant, autotune_key = "", ""
    if autotune_enabled():
        autotune_key = ingest_bucket_key(rows or chunk, features,
                                         num_groups, item_bytes)
        m = measured_ingest_election(rows or chunk, features, num_groups,
                                     item_bytes)
        with _AUTOTUNE_LOCK:
            if m is not None:
                measured_variant = m["variant"]
                variant, elected_by = measured_variant, "measured"
                _AUTOTUNE_STATS["hits"] += 1
                if variant != analytic:
                    _AUTOTUNE_STATS["flips"] += 1
            else:
                _AUTOTUNE_STATS["misses"] += 1
    o = _ingest_kernel_override()
    if o is not None:
        variant, elected_by = o, "env"
    if variant == "kernel" and tile is None and elected_by != "env":
        # a measured "kernel" from a bigger core must not OOM this one
        variant = "host"
    cb = ingest_chunk_bytes(chunk, features, num_groups, item_bytes)
    return IngestPlan(
        variant=variant,
        tile_rows=(tile["tile_rows"] if tile is not None
                   else INGEST_TILES[-1]) if variant == "kernel" else 0,
        chunk_rows=chunk, chunk_bytes=cb,
        budget_bytes=budget, limit_bytes=limit, limit_source=source,
        feasible=cb <= budget, elected_by=elected_by,
        measured_variant=measured_variant, autotune_key=autotune_key)
