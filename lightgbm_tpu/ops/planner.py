"""HBM budget planner: pick histogram execution parameters at trace time.

The r5 bench died in compile with an HBM OOM — a lane-padded
``f32[308000000, 3]`` whole-dataset record arena (157.7 GB requested vs
17.2 GB HBM) — because every kernel materialized O(n*F) intermediates
and nothing MODELED whether they fit.  This module is the model: it
predicts per-variant peak HBM bytes for the histogram pipeline
(device binned matrix, carried scores/gradients, per-tree hist cache
including TPU lane padding, per-pass transients, pack/sort arenas,
cross-device psum payloads) against the device's reported HBM limit and
picks, at trace time:

- ``tile_rows`` — the row-tile size every kernel in ops/histogram.py
  streams through (power of two; 0 = untiled).  Peak transient HBM
  becomes O(tile), not O(n*F);
- whether the whole-dataset ``pack_cols_u32`` record arena may be
  hoisted (``use_pack``) or records must be assembled per tile inside
  the kernel loops;
- the psum payload width for quantized histograms (``narrow_int16`` —
  the record of ``ops.histogram.quant_psum_narrow``'s static bound).

The same plan governs serial and sharded training: the GBDT layer plans
with PER-SHARD rows and threads the result through ``GrowerConfig``
(tile_rows / hist_pack), so the serial grower, the batched-frontier
grower, the fused macro-chunk program and the data-/voting-parallel
learners all execute under one verdict.  bench.py gates its >=10M-row
stage on ``feasible`` and journals the chosen tile instead of crashing.

Env overrides:
- ``LGBM_TPU_TILE_ROWS``: force a tile size (``0``/``off`` forces
  untiled; a positive integer forces that many rows per tile).
- ``LGBM_TPU_HBM_BYTES``: override the device HBM limit (useful off-TPU
  and in tests, which plan against a fake memory model).

Related work: bounding device memory by streaming row chunks through a
fixed-footprint histogram kernel is the GPU GBDT move (Wen et al.,
arXiv:1706.08359; Ou, arXiv:1806.11248 — gradient-based sketching to
bound device memory); here the bound is a *planner verdict* instead of
an operator-tuned chunk count.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

# default assumed HBM when the backend reports nothing (one v5e-class
# chip; r5 measured 17.2 GB reported — stay conservative)
DEFAULT_HBM_BYTES = 16 * (1 << 30)
# fraction of the limit a plan may claim: XLA needs slack for fusion
# temps, the program image, and collectives' staging buffers
HEADROOM = 0.85
# smallest tile the planner will degrade to (a histogram pass over fewer
# rows is dominated by fixed per-pass overhead)
MIN_TILE_ROWS = 1 << 16
_DEFAULT_BLOCK_ROWS = 4096


def _pad(x: int, m: int) -> int:
    return -(-int(x) // m) * m


def _arr(minor: int, second: int, itemsize: int, accel: bool,
         leading: int = 1) -> int:
    """Bytes of an array whose two minor dims are (second, minor).

    On accelerators the two minor-most dims tile to (sublanes, 128) with
    sublanes scaling inversely with itemsize — (8, 128) for 4-byte,
    (16, 128) for 2-byte, (32, 128) for 1-byte (ops/histogram.py LAYOUT
    DOCTRINE).  Off-accelerator: dense.
    """
    if not accel:
        return leading * second * minor * itemsize
    sub = {4: 8, 2: 16, 1: 32}.get(itemsize, 8)
    return leading * _pad(second, sub) * _pad(minor, 128) * itemsize


class HistPlan(NamedTuple):
    """Trace-time histogram execution plan (see module docstring)."""

    tile_rows: int              # 0 = untiled
    use_pack: bool              # whole-dataset u32 record arena allowed
    variant: str                # resolved histogram kernel family
    quant: bool
    narrow_int16: bool          # quantized psum payload narrowed
    predicted_peak_bytes: int   # at the chosen tile
    untiled_peak_bytes: int     # what the unplanned pipeline would take
    budget_bytes: int           # limit * HEADROOM
    limit_bytes: int
    limit_source: str           # "memory_stats" | "env" | "default"
    feasible: bool              # predicted peak fits the budget
    degraded: bool              # tiling was forced by the budget

    def summary(self) -> dict:
        """JSON-friendly form for bench journals / telemetry."""
        return {
            "tile_rows": self.tile_rows,
            "use_pack": self.use_pack,
            "variant": self.variant,
            "quant": self.quant,
            "narrow_int16": self.narrow_int16,
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "untiled_peak_bytes": self.untiled_peak_bytes,
            "budget_bytes": self.budget_bytes,
            "hbm_limit_bytes": self.limit_bytes,
            "limit_source": self.limit_source,
            "feasible": self.feasible,
            "degraded": self.degraded,
        }


def hbm_limit_bytes() -> tuple:
    """(limit_bytes, source) for the active device.

    Priority: ``LGBM_TPU_HBM_BYTES`` env (tests / fake memory models) >
    the device allocator's reported ``bytes_limit`` > the conservative
    default.  Never raises — planning must work before/without a
    backend.
    """
    env = os.environ.get("LGBM_TPU_HBM_BYTES", "").strip()
    if env:
        try:
            return max(int(float(env)), 1), "env"
        except ValueError:
            pass
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit, "memory_stats"
    except Exception:
        pass
    return DEFAULT_HBM_BYTES, "default"


def predict_peak_bytes(
    rows: int,                  # per-shard row count the kernels see
    features: int,              # device column count (groups under EFB)
    num_bins: int,              # padded bin axis B
    num_leaves: int = 31,
    num_class: int = 1,
    quant: bool = False,
    variant: str = "scatter",   # resolved kernel family name
    tile_rows: int = 0,         # 0 = untiled
    use_pack: bool = True,
    round_width: int = 128,
    machines: int = 1,
    accel: Optional[bool] = None,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> tuple:
    """(peak_bytes, breakdown dict) for one training step's histogram
    pipeline on one device.

    A deliberately simple sum of the dominant allocations — resident
    state plus the largest per-pass transient — NOT an XLA simulator.
    Accuracy target: the right ORDER for the feasibility verdict (the
    r5 failure was off by 9x, not 10%).
    """
    if accel is None:
        from .histogram import on_accelerator
        accel = on_accelerator()
    n = max(int(rows), 1)
    F = max(int(features), 1)
    B = max(int(num_bins), 2)
    L = max(int(num_leaves), 2)
    K = max(int(num_class), 1)
    S = max(int(round_width), 1)
    T = n if tile_rows <= 0 else min(int(tile_rows), n)
    C = min(block_rows, _pad(T, 128))
    ch = 2 if quant else 3          # histogram channels
    hitem = 4                       # i32 / f32 cells

    b = {}
    bin_item = 1 if B <= 256 else 2
    # resident: the device binned matrix (feature-major [F, n]) and one
    # transformation copy (pad / compaction gather of the same shape)
    b["binned"] = _arr(n, F, bin_item, accel) * 2
    # carried scores (donated in+out) + per-class grad/hess f32 rows
    b["scores"] = 2 * K * _arr(n, 1, 4, accel)
    b["grads"] = 2 * K * _arr(n, 1, 4, accel)
    if quant:
        b["grads"] += 2 * K * _arr(n, 1, 1, accel)      # int8 gq/hq
    # per-tree histogram cache [L, ch, F, B] + the round's segment
    # output [S, ch, F, B]
    b["hist_cache"] = L * ch * _arr(B, F, hitem, accel)
    b["seg_hist"] = (S + 1) * ch * _arr(B, F, hitem, accel)
    # sorted-arena fixed state: u32 sort keys (key + sorted + order)
    if variant in ("sorted", "matmul", "matmul_int8"):
        b["sort_keys"] = 3 * _arr(n, 1, 4, accel)
    # whole-dataset fused record arena (pack_cols_u32): Wb+3 u32 words
    # per row (Wb+1 quantized)
    if use_pack:
        wb = (F + 3) // 4
        b["pack_arena"] = _arr(n, wb + (1 if quant else 3), 4, accel)

    # dominant per-pass transient, by kernel family
    if variant.startswith("scatter"):
        # the r5 OOM shape: [T*F, ch] update buffer (lane-padded on
        # accel) + [T, F] i32 flat indices
        b["scatter_updates"] = _arr(ch, T * F, hitem, accel)
        b["scatter_index"] = _arr(F, T, 4, accel)
    elif variant.startswith("matmul"):
        onehot_item = 1 if (quant or variant == "matmul") else 4
        if variant == "matmul" and not quant:
            onehot_item = 2                      # bf16 one-hot
        b["onehot"] = _arr(B * F, C, onehot_item, accel)
        b["vals_pad"] = _arr(n, ch, 4, accel)    # padded vals copy
    else:                                        # sorted / expanded
        b["onehot"] = _arr(B * F, C, 1 if quant else 2, accel)
        if tile_rows <= 0:
            # hoisted whole-arena record gather
            wb = (F + 3) // 4
            width = (wb + (1 if quant else 3)) if use_pack else (F + 3)
            b["arena_gather"] = _arr(n, width, 4, accel)
        else:
            wb = (F + 3) // 4
            width = (wb + (1 if quant else 3)) if use_pack else (F + 3)
            b["arena_gather"] = _arr(C, width, 4, accel)
    # cross-device histogram reduction staging
    if machines > 1:
        from .histogram import hist_payload_bytes
        b["psum"] = 2 * hist_payload_bytes(
            F, B, rows_global=n * machines,
            quant_bins=None if not quant else 64) * S

    return sum(b.values()), b


def _resolved_variant(method: str, quant: bool) -> str:
    from .histogram import resolve_hist_method, use_sorted_seghist
    m = resolve_hist_method(method, quantized=quant)
    # the segment passes dominate peak; their dispatch follows
    # use_sorted_seghist, not the point-histogram method
    if use_sorted_seghist():
        return "sorted"
    return m


def _tile_override():
    """LGBM_TPU_TILE_ROWS: None = unset, 0 = force untiled, >0 = force."""
    v = os.environ.get("LGBM_TPU_TILE_ROWS", "").strip().lower()
    if not v:
        return None
    if v in ("0", "off", "none", "false"):
        return 0
    try:
        return max(int(v), 1)
    except ValueError:
        return None


def plan_histograms(
    rows: int,
    features: int,
    num_bins: int,
    num_leaves: int = 31,
    num_class: int = 1,
    quant: bool = False,
    quant_bins: int = 4,
    method: str = "auto",
    round_width: int = 128,
    machines: int = 1,
    budget_bytes: Optional[int] = None,   # tests: fake memory model
    accel: Optional[bool] = None,
) -> HistPlan:
    """Choose {tile_rows, use_pack, psum narrowing} for a training shape.

    Search: untiled first (fastest dispatch); if its predicted peak
    exceeds the budget, walk tile_rows down through powers of two until
    the prediction fits (records un-hoisted — ``use_pack=False`` — the
    moment tiling engages, so no whole-dataset record arena is ever
    materialized in tiled mode).  ``feasible=False`` means even
    MIN_TILE_ROWS does not fit: the caller should refuse to launch the
    shape rather than hand XLA a guaranteed OOM.
    """
    from .histogram import quant_psum_narrow

    if budget_bytes is not None:
        limit, source = int(budget_bytes), "caller"
    else:
        limit, source = hbm_limit_bytes()
    # HEADROOM applies to EVERY limit source (caller-supplied fake
    # memory models included) so tests exercise the shipped decision rule
    budget = int(limit * HEADROOM)
    variant = _resolved_variant(method, quant)
    narrow = bool(quant and quant_psum_narrow(rows * machines, quant_bins))

    def peak(tile, pack):
        return predict_peak_bytes(
            rows, features, num_bins, num_leaves, num_class, quant,
            variant, tile, pack, round_width, machines, accel)[0]

    untiled_peak = peak(0, True)
    forced = _tile_override()

    def mk(tile, pack, degraded):
        p = peak(tile, pack)
        return HistPlan(
            tile_rows=tile, use_pack=pack, variant=variant, quant=quant,
            narrow_int16=narrow, predicted_peak_bytes=p,
            untiled_peak_bytes=untiled_peak, budget_bytes=budget,
            limit_bytes=limit, limit_source=source,
            feasible=p <= budget, degraded=degraded)

    if forced is not None:
        if forced == 0 or forced >= rows:
            return mk(0, True, False)
        return mk(int(forced), False, False)

    if untiled_peak <= budget:
        return mk(0, True, False)

    # degrade: largest power-of-two tile whose prediction fits
    tile = 1 << max(int(rows - 1).bit_length() - 1, 0)
    tile = max(tile, MIN_TILE_ROWS)
    while tile > MIN_TILE_ROWS and peak(tile, False) > budget:
        tile //= 2
    return mk(tile, False, True)


def apply_plan(cfg, rows: int, features: int, accel: Optional[bool] = None):
    """Thread a plan into a ``GrowerConfig``; returns (cfg, plan).

    Shared by the GBDT layer (per-shard rows) and the standalone
    parallel learners so every path trains under the same verdict.
    """
    plan = plan_histograms(
        rows=rows, features=features, num_bins=cfg.num_bins,
        num_leaves=cfg.num_leaves, quant=cfg.quant,
        quant_bins=cfg.quant_bins, method=cfg.hist_method,
        round_width=cfg.round_width, machines=max(cfg.num_machines, 1),
        accel=accel)
    # first-class predicted-peak event (docs/OBSERVABILITY.md): the bench
    # logs the allocator's MEASURED peak next to it, so memory-model
    # drift is visible per run on the same timeline
    from ..obs.trace import instant
    instant("planner.plan", rows=rows, features=features, **plan.summary())
    cfg = cfg._replace(tile_rows=plan.tile_rows,
                       hist_pack=cfg.hist_pack and plan.use_pack)
    return cfg, plan
