"""Per-leaf weighted residual percentiles, on device.

reference: SerialTreeLearner::RenewTreeOutput (serial_tree_learner.cpp:628)
+ RegressionL1loss::RenewTreeOutput (regression_objective.hpp:250) — for
L1-family objectives, leaf outputs are re-fit to the (weighted) alpha-
percentile of the residuals in each leaf rather than the Newton step.

TPU design: one global sort of (leaf_id, residual) pairs (lax.sort, runs on
device), then per-row segment-local cumulative weights; the percentile
crossing row of each segment is detected branch-free and scattered out.
O(n log n) on device, no host round-trip, fixed shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def leaf_percentile(
    leaf_id: jax.Array,    # [n] i32
    residual: jax.Array,   # [n] f32
    weight: jax.Array,     # [n] f32 (bagging mask times row weight; 0 = excluded)
    num_leaves: int,
    alpha: float,
) -> jax.Array:
    """Weighted alpha-percentile of residual per leaf. Returns [L] f32.

    Weighted definition matches reference Common::WeightedPercentile
    (utils/common.h): positions p_i = (cumsum(w)_i - w_i/2) / W; linear
    interpolation between the rows bracketing alpha.  Rows with zero weight
    are pushed out of their segment (leaf key = L) so they never contribute.
    """
    n = leaf_id.shape[0]
    L = num_leaves
    # exclude zero-weight rows from segments
    seg = jnp.where(weight > 0, leaf_id, L).astype(jnp.int32)
    seg_sorted, res_sorted, w_sorted = lax.sort(
        (seg, residual, weight), dimension=0, num_keys=2)

    # segment-local cumulative weight: global cumsum minus segment offset
    cw = jnp.cumsum(w_sorted)
    seg_total = jax.ops.segment_sum(w_sorted, seg_sorted, num_segments=L + 1)
    seg_start_w = jnp.concatenate([jnp.zeros(1), jnp.cumsum(seg_total)[:-1]])
    local_cw = cw - seg_start_w[seg_sorted]
    tot = seg_total[seg_sorted]
    p = jnp.where(tot > 0, (local_cw - w_sorted / 2.0) / tot, 0.0)

    # previous row's p within the same segment (else -inf)
    prev_same = jnp.concatenate([jnp.array([False]), seg_sorted[1:] == seg_sorted[:-1]])
    p_prev = jnp.concatenate([jnp.zeros(1), p[:-1]])
    p_prev = jnp.where(prev_same, p_prev, -jnp.inf)
    r_prev = jnp.concatenate([jnp.zeros(1), res_sorted[:-1]])

    # crossing row: first row in segment with p >= alpha
    crossing = (p >= alpha) & (p_prev < alpha)
    frac = jnp.where(p > p_prev, (alpha - p_prev) / jnp.maximum(p - p_prev, 1e-30), 0.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    interp = jnp.where(jnp.isfinite(p_prev), r_prev * (1 - frac) + res_sorted * frac,
                       res_sorted)

    out = jnp.zeros(L + 1, jnp.float32)
    out = out.at[jnp.where(crossing, seg_sorted, L)].set(interp.astype(jnp.float32))
    # segments where alpha beyond last row (p_n < alpha): use last row's residual
    is_last = jnp.concatenate([seg_sorted[1:] != seg_sorted[:-1], jnp.array([True])])
    need_last = is_last & (p < alpha)
    out = out.at[jnp.where(need_last, seg_sorted, L)].set(
        jnp.where(need_last, res_sorted, 0.0).astype(jnp.float32), mode="drop")
    return out[:L]


def quant_train_renew_leaf(
    leaf_id: jax.Array,    # [n] i32 final leaf assignment
    grad: jax.Array,       # [n] f32 TRUE (un-quantized) gradients
    hess: jax.Array,       # [n] f32 TRUE hessians
    weight: jax.Array,     # [n] f32 bagging/GOSS weights (0 = excluded)
    num_leaves: int,
):
    """True-f32 per-leaf gradient/hessian sums for quantized training's
    leaf renewal (config ``quant_train_renew_leaf``).

    reference: CUDASingleGPUTreeLearner::RenewDiscretizedTreeLeaves /
    GradientDiscretizer::RenewIntGradTreeOutput — with
    ``use_quantized_grad`` the tree STRUCTURE comes from the integer
    histograms, but the committed leaf outputs are re-fit from the true
    float gradient sums, removing the discretization bias from the
    scores the next round boosts against.  Returns ``(sg [L], sh [L])``
    f32; the grower turns them into outputs via ``ops.split.leaf_output``
    (and psums them under data sharding).
    """
    w = weight
    sg = jax.ops.segment_sum(grad * w, leaf_id, num_segments=num_leaves)
    sh = jax.ops.segment_sum(hess * w, leaf_id, num_segments=num_leaves)
    return sg.astype(jnp.float32), sh.astype(jnp.float32)
