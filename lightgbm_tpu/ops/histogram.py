"""On-device gradient/hessian histogram construction.

TPU-native replacement for LightGBM's histogram kernels
(reference: src/io/dense_bin.hpp:97 ConstructHistogramInner — CPU scatter-add;
src/treelearner/ocl/histogram256.cl:317 — GPU atomic scatter).

Design inversion for the MXU: instead of scatter-add (random-access, serializes
on TPU), the histogram is a **one-hot matmul**: for a block of rows build the
0/1 matrix ``onehot[C, F*B]`` (row r has a 1 at column f*B + bin(r, f)) in
bfloat16 (exact for 0/1) and compute ``vals @ onehot`` with
``vals = mask * [grad, hess, 1]`` — a [3, C] x [C, F*B] matmul accumulated in
float32 over row blocks.  This keeps the hot loop on the systolic array at
~100% HBM streaming rate instead of scalar scatter.  Leaf membership is folded
into ``mask``, which replaces the reference's ordered-gradient gather
(src/io/dataset.cpp:1318-1333) with a branch-free masked pass.

LAYOUT DOCTRINE (round 5, measured): TPU tiles the two minor-most dims to
(8, 128) — f32 [n, 3] pads 42x, u8 [n, 28] pads 4.6x, u32 [n, 13] pads 10x
(the OOM at 11M rows was exactly a lane-padded [n*F, 3]).  Therefore:

- the binned matrix lives on device FEATURE-MAJOR: ``binned_t`` [F, n]
  (minor dim n — unpadded), and every kernel here consumes that layout;
- histograms are ``[3, F, B]`` / ``[S, 3, F, B]`` with the tiny component
  axis LEADING (minor dims (F, B) pad ~2x instead of 128/3 = 42x);
- per-row values ride as separate [n] vectors or [3, n] / [W, n] blocks,
  never as [n, small] matrices.

A scatter-based variant is kept for CPU testing / tiny inputs; `auto` probes
are selected at trace time by platform.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# rows per block of the one-hot matmul; 8 sublanes * 128 lanes friendly
_DEFAULT_BLOCK_ROWS = 4096

# backends where the MXU/one-hot formulations win; everywhere this set is
# consulted it must stay in sync with the bf16/f32 precision pairing
ACCEL_BACKENDS = ("tpu", "axon")


def on_accelerator() -> bool:
    return jax.default_backend() in ACCEL_BACKENDS


def use_sorted_seghist() -> bool:
    """Whether the segment histogram takes the sorted-arena path (ONE
    shared predicate for the kernel dispatch and the grower's decision to
    pre-pack column records).  LGBM_TPU_SEGHIST=sorted|scatter overrides."""
    forced = os.environ.get("LGBM_TPU_SEGHIST")
    if forced in ("sorted", "scatter"):
        return forced == "sorted"
    return on_accelerator()


def resolve_hist_method(method: str, quantized: bool = False) -> str:
    """The concrete kernel ``method='auto'`` resolves to on this backend.

    Kept in ONE place so the grower's segment-histogram precision choice
    (bf16 one-hot vs f32-exact) can never disagree with the parent
    histogram kernel it subtracts from.

    ``quantized=True`` resolves within the INTEGER kernel family
    (use_quantized_grad): int8 one-hot matmul with int32 accumulation on
    accelerators, packed scatter on CPU.  A forced f32-family name maps
    to its integer analogue so ``tpu_hist_method`` keeps steering the
    matmul-vs-scatter axis in either mode.

    ``method="fused"`` (the Pallas histogram→split megakernel,
    ops/fused.py) resolves to itself in BOTH families — the growers gate
    where it actually applies and this module's plain-histogram entry
    points (``build_histogram*``) map it to the staged auto kernel,
    since a bare histogram has no split scan to fuse.  The growers'
    refusal set has shrunk: categorical features, monotone constraints
    and data-parallel sharding now run fused (the collective seam);
    only EFB bundles, per-node randomness and feature/voting sharding
    still force the staged family.
    """
    if method == "fused":
        return "fused"
    if quantized:
        if method in ("matmul_int8", "scatter_int"):
            return method
        if method == "auto":
            return "matmul_int8" if on_accelerator() else "scatter_int"
        if method in ("matmul", "matmul_f32", "pallas"):
            return "matmul_int8"
        if method == "scatter":
            return "scatter_int"
        raise ValueError(f"unknown histogram method {method!r}")
    if method == "auto":
        return "matmul" if on_accelerator() else "scatter"
    return method


def _pad_rows(n: int, block: int) -> int:
    return (n + block - 1) // block * block


def _vals_t(grad, hess, mask):
    """[3, n] f32 value block (g, h, 1) * mask — minor dim n, unpadded."""
    return jnp.stack([grad, hess, jnp.ones_like(grad)]) * mask[None, :]


def resolve_tile_rows(tile_rows, n: int):
    """Normalize a ``tile_rows`` request: None/0/>=n means untiled."""
    if tile_rows is None or tile_rows <= 0 or tile_rows >= n:
        return None
    return int(tile_rows)


def _tile_block(block_rows: int, tile_rows, lane: int = 128) -> int:
    """Streaming block size under a tile budget.

    The matmul-family kernels were ALWAYS streamed (a ``lax.scan`` over
    ``block_rows``-row blocks with an O(block) one-hot transient), so for
    them ``tile_rows`` simply CAPS the block: peak transient bytes track
    min(block, tile).  Rounded to the lane width so the one-hot stays
    tile-aligned.  TILE-MAJOR ORDER PIN: blocks accumulate into one shared
    f32 accumulator in ascending row order at every block size, so any
    ``tile_rows >= block_rows`` is bit-identical to untiled (the block
    partition is unchanged); a smaller tile refines the partition — still
    deterministic, exact for the int family (associative), and within
    f32 reassociation for the bf16/f32 matmuls."""
    if tile_rows is None:
        return block_rows
    return max(lane, min(block_rows, _pad_rows(tile_rows, lane)))


def histogram_matmul(
    binned_t: jax.Array,  # [F, n] uint8/uint16/int32 (feature-major)
    vals_t: jax.Array,    # [3, n] f32 rows already masked: (g, h, 1)*mask
    num_bins: int,        # padded bin axis B (static)
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    onehot_dtype=jnp.bfloat16,
    tile_rows: Optional[int] = None,
    init: Optional[jax.Array] = None,
) -> jax.Array:
    """Histogram via one-hot matmul over row blocks. Returns [3, F, B] f32.

    ``init`` is the carry-in accumulator for the out-of-core streaming
    fold (lightgbm_tpu/data/stream.py): a block pass that STARTS from the
    running histogram continues the same block-ascending accumulation
    sequence the one-shot kernel runs internally, so folding row blocks
    through carried calls is bit-identical to one resident call — the
    invariant behind streamed == resident f32 parity (the tile partition
    must align across the two runs for the matmul family; scatter is
    partition-free).
    """
    F, n = binned_t.shape
    B = num_bins
    block_rows = _tile_block(block_rows, resolve_tile_rows(tile_rows, n))
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        vals_t = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))
    iota = jnp.arange(B, dtype=binned_t.dtype)
    C = block_rows
    prec = (lax.Precision.HIGHEST if onehot_dtype == jnp.float32
            else lax.Precision.DEFAULT)

    def body(acc, i):
        b = lax.dynamic_slice(binned_t, (0, i * C), (F, C))   # [F, C]
        v = lax.dynamic_slice(vals_t, (0, i * C), (3, C))     # [3, C]
        onehot = (b.T[:, :, None] == iota).astype(onehot_dtype)
        onehot2d = onehot.reshape(C, F * B)
        part = lax.dot(v.astype(onehot_dtype), onehot2d, precision=prec,
                       preferred_element_type=jnp.float32)
        return acc + part, None

    acc0 = (jnp.zeros((3, F * B), dtype=jnp.float32) if init is None
            else init.reshape(3, F * B))
    acc, _ = lax.scan(body, acc0, jnp.arange(nb, dtype=jnp.int32))
    return acc.reshape(3, F, B)


def histogram_matmul_f32(
    binned_t: jax.Array, vals_t: jax.Array, num_bins: int,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    tile_rows: Optional[int] = None,
    init: Optional[jax.Array] = None,
) -> jax.Array:
    """Like histogram_matmul but f32 one-hot (exact grads; ~2x slower MXU)."""
    return histogram_matmul(binned_t, vals_t, num_bins, block_rows,
                            onehot_dtype=jnp.float32, tile_rows=tile_rows,
                            init=init)


def histogram_pallas(
    binned_t: jax.Array,  # [F, n] uint8/uint16 (feature-major)
    vals_t: jax.Array,    # [3, n] f32 rows already masked: (g, h, 1)*mask
    num_bins: int,
    block_rows: int = 512,
    feat_tile: int = 8,
    interpret: Optional[bool] = None,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Histogram via a Pallas VPU kernel accumulating in VMEM.

    Why not the MXU: the one-hot matmul formulation has M=3 output rows
    (grad/hess/count), so the 128x128 systolic array runs at <3% utilization
    AND materializes a [rows, F*B] one-hot intermediate in HBM.  This kernel
    instead streams `binned_t` once ([F, n] — its resident layout) and does
    the compare-select-accumulate on the VPU with the [3, F, B] accumulator
    resident in VMEM across row blocks — HBM traffic is exactly one read of
    the binned matrix + the vals block per pass, the memory-optimal floor.

    reference analogue: dense_bin.hpp:97 ConstructHistogramInner (CPU
    scatter) / ocl/histogram256.cl:317 (GPU atomic scatter); this is the
    TPU-shaped third answer.  Grid = (feature tiles, row blocks); the row
    axis iterates fastest so each feature tile's accumulator initializes
    once (@pl.when i==0) and revisits its output block across row blocks.

    ``tile_rows`` (the ops/planner.py row-tile budget) CAPS the VMEM row
    block like the matmul family's ``_tile_block``: the kernel was always
    streamed with an O(block) transient, so under a tile budget the block
    simply shrinks to min(block, tile) — this brings the one previously
    unbudgeted kernel in the family under the same planner accounting
    (``predict_peak_bytes`` variant "pallas"), so ``auto`` can elect it
    safely.  Off-accelerator the kernel runs ``interpret=True`` so the
    tier-1 CPU pytest run executes it rather than skipping.
    """
    from jax.experimental import pallas as pl

    F, n = binned_t.shape
    B = num_bins
    C = _tile_block(block_rows, resolve_tile_rows(tile_rows, n))
    Ft = min(feat_tile, F)
    if interpret is None:
        interpret = not on_accelerator()

    n_pad = _pad_rows(n, C)
    F_pad = _pad_rows(F, Ft)
    bt = binned_t
    # widened to i32 PER BLOCK inside the kernel so the HBM copy stays at
    # the narrow dtype (a .astype here would materialize a 4x intermediate)
    if n_pad != n or F_pad != F:
        # padded features get bin 0 with weight 0 (vals rows padded to 0)
        bt = jnp.pad(bt, ((0, F_pad - F), (0, n_pad - n)))
    vt = vals_t.astype(jnp.float32)
    if n_pad != n:
        vt = jnp.pad(vt, ((0, 0), (0, n_pad - n)))

    nb = n_pad // C
    nf = F_pad // Ft

    def kernel(b_ref, v_ref, out_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        blk = b_ref[...].astype(jnp.int32)              # [Ft, C]
        g = v_ref[0, :]                                 # [C]
        h = v_ref[1, :]
        w = v_ref[2, :]
        iota = lax.broadcasted_iota(jnp.int32, (B, C), 0)
        for f in range(Ft):                             # static unroll
            oh = blk[f, :][None, :] == iota             # [B, C]
            out_ref[f, 0, :] += jnp.sum(
                jnp.where(oh, g[None, :], 0.0), axis=1)
            out_ref[f, 1, :] += jnp.sum(
                jnp.where(oh, h[None, :], 0.0), axis=1)
            out_ref[f, 2, :] += jnp.sum(
                jnp.where(oh, w[None, :], 0.0), axis=1)

    out = pl.pallas_call(
        kernel,
        grid=(nf, nb),
        in_specs=[
            pl.BlockSpec((Ft, C), lambda j, i: (j, i)),
            pl.BlockSpec((3, C), lambda j, i: (0, i)),
        ],
        out_specs=pl.BlockSpec((Ft, 3, B), lambda j, i: (j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F_pad, 3, B), jnp.float32),
        interpret=interpret,
    )(bt, vt)
    return out[:F].transpose(1, 0, 2)                   # [3, F, B]


def histogram_scatter(
    binned_t: jax.Array, vals_t: jax.Array, num_bins: int,
    tile_rows: Optional[int] = None,
    init: Optional[jax.Array] = None,
) -> jax.Array:
    """Scatter-add histogram (XLA scatter). Reference semantics check path
    (CPU-oriented: the [n, F, 3] update buffer lane-pads on TPU).

    ``tile_rows`` streams row tiles through a ``fori_loop``: the update
    buffer shrinks from [n, F, 3] to [tile, F, 3] — THE r5 OOM class
    (f32[n*F, 3] lane-padded 42x at 11M rows).  Tiles accumulate into one
    shared histogram in ascending row order, so per-bin adds happen in
    the same sequence as the untiled scatter: tiled == untiled
    bit-identical (padded tail rows carry +0 values into bin 0).

    ``init`` carries a running [3, F, B] accumulator in for the
    out-of-core block fold (data/stream.py): per-bin adds always land in
    ascending row order, so a carried fold over row blocks is
    bit-identical to one resident pass regardless of the block
    partition."""
    F, n = binned_t.shape
    B = num_bins
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    hist0 = (jnp.zeros((F * B, 3), dtype=jnp.float32) if init is None
             else init.transpose(1, 2, 0).reshape(F * B, 3))
    T = resolve_tile_rows(tile_rows, n)
    if T is None:
        binned = binned_t.T                                # [n, F]
        vals = vals_t.T                                    # [n, 3]
        flat_idx = binned.astype(jnp.int32) + offsets      # [n, F]
        # vals broadcast across features: updates [n, F, 3]
        updates = jnp.broadcast_to(vals[:, None, :], (n, F, 3))
        hist = hist0.at[flat_idx.reshape(-1)].add(updates.reshape(-1, 3))
        return hist.reshape(F, B, 3).transpose(2, 0, 1)    # [3, F, B]
    nt = _pad_rows(n, T) // T
    n_pad = nt * T
    bt = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
    vt = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))

    def body(t, hist):
        b = lax.dynamic_slice(bt, (0, t * T), (F, T)).T    # [T, F]
        v = lax.dynamic_slice(vt, (0, t * T), (3, T)).T    # [T, 3]
        flat = b.astype(jnp.int32) + offsets               # [T, F]
        upd = jnp.broadcast_to(v[:, None, :], (T, F, 3))
        return hist.at[flat.reshape(-1)].add(upd.reshape(-1, 3))

    hist = lax.fori_loop(0, nt, body, hist0)
    return hist.reshape(F, B, 3).transpose(2, 0, 1)


def build_histogram(
    binned_t: jax.Array,   # [F, n] feature-major
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,
    num_bins: int,
    method: str = "auto",
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    tile_rows: Optional[int] = None,
    init: Optional[jax.Array] = None,
) -> jax.Array:
    """Masked histogram [3, F, B] = sum over rows with mask of (g, h, 1).

    ``mask`` is f32 and may carry bagging weights; leaf membership is encoded
    by zeroing non-member rows.  ``tile_rows`` streams the pass through
    row tiles so peak transient HBM is O(tile), not O(n) (planner-selected;
    see ops/planner.py).  ``init`` is the streaming block fold's carry-in
    accumulator (scatter/matmul families only — the pallas kernel
    initializes its VMEM accumulator in-grid).
    """
    vals_t = _vals_t(grad, hess, mask)
    # "fused" is a grower-level arm (ops/fused.py pairs the histogram
    # with its split scan); a bare histogram maps to a staged kernel.
    # PRECISION PAIRING (same invariant as the growers' seg_f32): the
    # fused kernel accumulates f32-exact (HIGHEST one-hot dot), and its
    # in-kernel sibling subtraction consumes THIS kernel's output as the
    # parent — so the root/parent pass must be f32-exact too, never the
    # bf16 one-hot (a bf16 parent minus an exact child could go negative
    # in derived sibling bins).  matmul_f32 on accelerators, auto
    # (scatter, exact) on CPU.
    if method == "fused":
        method = "matmul_f32" if on_accelerator() else "auto"
    method = resolve_hist_method(method)
    if method == "matmul":
        return histogram_matmul(binned_t, vals_t, num_bins, block_rows,
                                tile_rows=tile_rows, init=init)
    if method == "matmul_f32":
        return histogram_matmul_f32(binned_t, vals_t, num_bins, block_rows,
                                    tile_rows=tile_rows, init=init)
    if method == "scatter":
        return histogram_scatter(binned_t, vals_t, num_bins,
                                 tile_rows=tile_rows, init=init)
    if method == "pallas":
        if init is not None:
            raise ValueError("histogram_pallas does not take a carry-in "
                             "accumulator; stream folds use scatter/matmul")
        return histogram_pallas(binned_t, vals_t, num_bins,
                                tile_rows=tile_rows)
    raise ValueError(f"unknown histogram method {method!r}")


_probe_cache: dict = {}


def measured_best_method(n: int, num_features: int, num_bins: int,
                         candidates=("matmul", "scatter", "pallas"),
                         reps: int = 8) -> str:
    """Pick the histogram kernel by TIMING it on the live backend.

    reference: Dataset::GetShareStates times col-wise vs row-wise histogram
    construction at startup and keeps the winner (src/io/dataset.cpp:589-684)
    — the same idea applied to this module's kernel variants.  The probe
    runs once per (backend, F, B, n-bucket) per process (~seconds) on
    synthetic data of the training shape; CPU skips straight to "scatter"
    (measured fastest there every round, BENCH_r0*.json).
    """
    import time

    backend = jax.default_backend()
    if backend not in ACCEL_BACKENDS:
        return "scatter"
    n_probe = int(min(n, 1_000_000))
    key = (backend, num_features, num_bins, n_probe)
    if key in _probe_cache:
        return _probe_cache[key]
    import numpy as np
    rng = np.random.RandomState(0)
    host_dtype = np.uint8 if num_bins <= 256 else np.uint16
    binned_t = jnp.asarray(rng.randint(0, max(num_bins - 1, 1),
                                       (num_features, n_probe),
                                       dtype=host_dtype))
    grad = jnp.asarray(rng.randn(n_probe), jnp.float32)
    hess = jnp.abs(grad) + 0.1
    mask = jnp.ones((n_probe,), jnp.float32)

    def _sync(x):
        # block_until_ready is a NO-OP on the tunneled axon backend
        # (docs/PERFORMANCE.md round-5 correction); a device->host copy of
        # a dependent reduction is the only trustworthy barrier
        return float(np.asarray(jnp.sum(x.astype(jnp.float32))))

    timings = {}
    for method in candidates:
        fn = jax.jit(functools.partial(build_histogram, num_bins=num_bins,
                                       method=method))
        try:
            _sync(fn(binned_t, grad, hess, mask))   # compile
            # pipeline all reps, sync once: the sync round-trip itself is
            # ~75 ms on the tunnel, far above a single pass
            t0 = time.perf_counter()
            out = None
            for _ in range(reps):
                out = fn(binned_t, grad, hess, mask)
            _sync(out)
            timings[method] = (time.perf_counter() - t0) / reps
        except Exception:       # a variant may not lower on this backend
            continue
    winner = min(timings, key=timings.get) if timings else "matmul"
    from ..utils.log import log_info
    log_info("histogram kernel probe "
             f"({n_probe}x{num_features}, B={num_bins}): "
             + ", ".join(f"{k}={v * 1e3:.1f}ms" for k, v in timings.items())
             + f" -> {winner}")
    _probe_cache[key] = winner
    return winner


def capacity_schedule(n: int, min_cap: int = _DEFAULT_BLOCK_ROWS,
                      step: int = 4) -> list:
    """Descending capacities n, n/step, ... >= min_cap.

    Trace-time constants for the bucketed compaction below.  The smaller
    child of a split never exceeds n/2 rows, and leaf sizes shrink roughly
    geometrically in leaf-wise growth, so per-tree histogram work drops from
    O(n * num_leaves) (full masked pass per split) to ~O(n * log(num_leaves))
    — the same asymptotic the reference gets from per-leaf ordered gradients
    (src/io/dataset.cpp:1318-1333) without data-dependent shapes.

    The ladder stops at ``max(min_cap, n/256)``: every rung is a compiled
    branch of a ``lax.switch`` (XLA compile time — and the remote compile
    service's appetite — scales with them), and a histogram pass over
    n/256 rows is already noise next to the per-loop-step overhead the
    compaction exists to avoid.  ``step=4`` (default) keeps the rung
    count at ~4 for 11M rows: a rung overshoots the live set by at most
    4x, a bounded waste the slot-expanded pass has made cheap, while the
    branch count stays compile-friendly.
    """
    step = max(int(step), 2)
    min_cap = max(min_cap, _pad_rows(max(n, 1), min_cap) // 256)
    caps = []
    c = _pad_rows(n, min_cap)
    while c >= min_cap:
        caps.append(c)
        if c == min_cap:
            break
        c = _pad_rows((c + step - 1) // step, min_cap)
        if caps and c == caps[-1]:
            break
    if not caps:
        caps = [_pad_rows(max(n, 1), min_cap)]
    return caps


def compacted_histogram(
    binned_t: jax.Array,     # [F, n] feature-major
    grad: jax.Array,         # [n]
    hess: jax.Array,         # [n]
    weights: jax.Array,      # [n] f32 bagging/GOSS weights
    member: jax.Array,       # [n] bool leaf membership
    num_bins: int,
    caps: list,              # static descending capacities from capacity_schedule
    method: str = "auto",
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Masked histogram restricted to `member` rows via gather compaction.

    The member row-ids are compacted into the smallest static capacity that
    fits (lax.switch over precompiled bucket sizes); the histogram kernel
    then runs over `cap` rows instead of n.  Returns [3, F, B] f32.
    """
    F, n = binned_t.shape
    # zero-weight rows (bagged-out / GOSS-dropped) contribute nothing, so
    # exclude them from compaction too — same result, tighter capacity
    member = member & (weights > 0)
    count = jnp.sum(member)

    def branch(cap: int):
        def run():
            idx = jnp.nonzero(member, size=cap, fill_value=n)[0]
            valid = idx < n
            idxc = jnp.minimum(idx, n - 1)
            cols = jnp.take(binned_t, idxc, axis=1)        # [F, cap]
            w = jnp.where(valid, jnp.take(weights, idxc), 0.0)
            g = jnp.take(grad, idxc)
            h = jnp.take(hess, idxc)
            return build_histogram(cols, g, h, w, num_bins, method=method,
                                   tile_rows=tile_rows)
        return run

    if len(caps) == 1:
        return build_histogram(binned_t, grad, hess,
                               weights * member, num_bins, method=method,
                               tile_rows=tile_rows)
    caps_arr = jnp.asarray(caps, jnp.int32)
    # smallest capacity >= count (caps[0] >= n covers everything)
    bucket = jnp.sum(caps_arr >= count) - 1
    return lax.switch(bucket, [branch(c) for c in caps])


def segment_histogram(
    binned_t: jax.Array,     # [F, n] feature-major
    grad: jax.Array,         # [n]
    hess: jax.Array,         # [n]
    weights: jax.Array,      # [n] f32 bagging/GOSS weights
    slot: jax.Array,         # [n] i32 in [0, num_slots]; num_slots = dropped
    num_slots: int,
    num_bins: int,
    tile_rows: Optional[int] = None,
    init: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-slot masked histogram: [S, 3, F, B] where row r contributes its
    (g, h, 1)*w to slot[r]'s histogram.  Rows with slot == num_slots are
    dropped (the dummy slot).

    ``init`` carries a running [S, 3, F, B] accumulator in for the
    out-of-core block fold (data/stream.py): the dummy slot restarts at
    zero each block (it is dropped from the output anyway) while the S
    real slots continue the global ascending-row add sequence —
    bit-identical to one resident pass over the concatenated rows.

    This is the batched-frontier generalization of ``build_histogram``: one
    pass over the data builds the histograms of EVERY smaller child of a
    round's splits (reference equivalent: one ConstructHistograms call per
    leaf, serial_tree_learner.cpp:380-388 — here a whole frontier per call).
    Scatter-add formulation (CPU semantics-reference path): the work is
    O(n*F) independent of S, unlike a one-hot matmul over (slot, bin) which
    would cost O(n*F*B*S).

    ``tile_rows`` streams the [n, F, 3] update buffer — the EXACT
    f32[n*F, 3] allocation that OOM'd the r5 >=10M-row stage — through
    [tile, F, 3] pieces; tiles scatter sequentially in ascending row
    order, so tiled == untiled bit-identical (tail rows pad into the
    dummy slot with +0 values).
    """
    F, n = binned_t.shape
    B = num_bins
    S = num_slots
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    if init is None:
        hist0 = jnp.zeros(((S + 1) * F * B, 3), dtype=jnp.float32)
    else:
        hist0 = jnp.concatenate(
            [init.transpose(0, 2, 3, 1).reshape(S * F * B, 3),
             jnp.zeros((F * B, 3), jnp.float32)])
    T = resolve_tile_rows(tile_rows, n)
    if T is None:
        binned = binned_t.T
        vals = _vals_t(grad, hess, weights).T              # [n, 3]
        flat = (slot[:, None].astype(jnp.int32) * (F * B)
                + binned.astype(jnp.int32) + offsets)      # [n, F]
        updates = jnp.broadcast_to(vals[:, None, :], (n, F, 3))
        hist = hist0.at[flat.reshape(-1)].add(updates.reshape(-1, 3))
        return hist.reshape(S + 1, F, B, 3)[:S].transpose(0, 3, 1, 2)
    nt = _pad_rows(n, T) // T
    n_pad = nt * T
    bt = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
    vt = jnp.pad(_vals_t(grad, hess, weights), ((0, 0), (0, n_pad - n)))
    st = jnp.pad(slot.astype(jnp.int32), (0, n_pad - n), constant_values=S)

    def body(t, hist):
        b = lax.dynamic_slice(bt, (0, t * T), (F, T)).T    # [T, F]
        v = lax.dynamic_slice(vt, (0, t * T), (3, T)).T    # [T, 3]
        s = lax.dynamic_slice(st, (t * T,), (T,))
        flat = (s[:, None] * (F * B) + b.astype(jnp.int32) + offsets)
        upd = jnp.broadcast_to(v[:, None, :], (T, F, 3))
        return hist.at[flat.reshape(-1)].add(upd.reshape(-1, 3))

    hist = lax.fori_loop(0, nt, body, hist0)
    return hist.reshape(S + 1, F, B, 3)[:S].transpose(0, 3, 1, 2)


# one-time per-backend verdict of the table-matmul exactness probe:
# {backend_name: bool}.  Populated lazily by _table_matmul_verified.
_TABLE_MATMUL_PROBE: dict = {}


def _table_matmul_probe() -> bool:
    """Run the one-hot table matmul ON THE LIVE BACKEND and compare it
    bitwise against a host-side plain gather.

    The matmul path's exactness claim (one nonzero per one-hot row, so
    each output is a single f32 product that precision=HIGHEST must
    round-trip) is only TESTED on CPU (test_histogram.py monkeypatches
    on_accelerator); leaf values ride this kernel into train scores and
    predictions, so an accelerator where HIGHEST is not bit-exact would
    silently perturb every prediction (ADVICE.md round 5).  The probe
    covers both the single-block and the lax.scan-blocked variant (via a
    shrunken block size) and every table entry, with awkward magnitudes
    across the full NORMAL f32 range (tiny, huge, negatives, zeros).
    Subnormals are deliberately excluded: XLA's dot kernels flush them to
    zero on every backend (measured here even on CPU), and table entries
    — leaf values, per-leaf stat rows — are normal-range by construction,
    so failing the probe on an irrelevant domain would cost the MXU path
    for nothing.  Any mismatch — or any crash — demotes the backend to
    the plain gather, equivalent to LGBM_TPU_TABLE_MATMUL=0.
    """
    rng = np.random.RandomState(7)
    vals = np.concatenate([
        rng.standard_normal(40),
        10.0 ** rng.uniform(-37, 38, 20),
        -(10.0 ** rng.uniform(-37, 38, 20)),
        np.array([0.0, -0.0, 1.2e-38, -1.2e-38, np.float32(np.pi), 3e38]),
    ]).astype(np.float32)
    L = len(vals)
    idx = np.concatenate([np.arange(L), rng.randint(0, L, 4 * L)]) \
        .astype(np.int32)
    want = vals[idx]
    try:
        got1 = np.asarray(_take_matmul(jnp.asarray(vals), jnp.asarray(idx),
                                       leading=False))
        got2 = np.asarray(_take_matmul(jnp.asarray(vals), jnp.asarray(idx),
                                       leading=False, block=64))
        ok = (np.array_equal(got1, want) and np.array_equal(got2, want))
    except Exception:
        ok = False
    return ok


def _table_matmul_verified() -> bool:
    """True iff the one-hot table matmul is bit-exact on this backend
    (probed once per backend name, at first accelerator use)."""
    backend = jax.default_backend()
    ok = _TABLE_MATMUL_PROBE.get(backend)
    if ok is None:
        # eager probe on concrete arrays: safe to run even while another
        # function is being traced (nothing here consumes tracers)
        ok = _table_matmul_probe()
        _TABLE_MATMUL_PROBE[backend] = ok
        if not ok:
            import warnings
            warnings.warn(
                f"take_from_table: one-hot matmul is NOT bit-exact on "
                f"backend {backend!r}; falling back to plain gather "
                "(equivalent to LGBM_TPU_TABLE_MATMUL=0)")
    return ok


def take_from_table(table: jax.Array, idx: jax.Array,
                    leading: bool = False) -> jax.Array:
    """``table[idx]`` for a SMALL table and a huge ``idx`` vector.

    On this TPU backend an [n]-sized gather from even a tiny table lowers
    to serialized-gather territory (~130 ms at 11M rows, tpu_probe_r5);
    reformulated as a one-hot matmul it rides the MXU instead.  The
    one-hot has exactly one nonzero per row, so each output is a single
    product — numerically EXACT in f32 under precision=HIGHEST (XLA's
    bf16x3 expansion round-trips f32 multiplicands exactly; there is no
    accumulation ordering to worry about).  That claim is VERIFIED on the
    live backend by a one-time probe at first use
    (``_table_matmul_verified``); a backend that fails it serves plain
    gathers instead of silently perturbing predictions.

    ``table`` may be [L] or [L, k]; returns idx.shape (+ [k]) in
    table.dtype — or, with ``leading=True`` (and a 2-D table), [k] +
    idx.shape: the component-leading layout that avoids the [n, k]
    lane-padding tax for huge idx (see LAYOUT DOCTRINE).  Falls back to a
    plain gather off-accelerator, when ``LGBM_TPU_TABLE_MATMUL=0``, or
    when the probe failed.
    """
    if (not on_accelerator()
            or os.environ.get("LGBM_TPU_TABLE_MATMUL") == "0"
            or not jnp.issubdtype(table.dtype, jnp.floating)
            or not _table_matmul_verified()):
        out = table[idx]
        if leading and table.ndim == 2:
            return jnp.moveaxis(out, -1, 0)
        return out
    return _take_matmul(table, idx, leading)


def _take_matmul(table: jax.Array, idx: jax.Array, leading: bool = False,
                 block: int = 65536) -> jax.Array:
    """The MXU one-hot formulation of ``take_from_table`` (no dispatch)."""
    L = table.shape[0]
    squeeze = table.ndim == 1
    t2 = (table[:, None] if squeeze else table).astype(jnp.float32)
    flat = idx.reshape(-1)
    n = flat.shape[0]
    iota_L = jnp.arange(L, dtype=flat.dtype)
    # blocked like histogram_matmul's body: a single [n, L] f32 one-hot
    # would materialize ~11 GB at the 11M-row x 255-leaf headline shape
    # (dot operands are not producer-fused) — exactly the lane-padded-HBM
    # class of failure this module's layout doctrine exists to avoid
    k = t2.shape[1]
    C = block
    if n <= C:
        # [k, L] @ [L, n] keeps every intermediate k-leading (minor dim n)
        oh = (iota_L[:, None] == flat[None, :]).astype(jnp.float32)
        out_t = lax.dot(t2.T, oh, precision=lax.Precision.HIGHEST)  # [k, n]
    else:
        nb = _pad_rows(n, C) // C
        fpad = jnp.pad(flat, (0, nb * C - n), constant_values=-1)

        def body(_, blk):
            oh = (iota_L[:, None] == blk[None, :]).astype(jnp.float32)
            return _, lax.dot(t2.T, oh,
                              precision=lax.Precision.HIGHEST)   # [k, C]

        _, chunks = lax.scan(body, None, fpad.reshape(nb, C))
        out_t = jnp.moveaxis(chunks, 1, 0).reshape(k, nb * C)[:, :n]
    out_t = out_t.astype(table.dtype)
    if squeeze:
        return out_t[0].reshape(idx.shape)
    if leading:
        return out_t.reshape((k,) + idx.shape)
    return out_t.T.reshape(idx.shape + (k,))


def pack_cols_u32(binned_t: jax.Array, grad: jax.Array, hess: jax.Array,
                  weights: jax.Array):
    """Fuse a u8 feature-major matrix and the (g, h, 1)*w value triple into
    ONE u32 word-matrix [Wb + 3, n] (minor dim n — unpadded).

    Motivation (tpu_probe_r5.json): XLA gather cost on this backend scales
    with gathered ELEMENT count — packing 4 bins per u32 word and fusing
    the three f32 value rows into the same record turns the arena's four
    gathers into one with ~3x fewer elements.  Words are built
    arithmetically (b0 | b1<<8 | ...) so no [.., 4]-minor bitcast
    intermediate ever exists.  Returns (words_t, Wb) with Wb = bin words.
    """
    F, n = binned_t.shape
    if binned_t.dtype != jnp.uint8:
        return None, 0          # u16 bins (max_bin > 256): no packing
    Wb = (F + 3) // 4
    pad = Wb * 4 - F
    bt = jnp.pad(binned_t, ((0, pad), (0, 0))) if pad else binned_t
    b32 = bt.astype(jnp.uint32).reshape(Wb, 4, n)
    bin_words = (b32[:, 0] | (b32[:, 1] << 8)
                 | (b32[:, 2] << 16) | (b32[:, 3] << 24))   # [Wb, n]
    vals_t = _vals_t(grad, hess, weights)                   # [3, n] f32
    val_words = lax.bitcast_convert_type(vals_t, jnp.uint32)
    return jnp.concatenate([bin_words, val_words], axis=0), Wb


def segment_histogram_sorted(
    binned_t: jax.Array,     # [F, n] uint8/16 feature-major
    grad: jax.Array,         # [n]
    hess: jax.Array,         # [n]
    weights: jax.Array,      # [n] f32 bagging/GOSS weights
    slot: jax.Array,         # [n] i32 in [0, num_slots]; num_slots = dropped
    num_slots: int,
    num_bins: int,
    block_rows: int = 1024,
    f32_vals: bool = False,
    caps: Optional[list] = None,   # static descending arena capacities
    packed: Optional[tuple] = None,   # (words_t [Wb+3, n] u32, Wb) from
                                      # pack_cols_u32 — hoisted per tree
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """TPU-native segment histogram: sort-by-slot + block-aligned matmuls.

    The scatter formulation (``segment_histogram``) serializes on TPU and
    materializes an [n*F, 3] update buffer that XLA lane-pads to 128 (157 GB
    at HIGGS scale) — so here the problem is reshaped for the MXU instead:

      1. sort row ids by slot via ONE u32 combined key
         ``slot << 24 | row_id`` (stable by construction; falls back to a
         two-array stable sort when n >= 2^24);
      2. per-slot counts/starts come free from the sorted keys via
         ``searchsorted`` (a scatter-free bincount);
      3. lay the sorted rows into a block-aligned arena where every slot's
         segment starts on a ``block_rows`` boundary — so each C-row block
         belongs to exactly ONE slot.  The destination->source map is
         elementwise (no inverse permutation / scatter needed): destination
         q in block j holds the (q - C*blk_start[s])-th sorted row of slot
         s = blk_slot[j].  The arena size is the ladder's smallest static
         capacity that fits the slotted-row count (``lax.switch`` over
         ``caps``), so the gather+matmul cost tracks the live frontier,
         not n.  All gathers run in the TRANSPOSED layout ([W, n] ->
         [W, arena]: minor dim = arena, unpadded);
      4. one-hot matmul per block ([3, C] @ [C, F*B], the histogram_matmul
         body) producing per-block partials;
      5. reduce partials into slots with a tiny [S, NB] one-hot matmul
         (blocks of a slot are contiguous by construction).

    Every step is a gather, sort, or matmul — nothing scatters.  Returns
    [S, 3, F, B] f32.  reference analogue: ordered-gradient per-leaf
    histograms (src/io/dataset.cpp:1318-1333) built from a DataPartition
    that keeps leaves contiguous (src/treelearner/data_partition.hpp).

    Accumulation-order pin (tiling discipline): per-block partials fold
    into their slot INSIDE the block scan, in ascending block order — the
    same order whether the arena records were gathered up front (untiled:
    one big [W, cap] gather, fastest dispatch when it fits HBM) or
    per block inside the loop (``tile_rows`` set: O(block) transients, no
    whole-arena record materialization — the planner's O(tile) mode).
    Both modes therefore produce BIT-IDENTICAL histograms; the sort
    (n u32 words) is the only O(n) device state either way.

    DELIBERATE f32 reassociation vs the pre-tiling code: the old fold
    was one HIGHEST-precision ``slot_onehot @ parts`` dot; pinning the
    in-scan order (required for tiled == untiled parity) reassociates
    the per-slot f32 sums, so multi-block slots can differ from the
    previous release in the last bit.  Same class of difference as the
    reference's CPU-vs-GPU histograms (module docstring of
    grower_rounds.py); the int kernel's fold is associative and exact
    either way.  CPU defaults (scatter) and the golden guard are
    untouched — this kernel only runs on accelerators or when
    LGBM_TPU_SEGHIST=sorted forces it.
    """
    F, n = binned_t.shape
    B = num_bins
    S = num_slots
    if caps is None:
        caps = [n]

    if n < (1 << 24) and num_slots < 256:
        # single-array sort: the combined UNSIGNED key carries the payload
        # (u32 so slot values up to 255 — including the dummy num_slots —
        # never touch the sign bit; an i32 key would wrap for slot >= 128
        # and silently drop those slots' mass)
        key = ((slot.astype(jnp.uint32) << 24)
               | jnp.arange(n, dtype=jnp.uint32))
        skey = lax.sort(key)
        sorted_slot = (skey >> 24).astype(jnp.int32)
        order = (skey & jnp.uint32(0x00FFFFFF)).astype(jnp.int32)
    else:
        row_ids = jnp.arange(n, dtype=jnp.int32)
        sorted_slot, order = lax.sort((slot, row_ids), is_stable=True,
                                      num_keys=1)
    # counts without a scatter: positions of slot boundaries in sorted keys
    bounds = jnp.searchsorted(sorted_slot,
                              jnp.arange(S + 1, dtype=sorted_slot.dtype))
    row_start = bounds[:S].astype(jnp.int32)
    counts = (bounds[1:] - bounds[:S]).astype(jnp.int32)

    iota = jnp.arange(B, dtype=binned_t.dtype)
    acc_t = jnp.float32 if f32_vals else jnp.bfloat16
    prec = lax.Precision.HIGHEST if f32_vals else lax.Precision.DEFAULT

    def arena(cap: int):
        """Histogram over a cap-row block-aligned arena.

        The block size shrinks with the capacity rung so the worst-case
        per-slot padding (S partial blocks) stays a small multiple of the
        live rows instead of a fixed S*block_rows floor."""
        C = max(128, min(block_rows,
                         1 << max(0, (max(cap, 1) // (4 * max(S, 1))
                                      ).bit_length() - 1)))
        NB = _pad_rows(max(cap, 1), C) // C + S     # every slot may pad

        def run():
            nblk = (counts + C - 1) // C            # blocks per slot
            blk_end = jnp.cumsum(nblk)
            blk_start = (blk_end - nblk).astype(jnp.int32)
            # block j -> slot: first slot whose block range extends past j
            j_idx = jnp.arange(NB, dtype=blk_end.dtype)
            blk_slot = jnp.searchsorted(blk_end, j_idx,
                                        side="right").astype(jnp.int32)
            blk_slot = jnp.minimum(blk_slot, S)     # beyond last: dummy

            # destination -> source (elementwise over the arena)
            q = jnp.arange(NB * C, dtype=jnp.int32)
            s_of = blk_slot[q // C]
            s_c = jnp.minimum(s_of, S - 1)
            o = q - blk_start[s_c] * C
            valid = (s_of < S) & (o < counts[s_c])
            src_sorted = jnp.minimum(row_start[s_c] + o, n - 1)
            src = order[src_sorted]

            def block_partial(rows, vals):
                """Shared per-block one-hot matmul: [F, C] bins x [3, C]
                vals -> [3, F*B] partial (both gather branches feed this
                one body so dtype/precision tweaks can never diverge)."""
                onehot2d = (rows.T[:, :, None] == iota.astype(rows.dtype)
                            ).astype(acc_t).reshape(C, F * B)
                return lax.dot(vals.astype(acc_t), onehot2d,
                               precision=prec,
                               preferred_element_type=jnp.float32)

            use_packed = packed is not None and packed[0] is not None

            def part_from_packed(blk_rec, vm):
                """[Wb+3, C] u32 fused record block -> [3, F*B] partial."""
                Wb = packed[1]
                bw = blk_rec[:Wb]                       # [Wb, C] u32
                rows = jnp.concatenate(
                    [((bw >> (8 * j)) & 0xFF) for j in range(4)],
                    axis=0).reshape(4, Wb, C).transpose(
                        1, 0, 2).reshape(Wb * 4, C)[:F]   # [F, C]
                vals = lax.bitcast_convert_type(blk_rec[Wb:], jnp.float32)
                vals = jnp.where(vm, vals, 0.0)         # [3, C]
                return block_partial(rows.astype(jnp.int32), vals)

            def part_from_raw(cols, g, h, w, vm):
                vt = (jnp.stack([g, h, jnp.ones_like(g)])
                      * jnp.where(vm, w, 0.0)[None, :])
                return block_partial(cols, vt)

            # the block -> slot fold happens INSIDE the scan (ascending
            # block order, one shared f32 accumulator): the pinned order
            # that makes the hoisted and in-loop gather modes — and hence
            # tiled vs untiled — bit-identical
            acc0 = jnp.zeros((S + 1, 3 * F * B), jnp.float32)
            j_arange = jnp.arange(NB, dtype=jnp.int32)

            if resolve_tile_rows(tile_rows, n) is None:
                # untiled: ONE whole-arena gather up front (fastest
                # dispatch; O(cap) transient the planner must afford)
                if use_packed:
                    words_t, Wb = packed
                    rec = jnp.take(words_t, src, axis=1)  # [Wb+3, NBC] u32
                    recb = rec.reshape(Wb + 3, NB, C).transpose(1, 0, 2)
                    vmask = valid.reshape(NB, 1, C)

                    def body(acc, xs):
                        j, blk_rec, vm = xs
                        return acc.at[blk_slot[j]].add(
                            part_from_packed(blk_rec, vm).reshape(-1)), None

                    acc, _ = lax.scan(body, acc0, (j_arange, recb, vmask))
                else:
                    cols = jnp.take(binned_t, src, axis=1)  # [F, NBC]
                    w = jnp.take(weights, src)
                    g = jnp.take(grad, src)
                    h = jnp.take(hess, src)
                    colsb = cols.reshape(F, NB, C).transpose(1, 0, 2)
                    gb = g.reshape(NB, C)
                    hb = h.reshape(NB, C)
                    wb = w.reshape(NB, C)
                    vmask = valid.reshape(NB, C)

                    def body(acc, xs):
                        j, b, gg, hh, ww, vm = xs
                        return acc.at[blk_slot[j]].add(
                            part_from_raw(b, gg, hh, ww, vm).reshape(-1)), \
                            None

                    acc, _ = lax.scan(body, acc0,
                                      (j_arange, colsb, gb, hb, wb, vmask))
            else:
                # tiled: records are gathered/assembled PER BLOCK inside
                # the loop — no whole-arena (or whole-dataset) record
                # materialization; peak transient is O(block)
                def body(acc, j):
                    sb = lax.dynamic_slice(src, (j * C,), (C,))
                    vm = lax.dynamic_slice(valid, (j * C,), (C,))
                    if use_packed:
                        rec = jnp.take(packed[0], sb, axis=1)  # [Wb+3, C]
                        part = part_from_packed(rec, vm[None, :])
                    else:
                        cols = jnp.take(binned_t, sb, axis=1)  # [F, C]
                        part = part_from_raw(cols, jnp.take(grad, sb),
                                             jnp.take(hess, sb),
                                             jnp.take(weights, sb), vm)
                    return acc.at[blk_slot[j]].add(part.reshape(-1)), None

                acc, _ = lax.scan(body, acc0, j_arange)
            return acc[:S].reshape(S, 3, F, B)
        return run

    if len(caps) == 1:
        return arena(caps[0])()
    total = bounds[S].astype(jnp.int32)             # slotted-row count
    caps_arr = jnp.asarray(caps, jnp.int32)
    bucket = jnp.sum(caps_arr >= total) - 1
    return lax.switch(bucket, [arena(c) for c in caps])


_SMALL_ROUND_SLOTS = 4
# slot-expanded LHS rows: 3 * 42 = 126 <= the MXU's 128-row tile, so a
# 42-slot segment histogram costs the SAME matmul cycles as a 1-slot one
_EXPAND_SLOTS = 42


def segment_histogram_expanded(
    binned_t: jax.Array,     # [F, n] feature-major
    grad: jax.Array,
    hess: jax.Array,
    weights: jax.Array,      # [n] f32
    slot: jax.Array,         # [n] i32; values >= live_cap contribute nothing
    num_bins: int,
    live_cap: int = _EXPAND_SLOTS,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    f32_vals: bool = False,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Histograms of slots [0, live_cap) in ONE streamed full-matrix pass.

    The plain histogram matmul uses M=3 of the MXU's 128 output rows
    (grad/hess/count); expanding the LHS to ``[3*live_cap, C]`` — row
    (j*live_cap + s) carrying ``vals[j] * (slot == s)`` — fills the tile and
    computes every live slot's histogram in the SAME pass: no sort, no
    gather, no arena.  One systolic tile (3*live_cap <= 128) costs the
    same cycles as M=3, so this replaces the sorted arena for every
    round with <= ``live_cap`` candidates — i.e. all but the widest
    rounds of a 255-leaf tree (reference equivalent: one
    ConstructHistograms call per leaf, serial_tree_learner.cpp:380-388;
    here a frontier per PASS).  Returns [live_cap, 3, F, B] f32.
    """
    F, n = binned_t.shape
    B = num_bins
    SE = live_cap
    block_rows = _tile_block(block_rows, resolve_tile_rows(tile_rows, n))
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    vals_t = _vals_t(grad, hess, weights)
    slot_i = slot.astype(jnp.int32)
    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        vals_t = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))
        slot_i = jnp.pad(slot_i, (0, n_pad - n), constant_values=SE)
    iota_b = jnp.arange(B, dtype=binned_t.dtype)
    iota_s = jnp.arange(SE, dtype=jnp.int32)
    C = block_rows
    acc_t = jnp.float32 if f32_vals else jnp.bfloat16
    prec = lax.Precision.HIGHEST if f32_vals else lax.Precision.DEFAULT

    def body(acc, i):
        b = lax.dynamic_slice(binned_t, (0, i * C), (F, C))   # [F, C]
        v = lax.dynamic_slice(vals_t, (0, i * C), (3, C))     # [3, C]
        sl = lax.dynamic_slice(slot_i, (i * C,), (C,))        # [C]
        oh_s = (sl[None, :] == iota_s[:, None]).astype(acc_t)   # [SE, C]
        lhs = (v.astype(acc_t)[:, None, :] * oh_s[None, :, :]
               ).reshape(3 * SE, C)
        onehot2d = (b.T[:, :, None] == iota_b).astype(acc_t).reshape(
            C, F * B)
        part = lax.dot(lhs, onehot2d, precision=prec,
                       preferred_element_type=jnp.float32)
        return acc + part, None

    init = jnp.zeros((3 * SE, F * B), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, jnp.arange(nb, dtype=jnp.int32))
    return acc.reshape(3, SE, F, B).transpose(1, 0, 2, 3)


def compacted_segment_histogram(
    binned_t: jax.Array,     # [F, n] feature-major
    grad: jax.Array,
    hess: jax.Array,
    weights: jax.Array,      # [n] f32
    slot: jax.Array,         # [n] i32 in [0, num_slots]; num_slots = dropped
    num_slots: int,
    num_bins: int,
    caps: list,              # static descending capacities
    f32_vals: bool = False,
    num_live: Optional[jax.Array] = None,   # traced count of live slots
    packed: Optional[tuple] = None,         # pack_cols_u32 output, hoisted
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Segment histogram over only the rows with a real slot, with the
    work bounded by the smallest static capacity that fits (see
    ``compacted_histogram``).  Returns [S, 3, F, B] f32.

    Backend dispatch: sorted block-matmul arena on accelerators (the
    scatter formulation both OOMs — its [n*F, 3] update buffer lane-pads
    to 128 — and serializes there); XLA scatter with nonzero-compaction
    on CPU (measured fastest there every round, BENCH_r0*.json).
    When ``num_live`` (the round's live-slot count) is given and at most
    ``_EXPAND_SLOTS``, accelerators take ONE slot-expanded full-matrix
    pass instead (``segment_histogram_expanded``): a streamed matmul
    pass costs ~17 ms at 11M rows vs ~90 ms for sort+gather+arena
    (tpu_probe_r5.json), and the expanded LHS computes up to 42 slots
    for the cycles of one.  ``LGBM_TPU_SEGHIST=sorted|scatter``
    overrides (testing hook).
    """
    F, n = binned_t.shape
    if use_sorted_seghist():
        # zero-weight rows are dropped by reslotting (cheaper than compact)
        slot_w = jnp.where(weights > 0, slot, num_slots)

        def arena_path(_):
            return segment_histogram_sorted(
                binned_t, grad, hess, weights, slot_w, num_slots, num_bins,
                f32_vals=f32_vals, caps=caps, packed=packed,
                tile_rows=tile_rows)

        # LGBM_TPU_SMALL_ROUNDS=0 drops the expanded-pass branch (and its
        # lax.cond program duplication) — compile-cost bisect hook
        small_enabled = os.environ.get("LGBM_TPU_SMALL_ROUNDS") != "0"
        if num_live is None or num_slots <= _SMALL_ROUND_SLOTS \
                or not small_enabled:
            return arena_path(None)
        se = min(_EXPAND_SLOTS, num_slots)

        def expanded_path(_):
            hist = segment_histogram_expanded(
                binned_t, grad, hess, weights, slot_w, num_bins,
                live_cap=se, f32_vals=f32_vals, tile_rows=tile_rows)
            if num_slots > se:
                hist = jnp.concatenate(
                    [hist, jnp.zeros((num_slots - se, 3, F, num_bins),
                                     jnp.float32)], axis=0)
            return hist

        return lax.cond(num_live <= se, expanded_path, arena_path, None)

    member = (slot < num_slots) & (weights > 0)
    count = jnp.sum(member)

    def branch(cap: int):
        def run():
            idx = jnp.nonzero(member, size=cap, fill_value=n)[0]
            valid = idx < n
            idxc = jnp.minimum(idx, n - 1)
            cols = jnp.take(binned_t, idxc, axis=1)
            w = jnp.where(valid, jnp.take(weights, idxc), 0.0)
            g = jnp.take(grad, idxc)
            h = jnp.take(hess, idxc)
            s = jnp.where(valid, jnp.take(slot, idxc), num_slots)
            return segment_histogram(cols, g, h, w, s, num_slots, num_bins,
                                     tile_rows=tile_rows)
        return run

    if len(caps) == 1:
        return segment_histogram(binned_t, grad, hess, weights,
                                 jnp.where(member, slot, num_slots),
                                 num_slots, num_bins, tile_rows=tile_rows)
    caps_arr = jnp.asarray(caps, jnp.int32)
    bucket = jnp.sum(caps_arr >= count) - 1
    return lax.switch(bucket, [branch(c) for c in caps])


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """The subtraction trick: sibling = parent - child.

    reference: FeatureHistogram::Subtract (feature_histogram.hpp:79-84).

    Works unchanged on the quantized integer histograms below — and there
    it is EXACT: int32 subtraction has no rounding, so the sibling
    histogram carries no accumulated float error (the quantized-training
    selling point the reference's gradient_discretizer.hpp exploits).
    """
    return parent - child


# ======================================================================
# Quantized-gradient integer histogram family (use_quantized_grad)
#
# LightGBM 4.x lineage (src/treelearner/gradient_discretizer.{hpp,cpp}):
# per-round discretization of grad/hess to a few signed integer levels
# with stochastic rounding, integer histogram accumulation, and split
# gains computed from the integer sums rescaled in high precision.  On
# this backend the wins compound:
#
# - the one-hot matmul runs int8 x int8 -> int32 on the MXU
#   (``preferred_element_type=int32``), halving the one-hot operand
#   bytes vs bf16 and producing EXACT integer sums — no
#   accumulation-order nondeterminism, so parent - child subtraction
#   (``subtract_histogram``) is exact;
# - histograms shrink to TWO channels ([2, F, B] i32: grad, hess) —
#   per-bin COUNTS are estimated from the hessian channel at split time
#   exactly like the reference's main path
#   (``Common::RoundInt(sum_hess * cnt_factor)``,
#   feature_histogram.hpp:813), which is what lets the data-parallel
#   psum payload drop from 12 bytes/cell (3 x f32) to 8 (2 x i32), and
#   to 4 (2 x i16) when the static row x level bound allows
#   (``psum_quant_hist``);
# - per-row values ride as int8 [2, n] blocks (LAYOUT DOCTRINE: tiny
#   component axis leading, minor dim n unpadded).
#
# Accumulator width: per-cell |sum| <= n * level_bound; with
# num_grad_quant_bins <= 64 (config-validated) that stays inside int32
# up to ~34M rows — above every shape this repo targets (11M HIGGS).
# ======================================================================


def quant_levels(num_bins: int):
    """(grad level bound, hess level bound) for ``num_grad_quant_bins``.

    reference: gradient_discretizer.cpp — gradients take signed levels in
    [-bins/2 + 1, bins/2 - 1], hessians (non-negative) [0, bins - 1]."""
    return max(num_bins // 2 - 1, 1), max(num_bins - 1, 1)


def quantize_gradients(grad: jax.Array, hess: jax.Array, weights: jax.Array,
                       num_bins: int, key: jax.Array,
                       stochastic: bool = True,
                       axis_name: Optional[str] = None):
    """Discretize one class's grad/hess to signed integer levels.

    Bagging/GOSS weights are FOLDED INTO the values before discretization
    (the reference amplifies sampled gradients before discretizing,
    goss.hpp:94-98 + gradient_discretizer); the histogram mask is then
    binary membership, which is what keeps the histogram updates integer.
    Scales are the per-round max-abs over the GLOBAL rows (``lax.pmax``
    under data sharding) divided by the level bound; stochastic rounding
    is ``floor(x + u)`` (unbiased), round-to-nearest otherwise.

    Returns ``(gq int8 [n], hq int8 [n], g_scale f32, h_scale f32)`` with
    ``value ~= q * scale``.  Zero-weight rows quantize to exactly 0.
    """
    qg, qh = quant_levels(num_bins)
    gw = grad * weights
    hw = hess * weights
    gmax = jnp.max(jnp.abs(gw))
    hmax = jnp.max(jnp.abs(hw))
    if axis_name is not None:
        # pmax is exact under any association, so one fused collective
        # serves flat AND hierarchical meshes (tuple axis names OK)
        from ..parallel.collectives import pmax_tiered
        gmax = pmax_tiered(gmax, axis_name)
        hmax = pmax_tiered(hmax, axis_name)
    g_scale = (jnp.maximum(gmax, 1e-30) / qg).astype(jnp.float32)
    h_scale = (jnp.maximum(hmax, 1e-30) / qh).astype(jnp.float32)
    if stochastic:
        u = jax.random.uniform(key, (2,) + gw.shape)
        gq = jnp.floor(gw / g_scale + u[0])
        hq = jnp.floor(hw / h_scale + u[1])
    else:
        gq = jnp.round(gw / g_scale)
        hq = jnp.round(hw / h_scale)
    gq = jnp.clip(gq, -qg, qg).astype(jnp.int8)
    hq = jnp.clip(hq, 0, qh).astype(jnp.int8)
    return gq, hq, g_scale, h_scale


def quant_psum_narrow(rows_global: int, num_bins: int) -> bool:
    """True when the STATIC bound rows * hess_levels fits int16, so the
    cross-device histogram psum can ride a half-width payload.  The bound
    covers every partial AND the global sum, so no reduction order can
    overflow.  This is the "payload shrinks with the quantization width"
    lever: fewer levels => smaller bound => narrower psum."""
    _, qh = quant_levels(num_bins)
    return rows_global * qh < (1 << 15)


def psum_quant_hist(hist: jax.Array, axis_name,
                    rows_global: int, num_bins: int,
                    hierarchical: bool = False) -> jax.Array:
    """psum an integer histogram across the data axis (a single mesh axis
    or the hybrid ``("dcn", "ici")`` tuple), narrowed to int16 when
    ``quant_psum_narrow`` proves it safe.  ``hierarchical`` reduces the
    fast tier first (parallel/collectives.py); the narrowing bound covers
    every partial sum, so each stage rides the same narrowed payload.
    The ICI payload is 2 channels x {2,4} bytes vs the f32 path's 3 x 4
    (``hist_payload_bytes`` is the accounting twin used by
    tools/hist_probe.py and the bench stage)."""
    if axis_name is None:
        return hist
    from ..parallel.collectives import psum_int_tiered
    narrow = jnp.int16 if quant_psum_narrow(rows_global, num_bins) else None
    return psum_int_tiered(hist, axis_name, hierarchical=hierarchical,
                           narrow=narrow)


def hist_payload_bytes(num_features: int, num_bins: int,
                       rows_global: int = 0,
                       quant_bins: Optional[int] = None) -> int:
    """Per-psum histogram payload bytes for one [*, F, B] histogram.

    ``quant_bins=None`` = the f32 pipeline (3 channels x f32); otherwise
    the integer pipeline (2 channels, int16 when the static bound
    narrows, else int32).  Pure accounting — shared by the growers'
    documentation, tools/hist_probe.py and tests so the claimed payload
    can never drift from the psum'd dtypes."""
    if quant_bins is None:
        return 3 * num_features * num_bins * 4
    item = 2 if quant_psum_narrow(rows_global, quant_bins) else 4
    return 2 * num_features * num_bins * item


def _vals_t_int(gq, hq, member):
    """[2, n] int8 value block (g, h) * member — the integer twin of
    ``_vals_t`` (no count row: counts are hessian-estimated at split
    time, reference feature_histogram.hpp:813 cnt_factor)."""
    return jnp.stack([gq, hq]) * member.astype(jnp.int8)


def histogram_matmul_int(
    binned_t: jax.Array,   # [F, n] uint8/uint16 feature-major
    vals_t: jax.Array,     # [2, n] int8 (g, h) * member
    num_bins: int,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Integer histogram via int8 one-hot matmul. Returns [2, F, B] i32.

    The MXU's s8 x s8 -> s32 path: one-hot operands are int8 (half the
    bytes of the bf16 f32-path one-hot) and accumulation is exact int32
    (``preferred_element_type``), so there is no bf16 mantissa loss and
    no accumulation-order wobble to re-verify per backend.  ``tile_rows``
    caps the streaming block — int32 accumulation is associative, so
    EVERY tile size is exactly equal to untiled."""
    F, n = binned_t.shape
    B = num_bins
    block_rows = _tile_block(block_rows, resolve_tile_rows(tile_rows, n))
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        vals_t = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))
    iota = jnp.arange(B, dtype=binned_t.dtype)
    C = block_rows

    def body(acc, i):
        b = lax.dynamic_slice(binned_t, (0, i * C), (F, C))   # [F, C]
        v = lax.dynamic_slice(vals_t, (0, i * C), (2, C))     # [2, C]
        onehot2d = (b.T[:, :, None] == iota).astype(jnp.int8).reshape(
            C, F * B)
        part = lax.dot(v, onehot2d, preferred_element_type=jnp.int32)
        return acc + part, None

    init = jnp.zeros((2, F * B), dtype=jnp.int32)
    acc, _ = lax.scan(body, init, jnp.arange(nb, dtype=jnp.int32))
    return acc.reshape(2, F, B)


def _pack_modulus(n: int, levels) -> int:
    """Static modulus for the packed-scatter trick, or 0 when unsafe.

    Per-bin field bounds: hess sum in [0, n*qh], grad sum in
    [-n*qg, n*qg].  Packing word = g * M + h with M > n*qh keeps the two
    sums separable after accumulation (h never borrows into g because it
    is non-negative and < M); the whole packed value must stay inside
    int32."""
    if levels is None:
        return 0
    qg, qh = levels
    bound_h = n * qh
    M = 1
    while M <= bound_h:
        M <<= 1
    if n * qg * M + M < (1 << 31):
        return M
    return 0


def histogram_scatter_int(
    binned_t: jax.Array, vals_t: jax.Array, num_bins: int,
    levels: Optional[tuple] = None,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Integer scatter-add histogram (CPU semantics path) — [2, F, B] i32.

    When the static bound allows, the two channels are PACKED into one
    i32 word per row (``g * M + h``), halving the scatter update traffic;
    the fields are split back apart arithmetically after accumulation.
    ``tile_rows`` streams the update buffer in [tile, F] pieces
    (exact under any tiling: int32 adds are associative)."""
    F, n = binned_t.shape
    B = num_bins
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    M = _pack_modulus(n, levels)
    T = resolve_tile_rows(tile_rows, n)
    if T is not None:
        nt = _pad_rows(n, T) // T
        n_pad = nt * T
        bt = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        vt = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))
        if M:
            word_all = (vt[0].astype(jnp.int32) * M
                        + vt[1].astype(jnp.int32))         # [n_pad]

            def body(t, hist):
                b = lax.dynamic_slice(bt, (0, t * T), (F, T)).T  # [T, F]
                wd = lax.dynamic_slice(word_all, (t * T,), (T,))
                flat = b.astype(jnp.int32) + offsets
                return hist.at[flat.reshape(-1)].add(
                    jnp.broadcast_to(wd[:, None], (T, F)).reshape(-1))

            hist = lax.fori_loop(0, nt, body, jnp.zeros((F * B,), jnp.int32))
            h = jnp.mod(hist, M)
            g = (hist - h) // M
            return jnp.stack([g, h]).reshape(2, F, B)

        def body(t, hist):
            b = lax.dynamic_slice(bt, (0, t * T), (F, T)).T      # [T, F]
            v = lax.dynamic_slice(vt, (0, t * T), (2, T)).T.astype(jnp.int32)
            flat = b.astype(jnp.int32) + offsets
            upd = jnp.broadcast_to(v[:, None, :], (T, F, 2))
            return hist.at[flat.reshape(-1)].add(upd.reshape(-1, 2))

        hist = lax.fori_loop(0, nt, body, jnp.zeros((F * B, 2), jnp.int32))
        return hist.reshape(F, B, 2).transpose(2, 0, 1)
    binned = binned_t.T                                    # [n, F]
    flat_idx = binned.astype(jnp.int32) + offsets          # [n, F]
    if M:
        word = (vals_t[0].astype(jnp.int32) * M
                + vals_t[1].astype(jnp.int32))             # [n]
        hist = jnp.zeros((F * B,), jnp.int32)
        hist = hist.at[flat_idx.reshape(-1)].add(
            jnp.broadcast_to(word[:, None], (n, F)).reshape(-1))
        h = jnp.mod(hist, M)
        g = (hist - h) // M
        return jnp.stack([g, h]).reshape(2, F, B)
    vals = vals_t.T.astype(jnp.int32)                      # [n, 2]
    hist = jnp.zeros((F * B, 2), jnp.int32)
    updates = jnp.broadcast_to(vals[:, None, :], (n, F, 2))
    hist = hist.at[flat_idx.reshape(-1)].add(updates.reshape(-1, 2))
    return hist.reshape(F, B, 2).transpose(2, 0, 1)


def build_histogram_int(
    binned_t: jax.Array,   # [F, n] feature-major
    gq: jax.Array,         # [n] int8 quantized grad (weights folded)
    hq: jax.Array,         # [n] int8 quantized hess
    member: jax.Array,     # [n] bool leaf membership
    num_bins: int,
    method: str = "auto",
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    levels: Optional[tuple] = None,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Masked integer histogram [2, F, B] i32 = per-bin (sum gq, sum hq)
    over ``member`` rows — the quantized twin of ``build_histogram``,
    dispatched through the same ``resolve_hist_method`` seam."""
    vals_t = _vals_t_int(gq, hq, member)
    method = resolve_hist_method("auto" if method == "fused" else method,
                                 quantized=True)
    if method == "matmul_int8":
        return histogram_matmul_int(binned_t, vals_t, num_bins, block_rows,
                                    tile_rows=tile_rows)
    if method == "scatter_int":
        return histogram_scatter_int(binned_t, vals_t, num_bins, levels,
                                     tile_rows=tile_rows)
    raise ValueError(f"unknown quantized histogram method {method!r}")


def compacted_histogram_int(
    binned_t: jax.Array, gq: jax.Array, hq: jax.Array,
    weights: jax.Array,    # [n] f32 bagging/GOSS weights (0 = excluded)
    member: jax.Array,     # [n] bool leaf membership
    num_bins: int,
    caps: list,
    method: str = "auto",
    levels: Optional[tuple] = None,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Integer twin of ``compacted_histogram``: gather the member rows
    into the smallest static capacity that fits, then run the integer
    kernel over ``cap`` rows instead of n."""
    F, n = binned_t.shape
    member = member & (weights > 0)
    count = jnp.sum(member)

    def branch(cap: int):
        def run():
            idx = jnp.nonzero(member, size=cap, fill_value=n)[0]
            valid = idx < n
            idxc = jnp.minimum(idx, n - 1)
            cols = jnp.take(binned_t, idxc, axis=1)        # [F, cap]
            g = jnp.take(gq, idxc)
            h = jnp.take(hq, idxc)
            return build_histogram_int(cols, g, h, valid, num_bins,
                                       method=method, levels=levels,
                                       tile_rows=tile_rows)
        return run

    if len(caps) == 1:
        return build_histogram_int(binned_t, gq, hq, member, num_bins,
                                   method=method, levels=levels,
                                   tile_rows=tile_rows)
    caps_arr = jnp.asarray(caps, jnp.int32)
    bucket = jnp.sum(caps_arr >= count) - 1
    return lax.switch(bucket, [branch(c) for c in caps])


def segment_histogram_int(
    binned_t: jax.Array, gq: jax.Array, hq: jax.Array,
    member: jax.Array,     # [n] bool; non-members land in the dummy slot
    slot: jax.Array,       # [n] i32 in [0, num_slots]
    num_slots: int,
    num_bins: int,
    levels: Optional[tuple] = None,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Per-slot integer histogram [S, 2, F, B] i32 (scatter formulation,
    CPU semantics path) — the quantized twin of ``segment_histogram``,
    with the same packed-word shrink as ``histogram_scatter_int`` and the
    same [tile, F] update-buffer streaming under ``tile_rows`` (exact:
    integer adds are associative)."""
    F, n = binned_t.shape
    B = num_bins
    S = num_slots
    slot_m = jnp.where(member, slot.astype(jnp.int32), S)
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    M = _pack_modulus(n, levels)
    T = resolve_tile_rows(tile_rows, n)
    if T is not None:
        nt = _pad_rows(n, T) // T
        n_pad = nt * T
        bt = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        st = jnp.pad(slot_m, (0, n_pad - n), constant_values=S)
        if M:
            word_all = jnp.pad(
                (gq.astype(jnp.int32) * M + hq.astype(jnp.int32))
                * member.astype(jnp.int32), (0, n_pad - n))

            def body(t, hist):
                b = lax.dynamic_slice(bt, (0, t * T), (F, T)).T  # [T, F]
                s = lax.dynamic_slice(st, (t * T,), (T,))
                wd = lax.dynamic_slice(word_all, (t * T,), (T,))
                flat = (s[:, None] * (F * B) + b.astype(jnp.int32)
                        + offsets)
                return hist.at[flat.reshape(-1)].add(
                    jnp.broadcast_to(wd[:, None], (T, F)).reshape(-1))

            hist = lax.fori_loop(0, nt, body,
                                 jnp.zeros(((S + 1) * F * B,), jnp.int32))
            h = jnp.mod(hist, M)
            g = (hist - h) // M
            return jnp.stack([g, h]).reshape(2, S + 1, F, B).transpose(
                1, 0, 2, 3)[:S]
        vt = jnp.pad(_vals_t_int(gq, hq, member), ((0, 0), (0, n_pad - n)))

        def body(t, hist):
            b = lax.dynamic_slice(bt, (0, t * T), (F, T)).T      # [T, F]
            s = lax.dynamic_slice(st, (t * T,), (T,))
            v = lax.dynamic_slice(vt, (0, t * T), (2, T)).T.astype(jnp.int32)
            flat = s[:, None] * (F * B) + b.astype(jnp.int32) + offsets
            upd = jnp.broadcast_to(v[:, None, :], (T, F, 2))
            return hist.at[flat.reshape(-1)].add(upd.reshape(-1, 2))

        hist = lax.fori_loop(0, nt, body,
                             jnp.zeros(((S + 1) * F * B, 2), jnp.int32))
        return hist.reshape(S + 1, F, B, 2)[:S].transpose(0, 3, 1, 2)
    binned = binned_t.T
    flat = (slot_m[:, None] * (F * B)
            + binned.astype(jnp.int32) + offsets)          # [n, F]
    if M:
        word = (gq.astype(jnp.int32) * M + hq.astype(jnp.int32)) \
            * member.astype(jnp.int32)
        hist = jnp.zeros(((S + 1) * F * B,), jnp.int32)
        hist = hist.at[flat.reshape(-1)].add(
            jnp.broadcast_to(word[:, None], (n, F)).reshape(-1))
        h = jnp.mod(hist, M)
        g = (hist - h) // M
        return jnp.stack([g, h]).reshape(2, S + 1, F, B).transpose(
            1, 0, 2, 3)[:S]
    vals = _vals_t_int(gq, hq, member).T.astype(jnp.int32)  # [n, 2]
    hist = jnp.zeros(((S + 1) * F * B, 2), jnp.int32)
    updates = jnp.broadcast_to(vals[:, None, :], (n, F, 2))
    hist = hist.at[flat.reshape(-1)].add(updates.reshape(-1, 2))
    return hist.reshape(S + 1, F, B, 2)[:S].transpose(0, 3, 1, 2)


def pack_cols_u32_quant(binned_t: jax.Array, gq: jax.Array, hq: jax.Array,
                        member: jax.Array):
    """Quantized twin of ``pack_cols_u32``: bins pack 4-per-u32 as before,
    and the THREE f32 value words collapse into ONE
    (``(gq+128) | hq<<8 | member<<16``) — the arena's single fused gather
    moves Wb+1 words per row instead of Wb+3."""
    F, n = binned_t.shape
    if binned_t.dtype != jnp.uint8:
        return None, 0          # u16 bins (max_bin > 256): no packing
    Wb = (F + 3) // 4
    pad = Wb * 4 - F
    bt = jnp.pad(binned_t, ((0, pad), (0, 0))) if pad else binned_t
    b32 = bt.astype(jnp.uint32).reshape(Wb, 4, n)
    bin_words = (b32[:, 0] | (b32[:, 1] << 8)
                 | (b32[:, 2] << 16) | (b32[:, 3] << 24))   # [Wb, n]
    val_word = ((gq.astype(jnp.int32) + 128).astype(jnp.uint32)
                | (hq.astype(jnp.uint32) << 8)
                | (member.astype(jnp.uint32) << 16))        # [1, n]
    return jnp.concatenate([bin_words, val_word[None, :]], axis=0), Wb


def segment_histogram_sorted_int(
    binned_t: jax.Array,   # [F, n] uint8/16 feature-major
    gq: jax.Array,         # [n] int8
    hq: jax.Array,         # [n] int8
    slot: jax.Array,       # [n] i32 in [0, num_slots]; dummies pre-slotted
    num_slots: int,
    num_bins: int,
    block_rows: int = 1024,
    caps: Optional[list] = None,
    packed: Optional[tuple] = None,    # pack_cols_u32_quant output
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Integer sorted-arena segment histogram: same sort + block-aligned
    arena as ``segment_histogram_sorted`` but the per-block one-hot
    matmul runs int8 -> int32 and the block->slot fold accumulates exact
    int32 inside the block scan (a slot-fold matmul would lose integer
    exactness past 2^24).  ``tile_rows`` switches the record gathers
    from one hoisted whole-arena gather to per-block in-loop gathers —
    O(block) transients, identical values.  Returns [S, 2, F, B] i32."""
    F, n = binned_t.shape
    B = num_bins
    S = num_slots
    if caps is None:
        caps = [n]

    if n < (1 << 24) and num_slots < 256:
        key = ((slot.astype(jnp.uint32) << 24)
               | jnp.arange(n, dtype=jnp.uint32))
        skey = lax.sort(key)
        sorted_slot = (skey >> 24).astype(jnp.int32)
        order = (skey & jnp.uint32(0x00FFFFFF)).astype(jnp.int32)
    else:
        row_ids = jnp.arange(n, dtype=jnp.int32)
        sorted_slot, order = lax.sort((slot, row_ids), is_stable=True,
                                      num_keys=1)
    bounds = jnp.searchsorted(sorted_slot,
                              jnp.arange(S + 1, dtype=sorted_slot.dtype))
    row_start = bounds[:S].astype(jnp.int32)
    counts = (bounds[1:] - bounds[:S]).astype(jnp.int32)

    iota = jnp.arange(B, dtype=binned_t.dtype)

    def arena(cap: int):
        C = max(128, min(block_rows,
                         1 << max(0, (max(cap, 1) // (4 * max(S, 1))
                                      ).bit_length() - 1)))
        NB = _pad_rows(max(cap, 1), C) // C + S

        def run():
            nblk = (counts + C - 1) // C
            blk_end = jnp.cumsum(nblk)
            blk_start = (blk_end - nblk).astype(jnp.int32)
            j_idx = jnp.arange(NB, dtype=blk_end.dtype)
            blk_slot = jnp.searchsorted(blk_end, j_idx,
                                        side="right").astype(jnp.int32)
            blk_slot = jnp.minimum(blk_slot, S)

            q = jnp.arange(NB * C, dtype=jnp.int32)
            s_of = blk_slot[q // C]
            s_c = jnp.minimum(s_of, S - 1)
            o = q - blk_start[s_c] * C
            valid = (s_of < S) & (o < counts[s_c])
            src_sorted = jnp.minimum(row_start[s_c] + o, n - 1)
            src = order[src_sorted]

            def block_partial(rows, vals):
                """[F, C] bins x [2, C] int8 vals -> [2, F*B] i32."""
                onehot2d = (rows.T[:, :, None] == iota.astype(rows.dtype)
                            ).astype(jnp.int8).reshape(C, F * B)
                return lax.dot(vals, onehot2d,
                               preferred_element_type=jnp.int32)

            use_packed = packed is not None and packed[0] is not None

            def part_from_packed(blk_rec, vm):
                Wb = packed[1]
                bw = blk_rec[:Wb]                       # [Wb, C] u32
                rows = jnp.concatenate(
                    [((bw >> (8 * j)) & 0xFF) for j in range(4)],
                    axis=0).reshape(4, Wb, C).transpose(
                        1, 0, 2).reshape(Wb * 4, C)[:F]   # [F, C]
                vw = blk_rec[Wb]                        # [C] u32
                g = (vw & 0xFF).astype(jnp.int32) - 128
                h = ((vw >> 8) & 0xFF).astype(jnp.int32)
                m = ((vw >> 16) & 1).astype(jnp.int32)
                sel = vm[0] & (m == 1)
                vals = jnp.where(sel, jnp.stack([g, h]), 0).astype(jnp.int8)
                return block_partial(rows.astype(jnp.int32), vals)

            def part_from_raw(cols, g, h, vm):
                vt = jnp.stack([jnp.where(vm, g, 0),
                                jnp.where(vm, h, 0)]).astype(jnp.int8)
                return block_partial(cols, vt)

            # blocks -> slots: exact int32 accumulation inside the scan
            # (shared by the hoisted and in-loop gather modes)
            acc0 = jnp.zeros((S + 1, 2 * F * B), jnp.int32)
            j_arange = jnp.arange(NB, dtype=jnp.int32)

            if resolve_tile_rows(tile_rows, n) is None:
                if use_packed:
                    words_t, Wb = packed
                    rec = jnp.take(words_t, src, axis=1)  # [Wb+1, NBC] u32
                    recb = rec.reshape(Wb + 1, NB, C).transpose(1, 0, 2)
                    vmask = valid.reshape(NB, 1, C)

                    def body(acc, xs):
                        j, blk_rec, vm = xs
                        return acc.at[blk_slot[j]].add(
                            part_from_packed(blk_rec, vm).reshape(-1)), None

                    acc, _ = lax.scan(body, acc0, (j_arange, recb, vmask))
                else:
                    cols = jnp.take(binned_t, src, axis=1)  # [F, NBC]
                    g = jnp.take(gq, src)
                    h = jnp.take(hq, src)
                    colsb = cols.reshape(F, NB, C).transpose(1, 0, 2)
                    gb = g.reshape(NB, C)
                    hb = h.reshape(NB, C)
                    vmask = valid.reshape(NB, C)

                    def body(acc, xs):
                        j, b, gg, hh, vm = xs
                        return acc.at[blk_slot[j]].add(
                            part_from_raw(b, gg, hh, vm).reshape(-1)), None

                    acc, _ = lax.scan(body, acc0,
                                      (j_arange, colsb, gb, hb, vmask))
            else:
                def body(acc, j):
                    sb = lax.dynamic_slice(src, (j * C,), (C,))
                    vm = lax.dynamic_slice(valid, (j * C,), (C,))
                    if use_packed:
                        rec = jnp.take(packed[0], sb, axis=1)  # [Wb+1, C]
                        part = part_from_packed(rec, vm[None, :])
                    else:
                        cols = jnp.take(binned_t, sb, axis=1)  # [F, C]
                        part = part_from_raw(cols, jnp.take(gq, sb),
                                             jnp.take(hq, sb), vm)
                    return acc.at[blk_slot[j]].add(part.reshape(-1)), None

                acc, _ = lax.scan(body, acc0, j_arange)
            return acc[:S].reshape(S, 2, F, B)
        return run

    if len(caps) == 1:
        return arena(caps[0])()
    total = bounds[S].astype(jnp.int32)
    caps_arr = jnp.asarray(caps, jnp.int32)
    bucket = jnp.sum(caps_arr >= total) - 1
    return lax.switch(bucket, [arena(c) for c in caps])


# 2 int channels instead of 3 f32: 2 * 64 = 128 rows fill the MXU tile,
# so the quantized expanded pass covers 64 live slots for the cycles the
# f32 path spends on 42
_EXPAND_SLOTS_QUANT = 64


def segment_histogram_expanded_int(
    binned_t: jax.Array,   # [F, n] feature-major
    gq: jax.Array,
    hq: jax.Array,
    member: jax.Array,     # [n] bool
    slot: jax.Array,       # [n] i32; values >= live_cap contribute nothing
    num_bins: int,
    live_cap: int = _EXPAND_SLOTS_QUANT,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Integer slot-expanded full-matrix pass: LHS [2*live_cap, C] int8
    (row j*cap+s carries vals[j] where slot == s), one s8 MXU tile per
    block.  Returns [live_cap, 2, F, B] i32."""
    F, n = binned_t.shape
    B = num_bins
    SE = live_cap
    block_rows = _tile_block(block_rows, resolve_tile_rows(tile_rows, n))
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    vals_t = _vals_t_int(gq, hq, member)
    slot_i = slot.astype(jnp.int32)
    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)))
        vals_t = jnp.pad(vals_t, ((0, 0), (0, n_pad - n)))
        slot_i = jnp.pad(slot_i, (0, n_pad - n), constant_values=SE)
    iota_b = jnp.arange(B, dtype=binned_t.dtype)
    iota_s = jnp.arange(SE, dtype=jnp.int32)
    C = block_rows

    def body(acc, i):
        b = lax.dynamic_slice(binned_t, (0, i * C), (F, C))   # [F, C]
        v = lax.dynamic_slice(vals_t, (0, i * C), (2, C))     # [2, C]
        sl = lax.dynamic_slice(slot_i, (i * C,), (C,))        # [C]
        oh_s = (sl[None, :] == iota_s[:, None]).astype(jnp.int8)  # [SE, C]
        lhs = (v[:, None, :] * oh_s[None, :, :]).reshape(2 * SE, C)
        onehot2d = (b.T[:, :, None] == iota_b).astype(jnp.int8).reshape(
            C, F * B)
        part = lax.dot(lhs, onehot2d, preferred_element_type=jnp.int32)
        return acc + part, None

    init = jnp.zeros((2 * SE, F * B), dtype=jnp.int32)
    acc, _ = lax.scan(body, init, jnp.arange(nb, dtype=jnp.int32))
    return acc.reshape(2, SE, F, B).transpose(1, 0, 2, 3)


def compacted_segment_histogram_int(
    binned_t: jax.Array,   # [F, n] feature-major
    gq: jax.Array,
    hq: jax.Array,
    weights: jax.Array,    # [n] f32 (0 = excluded)
    slot: jax.Array,       # [n] i32 in [0, num_slots]
    num_slots: int,
    num_bins: int,
    caps: list,
    num_live: Optional[jax.Array] = None,
    packed: Optional[tuple] = None,     # pack_cols_u32_quant output
    levels: Optional[tuple] = None,
    tile_rows: Optional[int] = None,
) -> jax.Array:
    """Integer twin of ``compacted_segment_histogram`` with the same
    backend dispatch: sorted int arena / expanded int pass on
    accelerators (LGBM_TPU_SEGHIST overrides), packed scatter with
    nonzero compaction on CPU.  Returns [S, 2, F, B] i32."""
    F, n = binned_t.shape
    member = weights > 0
    if use_sorted_seghist():
        slot_w = jnp.where(member, slot, num_slots)

        def arena_path(_):
            return segment_histogram_sorted_int(
                binned_t, gq, hq, slot_w, num_slots, num_bins,
                caps=caps, packed=packed, tile_rows=tile_rows)

        small_enabled = os.environ.get("LGBM_TPU_SMALL_ROUNDS") != "0"
        if num_live is None or num_slots <= _SMALL_ROUND_SLOTS \
                or not small_enabled:
            return arena_path(None)
        se = min(_EXPAND_SLOTS_QUANT, num_slots)

        def expanded_path(_):
            hist = segment_histogram_expanded_int(
                binned_t, gq, hq, member, slot_w, num_bins, live_cap=se,
                tile_rows=tile_rows)
            if num_slots > se:
                hist = jnp.concatenate(
                    [hist, jnp.zeros((num_slots - se, 2, F, num_bins),
                                     jnp.int32)], axis=0)
            return hist

        return lax.cond(num_live <= se, expanded_path, arena_path, None)

    in_play = (slot < num_slots) & member
    count = jnp.sum(in_play)

    def branch(cap: int):
        def run():
            idx = jnp.nonzero(in_play, size=cap, fill_value=n)[0]
            valid = idx < n
            idxc = jnp.minimum(idx, n - 1)
            cols = jnp.take(binned_t, idxc, axis=1)
            g = jnp.take(gq, idxc)
            h = jnp.take(hq, idxc)
            s = jnp.where(valid, jnp.take(slot, idxc), num_slots)
            return segment_histogram_int(cols, g, h, valid, s, num_slots,
                                         num_bins, levels=levels,
                                         tile_rows=tile_rows)
        return run

    if len(caps) == 1:
        return segment_histogram_int(binned_t, gq, hq, in_play, slot,
                                     num_slots, num_bins, levels=levels,
                                     tile_rows=tile_rows)
    caps_arr = jnp.asarray(caps, jnp.int32)
    bucket = jnp.sum(caps_arr >= count) - 1
    return lax.switch(bucket, [branch(c) for c in caps])
