"""On-device gradient/hessian histogram construction.

TPU-native replacement for LightGBM's histogram kernels
(reference: src/io/dense_bin.hpp:97 ConstructHistogramInner — CPU scatter-add;
src/treelearner/ocl/histogram256.cl:317 — GPU atomic scatter).

Design inversion for the MXU: instead of scatter-add (random-access, serializes
on TPU), the histogram is a **one-hot matmul**: for a block of rows build the
0/1 matrix ``onehot[C, F*B]`` (row r has a 1 at column f*B + bin(r, f)) in
bfloat16 (exact for 0/1) and compute ``vals.T @ onehot`` with
``vals = mask * [grad, hess, 1]`` — a [4, C] x [C, F*B] matmul accumulated in
float32 over row blocks.  This keeps the hot loop on the systolic array at
~100% HBM streaming rate instead of scalar scatter.  Leaf membership is folded
into ``mask``, which replaces the reference's ordered-gradient gather
(src/io/dataset.cpp:1318-1333) with a branch-free masked pass.

A scatter-based variant is kept for CPU testing / tiny inputs; `auto` probes
are selected at trace time by platform.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# rows per block of the one-hot matmul; 8 sublanes * 128 lanes friendly
_DEFAULT_BLOCK_ROWS = 4096


def _pad_rows(n: int, block: int) -> int:
    return (n + block - 1) // block * block


def histogram_matmul(
    binned: jax.Array,   # [n, F] uint8/uint16/int32
    vals: jax.Array,     # [n, 3] f32 rows already masked: (g, h, 1)*mask
    num_bins: int,       # padded bin axis B (static)
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Histogram via one-hot matmul over row blocks. Returns [F, B, 3] f32."""
    n, F = binned.shape
    B = num_bins
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    if n_pad != n:
        binned = jnp.pad(binned, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))
    binned_blocks = binned.reshape(nb, block_rows, F)
    vals_blocks = vals.reshape(nb, block_rows, 3)
    iota = jnp.arange(B, dtype=binned.dtype)

    def body(acc, blk):
        b, v = blk
        onehot = (b[:, :, None] == iota).astype(jnp.bfloat16)  # [C, F, B]
        onehot2d = onehot.reshape(block_rows, F * B)
        # [3, C] @ [C, F*B] -> [3, F*B], f32 accumulate
        part = jax.lax.dot(
            v.astype(jnp.bfloat16).T, onehot2d,
            precision=lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        )
        return acc + part, None

    init = jnp.zeros((3, F * B), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, (binned_blocks, vals_blocks))
    return acc.reshape(3, F, B).transpose(1, 2, 0)


def histogram_matmul_f32(
    binned: jax.Array, vals: jax.Array, num_bins: int,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Like histogram_matmul but f32 one-hot (exact grads; ~2x slower MXU)."""
    n, F = binned.shape
    B = num_bins
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    if n_pad != n:
        binned = jnp.pad(binned, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))
    binned_blocks = binned.reshape(nb, block_rows, F)
    vals_blocks = vals.reshape(nb, block_rows, 3)
    iota = jnp.arange(B, dtype=binned.dtype)

    def body(acc, blk):
        b, v = blk
        onehot = (b[:, :, None] == iota).astype(jnp.float32).reshape(block_rows, F * B)
        part = jax.lax.dot(v.T, onehot, preferred_element_type=jnp.float32)
        return acc + part, None

    init = jnp.zeros((3, F * B), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, (binned_blocks, vals_blocks))
    return acc.reshape(3, F, B).transpose(1, 2, 0)


def histogram_scatter(
    binned: jax.Array, vals: jax.Array, num_bins: int,
) -> jax.Array:
    """Scatter-add histogram (XLA scatter). Reference semantics check path."""
    n, F = binned.shape
    B = num_bins
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    flat_idx = binned.astype(jnp.int32) + offsets          # [n, F]
    hist = jnp.zeros((F * B, 3), dtype=jnp.float32)
    # vals broadcast across features: updates [n, F, 3]
    updates = jnp.broadcast_to(vals[:, None, :], (n, F, 3))
    hist = hist.at[flat_idx.reshape(-1)].add(updates.reshape(-1, 3))
    return hist.reshape(F, B, 3)


def build_histogram(
    binned: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,
    num_bins: int,
    method: str = "auto",
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Masked histogram [F, B, 3] = sum over rows with mask of (g, h, 1).

    ``mask`` is f32 and may carry bagging weights; leaf membership is encoded
    by zeroing non-member rows.
    """
    vals = jnp.stack([grad, hess, jnp.ones_like(grad)], axis=1) * mask[:, None]
    if method == "auto":
        platform = jax.default_backend()
        method = "matmul" if platform in ("tpu", "axon") else "scatter"
    if method == "matmul":
        return histogram_matmul(binned, vals, num_bins, block_rows)
    if method == "matmul_f32":
        return histogram_matmul_f32(binned, vals, num_bins, block_rows)
    if method == "scatter":
        return histogram_scatter(binned, vals, num_bins)
    raise ValueError(f"unknown histogram method {method!r}")


def capacity_schedule(n: int, min_cap: int = _DEFAULT_BLOCK_ROWS) -> list:
    """Descending power-of-two-ish capacities n, n/2, ... >= min_cap.

    Trace-time constants for the bucketed compaction below.  The smaller
    child of a split never exceeds n/2 rows, and leaf sizes shrink roughly
    geometrically in leaf-wise growth, so per-tree histogram work drops from
    O(n * num_leaves) (full masked pass per split) to ~O(n * log(num_leaves))
    — the same asymptotic the reference gets from per-leaf ordered gradients
    (src/io/dataset.cpp:1318-1333) without data-dependent shapes.
    """
    caps = []
    c = _pad_rows(n, min_cap)
    while c >= min_cap:
        caps.append(c)
        if c == min_cap:
            break
        c = _pad_rows((c + 1) // 2, min_cap)
        if caps and c == caps[-1]:
            break
    if not caps:
        caps = [_pad_rows(max(n, 1), min_cap)]
    return caps


def compacted_histogram(
    binned: jax.Array,       # [n, F]
    grad: jax.Array,         # [n]
    hess: jax.Array,         # [n]
    weights: jax.Array,      # [n] f32 bagging/GOSS weights
    member: jax.Array,       # [n] bool leaf membership
    num_bins: int,
    caps: list,              # static descending capacities from capacity_schedule
    method: str = "auto",
) -> jax.Array:
    """Masked histogram restricted to `member` rows via gather compaction.

    The member row-ids are compacted into the smallest static capacity that
    fits (lax.switch over precompiled bucket sizes); the histogram kernel
    then runs over `cap` rows instead of n.  Returns [F, B, 3] f32.
    """
    n, F = binned.shape
    # zero-weight rows (bagged-out / GOSS-dropped) contribute nothing, so
    # exclude them from compaction too — same result, tighter capacity
    member = member & (weights > 0)
    count = jnp.sum(member)

    def branch(cap: int):
        def run():
            idx = jnp.nonzero(member, size=cap, fill_value=n)[0]
            valid = idx < n
            idxc = jnp.minimum(idx, n - 1)
            rows = jnp.take(binned, idxc, axis=0)
            w = jnp.where(valid, jnp.take(weights, idxc), 0.0)
            g = jnp.take(grad, idxc)
            h = jnp.take(hess, idxc)
            return build_histogram(rows, g, h, w, num_bins, method=method)
        return run

    if len(caps) == 1:
        return build_histogram(binned, grad, hess,
                               weights * member, num_bins, method=method)
    caps_arr = jnp.asarray(caps, jnp.int32)
    # smallest capacity >= count (caps[0] >= n covers everything)
    bucket = jnp.sum(caps_arr >= count) - 1
    return lax.switch(bucket, [branch(c) for c in caps])


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """The subtraction trick: sibling = parent - child.

    reference: FeatureHistogram::Subtract (feature_histogram.hpp:79-84).
    """
    return parent - child
