"""On-device gradient/hessian histogram construction.

TPU-native replacement for LightGBM's histogram kernels
(reference: src/io/dense_bin.hpp:97 ConstructHistogramInner — CPU scatter-add;
src/treelearner/ocl/histogram256.cl:317 — GPU atomic scatter).

Design inversion for the MXU: instead of scatter-add (random-access, serializes
on TPU), the histogram is a **one-hot matmul**: for a block of rows build the
0/1 matrix ``onehot[C, F*B]`` (row r has a 1 at column f*B + bin(r, f)) in
bfloat16 (exact for 0/1) and compute ``vals.T @ onehot`` with
``vals = mask * [grad, hess, 1]`` — a [4, C] x [C, F*B] matmul accumulated in
float32 over row blocks.  This keeps the hot loop on the systolic array at
~100% HBM streaming rate instead of scalar scatter.  Leaf membership is folded
into ``mask``, which replaces the reference's ordered-gradient gather
(src/io/dataset.cpp:1318-1333) with a branch-free masked pass.

A scatter-based variant is kept for CPU testing / tiny inputs; `auto` probes
are selected at trace time by platform.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# rows per block of the one-hot matmul; 8 sublanes * 128 lanes friendly
_DEFAULT_BLOCK_ROWS = 4096


def _pad_rows(n: int, block: int) -> int:
    return (n + block - 1) // block * block


def histogram_matmul(
    binned: jax.Array,   # [n, F] uint8/uint16/int32
    vals: jax.Array,     # [n, 3] f32 rows already masked: (g, h, 1)*mask
    num_bins: int,       # padded bin axis B (static)
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Histogram via one-hot matmul over row blocks. Returns [F, B, 3] f32."""
    n, F = binned.shape
    B = num_bins
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    if n_pad != n:
        binned = jnp.pad(binned, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))
    binned_blocks = binned.reshape(nb, block_rows, F)
    vals_blocks = vals.reshape(nb, block_rows, 3)
    iota = jnp.arange(B, dtype=binned.dtype)

    def body(acc, blk):
        b, v = blk
        onehot = (b[:, :, None] == iota).astype(jnp.bfloat16)  # [C, F, B]
        onehot2d = onehot.reshape(block_rows, F * B)
        # [3, C] @ [C, F*B] -> [3, F*B], f32 accumulate
        part = jax.lax.dot(
            v.astype(jnp.bfloat16).T, onehot2d,
            precision=lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        )
        return acc + part, None

    init = jnp.zeros((3, F * B), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, (binned_blocks, vals_blocks))
    return acc.reshape(3, F, B).transpose(1, 2, 0)


def histogram_matmul_f32(
    binned: jax.Array, vals: jax.Array, num_bins: int,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Like histogram_matmul but f32 one-hot (exact grads; ~2x slower MXU)."""
    n, F = binned.shape
    B = num_bins
    nb = max(1, _pad_rows(n, block_rows) // block_rows)
    n_pad = nb * block_rows
    if n_pad != n:
        binned = jnp.pad(binned, ((0, n_pad - n), (0, 0)))
        vals = jnp.pad(vals, ((0, n_pad - n), (0, 0)))
    binned_blocks = binned.reshape(nb, block_rows, F)
    vals_blocks = vals.reshape(nb, block_rows, 3)
    iota = jnp.arange(B, dtype=binned.dtype)

    def body(acc, blk):
        b, v = blk
        onehot = (b[:, :, None] == iota).astype(jnp.float32).reshape(block_rows, F * B)
        part = jax.lax.dot(v.T, onehot, preferred_element_type=jnp.float32)
        return acc + part, None

    init = jnp.zeros((3, F * B), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, (binned_blocks, vals_blocks))
    return acc.reshape(3, F, B).transpose(1, 2, 0)


def histogram_scatter(
    binned: jax.Array, vals: jax.Array, num_bins: int,
) -> jax.Array:
    """Scatter-add histogram (XLA scatter). Reference semantics check path."""
    n, F = binned.shape
    B = num_bins
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    flat_idx = binned.astype(jnp.int32) + offsets          # [n, F]
    hist = jnp.zeros((F * B, 3), dtype=jnp.float32)
    # vals broadcast across features: updates [n, F, 3]
    updates = jnp.broadcast_to(vals[:, None, :], (n, F, 3))
    hist = hist.at[flat_idx.reshape(-1)].add(updates.reshape(-1, 3))
    return hist.reshape(F, B, 3)


def build_histogram(
    binned: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,
    num_bins: int,
    method: str = "auto",
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Masked histogram [F, B, 3] = sum over rows with mask of (g, h, 1).

    ``mask`` is f32 and may carry bagging weights; leaf membership is encoded
    by zeroing non-member rows.
    """
    vals = jnp.stack([grad, hess, jnp.ones_like(grad)], axis=1) * mask[:, None]
    if method == "auto":
        platform = jax.default_backend()
        method = "matmul" if platform in ("tpu", "axon") else "scatter"
    if method == "matmul":
        return histogram_matmul(binned, vals, num_bins, block_rows)
    if method == "matmul_f32":
        return histogram_matmul_f32(binned, vals, num_bins, block_rows)
    if method == "scatter":
        return histogram_scatter(binned, vals, num_bins)
    raise ValueError(f"unknown histogram method {method!r}")


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """The subtraction trick: sibling = parent - child.

    reference: FeatureHistogram::Subtract (feature_histogram.hpp:79-84).
    """
    return parent - child
