// Row-parallel stacked-forest predictor.
//
// reference: src/application/predictor.hpp:29 (OpenMP row-parallel
// Predictor) + include/LightGBM/tree.h:190 (inline scalar traversal) +
// src/boosting/prediction_early_stop.cpp (margin early stop).
//
// The Python package passes the StackedForest's padded arrays; each thread
// walks rows scalar root-to-leaf exactly like the reference — double
// thresholds, so results are bit-identical to the NumPy host path.
//
// Built by lightgbm_tpu/native/build.py via `g++ -O3 -fopenmp -shared`.

#include <cmath>
#include <cstdint>

namespace {

constexpr double kZeroThreshold = 1e-35;

struct Forest {
  int64_t T, I, L;
  const int32_t* split_feature;  // [T, I]
  const double* threshold;       // [T, I]
  const int32_t* left;           // [T, I]
  const int32_t* right;          // [T, I]
  const uint8_t* is_cat;         // [T, I]
  const uint8_t* default_left;   // [T, I]
  const int8_t* missing_type;    // [T, I]
  const double* leaf_value;      // [T, L]
  const int64_t* cat_offset;     // [T, I]
  const int32_t* cat_nwords;     // [T, I]
  const uint32_t* cat_words;     // flat
};

inline int32_t leaf_for_row(const Forest& f, int64_t t, const double* x) {
  int32_t node = 0;
  const int64_t base = t * f.I;
  while (node >= 0) {
    const int64_t j = base + node;
    const double fval = x[f.split_feature[j]];
    bool go_left;
    if (f.is_cat[j]) {
      const bool nan = std::isnan(fval);
      const int64_t iv = nan ? -1 : static_cast<int64_t>(fval);
      const int64_t nbits = static_cast<int64_t>(f.cat_nwords[j]) * 32;
      if (iv >= 0 && iv < nbits) {
        const uint32_t w = f.cat_words[f.cat_offset[j] + iv / 32];
        go_left = (w >> (iv % 32)) & 1u;
      } else {
        go_left = false;
      }
    } else {
      const int mt = f.missing_type[j];
      double fz = fval;
      bool nan = std::isnan(fval);
      if (mt != 2 && nan) { fz = 0.0; nan = false; }
      const bool missing = (mt == 1 && std::fabs(fz) <= kZeroThreshold) ||
                           (mt == 2 && nan);
      go_left = missing ? (f.default_left[j] != 0) : (fz <= f.threshold[j]);
    }
    node = go_left ? f.left[j] : f.right[j];
  }
  return ~node;
}

}  // namespace

extern "C" {

// out: [K, n] accumulated raw scores (tree t adds into class t % K).
// leaf_out: optional [n, T] leaf indices (pass nullptr to skip).
// early_stop_kind: 0 none, 1 binary (|2*raw|>margin), 2 multiclass
// (top-2 gap > margin), checked every `freq` iterations as in the
// reference single-row predictor.
void lgbt_predict(const double* X, int64_t n, int64_t F,
                  int64_t T, int64_t I, int64_t L,
                  const int32_t* split_feature, const double* threshold,
                  const int32_t* left, const int32_t* right,
                  const uint8_t* is_cat, const uint8_t* default_left,
                  const int8_t* missing_type, const double* leaf_value,
                  const int64_t* cat_offset, const int32_t* cat_nwords,
                  const uint32_t* cat_words,
                  int64_t K, int early_stop_kind, int freq, double margin,
                  double* out, int32_t* leaf_out) {
  const Forest f{T, I, L, split_feature, threshold, left, right,
                 is_cat, default_left, missing_type, leaf_value,
                 cat_offset, cat_nwords, cat_words};
  const int64_t iters = (K > 0) ? T / K : 0;
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n; ++r) {
    const double* x = X + r * F;
    for (int64_t it = 0; it < iters; ++it) {
      for (int64_t k = 0; k < K; ++k) {
        const int64_t t = it * K + k;
        const int32_t leaf = leaf_for_row(f, t, x);
        if (leaf_out) leaf_out[r * T + t] = leaf;
        if (out) out[k * n + r] += leaf_value[t * L + leaf];
      }
      if (out && early_stop_kind != 0 && freq > 0 && (it + 1) % freq == 0 &&
          it + 1 < iters) {
        if (early_stop_kind == 1) {
          if (std::fabs(2.0 * out[r]) > margin) break;
        } else if (early_stop_kind == 2 && K >= 2) {
          double best = out[r], second = -1e300;
          for (int64_t k = 1; k < K; ++k) {
            const double v = out[k * n + r];
            if (v > best) { second = best; best = v; }
            else if (v > second) { second = v; }
          }
          if (best - second > margin) break;
        }
      }
    }
  }
}

}  // extern "C"
