"""Build/load the native predictor shared library.

The C++ sources next to this file are compiled once per interpreter
environment with ``g++ -O3 -fopenmp -shared -fPIC`` into
``<this dir>/_liblgbt.so`` (rebuilt when any source is newer).  Loading is
ctypes — no pybind11 in this image (see repo environment notes); the ABI is
plain C (extern "C" + raw pointers), mirroring how the reference exposes
lib_lightgbm.so to its Python package.

Everything degrades gracefully: if g++ or OpenMP is unavailable the callers
fall back to the pure-NumPy paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform as _platform
import subprocess
import threading


def _host_tag() -> str:
    """Short host/arch fingerprint for the cached .so filename.

    The library is compiled with -march=native, so a binary baked into a
    container image or shared filesystem can SIGILL on a host with a
    different CPU; keying the filename on the CPU identity forces a
    rebuild there instead.
    """
    bits = [_platform.machine(), _platform.system()]
    model = flags = None
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if model is None and line.startswith("model name"):
                    model = line.strip()
                elif flags is None and line.startswith("flags"):
                    flags = line.strip()   # ISA flags catch hypervisor masks
                if model is not None and flags is not None:
                    break
    except OSError:
        pass
    bits.extend(b for b in (model, flags) if b)
    return hashlib.sha1("|".join(bits).encode()).hexdigest()[:12]


_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, f"_liblgbt_{_host_tag()}.so")
_SOURCES = ["predictor.cpp", "findbin.cpp"]

_lock = threading.Lock()
_lib = None
_tried = False


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_DIR, s)) > lib_mtime for s in _SOURCES)


def _build() -> None:
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    tmp = _LIB_PATH + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           "-std=c++17", "-o", tmp] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_native_lib():
    """The loaded CDLL, or None if the toolchain is unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _needs_build():
                _build()
            _lib = ctypes.CDLL(_LIB_PATH)
        except Exception:
            _lib = None
        return _lib
