// Native GreedyFindBin: the equal-ish-frequency boundary search over
// sorted distinct values (reference algorithm: src/io/bin.cpp:77-155 —
// re-implemented from this package's Python mirror in binning.py, which
// the tests pin bit-for-bit against the reference's bins).
//
// This is the last Python-loop hot spot of Dataset.construct: the greedy
// scan is inherently sequential over up to bin_construct_sample_cnt
// distinct values per feature (~0.3 s/feature in CPython, ~microseconds
// here).  Exposed as plain C for ctypes (no pybind11 in this image).
//
// Float semantics mirrored exactly:
//  - bound = nextafter((upper + lower) / 2, +inf)
//  - dedup: CheckDoubleEqualOrdered(a, b) == (b <= nextafter(a, +inf))
//  - the "half mean bin" trigger compares at DOUBLE precision (the
//    reference's std::max(1.0, mean_bin_size * 0.5f) promotes:
//    double * float -> double)

#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

// Returns the number of bounds written to out (out has capacity max_bin;
// the +inf terminator IS written and counted).
int lgbt_greedy_find_bin(const double* distinct_values,
                         const int64_t* counts,
                         int64_t num_distinct,
                         int max_bin,
                         int64_t total_cnt,
                         int min_data_in_bin,
                         double* out) {
    int n_out = 0;
    if (max_bin <= 0) return 0;
    if (num_distinct == 0) {
        out[n_out++] = HUGE_VAL;
        return n_out;
    }
    if (num_distinct <= max_bin) {
        int64_t cur_cnt_inbin = 0;
        for (int64_t i = 0; i < num_distinct - 1; ++i) {
            cur_cnt_inbin += counts[i];
            if (cur_cnt_inbin >= min_data_in_bin) {
                double val = std::nextafter(
                    (distinct_values[i] + distinct_values[i + 1]) / 2.0,
                    HUGE_VAL);
                if (n_out == 0 ||
                    !(val <= std::nextafter(out[n_out - 1], HUGE_VAL))) {
                    out[n_out++] = val;
                    cur_cnt_inbin = 0;
                }
            }
        }
        out[n_out++] = HUGE_VAL;
        return n_out;
    }

    if (min_data_in_bin > 0) {
        int cap = (int)(total_cnt / min_data_in_bin);
        if (max_bin > cap) max_bin = cap;
        if (max_bin < 1) max_bin = 1;
    }
    double mean_bin_size = (double)total_cnt / max_bin;

    int64_t rest_bin_cnt = max_bin;
    int64_t rest_sample_cnt = total_cnt;
    std::vector<char> is_big(num_distinct);
    for (int64_t i = 0; i < num_distinct; ++i) {
        is_big[i] = counts[i] >= mean_bin_size;
        if (is_big[i]) {
            --rest_bin_cnt;
            rest_sample_cnt -= counts[i];
        }
    }
    mean_bin_size = rest_bin_cnt > 0
        ? (double)rest_sample_cnt / rest_bin_cnt : HUGE_VAL;

    std::vector<double> upper(max_bin, HUGE_VAL), lower(max_bin, HUGE_VAL);
    int bin_cnt = 0;
    lower[0] = distinct_values[0];
    int64_t cur_cnt_inbin = 0;
    for (int64_t i = 0; i < num_distinct - 1; ++i) {
        if (!is_big[i]) rest_sample_cnt -= counts[i];
        cur_cnt_inbin += counts[i];
        // the reference's std::max(1.0, mean_bin_size * 0.5f) promotes
        // to DOUBLE (double * float -> double), so the half-mean compare
        // runs at double precision — mirrored by binning.py
        double half = mean_bin_size * 0.5;
        if (half < 1.0) half = 1.0;
        if (is_big[i] || (double)cur_cnt_inbin >= mean_bin_size ||
            (is_big[i + 1] && (double)cur_cnt_inbin >= half)) {
            upper[bin_cnt] = distinct_values[i];
            ++bin_cnt;
            lower[bin_cnt] = distinct_values[i + 1];
            if (bin_cnt >= max_bin - 1) break;
            cur_cnt_inbin = 0;
            if (!is_big[i]) {
                --rest_bin_cnt;
                mean_bin_size = rest_bin_cnt > 0
                    ? (double)rest_sample_cnt / rest_bin_cnt : HUGE_VAL;
            }
        }
    }
    ++bin_cnt;
    for (int i = 0; i < bin_cnt - 1; ++i) {
        double val = std::nextafter((upper[i] + lower[i + 1]) / 2.0,
                                    HUGE_VAL);
        if (n_out == 0 ||
            !(val <= std::nextafter(out[n_out - 1], HUGE_VAL))) {
            out[n_out++] = val;
        }
    }
    out[n_out++] = HUGE_VAL;
    return n_out;
}

}  // extern "C"
