"""Plotting utilities (importance / metric / tree).

reference: python-package/lightgbm/plotting.py (628 LoC): plot_importance,
plot_metric, plot_tree, plot_split_value_histogram, create_tree_digraph.
matplotlib/graphviz are imported lazily.
"""

from __future__ import annotations

import numpy as np


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt  # noqa: F401
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("You must install matplotlib for plotting") from e


def plot_importance(booster, ax=None, height: float = 0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    grid=True, precision=3, **kwargs):
    """reference: plotting.py plot_importance."""
    plt = _check_matplotlib()
    from .basic import Booster
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    importance = booster.feature_importance(importance_type)
    feature_name = booster.feature_name()
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("cannot plot importance with no nonzero feature")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, f"{x:.{precision}g}" if isinstance(x, float) else str(x),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None, xlim=None,
                ylim=None, title="Metric during training", xlabel="Iterations",
                ylabel="auto", figsize=None, grid=True):
    """reference: plotting.py plot_metric."""
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):
        eval_results = booster.evals_result_
    else:
        raise TypeError("booster must be dict or LGBMModel with evals_result_")
    if not eval_results:
        raise ValueError("eval results cannot be empty")
    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    msets = eval_results[dataset_names[0]]
    if metric is None:
        metric = list(msets.keys())[0]
    for name in dataset_names:
        results = eval_results[name][metric]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, grid=True, **kwargs):
    plt = _check_matplotlib()
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    hist, edges = booster.get_split_value_histogram(feature, bins)
    if hist.sum() == 0:
        raise ValueError(f"Cannot plot split value histogram, "
                         f"because feature {feature} was not used in splitting")
    centers = (edges[:-1] + edges[1:]) / 2
    width = width_coef * (edges[1] - edges[0]) if len(edges) > 1 else 1.0
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ax.bar(centers, hist, width=width, **kwargs)
    if title:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@", "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: int = 3, **kwargs):
    """reference: plotting.py create_tree_digraph (graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError as e:  # pragma: no cover
        raise ImportError("You must install graphviz for plot_tree") from e
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    t = booster.models[tree_index]
    fnames = booster.feature_name()
    show_info = show_info or []
    graph = Digraph(**kwargs)

    def add(node, parent=None, decision=None):
        if node < 0:
            li = ~node
            name = f"leaf{li}"
            label = f"leaf {li}: {t.leaf_value[li]:.{precision}f}"
            if "leaf_count" in show_info and len(t.leaf_count) > li:
                label += f"\ncount: {int(t.leaf_count[li])}"
            graph.node(name, label=label)
        else:
            name = f"split{node}"
            label = f"{fnames[int(t.split_feature[node])]}"
            dt = int(t.decision_type[node])
            op = "==" if dt & 1 else "<="
            label += f" {op} {t.threshold[node]:.{precision}g}"
            if "split_gain" in show_info:
                label += f"\ngain: {t.split_gain[node]:.{precision}g}"
            if "internal_count" in show_info:
                label += f"\ncount: {int(t.internal_count[node])}"
            graph.node(name, label=label)
            add(int(t.left_child[node]), name, "yes")
            add(int(t.right_child[node]), name, "no")
        if parent is not None:
            graph.edge(parent, name, decision)
        return name

    add(0 if t.num_leaves > 1 else -1)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info=None, precision: int = 3, **kwargs):
    plt = _check_matplotlib()
    import io
    try:
        import matplotlib.image as mpimg
    except ImportError as e:  # pragma: no cover
        raise ImportError("matplotlib is required for plot_tree") from e
    graph = create_tree_digraph(booster, tree_index, show_info, precision, **kwargs)
    s = io.BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ax.imshow(img)
    ax.axis("off")
    return ax
