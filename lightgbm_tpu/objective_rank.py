"""Ranking objectives: LambdaRank-NDCG and RankXENDCG.

reference: src/objective/rank_objective.hpp — RankingObjective base (:48,
per-query parallel loop), LambdarankNDCG (:98, pairwise lambdas x deltaNDCG
with sigmoid table and optional normalization), RankXENDCG (:288).

TPU re-design of the per-query loop (SURVEY hard part (d)): queries are
**bucketed by padded size** (next power of two) at init; each bucket is a
dense [num_queries_in_bucket, Q] array of row indices with padding.  The
pairwise [Q, Q] lambda computation is vmapped over queries and chunked to
bound memory; results scatter-add back into the flat [n] gradient vector.
No sigmoid lookup table — the VPU computes exact sigmoids faster than a
gather would be.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .objectives import ObjectiveFunction

K_EPSILON = 1e-15
_MIN_BUCKET = 8
_PAIR_BUDGET = 1 << 22  # max elements per [chunk, Q, Q] intermediate


def _bucket_queries(qb: np.ndarray) -> Dict[int, np.ndarray]:
    """Group query ids by padded (next pow2) size. Returns {Q: query_ids}."""
    sizes = np.diff(qb)
    buckets: Dict[int, List[int]] = {}
    for q, s in enumerate(sizes):
        Q = _MIN_BUCKET
        while Q < s:
            Q *= 2
        buckets.setdefault(Q, []).append(q)
    return {Q: np.asarray(v, np.int64) for Q, v in buckets.items()}


class RankingObjective(ObjectiveFunction):
    need_group = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise RuntimeError("Ranking tasks require query information")
        self.qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.qb) - 1
        lbl = np.asarray(metadata.label, np.float64)
        self.buckets = _bucket_queries(self.qb)
        # per bucket: row indices [nq, Q] (n = padding), labels [nq, Q]
        self.bucket_data = {}
        n = num_data
        for Q, qids in self.buckets.items():
            idx = np.full((len(qids), Q), n, np.int32)   # n = padding slot
            for r, q in enumerate(qids):
                lo, hi = self.qb[q], self.qb[q + 1]
                idx[r, :hi - lo] = np.arange(lo, hi)
            labels = np.where(idx < n, lbl[np.minimum(idx, n - 1)], -1.0)
            self.bucket_data[Q] = (jnp.asarray(idx), jnp.asarray(labels, jnp.float32),
                                   qids)

    def get_gradients(self, score):
        n = self.num_data
        grad = jnp.zeros(n + 1, jnp.float32)
        hess = jnp.zeros(n + 1, jnp.float32)
        score_pad = jnp.concatenate([score, jnp.zeros(1, score.dtype)])
        for Q, (idx, labels, qids) in self.bucket_data.items():
            s = score_pad[idx]                    # [nq, Q]
            valid = idx < n
            g, h = self._query_gradients(Q, s, labels, valid, qids)
            grad = grad.at[idx.reshape(-1)].add(g.reshape(-1))
            hess = hess.at[idx.reshape(-1)].add(h.reshape(-1))
        grad, hess = grad[:n], hess[:n]
        if self.weight is not None:
            grad = grad * self.weight
            hess = hess * self.weight
        return grad, hess

    def _query_gradients(self, Q, s, labels, valid, qids):
        raise NotImplementedError


class LambdarankNDCG(RankingObjective):
    """reference: LambdarankNDCG (rank_objective.hpp:98)."""

    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        lg = list(config.label_gain)
        if not lg:
            lg = [float((1 << i) - 1) for i in range(31)]
        self.label_gain_np = np.asarray(lg, np.float64)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.int64)
        if lbl.min() < 0 or lbl.max() >= len(self.label_gain_np):
            raise ValueError("ranking label out of range of label_gain")
        # inverse max DCG at truncation level per query
        # (reference: rank_objective.hpp:124-132)
        inv = np.zeros(self.num_queries, np.float64)
        for q in range(self.num_queries):
            ls = np.sort(lbl[self.qb[q]:self.qb[q + 1]])[::-1][:self.truncation_level]
            dcg = (self.label_gain_np[ls] / np.log2(np.arange(len(ls)) + 2.0)).sum()
            inv[q] = 1.0 / dcg if dcg > 0 else 0.0
        self.inverse_max_dcgs = inv
        self.label_gain_j = jnp.asarray(self.label_gain_np, jnp.float32)

    def _query_gradients(self, Q, s, labels, valid, qids):
        inv_max_dcg = jnp.asarray(self.inverse_max_dcgs[qids], jnp.float32)
        sig = self.sigmoid
        norm = self.norm
        gain = self.label_gain_j[jnp.maximum(labels, 0.0).astype(jnp.int32)]
        gain = jnp.where(valid, gain, 0.0)

        def one_chunk(args):
            s_c, lbl_c, gain_c, valid_c, inv_c = args
            smask = jnp.where(valid_c, s_c, -jnp.inf)
            order = jnp.argsort(-smask, axis=1, stable=True)
            rank = jnp.argsort(order, axis=1, stable=True)      # [c, Q]
            disc = 1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0)
            nvalid = valid_c.sum(axis=1)
            best = jnp.max(smask, axis=1)
            worst = jnp.min(jnp.where(valid_c, s_c, jnp.inf), axis=1)
            # pair (i=high, j=low): label_i > label_j
            pair_valid = (lbl_c[:, :, None] > lbl_c[:, None, :]) & \
                valid_c[:, :, None] & valid_c[:, None, :]
            dcg_gap = gain_c[:, :, None] - gain_c[:, None, :]
            paired_disc = jnp.abs(disc[:, :, None] - disc[:, None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_c[:, None, None]
            ds = s_c[:, :, None] - s_c[:, None, :]
            if norm:
                has_range = (best != worst)[:, None, None]
                delta_ndcg = jnp.where(has_range,
                                       delta_ndcg / (0.01 + jnp.abs(ds)), delta_ndcg)
            p = 1.0 / (1.0 + jnp.exp(sig * ds))
            p_lambda = -sig * delta_ndcg * p            # negative
            p_hess = sig * sig * delta_ndcg * p * (1.0 - p)
            p_lambda = jnp.where(pair_valid, p_lambda, 0.0)
            p_hess = jnp.where(pair_valid, p_hess, 0.0)
            lam = p_lambda.sum(axis=2) - p_lambda.sum(axis=1)   # high minus low
            hes = p_hess.sum(axis=2) + p_hess.sum(axis=1)
            sum_lambdas = -2.0 * p_lambda.sum(axis=(1, 2))
            if norm:
                factor = jnp.where(sum_lambdas > 0,
                                   jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, K_EPSILON),
                                   1.0)
                lam = lam * factor[:, None]
                hes = hes * factor[:, None]
            del nvalid
            return lam, hes

        chunk = max(1, _PAIR_BUDGET // (Q * Q))
        nq = s.shape[0]
        pad = (-nq) % chunk
        def padq(x, fill=0.0):
            return jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1),
                           constant_values=fill)
        args = (padq(s), padq(labels, -1.0), padq(gain), padq(valid, False),
                padq(inv_max_dcg))
        args = jax.tree_util.tree_map(
            lambda x: x.reshape((nq + pad) // chunk, chunk, *x.shape[1:]), args)
        lam, hes = jax.lax.map(one_chunk, args)
        lam = lam.reshape(nq + pad, Q)[:nq]
        hes = hes.reshape(nq + pad, Q)[:nq]
        return lam, hes


class RankXENDCG(RankingObjective):
    """reference: RankXENDCG (rank_objective.hpp:288, arxiv 1911.09798)."""

    name = "rank_xendcg"

    def __init__(self, config: Config):
        super().__init__(config)
        self._key = jax.random.PRNGKey(config.objective_seed)

    def get_gradients(self, score):
        # fresh per-call randomness (reference: rands_[query].NextFloat())
        self._key, sub = jax.random.split(self._key)
        self._cur_key = sub
        return super().get_gradients(score)

    def _query_gradients(self, Q, s, labels, valid, qids):
        key = jax.random.fold_in(self._cur_key, Q)
        gammas = jax.random.uniform(key, s.shape)
        rho = jax.nn.softmax(jnp.where(valid, s, -jnp.inf), axis=1)
        rho = jnp.where(valid, rho, 0.0)
        phi = jnp.exp2(jnp.maximum(labels, 0.0)) - gammas
        phi = jnp.where(valid, phi, 0.0)
        sum_labels = jnp.maximum(phi.sum(axis=1, keepdims=True), K_EPSILON)
        l1 = jnp.where(valid, -phi / sum_labels + rho, 0.0)
        sum_l1 = l1.sum(axis=1, keepdims=True)
        denom = jnp.maximum(1.0 - rho, K_EPSILON)
        l2 = jnp.where(valid, (sum_l1 - l1) / denom, 0.0)
        sum_l2 = l2.sum(axis=1, keepdims=True)
        l3 = jnp.where(valid, (sum_l2 - l2) / denom, 0.0)
        cnt = valid.sum(axis=1, keepdims=True)
        lam_many = l1 + rho * l2 + rho * rho * l3
        lam = jnp.where(cnt <= 1, l1, lam_many)
        hes = rho * (1.0 - rho)
        return jnp.where(valid, lam, 0.0), jnp.where(valid, hes, 0.0)
