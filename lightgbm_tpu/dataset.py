"""Dataset: binned feature matrix + metadata, host construction, device views.

TPU-native redesign of LightGBM's Dataset / DatasetLoader / Metadata
(reference: include/LightGBM/dataset.h:41,333, src/io/dataset_loader.cpp:167,
src/io/metadata.cpp).  The key inversion vs the reference: instead of
per-feature-group Bin objects with sparse/dense variants and 4-bit packing,
the binned matrix is ONE dense row-major uint8 (or uint16) array
``[num_data, num_features]`` that is transferred once to HBM; histograms are
then built on-device over the whole matrix (see ops/histogram.py).  Sparse
inputs are densified at bin time — after binning, "sparse" just means the
most-frequent bin repeats, which costs nothing on the MXU path.
"""

from __future__ import annotations

import io
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .binning import BinMapper, BinType, MissingType

_BINARY_MAGIC = b"lgbm_tpu.dataset.v1\n"


def _as_2d(data) -> np.ndarray:
    """2-D view of the input WITHOUT materializing a float64 copy.

    Streaming construction (reference: the two-pass DatasetLoader never
    holds a dense double matrix either — SampleTextDataFromFile +
    ExtractFeaturesFromFile push row by row, dataset_loader.cpp:775,1101):
    binning walks one column at a time, so an 11M x 28 float32 input costs
    one float64 COLUMN of scratch (88 MB) instead of a 2.5 GB full copy.
    Non-float dtypes (ints, object) still need one up-front cast.
    """
    if hasattr(data, "values"):  # pandas
        data = data.values
    if isinstance(data, (list, tuple)) and data and all(
            isinstance(a, np.ndarray) for a in data):
        # list of row-chunk arrays (reference: list-of-numpy input,
        # basic.py __init_from_list_np2d)
        data = np.vstack([np.atleast_2d(a) for a in data])
    arr = np.asarray(data)
    if arr.ndim != 2:
        raise ValueError(f"data must be 2-D, got shape {arr.shape}")
    if arr.dtype not in (np.float32, np.float64):
        arr = arr.astype(np.float64)
    return arr


def _data_from_pandas(data, feature_name, categorical_feature,
                      pandas_categorical):
    """DataFrame -> float matrix with category columns as codes.

    reference: _data_from_pandas (python-package/lightgbm/basic.py:331):
    category-dtype columns map to their codes (-1/unseen -> NaN); the
    category VALUE lists (pandas_categorical) are recorded at train time
    and re-applied to valid/predict frames so codes align; 'auto'
    categorical_feature resolves to the NOT-ordered category columns
    (ordered categoricals stay ordinal/numeric).
    Returns (values, feature_name, categorical_feature, pandas_categorical).
    """
    if not (hasattr(data, "dtypes") and hasattr(data, "columns")):
        return data, feature_name, categorical_feature, pandas_categorical
    import pandas as pd
    if feature_name in ("auto", None):
        data = data.rename(columns=str)
    cat_cols = [str(c) for c in
                data.select_dtypes(include=["category"]).columns]
    cat_cols_not_ordered = [c for c in cat_cols
                            if not data[c].cat.ordered]
    if pandas_categorical is None:     # train dataset
        pandas_categorical = [list(data[c].cat.categories)
                              for c in cat_cols]
    else:
        if len(cat_cols) != len(pandas_categorical):
            raise ValueError(
                "train and valid dataset categorical_feature do not match.")
        for col, category in zip(cat_cols, pandas_categorical):
            if list(data[col].cat.categories) != list(category):
                data[col] = data[col].cat.set_categories(category)
    if cat_cols:
        data = data.copy()
        data[cat_cols] = (data[cat_cols]
                          .apply(lambda x: x.cat.codes)
                          .replace({-1: np.nan}))
    if categorical_feature is not None:
        if categorical_feature == "auto":
            categorical_feature = cat_cols_not_ordered
        else:
            categorical_feature = list(categorical_feature)
    if feature_name == "auto":
        feature_name = [str(c) for c in data.columns]
    values = data.values
    if values.dtype not in (np.float32, np.float64):
        values = values.astype(np.float32)
    return values, feature_name, categorical_feature, pandas_categorical


def _sample_indices(num_data: int, sample_cnt: int, seed: int) -> np.ndarray:
    if num_data <= sample_cnt:
        return np.arange(num_data)
    rng = np.random.RandomState(seed)
    return np.sort(rng.choice(num_data, size=sample_cnt, replace=False))


def _avoid_inf(value):
    """reference: Common::AvoidInf (utils/common.h:697-715), applied by
    Metadata::SetLabel/SetWeights/SetInitScore — NaN becomes 0 and
    infinities clamp to the type's sane maximum, so downstream math never
    sees NaN/Inf metadata."""
    a = np.asarray(value)
    if a.dtype.kind != "f":
        return a
    lim = 1e300 if a.dtype == np.float64 else np.finfo(a.dtype).max
    if np.isnan(a).any() or np.isinf(a).any():
        a = np.nan_to_num(a, nan=0.0, posinf=lim, neginf=-lim)
    return a


@dataclass
class Metadata:
    """Labels / weights / query boundaries / init scores.

    reference: include/LightGBM/dataset.h:41-249, src/io/metadata.cpp.
    """

    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries + 1]
    init_score: Optional[np.ndarray] = None

    def __setattr__(self, name, value):
        # every ingestion path (ctor, set_field, properties, binary load)
        # funnels through attribute assignment — sanitize centrally
        if name in ("label", "weight", "init_score") and value is not None:
            value = _avoid_inf(value)
        object.__setattr__(self, name, value)

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        if group is None:
            self.query_boundaries = None
            return
        g = np.asarray(group, dtype=np.int64)
        self.query_boundaries = np.concatenate([[0], np.cumsum(g)]).astype(np.int32)

    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def check(self, num_data: int) -> None:
        if self.label is not None and len(self.label) != num_data:
            raise ValueError(f"label length {len(self.label)} != num_data {num_data}")
        if self.weight is not None and len(self.weight) != num_data:
            raise ValueError("weight length mismatch")
        if self.query_boundaries is not None and self.query_boundaries[-1] != num_data:
            raise ValueError("sum of query group sizes != num_data")


class Dataset:
    """User-facing dataset; lazily constructed (binned) on first use.

    Mirrors the Python-side semantics of the reference's ``lightgbm.Dataset``
    (python-package/lightgbm/basic.py:730) with construction logic from
    DatasetLoader (src/io/dataset_loader.cpp:527 ConstructFromSampleData).
    """

    def __init__(
        self,
        data,
        label=None,
        reference: Optional["Dataset"] = None,
        weight=None,
        group=None,
        init_score=None,
        silent: bool = False,
        feature_name="auto",
        categorical_feature="auto",
        params: Optional[dict] = None,
        free_raw_data: bool = True,
    ):
        # positional order mirrors the reference Dataset.__init__
        # (python-package/lightgbm/basic.py:730) — callers pass reference/
        # weight/group positionally; ``silent`` accepted for compatibility
        self.params = dict(params or {})
        self.raw_data = data
        self.reference = reference
        self.free_raw_data = free_raw_data
        self.metadata = Metadata()
        if label is not None:
            self.metadata.label = np.asarray(label, dtype=np.float32).reshape(-1)
        if weight is not None:
            self.metadata.weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        if group is not None:
            self.metadata.set_group(group)
        if init_score is not None:
            self.metadata.init_score = np.asarray(init_score, dtype=np.float64)
        self._feature_name_param = feature_name
        self._categorical_feature_param = categorical_feature
        self.pandas_categorical = None      # category values per cat column
        # filled by construct():
        self.constructed = False
        self.bin_mappers: List[BinMapper] = []         # per ORIGINAL feature
        self.used_features: List[int] = []             # original idx of non-trivial features
        self.binned: Optional[np.ndarray] = None       # [n, F_used] uint8/uint16
        self.feature_names: List[str] = []
        self.num_data = 0
        self.num_total_features = 0

    # -- construction --------------------------------------------------------

    def construct(self) -> "Dataset":
        if self.constructed:
            return self
        if getattr(self, "_streaming", False):
            # name the first gap so an out-of-order loader sees WHERE its
            # coverage broke, not just a count
            missing = np.flatnonzero(~self._pushed)
            first = int(missing[0]) if len(missing) else 0
            raise RuntimeError(
                f"streaming dataset load incomplete: "
                f"{int(self._pushed.sum())}/{self.num_data} rows pushed "
                f"(first unpushed row: {first})")
        from .utils.timer import global_timer
        with global_timer.section("Dataset::Construct"):
            return self._construct_inner()

    def _construct_inner(self) -> "Dataset":
        if self.raw_data is None:
            raise RuntimeError("cannot construct Dataset: raw data was freed")
        data = self.raw_data
        if hasattr(data, "dtypes") and hasattr(data, "columns"):
            # pandas: category columns -> codes with the category values
            # recorded (train) or re-applied (valid/aligned sets)
            pc_in = None
            if self.reference is not None:
                pc_in = getattr(self.reference.construct(),
                                "pandas_categorical", None)
            data, fn, cf, pc = _data_from_pandas(
                data, self._feature_name_param,
                self._categorical_feature_param, pc_in)
            self.pandas_categorical = pc
            if self._feature_name_param in ("auto", None) and fn:
                self.feature_names = list(fn)
            if self._categorical_feature_param in ("auto", None):
                self._categorical_auto_resolved = cf or []
        if isinstance(data, (str, os.PathLike)):
            # a saved binary cache routes to the binary loader, whatever
            # the filename (reference: DatasetLoader::LoadFromFile checks
            # the binary token first, dataset_loader.cpp:273); the sniff
            # uses the scheme-routed opener so gs://-style caches route too
            from .utils.file_io import open_file
            try:
                with open_file(str(data), "rb") as _fh:
                    is_bin = _fh.read(len(_BINARY_MAGIC)) == _BINARY_MAGIC
            except OSError:
                is_bin = False
            if is_bin:
                pre = self.metadata
                # file params win: the cache carries its construction
                # params and the Booster's param-change check must see
                # the TRUE old values
                loaded = Dataset.load_binary(str(data), params=None)
                keep = {"reference", "free_raw_data",
                        "_feature_name_param", "_categorical_feature_param"}
                if not self.free_raw_data:
                    # get_data() on a kept binary-file dataset returns the
                    # PATH (reference basic.py get_data semantics)
                    keep.add("raw_data")
                for k, v in loaded.__dict__.items():
                    if k not in keep:
                        self.__dict__[k] = v
                # self.params now holds the file's TRUE construction
                # params; the flag makes the Booster's param-change check
                # compare explicit caller params against them (reference
                # DatasetUpdateParamChecking on binary load — binned data
                # cannot be rebuilt from a cache)
                self._from_binary_cache = True
                if self.reference is not None:
                    # a cache used as a VALIDATION set must have been
                    # binned identically to the training set (reference:
                    # "Cannot add validation data, since it has different
                    # bin mappers with training data")
                    ref = self.reference
                    ref.construct()
                    aligned = (
                        len(ref.bin_mappers) == len(self.bin_mappers)
                        and ref.used_features == self.used_features
                        and np.array_equal(ref.feat_group, self.feat_group)
                        and np.array_equal(ref.feat_start, self.feat_start)
                        and all(a.to_dict() == b.to_dict()
                                for a, b in zip(ref.bin_mappers,
                                                self.bin_mappers)))
                    if not aligned:
                        from .config import LightGBMError
                        raise LightGBMError(
                            "Cannot add validation data, since it has "
                            "different bin mappers with training data")
                # fields handed to the ctor override the file's sidecars
                for f in ("label", "weight", "init_score",
                          "query_boundaries"):
                    v = getattr(pre, f, None)
                    if v is not None:
                        setattr(self.metadata, f, v)
                self.metadata.check(self.num_data)
                self.constructed = True
                return self
            from .io_utils import _param_bool
            if _param_bool(self.params, "two_round"):
                # two-pass streamed load: never holds the full float matrix
                # (reference: two_round config, dataset_loader.cpp:775,1101)
                from .io_utils import load_text_dataset_two_round
                load_text_dataset_two_round(str(data), self)
                return self
            from .io_utils import load_text_dataset
            data = load_text_dataset(str(data), self)
        if _is_sparse(data):
            raw = None
            sp = data.tocsc()
            self.num_data, self.num_total_features = sp.shape
        else:
            raw = _as_2d(data)
            sp = None
            self.num_data, self.num_total_features = raw.shape

        p = self.params
        sample_cnt = int(p.get("bin_construct_sample_cnt", 200000))
        seed = int(p.get("data_random_seed", 1))

        if self._feature_name_param == "auto" or self._feature_name_param is None:
            if hasattr(self.raw_data, "columns"):
                self.feature_names = [str(c) for c in self.raw_data.columns]
            else:
                self.feature_names = [f"Column_{i}" for i in range(self.num_total_features)]
        else:
            self.feature_names = list(self._feature_name_param)

        categorical = self._resolve_categorical()

        if self.reference is not None:
            # validation set: reuse the reference's bin mappers
            # (reference: DatasetLoader::LoadFromFileAlignWithOtherDataset,
            # src/io/dataset_loader.cpp:229)
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.feature_names = ref.feature_names
            # identical EFB layout so valid sets bin into the same columns
            self.feat_group = ref.feat_group
            self.feat_start = ref.feat_start
            self.num_groups = ref.num_groups
            self._group_size = ref._group_size
            self.group_num_bin = ref.group_num_bin
            self.max_group_bin = ref.max_group_bin
        else:
            sample_idx = _sample_indices(self.num_data, sample_cnt, seed)
            self._fit_bin_mappers(raw, sp, sample_idx, categorical)

        # second pass: bin every row into the per-GROUP merged columns —
        # on device when plan_ingest elects the bucketize+pack kernel
        # (ops/ingest.py), with the host path as fallback/parity oracle
        G = self.num_groups
        dtype = np.uint8 if self.max_group_bin <= 256 else np.uint16
        self.binned = np.zeros((self.num_data, G), dtype=dtype)
        if not self._maybe_device_bin(raw, sp, self.binned):
            self._bin_block(raw, sp, self.binned)

        self.metadata.check(self.num_data)
        if self.metadata.label is None:
            self.metadata.label = np.zeros(self.num_data, dtype=np.float32)
        self.constructed = True
        if self.free_raw_data:
            self.raw_data = None
        return self

    def _fit_bin_mappers(self, raw, sp, sample_idx, categorical) -> None:
        """FindBin per feature over a row sample + EFB grouping.

        reference: DatasetLoader::ConstructBinMappersFromTextData
        (dataset_loader.cpp:823) + Dataset::Construct EFB
        (dataset.cpp:97-313)."""
        p = self.params
        max_bin = int(p.get("max_bin", 255))
        # per-feature bin budgets (reference: Config::max_bin_by_feature,
        # applied per feature in DatasetLoader::ConstructBinMappers)
        mbbf = p.get("max_bin_by_feature") or []
        if isinstance(mbbf, str):
            mbbf = [int(v) for v in mbbf.split(",") if v.strip()]
        if mbbf and len(mbbf) != self.num_total_features:
            from .basic import LightGBMError
            raise LightGBMError(
                "Length of max_bin_by_feature is not same with feature "
                "number")
        min_data_in_bin = int(p.get("min_data_in_bin", 3))
        min_data_in_leaf = int(p.get("min_data_in_leaf", 20))
        use_missing = bool(p.get("use_missing", True))
        zero_as_missing = bool(p.get("zero_as_missing", False))
        pre_filter = bool(p.get("feature_pre_filter", True))
        forced_bounds = _load_forced_bins(p, self.num_total_features)
        total_sample_cnt = len(sample_idx)
        sample_nonzero = {}               # used-feature pos -> bool [S]
        # one row-gather of the whole sample block: per-feature strided
        # column gathers from the [n, F] matrix cost ~7 s at 968 features
        # (profiled); a [S, F] contiguous block makes them slices
        sraw = (np.ascontiguousarray(raw[sample_idx])
                if raw is not None else None)
        self.bin_mappers = []
        for f in range(self.num_total_features):
            col = _get_col(sraw, sp, f,
                           None if sraw is not None else sample_idx)
            # keep NaN and non-zero samples; zeros are implicit
            keep = np.isnan(col) | (np.abs(col) > 1e-35)
            vals = col[keep]
            m = BinMapper()
            btype = (BinType.CATEGORICAL if f in categorical
                     else BinType.NUMERICAL)
            m.find_bin(
                vals, total_sample_cnt,
                int(mbbf[f]) if mbbf else max_bin,
                min_data_in_bin=min_data_in_bin,
                min_split_data=min_data_in_leaf,
                pre_filter=pre_filter,
                bin_type=btype,
                use_missing=use_missing,
                zero_as_missing=zero_as_missing,
                forced_upper_bounds=forced_bounds.get(f, ()),
            )
            self.bin_mappers.append(m)
        self.used_features = [f for f, m in enumerate(self.bin_mappers)
                              if not m.is_trivial]
        if not self.used_features and self.bin_mappers:
            # every feature is constant: keep one never-splittable dummy
            # column so the jitted grower has a non-empty feature axis and
            # trains stump trees (the reference trains with zero usable
            # features the same way — all split gains invalid;
            # boost_from_average supplies the constant prediction)
            self.bin_mappers[0] = BinMapper(
                num_bin=2, is_trivial=False,
                bin_upper_bound=np.array([0.0, np.inf]))
            self.used_features = [0]
        # EFB grouping from the sample (reference: FindGroups /
        # FastFeatureBundling, dataset.cpp:97-313)
        for j, f in enumerate(self.used_features):
            col = _get_col(sraw, sp, f,
                           None if sraw is not None else sample_idx)
            # NaN counts as non-default: a NaN row occupies the
            # feature's NaN bin in the merged column, so it can
            # conflict with other bundle members (reference counts
            # sampled NaN values as non-zero entries)
            sample_nonzero[j] = np.isnan(col) | (np.abs(col) > 1e-35)
        self._build_groups(sample_nonzero, total_sample_cnt)

    def _bin_block(self, raw, sp, out: np.ndarray) -> None:
        """Bin a block of raw rows into ``out`` (a [rows, G] uint view).

        Parallelized over GROUPS (numpy's searchsorted releases the GIL;
        the reference's second pass is likewise OpenMP row-parallel,
        dataset_loader.cpp ExtractFeaturesFromFile).  Bundle members share
        an output column and EFB tolerates bounded conflicts where write
        ORDER is observable, so each group's features stay serial within
        one task — output columns are disjoint across tasks.  Peak host
        scratch is ``workers`` float64 columns (8 x 88 MB at 11M rows)
        instead of the serial path's one.
        """
        dtype = out.dtype
        by_group: Dict[int, list] = {}
        for j, f in enumerate(self.used_features):
            by_group.setdefault(int(self.feat_group[j]), []).append((j, f))

        def run_group(g, members):
            for j, f in members:
                col = _get_col(raw, sp, f, None)
                bins = self.bin_mappers[f].value_to_bin(col)
                start = int(self.feat_start[j])
                if start == 1 and self._group_size[g] == 1:
                    out[:, g] = bins.astype(dtype)
                else:
                    nz = bins != 0   # bundled features are zero-default
                    out[nz, g] = (start + bins[nz] - 1).astype(dtype)

        if len(by_group) > 1 and out.shape[0] * len(self.used_features) > (1 << 22):
            from concurrent.futures import ThreadPoolExecutor
            workers = min(8, len(by_group), os.cpu_count() or 1)
            with ThreadPoolExecutor(max_workers=workers) as ex:
                list(ex.map(lambda kv: run_group(*kv), by_group.items()))
        else:
            for g, members in by_group.items():
                run_group(g, members)

    # -- device-side ingest (ops/ingest.py): the fused bucketize+pack
    #    kernel path; ``_bin_block`` above is the never-deleted host
    #    fallback AND the parity oracle its bytes are checked against --

    def _ingest_state(self) -> Optional[dict]:
        """Build (once per dataset) the device-ingest state: tables,
        plan, compiled binner.  None == this dataset bins on host
        (unsupported recipe, or the election said so); the verdict is
        cached so repeated pushes pay nothing."""
        st = getattr(self, "_ingest", None)
        if st is not None:
            return st or None                 # {} == demoted for good
        from .ops import ingest as ING
        from .ops.planner import active_ledger, plan_ingest
        try:
            tables = ING.build_ingest_tables(self)
        except ING.IngestUnsupported as e:
            ING.demote(str(e), warn=False)
            self._ingest = {}
            return None
        plan = plan_ingest(
            rows=self.num_data, features=tables.num_features,
            num_groups=tables.num_groups,
            item_bytes=tables.out_dtype.itemsize,
            bounds_width=tables.bounds.shape[1],
            cats_width=tables.cats.shape[1],
            ledger=active_ledger())
        if plan.variant != "kernel":
            ING.record_ingest_story(
                path="host", elected_by=plan.elected_by,
                reason=f"planner elected host ({plan.elected_by})",
                plan=plan.summary())
            self._ingest = {}
            return None
        st = {"plan": plan, "binner": ING.DeviceBinner(tables,
                                                       plan.tile_rows),
              "probed": False}
        self._ingest = st
        return st

    def _maybe_device_bin(self, raw, sp, out: np.ndarray) -> bool:
        """Bin ``raw`` into ``out`` on device when the election says
        so.  True only when every byte was committed device-side and
        the salted parity probe passed first (byte-identical to
        ``_bin_block`` by contract); any failure re-zeroes ``out`` and
        returns False so the host oracle runs."""
        from .ops import ingest as ING
        if sp is not None:
            return False
        if not isinstance(raw, np.ndarray) or raw.dtype != np.float32:
            # the kernel's directed-rounded boundary table is exact
            # ONLY against f32 inputs (ops/ingest.py); f64 stays host
            return False
        st = self._ingest_state()
        if st is None:
            return False
        plan, binner = st["plan"], st["binner"]
        n = out.shape[0]
        if n == 0 or (n < 4096 and plan.elected_by != "env"):
            return False          # dispatch overhead beats tiny blocks
        import time as _time

        from .obs.trace import span as _span
        try:
            if not st["probed"]:
                with _span("ingest.parity_probe"):
                    if not ING.parity_probe(binner, self, raw):
                        ING.demote(
                            "parity probe: device bytes diverge from "
                            "host value_to_bin")
                        self._ingest = {}
                        return False
                st["probed"] = True
            import jax

            from .data.stream import IngestPump
            local = jax.local_devices()
            devices = local if len(local) > 1 else None
            t0 = _time.perf_counter()
            with _span("ingest.device_bin", rows=n,
                       chunk_rows=plan.chunk_rows,
                       tile_rows=plan.tile_rows):
                for _i, start, rows, chunk in IngestPump(
                        raw, plan.chunk_rows, devices=devices):
                    out[start:start + rows] = np.asarray(binner(chunk))
            dt = _time.perf_counter() - t0
            rps = round(n / max(dt, 1e-9), 1)
            ING.record_ingest_story(
                path="kernel", elected_by=plan.elected_by, rows=n,
                chunk_rows=plan.chunk_rows, tile_rows=plan.tile_rows,
                bin_seconds=round(dt, 4), bin_rows_per_sec=rps,
                parity_probe=True)
            from .obs.metrics import global_registry
            global_registry.counter("ingest_rows_total").inc(n)
            global_registry.gauge("bin_rows_per_sec").set(rps)
            return True
        except Exception as e:    # lowering/OOM/backend loss — any of it
            out[:] = 0            # the host fold assumes zero-init
            ING.demote(f"{type(e).__name__}: {str(e)[:200]}")
            self._ingest = {}
            return False

    # -- streaming construction (reference: LGBM_DatasetCreateFromSampledColumn
    #    + LGBM_DatasetPushRows / PushRowsByCSR, c_api.h:98-144) -------------

    @classmethod
    def from_sample(cls, sample, num_total_rows: int, params=None,
                    feature_name="auto", categorical_feature="auto",
                    spill=None, spill_block_rows: Optional[int] = None):
        """Create a streaming Dataset: bin boundaries + EFB layout from a
        row sample, the binned matrix preallocated for ``num_total_rows``;
        fill it with ``push_rows`` (rows never all resident as floats).

        reference: LGBM_DatasetCreateFromSampledColumn (c_api.cpp) decides
        bins from sampled columns, then LGBM_DatasetPushRows streams row
        blocks in; the load auto-finishes when every row has been pushed.

        ``spill`` routes the binned rows to an out-of-core block store
        (lightgbm_tpu/data/) instead of a host-resident matrix — host RSS
        stays O(chunk) no matter how many rows stream in, and training
        executes out-of-core (docs/PERF.md "out-of-core streaming").
        ``spill=True`` picks a temp directory (``LGBM_TPU_STREAM_DIR``
        honored); a string is the store directory.  Spill-mode pushes
        must be sequential (append-only); chunk sizes may vary freely,
        including a ragged final chunk.
        """
        ds = cls(sample, params=params, feature_name=feature_name,
                 categorical_feature=categorical_feature)
        sample = _as_2d(sample)
        ds.num_data = int(num_total_rows)
        ds.num_total_features = sample.shape[1]
        if ds._feature_name_param == "auto" or ds._feature_name_param is None:
            ds.feature_names = [f"Column_{i}"
                                for i in range(ds.num_total_features)]
        else:
            ds.feature_names = list(ds._feature_name_param)
        categorical = ds._resolve_categorical()
        ds._fit_bin_mappers(sample, None, np.arange(sample.shape[0]),
                            categorical)
        G = ds.num_groups
        dtype = np.uint8 if ds.max_group_bin <= 256 else np.uint16
        if spill:
            ds._setup_spill(spill, dtype, spill_block_rows)
        else:
            ds.binned = np.zeros((ds.num_data, G), dtype=dtype)
        ds.raw_data = None
        ds._pushed = np.zeros(ds.num_data, bool)   # per-row coverage
        ds._streaming = True
        ds._append_cursor = 0
        return ds

    def _setup_spill(self, spill, dtype, block_rows: Optional[int]) -> None:
        """Route streamed pushes to a block store (spill mode)."""
        import weakref

        from .data.blockstore import BlockStore
        from .data.stream import default_spill_dir
        path = spill if isinstance(spill, (str, os.PathLike)) \
            else default_spill_dir()
        if block_rows is None:
            from .ops.planner import plan_stream
            plan = plan_stream(rows=self.num_data, features=self.num_groups,
                               num_bins=self.max_group_bin)
            block_rows = plan.block_rows or self.num_data
        self.binned = None
        self._block_store = BlockStore.create(
            str(path), self.num_data, self.num_groups, dtype,
            int(block_rows))
        self._block_store_owned = not isinstance(spill, (str, os.PathLike))
        if self._block_store_owned:
            weakref.finalize(self, BlockStore.cleanup, self._block_store)
        # spill scratch: one chunk of binned rows, reused per push
        self._spill_scratch = None

    @classmethod
    def from_reference_streaming(cls, reference: "Dataset",
                                 num_total_rows: int,
                                 params=None) -> "Dataset":
        """Empty streaming Dataset aligned with ``reference``'s binning
        (reference: LGBM_DatasetCreateByReference, c_api.h) — fill with
        ``push_rows``."""
        ref = reference.construct()
        ds = cls(None, reference=reference, params=params)
        ds.num_data = int(num_total_rows)
        ds.num_total_features = ref.num_total_features
        ds.feature_names = list(ref.feature_names)
        ds.bin_mappers = ref.bin_mappers
        ds.used_features = ref.used_features
        ds.feat_group = ref.feat_group
        ds.feat_start = ref.feat_start
        ds.num_groups = ref.num_groups
        ds._group_size = ref._group_size
        ds.group_num_bin = ref.group_num_bin
        ds.max_group_bin = ref.max_group_bin
        dtype = np.uint8 if ds.max_group_bin <= 256 else np.uint16
        ds.binned = np.zeros((ds.num_data, ds.num_groups), dtype=dtype)
        ds.raw_data = None
        ds._pushed = np.zeros(ds.num_data, bool)
        ds._streaming = True
        ds._append_cursor = 0
        return ds

    def push_rows(self, chunk, start_row: Optional[int] = None) -> "Dataset":
        """Bin a block of raw rows into [start_row, start_row+len) of the
        preallocated matrix (reference: LGBM_DatasetPushRows, c_api.h:98).
        ``start_row=None`` appends after the previous push.  Chunk sizes
        may vary push to push — a ragged final chunk smaller than the
        sample/chunk-size hint is fine.  The dataset marks itself
        constructed when every row has been pushed.

        Overlap with already-pushed rows raises (a silent overwrite would
        corrupt the load invisibly); a retry of a FAILED push is not an
        overlap — coverage is only recorded after a chunk bins cleanly.
        Spill-mode datasets (``from_sample(spill=...)``) additionally
        require appends in order: the block store is append-only, so a
        ``start_row`` past the cursor (a gap) raises too."""
        if not getattr(self, "_streaming", False):
            raise RuntimeError(
                "push_rows requires a Dataset created by from_sample")
        if self.constructed:
            raise RuntimeError("dataset load already finished")
        if _is_sparse(chunk):
            sp, raw = chunk.tocsc(), None
            rows = sp.shape[0]
        else:
            raw = _as_2d(chunk)
            sp = None
            rows = raw.shape[0]
        if start_row is None:
            start_row = self._append_cursor
        if start_row + rows > self.num_data:
            raise ValueError(
                f"push past the end: {start_row}+{rows} > {self.num_data}")
        # per-ROW coverage (not a count): a silent overwrite of loaded
        # rows would make the finished matrix depend on push order
        already = np.flatnonzero(self._pushed[start_row:start_row + rows])
        if len(already):
            raise ValueError(
                f"push_rows overlap: row {start_row + int(already[0])} was "
                f"already pushed (chunk covers [{start_row}, "
                f"{start_row + rows})); pushes must cover disjoint row "
                "ranges — only a failed push may be retried")
        store = getattr(self, "_block_store", None)
        if store is not None:
            if start_row != self._append_cursor:
                raise ValueError(
                    f"spill-mode push_rows must append in order: expected "
                    f"start_row={self._append_cursor}, got {start_row} "
                    "(the block store is append-only)")
            if self._spill_scratch is None \
                    or self._spill_scratch.shape[0] < rows:
                self._spill_scratch = np.zeros(
                    (rows, self.num_groups), store.dtype)
            out = self._spill_scratch[:rows]
            out[:] = 0
            if not self._maybe_device_bin(raw, sp, out):
                self._bin_block(raw, sp, out)
            store.append_rows(out)
        else:
            out = self.binned[start_row:start_row + rows]
            if not self._maybe_device_bin(raw, sp, out):
                self._bin_block(raw, sp, out)
        self._pushed[start_row:start_row + rows] = True
        self._append_cursor = max(self._append_cursor, start_row + rows)
        if self._pushed.all():                   # auto-finish like the C API
            if store is not None:
                store.finalize()
            self.metadata.check(self.num_data)
            if self.metadata.label is None:
                self.metadata.label = np.zeros(self.num_data, np.float32)
            self.constructed = True
        return self

    def _build_groups(self, sample_nonzero: dict, total_sample_cnt: int) -> None:
        """Greedy conflict-bounded exclusive feature bundling.

        reference: Dataset::FindGroups (dataset.cpp:97-234) — features whose
        non-default rows rarely overlap share one stored column; conflict
        budget is total_sample_cnt/10000 (dataset.cpp:105), bins per merged
        column capped at 256 (dataset.cpp:104,127 — the GPU cap, which TPU
        uint8 storage likes too).  Only zero-default numerical features are
        bundled; everything else gets a singleton column.
        """
        F = len(self.used_features)
        enable = str(self.params.get("enable_bundle", True)).lower() not in (
            "false", "0", "no")
        eligible = []
        for j, f in enumerate(self.used_features):
            m = self.bin_mappers[f]
            if (enable and m.bin_type == BinType.NUMERICAL
                    and m.most_freq_bin == 0 and m.default_bin == 0
                    and m.num_bin <= 256 and j in sample_nonzero):
                eligible.append(j)
        budget = max(total_sample_cnt // 10000, 0)

        groups: List[List[int]] = []       # positions (into used_features)
        group_nz: List[np.ndarray] = []    # bool [S] union of nonzeros
        group_cnt: List[int] = []          # popcount of the union
        group_conflict: List[int] = []
        group_bins: List[int] = []         # 1 + sum(nb_f - 1)
        nz_cnt = {j: int(sample_nonzero[j].sum()) for j in eligible}
        eligible.sort(key=lambda j: nz_cnt[j], reverse=True)
        # bounded search, like the reference: at most max_search_group
        # groups are probed per feature (dataset.cpp FindGroups samples
        # kMaxSearchGroup candidates), and a group is only probed when the
        # PIGEONHOLE lower bound on overlap — cnt_j + cnt_g - S — leaves
        # the budget reachable.  Without these, 2000 dense features cost
        # O(F^2 * S) boolean ANDs (measured: minutes at Epsilon shape).
        max_search_group = 100
        for j in eligible:
            nz = sample_nonzero[j]
            cnt_j = nz_cnt[j]
            nb = self.bin_mappers[self.used_features[j]].num_bin
            placed = False
            searched = 0
            for gi in range(len(groups)):
                if searched >= max_search_group:
                    break
                if group_bins[gi] + nb - 1 > 256:
                    continue
                lower = max(0, cnt_j + group_cnt[gi] - total_sample_cnt)
                if group_conflict[gi] + lower > budget:
                    continue
                searched += 1
                conflict = int(np.count_nonzero(group_nz[gi] & nz))
                if group_conflict[gi] + conflict <= budget:
                    groups[gi].append(j)
                    group_nz[gi] = group_nz[gi] | nz
                    group_cnt[gi] = group_cnt[gi] + cnt_j - conflict
                    group_conflict[gi] += conflict
                    group_bins[gi] += nb - 1
                    placed = True
                    break
            if not placed:
                groups.append([j])
                group_nz.append(nz.copy())
                group_cnt.append(cnt_j)
                group_conflict.append(0)
                group_bins.append(1 + (nb - 1))

        feat_group = np.zeros(F, np.int32)
        feat_start = np.ones(F, np.int32)
        group_size: List[int] = []
        group_num_bin: List[int] = []
        gid = 0
        bundled_pos = set()
        for gi, members in enumerate(groups):
            if len(members) == 1:
                continue   # singletons handled below for stable ordering
            off = 1
            for j in members:
                feat_group[j] = gid
                feat_start[j] = off
                off += self.bin_mappers[self.used_features[j]].num_bin - 1
                bundled_pos.add(j)
            group_size.append(len(members))
            group_num_bin.append(off)
            gid += 1
        for j in range(F):
            if j in bundled_pos:
                continue
            feat_group[j] = gid
            feat_start[j] = 1
            group_size.append(1)
            group_num_bin.append(
                self.bin_mappers[self.used_features[j]].num_bin)
            gid += 1

        self.feat_group = feat_group
        self.feat_start = feat_start
        self.num_groups = gid
        self._group_size = group_size
        self.group_num_bin = group_num_bin
        self.max_group_bin = max(group_num_bin, default=2)

    def _resolve_categorical(self) -> set:
        cf = self._categorical_feature_param
        if cf == "auto" or cf is None:
            cats = set()
            # pandas auto-resolution: the NOT-ordered category columns
            # (recorded by _data_from_pandas during construct)
            auto = getattr(self, "_categorical_auto_resolved", None)
            if auto:
                cats |= self._names_to_indices(auto)
            # also honor categorical_feature in params (CLI-style)
            pcf = self.params.get("categorical_feature") or self.params.get("categorical_column")
            if pcf:
                cats |= self._names_to_indices(pcf)
            return cats
        return self._names_to_indices(cf)

    @property
    def categorical_feature(self):
        """The categorical_feature spec as given (reference keeps the
        user's names/indices on the Dataset)."""
        return self._categorical_feature_param

    def _names_to_indices(self, spec) -> set:
        if isinstance(spec, str):
            spec = [s for s in spec.split(",") if s]
        out = set()
        for s in spec:
            if isinstance(s, str) and not s.lstrip("-").isdigit():
                if s in self.feature_names:
                    out.add(self.feature_names.index(s))
                else:
                    raise ValueError(f"unknown categorical feature {s!r}")
            else:
                out.add(int(s))
        return out

    # -- accessors mirroring reference python API ----------------------------

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        """Create a validation Dataset aligned with this one (bins with
        THIS dataset's BinMappers).

        reference: Dataset.create_valid (python-package/lightgbm/basic.py:1142).
        """
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       feature_name=self._feature_name_param,
                       categorical_feature=self._categorical_feature_param,
                       params=dict(params or self.params),
                       free_raw_data=self.free_raw_data)

    # -- field accessors (reference: Dataset.get_field/set_field,
    # python-package/lightgbm/basic.py:1255-1339 -> LGBM_DatasetGetField /
    # SetField, src/c_api.cpp; 'group' follows the reference's asymmetry:
    # set takes per-query SIZES, get returns CUMULATIVE boundaries) -------

    _FIELDS = ("label", "weight", "init_score", "group")

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name not in self._FIELDS:
            raise ValueError(f"unknown field {field_name!r}")
        if field_name == "label":
            self.metadata.label = (None if data is None else
                                   np.asarray(data, np.float32).reshape(-1))
        elif field_name == "weight":
            self.metadata.weight = (None if data is None else
                                    np.asarray(data, np.float32).reshape(-1))
        elif field_name == "init_score":
            self.metadata.init_score = (None if data is None else
                                        np.asarray(data, np.float64))
        else:
            self.metadata.set_group(data)
        return self

    def get_field(self, field_name: str):
        if field_name not in self._FIELDS:
            raise ValueError(f"unknown field {field_name!r}")
        if field_name == "group":
            return self.metadata.query_boundaries
        if field_name == "init_score":
            return self.metadata.init_score
        return getattr(self.metadata, field_name)

    def get_data(self):
        """The raw data this Dataset was built from (reference:
        Dataset.get_data, basic.py — raises after raw data was freed)."""
        if self.raw_data is None and self.constructed:
            raise RuntimeError(
                "Cannot get data: raw data was freed after construction "
                "(pass free_raw_data=False to keep it)")
        return self.raw_data

    def release_host_binned(self) -> "Dataset":
        """Free the host [n, F] binned matrix once a device-resident copy
        exists (GBDT.__init__ calls this when ``free_raw_data`` is set on
        accelerator backends, halving peak RSS for large matrices).  The
        Dataset can no longer build another booster, subset, save_binary
        or add_features_from afterwards; ``host_binned`` raises then."""
        if self.binned is not None:
            self.binned = None
            self._host_binned_released = True
            # the device-binned reuse cache (boosting/gbdt.py) rides on
            # the live Dataset; a released Dataset keeps the documented
            # cannot-build-another-booster contract
            self._dev_binned_cache = None
        return self

    def host_binned(self) -> np.ndarray:
        """The host binned matrix DATA, with an informative error when it
        is not resident.  Consumers that only need shape/dtype metadata
        must use ``binned_shape``/``binned_dtype`` instead — those stay
        valid on released and block-backed (out-of-core) datasets."""
        if self.binned is None:
            if getattr(self, "_block_store", None) is not None:
                raise RuntimeError(
                    "this Dataset's binned matrix lives in an out-of-core "
                    "block store (lightgbm_tpu/data/), not host memory; "
                    "metadata consumers should use binned_shape()/"
                    "binned_dtype(), bulk consumers must stream blocks "
                    "via Dataset._block_store.read_block")
            if getattr(self, "_host_binned_released", False):
                raise RuntimeError(
                    "the Dataset's host binned matrix was released after "
                    "device upload (free_raw_data=True on an accelerator "
                    "backend); pass free_raw_data=False or set "
                    "LGBM_TPU_FREE_BINNED=0 to keep it for reuse")
        return self.binned

    def binned_shape(self) -> tuple:
        """(num_data, num_groups) of the binned matrix — metadata only,
        valid whether the data is host-resident, released after device
        upload, or spilled to an out-of-core block store."""
        self.construct()
        return (self.num_data, self.num_groups)

    def binned_dtype(self) -> np.dtype:
        """Storage dtype of the binned matrix (metadata twin of
        ``binned_shape``)."""
        self.construct()
        return np.dtype(np.uint8 if self.max_group_bin <= 256
                        else np.uint16)

    def get_params(self) -> dict:
        return dict(self.params)

    def get_ref_chain(self, ref_limit: int = 100) -> set:
        """Chain of Datasets reachable through .reference (reference:
        Dataset.get_ref_chain, basic.py:1633)."""
        head, chain = self, set()
        while len(chain) < ref_limit:
            if isinstance(head, Dataset):
                chain.add(head)
                if head.reference is not None and head.reference not in chain:
                    head = head.reference
                else:
                    break
            else:
                break
        return chain

    def num_feature(self) -> int:
        """Number of (original) features, after construction (reference:
        LGBM_DatasetGetNumFeature -> max_feature_idx + 1)."""
        self.construct()
        return self.num_total_features

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if self._categorical_feature_param == categorical_feature:
            return self
        if self.constructed:
            raise RuntimeError(
                "Cannot set categorical feature after dataset construction; "
                "create a new Dataset")
        self._categorical_feature_param = categorical_feature
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        if feature_name != "auto":
            self._feature_name_param = feature_name
            if self.constructed:
                if len(feature_name) != self.num_total_features:
                    raise ValueError(
                        f"Length of feature names ({len(feature_name)}) does "
                        f"not equal number of features "
                        f"({self.num_total_features})")
                self.feature_names = list(feature_name)
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if self.reference is reference:
            return self
        if self.constructed:
            raise RuntimeError(
                "Cannot set reference after dataset construction; "
                "create a new Dataset")
        self.reference = reference
        return self

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Append ``other``'s feature columns to this Dataset in place.

        Both must be constructed with the same number of rows (reference:
        LGBM_DatasetAddFeaturesFrom -> Dataset::AddFeaturesFrom,
        src/io/dataset.cpp).  Bin groups are concatenated: the merged matrix
        keeps each source's EFB bundling with the other's group ids offset.
        """
        if not (self.constructed and other.constructed):
            raise ValueError(
                "Both source and target Datasets must be constructed "
                "before adding features")
        if self.num_data != other.num_data:
            from .basic import LightGBMError
            raise LightGBMError(
                f"Cannot add features from {other.num_data}-row Dataset to "
                f"{self.num_data}-row Dataset")
        base = self.num_total_features
        self.bin_mappers = list(self.bin_mappers) + list(other.bin_mappers)
        self.used_features = list(self.used_features) + [
            base + f for f in other.used_features]
        dtype = (np.uint16 if max(self.max_group_bin, other.max_group_bin) > 256
                 else np.uint8)
        self.binned = np.hstack([self.host_binned().astype(dtype, copy=False),
                                 other.host_binned().astype(dtype, copy=False)])
        self.feat_group = np.concatenate(
            [self.feat_group, other.feat_group + self.num_groups]).astype(np.int32)
        self.feat_start = np.concatenate(
            [self.feat_start, other.feat_start]).astype(np.int32)
        self._group_size = list(self._group_size) + list(other._group_size)
        self.group_num_bin = list(self.group_num_bin) + list(other.group_num_bin)
        self.num_groups += other.num_groups
        self.max_group_bin = max(self.max_group_bin, other.max_group_bin)
        self.num_total_features += other.num_total_features
        self.feature_names = list(self.feature_names) + list(other.feature_names)
        return self

    def _dump_text(self, filename: str) -> "Dataset":
        """Debug dump of the binned matrix (reference: Dataset::DumpTextFile,
        src/io/dataset.cpp:994 via LGBM_DatasetDumpText): header stats,
        feature names, then one line of per-feature BIN values per row.
        Not loadable back; for debugging parity only."""
        self.construct()
        from .utils.file_io import open_atomic
        F = len(self.used_features)
        # streamed row-by-row (num_data lines): open_atomic keeps the
        # per-row write with O(1) extra memory and still lands atomically
        with open_atomic(filename, "w") as fh:
            fh.write(f"num_features: {F}\n")
            fh.write(f"num_total_features: {self.num_total_features}\n")
            fh.write(f"num_groups: {self.num_groups}\n")
            fh.write(f"num_data: {self.num_data}\n")
            fh.write("feature_names: "
                     + ", ".join(self.feature_names) + "\n")
            meta = self.feature_meta().resolved()
            for i in range(self.num_data):
                row = self.host_binned()[i]
                bins = []
                for j in range(F):
                    g = meta.feat_group[j]
                    st = meta.feat_start[j]
                    dec = int(row[g]) - st + 1
                    bins.append(dec if 1 <= dec < meta.num_bin[j] else 0)
                fh.write(", ".join(str(b) for b in bins) + "\n")
        return self

    def get_label(self):
        return self.metadata.label

    def set_label(self, label):
        self.metadata.label = np.asarray(label, dtype=np.float32).reshape(-1)

    # attribute-style field access (the reference Dataset keeps .label /
    # .weight / .init_score / .group instance attributes)
    @property
    def label(self):
        return self.metadata.label

    @label.setter
    def label(self, value):
        self.metadata.label = (None if value is None else
                               np.asarray(value, np.float32).reshape(-1))

    @property
    def weight(self):
        return self.metadata.weight

    @weight.setter
    def weight(self, value):
        self.metadata.weight = (None if value is None else
                                np.asarray(value, np.float32).reshape(-1))

    @property
    def init_score(self):
        return self.metadata.init_score

    @init_score.setter
    def init_score(self, value):
        self.metadata.init_score = (None if value is None else
                                    np.asarray(value, np.float64))

    @property
    def group(self):
        return self.get_group()

    @group.setter
    def group(self, value):
        self.metadata.set_group(value)

    def get_weight(self):
        return self.metadata.weight

    def set_weight(self, weight):
        self.metadata.weight = None if weight is None else np.asarray(weight, np.float32).reshape(-1)

    def set_group(self, group):
        self.metadata.set_group(group)

    def set_init_score(self, init_score):
        self.metadata.init_score = None if init_score is None else np.asarray(init_score, np.float64)

    def get_init_score(self):
        return self.metadata.init_score

    def get_group(self):
        """Per-query group SIZES (reference: Dataset.get_group converts the
        stored cumulative boundaries back with np.diff)."""
        qb = self.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def num_features(self) -> int:
        self.construct()
        return len(self.used_features)

    def get_feature_names(self) -> List[str]:
        return self.feature_names

    def subset(self, used_indices, params=None) -> "Dataset":
        self.construct()
        idx = np.asarray(used_indices, dtype=np.int64)
        sub = Dataset.__new__(Dataset)
        sub.params = dict(params or self.params)
        # a kept-raw parent hands its subset the raw rows too (reference:
        # subsets re-materialize from the parent's data — needed for
        # fpreproc / continued training on subsets)
        import os as _os
        if self.raw_data is not None and not isinstance(
                self.raw_data, (str, _os.PathLike)):
            sub.raw_data = (self.raw_data.iloc[idx]
                            if hasattr(self.raw_data, "iloc")
                            else self.raw_data[idx])
            sub.free_raw_data = self.free_raw_data
        elif isinstance(self.raw_data, (str, _os.PathLike)):
            # file-backed parent: subsets report the same path from
            # get_data() (reference test_init_with_subset asserts this)
            sub.raw_data = self.raw_data
            sub.free_raw_data = self.free_raw_data
        else:
            sub.raw_data = None
            sub.free_raw_data = True
        sub.reference = self
        qb = None
        if self.metadata.query_boundaries is not None:
            # rows of one query must stay contiguous in the subset (true for
            # group-aware fold splits); rebuild boundaries from run-lengths
            gid = np.searchsorted(self.metadata.query_boundaries, idx,
                                  side="right") - 1
            if np.any(np.diff(gid) < 0):
                raise ValueError(
                    "subset() of grouped (ranking) data requires used_indices "
                    "to keep each query's rows contiguous and in order")
            change = np.flatnonzero(np.diff(gid)) + 1
            qb = np.concatenate([[0], change, [len(idx)]]).astype(np.int32)
        sub.metadata = Metadata(
            label=None if self.metadata.label is None else self.metadata.label[idx],
            weight=None if self.metadata.weight is None else self.metadata.weight[idx],
            init_score=None if self.metadata.init_score is None else
            np.asarray(self.metadata.init_score).reshape(self.num_data, -1)[idx].reshape(-1),
            query_boundaries=qb,
        )
        sub._feature_name_param = self.feature_names
        sub._categorical_feature_param = self._categorical_feature_param
        sub.pandas_categorical = getattr(self, "pandas_categorical", None)
        sub.constructed = True
        sub.bin_mappers = self.bin_mappers
        sub.used_features = self.used_features
        sub.binned = self.host_binned()[idx]
        sub.feat_group = self.feat_group
        sub.feat_start = self.feat_start
        sub.num_groups = self.num_groups
        sub._group_size = self._group_size
        sub.group_num_bin = self.group_num_bin
        sub.max_group_bin = self.max_group_bin
        sub.feature_names = self.feature_names
        sub.num_data = len(idx)
        sub.num_total_features = self.num_total_features
        return sub

    # -- binary serialization (reference: Dataset::SaveBinaryFile dataset.cpp:890)

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        meta = {
            "version": 1,
            "params": {k: v for k, v in self.params.items()
                       if isinstance(v, (int, float, str, bool, list))
                       or v is None},
            "num_data": int(self.num_data),
            "num_total_features": int(self.num_total_features),
            "used_features": list(map(int, self.used_features)),
            "feature_names": self.feature_names,
            "bin_mappers": [m.to_dict() for m in self.bin_mappers],
            "dtype": str(self.host_binned().dtype),
            "feat_group": list(map(int, self.feat_group)),
            "feat_start": list(map(int, self.feat_start)),
            "num_groups": int(self.num_groups),
            "group_size": list(map(int, self._group_size)),
            "group_num_bin": list(map(int, self.group_num_bin)),
            "has_label": self.metadata.label is not None,
            "has_weight": self.metadata.weight is not None,
            "has_group": self.metadata.query_boundaries is not None,
            "has_init_score": self.metadata.init_score is not None,
        }
        # a binary cache is reloaded by later runs: a crash mid-write must
        # not leave a truncated file that load_binary trusts — stream
        # through the atomic seam (the binned matrix can be GBs; no
        # second resident copy)
        from .utils.file_io import open_atomic
        with open_atomic(filename, "wb") as fh:
            fh.write(_BINARY_MAGIC)
            hdr = json.dumps(meta).encode()
            fh.write(len(hdr).to_bytes(8, "little"))
            fh.write(hdr)
            fh.write(np.ascontiguousarray(self.binned).tobytes())
            for arr in (self.metadata.label, self.metadata.weight,
                        self.metadata.query_boundaries,
                        self.metadata.init_score):
                if arr is not None:
                    fh.write(np.ascontiguousarray(arr).tobytes())
        return self

    @staticmethod
    def load_binary(filename: str, params: Optional[dict] = None) -> "Dataset":
        from .utils.file_io import open_file
        with open_file(filename, "rb") as fh:
            magic = fh.read(len(_BINARY_MAGIC))
            if magic != _BINARY_MAGIC:
                raise ValueError(f"{filename} is not a lightgbm_tpu binary dataset")
            n = int.from_bytes(fh.read(8), "little")
            meta = json.loads(fh.read(n).decode())
            ds = Dataset.__new__(Dataset)
            # the binary cache carries the construction params (reference:
            # SaveBinaryFile serializes the Config the dataset was built
            # with) so param-change checking sees the true old values
            ds.params = dict(params or meta.get("params") or {})
            ds.raw_data = None
            ds.reference = None
            ds.free_raw_data = True
            ds._feature_name_param = meta["feature_names"]
            ds._categorical_feature_param = None
            ds.constructed = True
            ds.num_data = meta["num_data"]
            ds.num_total_features = meta["num_total_features"]
            ds.used_features = meta["used_features"]
            ds.feature_names = meta["feature_names"]
            ds.bin_mappers = [BinMapper.from_dict(d) for d in meta["bin_mappers"]]
            F = len(ds.used_features)
            if "feat_group" in meta:
                ds.feat_group = np.asarray(meta["feat_group"], np.int32)
                ds.feat_start = np.asarray(meta["feat_start"], np.int32)
                ds.num_groups = int(meta["num_groups"])
                ds._group_size = list(meta["group_size"])
                ds.group_num_bin = list(meta["group_num_bin"])
                ds.max_group_bin = max(ds.group_num_bin, default=2)
            else:   # pre-EFB file: identity groups
                ds.feat_group = np.arange(F, dtype=np.int32)
                ds.feat_start = np.ones(F, np.int32)
                ds.num_groups = F
                ds._group_size = [1] * F
                ds.group_num_bin = [ds.bin_mappers[f].num_bin
                                    for f in ds.used_features]
                ds.max_group_bin = max(ds.group_num_bin, default=2)
            ncols = ds.num_groups
            dtype = np.dtype(meta["dtype"])
            ds.binned = np.frombuffer(
                fh.read(ds.num_data * ncols * dtype.itemsize), dtype=dtype
            ).reshape(ds.num_data, ncols).copy()
            ds.metadata = Metadata()
            if meta["has_label"]:
                ds.metadata.label = np.frombuffer(fh.read(ds.num_data * 4), np.float32).copy()
            if meta["has_weight"]:
                ds.metadata.weight = np.frombuffer(fh.read(ds.num_data * 4), np.float32).copy()
            if meta["has_group"]:
                rest = fh.read()
                # query boundaries precede init score; length unknown → parse both
                if meta["has_init_score"]:
                    qb_len = len(rest) - ds.num_data * 8
                    ds.metadata.query_boundaries = np.frombuffer(rest[:qb_len], np.int32).copy()
                    ds.metadata.init_score = np.frombuffer(rest[qb_len:], np.float64).copy()
                else:
                    ds.metadata.query_boundaries = np.frombuffer(rest, np.int32).copy()
            elif meta["has_init_score"]:
                ds.metadata.init_score = np.frombuffer(fh.read(ds.num_data * 8), np.float64).copy()
            return ds

    # -- device view ---------------------------------------------------------

    def feature_meta(self) -> "FeatureMeta":
        self.construct()
        return FeatureMeta.from_mappers(
            [self.bin_mappers[f] for f in self.used_features],
            feat_group=self.feat_group, feat_start=self.feat_start,
            num_groups=self.num_groups, max_group_bin=self.max_group_bin)


@dataclass(frozen=True)
class FeatureMeta:
    """Static (trace-time) per-used-feature metadata arrays for device kernels.

    EFB mapping (reference: FeatureGroup bin stacking, feature_group.h:32-50):
    scan/tree/partition all operate on ORIGINAL used features; the stored
    matrix has one column per GROUP.  Feature f's non-default bins b>=1 live
    at merged bin ``feat_start[f] + b - 1`` of column ``feat_group[f]``; its
    bin 0 (the shared default) is reconstructed from leaf totals at scan time
    (the reference's FixHistogram trick, dataset.cpp:1410).  Singleton groups
    use feat_start=1 so the same formulas hold (merged bin == feature bin).
    """

    num_bin: np.ndarray        # int32 [F]
    missing_type: np.ndarray   # int32 [F]
    default_bin: np.ndarray    # int32 [F]
    most_freq_bin: np.ndarray  # int32 [F]
    is_categorical: np.ndarray  # bool [F]
    max_num_bin: int           # padded per-feature bin axis size B
    feat_group: Optional[np.ndarray] = None   # int32 [F] column of feature
    feat_start: Optional[np.ndarray] = None   # int32 [F] merged-bin start
    num_groups: int = 0                       # G (0 -> identity: G == F)
    max_group_bin: int = 0                    # padded group bin axis Bg

    def with_identity_groups(self) -> "FeatureMeta":
        F = len(self.num_bin)
        import dataclasses
        return dataclasses.replace(
            self,
            feat_group=np.arange(F, dtype=np.int32),
            feat_start=np.ones(F, np.int32),
            num_groups=F,
            max_group_bin=self.max_num_bin,
        )

    @property
    def has_bundles(self) -> bool:
        return (self.num_groups != 0 and
                self.num_groups != len(self.num_bin))

    def resolved(self) -> "FeatureMeta":
        return self if self.num_groups else self.with_identity_groups()

    def as_runtime_arrays(self) -> tuple:
        """The per-feature metadata as DEVICE arrays in the canonical
        (num_bin, missing_type, default_bin, is_categorical, feat_group,
        feat_start) order that grow_tree / grow_tree_rounds /
        predict_leaf_index_binned unpack — the single construction site
        for the runtime-metadata tuple that lets one compiled program
        serve every same-shaped dataset."""
        import jax.numpy as jnp
        m = self.resolved()
        return tuple(jnp.asarray(a) for a in (
            m.num_bin, m.missing_type, m.default_bin,
            m.is_categorical, m.feat_group, m.feat_start))

    @staticmethod
    def from_mappers(mappers: Sequence[BinMapper],
                     feat_group=None, feat_start=None,
                     num_groups: int = 0, max_group_bin: int = 0) -> "FeatureMeta":
        nb = np.array([m.num_bin for m in mappers], dtype=np.int32)
        meta = FeatureMeta(
            num_bin=nb,
            missing_type=np.array([m.missing_type for m in mappers], dtype=np.int32),
            default_bin=np.array([m.default_bin for m in mappers], dtype=np.int32),
            most_freq_bin=np.array([m.most_freq_bin for m in mappers], dtype=np.int32),
            is_categorical=np.array([m.bin_type == BinType.CATEGORICAL for m in mappers], dtype=bool),
            max_num_bin=int(nb.max()) if len(nb) else 2,
            feat_group=feat_group, feat_start=feat_start,
            num_groups=num_groups, max_group_bin=max_group_bin,
        )
        return meta.resolved()


def _is_sparse(data) -> bool:
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


def _get_col(raw, sp, f: int, rows: Optional[np.ndarray]) -> np.ndarray:
    if raw is not None:
        if rows is not None:
            # gather first, THEN widen: the float64 scratch is O(sample)
            return np.asarray(raw[rows, f], dtype=np.float64)
        return np.asarray(raw[:, f], dtype=np.float64)
    col = np.asarray(sp[:, f].todense()).reshape(-1).astype(np.float64)
    return col if rows is None else col[rows]


def _load_forced_bins(params: dict, num_features: int) -> Dict[int, List[float]]:
    """reference: forcedbins_filename (dataset_loader.cpp DatasetLoader ctor)."""
    fn = params.get("forcedbins_filename", "")
    if not fn:
        return {}
    with open(fn) as fh:
        spec = json.load(fh)
    out: Dict[int, List[float]] = {}
    for entry in spec:
        out[int(entry["feature"])] = [float(x) for x in entry["bin_upper_bound"]]
    return out
