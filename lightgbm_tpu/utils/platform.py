"""Host-platform environment helpers.

This image injects a TPU PJRT plugin into every Python process via
``PYTHONPATH`` + ``sitecustomize`` (the ``axon`` plugin).  JAX initializes
every *registered* plugin on first backend access — even when
``JAX_PLATFORMS=cpu`` — so any process that only needs the virtual CPU
mesh (tests, multichip dry-runs, CI) must strip the plugin from the
environment *before* the interpreter starts.  These helpers build such an
environment for subprocess/re-exec use.
"""
from __future__ import annotations

import os
import sys

_AXON_MARKER = ".axon_site"
_AXON_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
    "PALLAS_AXON_TPU_GEN",
    "AXON_LOOPBACK_RELAY",
    "AXON_POOL_SVC_OVERRIDE",
    "TPU_WORKER_HOSTNAMES",
)


def tpu_plugin_active(environ=None) -> bool:
    """True if the TPU plugin would be registered in a child interpreter."""
    env = os.environ if environ is None else environ
    if env.get("PALLAS_AXON_POOL_IPS"):
        return True
    return any(
        _AXON_MARKER in p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
    )


def clean_cpu_env(n_devices: int = 8, base=None) -> dict:
    """Environment for a subprocess that must run on N virtual CPU devices.

    Strips the TPU plugin injection, forces ``JAX_PLATFORMS=cpu`` and the
    host-platform device count, and enables the persistent compilation
    cache so repeated test runs skip recompiles.
    """
    env = dict(os.environ if base is None else base)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and _AXON_MARKER not in p
    )
    for var in _AXON_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.setdefault("JAX_ENABLE_X64", "0")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    return env


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _cache_dir() -> str:
    """Per-host-CPU XLA compile cache dir.

    XLA:CPU AOT blobs embed the compile machine's ISA features and LOAD
    even on hosts missing them ("could lead to execution errors such as
    SIGILL" warning observed when the repo cache moved hosts); keying the
    directory on the CPU fingerprint makes a foreign cache a miss instead.
    """
    from ..native.build import _host_tag
    return os.path.join(_repo_root(), ".jax_cache", _host_tag())


def enable_compile_cache(path=None, family=None):
    """Wire the persistent XLA compilation cache for THIS process.

    ``LGBM_TPU_COMPILE_CACHE=<dir>`` (or an explicit ``path``) points the
    cache at a directory and drops the min-entry thresholds so every
    compiled program is banked — r5 spent 130 s of a 155 s stage
    compiling, so a warm cache is the single biggest wall-clock lever.
    Called at engine init (lgb.train / cv) and by bench.py; idempotent,
    and a no-op when neither the env var nor ``path`` is set (the
    JAX_COMPILATION_CACHE_DIR env route still works independently).

    ``family`` ("train", "serving", ...) keys the warmth GAUGES by
    program family so the cold-start bar is attributable: before this,
    ``compile_cache.entries_before`` counted training XLA JIT blobs and
    serving AOT exports (``<dir>/serving``, fleet/aot.py) in one
    number, and a serving-only prior run made a training cold start
    report ``warm_start=true``.  The JIT blob pool itself stays ONE
    shared directory (XLA keys blobs by program hash, so planes cannot
    collide — and moving the pool would cold-start every existing
    cache); attribution is by entry CLASS: the train family's warmth
    counts JIT blobs only, the serving family's counts its AOT export
    store, and the reserved subtrees (``serving/``, ``autotune/``)
    never inflate another family's count.

    Returns the active cache dir, or None when disabled.
    """
    d = path or os.environ.get("LGBM_TPU_COMPILE_CACHE", "").strip()
    if not d or d.lower() in ("0", "off", "none"):
        return None
    try:
        os.makedirs(d, exist_ok=True)
        # cache warmth on the unified registry: entries found at wiring
        # time discriminate cold vs warm starts (docs/OBSERVABILITY.md),
        # keyed by family when one is named so the cold-start bar is
        # attributable
        try:
            from ..obs.metrics import global_registry
            entries = compile_cache_entries(d)
            global_registry.gauge("compile_cache_entries_at_init").set(
                entries)
            global_registry.gauge("compile_cache_warm").set(entries > 0)
            if family:
                fam_entries = entries
                if family == "serving":
                    fam_entries = compile_cache_entries_by_family(d).get(
                        "serving_aot", 0)
                global_registry.gauge(
                    f"compile_cache_entries_at_init:{family}").set(
                        fam_entries)
                global_registry.gauge(
                    f"compile_cache_warm:{family}").set(fam_entries > 0)
        except Exception:
            pass
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
        # sane thresholds: bank everything that took real compile time,
        # regardless of blob size (the default 1 MiB floor would skip
        # most of this repo's per-iteration programs)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.2)
        except Exception:
            pass        # older jax without the knobs: dir alone still works
        return d
    except Exception:
        return None


# reserved non-JIT subtrees of the cache dir: the serving AOT export
# store (fleet/aot.py) and the autotuner's timing store (ops/planner.py)
# live BESIDE the XLA blob pool and must never count as JIT warmth
_CACHE_RESERVED_SUBDIRS = ("serving", "autotune")


def compile_cache_entries(path=None):
    """Number of banked XLA JIT blobs under the active cache dir (0 when
    disabled/missing) — bench.py's cold-vs-warm discriminator.

    Counts the JIT pool ONLY: the reserved ``serving/`` (AOT exports)
    and ``autotune/`` (timing store) subtrees are excluded, so a
    serving-only or probe-only prior run can no longer make a training
    cold start report warm (the family-attribution bugfix)."""
    d = path or os.environ.get("LGBM_TPU_COMPILE_CACHE", "").strip() \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR", "").strip()
    if not d or not os.path.isdir(d):
        return 0
    try:
        total = 0
        for root, dirs, files in os.walk(d):
            if root == d:
                dirs[:] = [s for s in dirs
                           if s not in _CACHE_RESERVED_SUBDIRS]
            total += len(files)
        return total
    except OSError:
        return 0


def compile_cache_entries_by_family(path=None):
    """Entry counts under the active cache dir, keyed by what each entry
    IS: ``jit`` for the shared XLA blob pool, ``serving_aot`` for the
    exported-program store (``<dir>/serving``, fleet/aot.py) and
    ``autotune`` for the planner's measured-timings store.  {} when the
    cache is disabled or missing — the attributable form of
    ``compile_cache_entries`` the bench journals per stage."""
    d = path or os.environ.get("LGBM_TPU_COMPILE_CACHE", "").strip() \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR", "").strip()
    if not d or d.lower() in ("0", "off", "none") or not os.path.isdir(d):
        return {}

    def count(p):
        try:
            return sum(len(files) for _, _, files in os.walk(p))
        except OSError:
            return 0

    out = {"jit": compile_cache_entries(d)}
    for name, key in (("serving", "serving_aot"), ("autotune", "autotune")):
        sub = os.path.join(d, name)
        if os.path.isdir(sub):
            out[key] = count(sub)
    return out


def force_cpu_inprocess(n_devices: int = 8) -> None:
    """Pin this process's JAX to N virtual CPU devices, de-registering any
    TPU plugin factory before backend initialization.

    Works even after ``import jax`` (the plugin registers a *factory*;
    the block happens at factory init inside ``backends()``), but must be
    called before the first backend access.  No-op with a warning if
    backends are already initialized.
    """
    flags = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    import jax
    from jax._src import xla_bridge as _xb
    if _xb.backends_are_initialized():
        import warnings
        warnings.warn("force_cpu_inprocess called after JAX backend init; "
                      "platform not changed")
        return
    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)


def reexec_clean_cpu(argv=None, n_devices: int = 8, guard_var: str = "_LGBM_TPU_CPU_REEXEC") -> None:
    """Replace the current process with one running under a clean CPU env.

    No-op (returns) when already re-exec'd or when no TPU plugin is
    active.  ``argv`` defaults to ``sys.argv`` (re-invoking the current
    script verbatim under the interpreter); callers invoked via ``-c``
    must pass an explicit argv.
    """
    if os.environ.get(guard_var):
        return
    if not tpu_plugin_active():
        return
    env = clean_cpu_env(n_devices)
    env[guard_var] = "1"
    os.execve(sys.executable, [sys.executable] + list(argv or sys.argv), env)
