"""Utilities: logging, SHAP, timers."""
