"""Hierarchical wall-clock timing.

reference: Common::Timer + RAII FunctionTimer (include/LightGBM/utils/
common.h:1026-1110), compile-time gated by -DUSE_TIMETAG and dumped at exit
through the single ``global_timer`` (application.cpp:30, tags through the
hot paths e.g. serial_tree_learner.cpp:150,232,262,322; gbdt.cpp:153,211).

Here the gate is runtime: set ``LIGHTGBM_TPU_TIMETAG=1`` in the environment
(or call ``global_timer.enable()``) and every tagged section accumulates
(count, total seconds) under its name; the table prints at interpreter exit
sorted by total time, like Timer::Print.  Disabled, a tagged section costs
one attribute check.

Machine-readable exit dump: ``LIGHTGBM_TPU_TIMETAG=json`` emits a JSON
object to stderr instead of the table; ``LIGHTGBM_TPU_TIMETAG=json:<path>``
writes it to ``<path>`` — so bench stages and CI journal timer totals
instead of scraping the human table.  ``publish()`` mirrors the totals
into the unified process metrics registry (``obs.metrics``,
docs/OBSERVABILITY.md) as ``timer.<name>.{calls,total_s}`` gauges.

Because device work is asynchronous under jit, host-side sections measure
dispatch + the points where the host blocks (fetching tree arrays, metric
values) — the same wall-clock decomposition the reference reports, with
"device program" time showing up in the section that first blocks on it.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from contextlib import contextmanager


class Timer:
    """Accumulating named wall-clock sections (thread-safe)."""

    def __init__(self, enabled: bool | None = None):
        if enabled is None:
            # any non-empty value but "0" enables ("1" = table at exit,
            # "json"/"json:<path>" = machine-readable exit dump)
            enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") \
                not in ("", "0")
        self.enabled = enabled
        self._acc: dict = {}          # name -> [count, total_seconds]
        self._lock = threading.Lock()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            slot = self._acc.setdefault(name, [0, 0.0])
            slot[0] += 1
            slot[1] += seconds

    @contextmanager
    def section(self, name: str):
        """``with global_timer.section("GBDT::TrainOneIter"): ...``
        (reference: FunctionTimer RAII guard, common.h:1091-1110)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def items(self):
        with self._lock:
            return {k: tuple(v) for k, v in self._acc.items()}

    def to_dict(self) -> dict:
        """JSON-ready totals: name -> {calls, total_s, mean_ms}."""
        return {
            name: {"calls": cnt, "total_s": round(total, 6),
                   "mean_ms": round(total / cnt * 1e3, 6) if cnt else 0.0}
            for name, (cnt, total) in self.items().items()
        }

    def dump_json(self, path=None) -> str:
        """The machine-readable form of ``print``; writes to ``path``
        when given, returns the JSON string either way."""
        import json
        s = json.dumps({"timers": self.to_dict()}, indent=1, sort_keys=True)
        if path:
            from .file_io import write_atomic
            write_atomic(path, s)
        return s

    def publish(self, registry=None) -> dict:
        """Mirror the totals into the unified process metrics registry
        (default: ``obs.metrics.global_registry``) as
        ``timer.<name>.calls`` / ``timer.<name>.total_s`` gauges, so
        bench stages journal them with the rest of the snapshot instead
        of scraping stderr.  Returns the mirrored totals."""
        if registry is None:
            from ..obs.metrics import global_registry as registry
        items = self.items()
        for name, (cnt, total) in items.items():
            registry.gauge(f"timer.{name}.calls").set(cnt)
            registry.gauge(f"timer.{name}.total_s").set(round(total, 6))
        return items

    def print(self, file=None) -> None:
        """reference: Timer::Print (common.h:1054-1070)."""
        if file is None:
            file = sys.stderr
        rows = sorted(self.items().items(), key=lambda kv: -kv[1][1])
        if not rows:
            return
        width = max(len(k) for k, _ in rows)
        print("LightGBM-TPU timers (name, calls, total s, mean ms):",
              file=file)
        for name, (cnt, total) in rows:
            print(f"  {name:<{width}}  {cnt:>8}  {total:>10.3f}  "
                  f"{total / cnt * 1e3:>10.3f}", file=file)


global_timer = Timer()


def function_timer(name: str, timer: Timer = global_timer):
    """Decorator form (reference FunctionTimer wraps whole functions)."""

    def wrap(fn):
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if not timer.enabled:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                timer.add(name, time.perf_counter() - t0)

        return inner

    return wrap


@atexit.register
def _print_at_exit() -> None:
    if not global_timer.enabled:
        return
    mode = os.environ.get("LIGHTGBM_TPU_TIMETAG", "")
    if mode == "json" or mode.startswith("json:"):
        # an empty path ("json:") falls back to stderr, never silence
        path = (mode[5:] or None) if mode.startswith("json:") else None
        try:
            s = global_timer.dump_json(path)
            if path is None:
                print(s, file=sys.stderr)
        except OSError:
            global_timer.print()
    else:
        global_timer.print()
