"""Exact TreeSHAP (unique-path algorithm).

reference: src/io/tree.cpp TreeSHAP / Tree::PredictContrib (tree.h:137),
which implements Lundberg et al.'s algorithm 2.  Host-side NumPy/recursion;
trees are small so this is fine off the hot path.
"""

from __future__ import annotations

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, f=-1, z=0.0, o=0.0, w=0.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend(path, unique_depth, zero_fraction, one_fraction, feature_index):
    path.append(_PathElement(feature_index, zero_fraction, one_fraction,
                             1.0 if unique_depth == 0 else 0.0))
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (unique_depth - i) / (unique_depth + 1)


def _unwind(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_sum(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((unique_depth - i) / (unique_depth + 1))
        else:
            total += path[i].pweight / (zero_fraction * ((unique_depth - i) / (unique_depth + 1)))
    return total


def tree_shap(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate SHAP values of one sample into phi [num_features+1]."""

    def node_count(node):
        return tree.internal_count[node] if node >= 0 else tree.leaf_count[~node]

    def node_value(node):
        return tree.internal_value[node] if node >= 0 else tree.leaf_value[~node]

    def recurse(node, path, parent_zero, parent_one, parent_feature):
        unique_depth = len(path)
        path = [p.copy() for p in path]
        _extend(path, unique_depth, parent_zero, parent_one, parent_feature)
        if node < 0:  # leaf
            for i in range(1, unique_depth + 1):
                w = _unwound_sum(path, unique_depth, i)
                el = path[i]
                phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * node_value(node)
            return
        hot = tree.left_child[node] if _goes_left(tree, x, node) else tree.right_child[node]
        cold = tree.right_child[node] if _goes_left(tree, x, node) else tree.left_child[node]
        hot_frac = node_count(hot) / max(node_count(node), 1e-30)
        cold_frac = node_count(cold) / max(node_count(node), 1e-30)
        incoming_zero, incoming_one = 1.0, 1.0
        path_index = 0
        feat = int(tree.split_feature[node])
        while path_index <= unique_depth:
            if path[path_index].feature_index == feat:
                break
            path_index += 1
        if path_index != unique_depth + 1:
            incoming_zero = path[path_index].zero_fraction
            incoming_one = path[path_index].one_fraction
            _unwind(path, unique_depth, path_index)
        recurse(hot, path, hot_frac * incoming_zero, incoming_one, feat)
        recurse(cold, path, cold_frac * incoming_zero, 0.0, feat)

    recurse(0, [], 1.0, 1.0, -1)
    # bias term: expected value
    phi[-1] += tree.expected_value()


def _goes_left(tree, x, node):
    fval = x[tree.split_feature[node]]
    return bool(np.asarray(tree._decide(np.array([fval]), node))[0])


# ---------------------------------------------------------------------------
# Batched TreeSHAP: one DFS over the tree serves every row at once.
#
# Key observation making this possible: the recursion ORDER and the path's
# (feature, zero_fraction) entries are row-independent — only one_fraction
# and pweight depend on the row (through the go-left decision at each
# node).  So the path state becomes (scalar feature, scalar zero_fraction,
# [n] one_fraction, [n] pweight) and EXTEND/UNWIND become vector ops.  The
# hot/cold asymmetry of the scalar algorithm (hot child inherits
# incoming_one, cold gets 0) is expressed as one_fraction * goes_to_child.
# ---------------------------------------------------------------------------


def tree_shap_batch(tree, X: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate SHAP values of a batch into phi [n, num_features+1]."""
    n = X.shape[0]
    if tree.num_leaves <= 1:
        phi[:, -1] += tree.expected_value()
        return

    # precompute per-node go-left decision vectors [n]
    ns = tree.num_leaves - 1
    goes_left = np.zeros((ns, n), bool)
    for nd in range(ns):
        goes_left[nd] = tree._decide(X[:, tree.split_feature[nd]], nd)

    def node_count(node):
        return float(tree.internal_count[node] if node >= 0
                     else tree.leaf_count[~node])

    ones = np.ones(n)

    # path element arrays, parallel lists indexed by path position
    def recurse(node, feats, zeros, one_list, pw_list,
                parent_zero, parent_one, parent_feature):
        ud = len(feats)  # unique_depth
        feats = feats + [parent_feature]
        zeros = zeros + [parent_zero]
        one_list = [o for o in one_list] + [parent_one]
        pw_list = [p.copy() for p in pw_list] + \
            [ones.copy() if ud == 0 else np.zeros(n)]
        for i in range(ud - 1, -1, -1):
            pw_list[i + 1] += parent_one * pw_list[i] * ((i + 1) / (ud + 1))
            pw_list[i] = parent_zero * pw_list[i] * ((ud - i) / (ud + 1))

        if node < 0:  # leaf: attribute along the unique path
            val = float(tree.leaf_value[~node])
            for pi in range(1, ud + 1):
                w = _unwound_sum_batch(zeros, one_list, pw_list, ud, pi)
                phi[:, feats[pi]] += w * (one_list[pi] - zeros[pi]) * val
            return

        feat = int(tree.split_feature[node])
        gl = goes_left[node]
        cnt = max(node_count(node), 1e-30)
        incoming_zero, incoming_one = 1.0, ones
        pi = 0
        while pi <= ud:
            if feats[pi] == feat:
                break
            pi += 1
        if pi != ud + 1:
            incoming_zero = zeros[pi]
            incoming_one = one_list[pi]
            feats, zeros, one_list, pw_list = _unwind_batch(
                feats, zeros, one_list, pw_list, ud, pi)
            ud -= 1
        for child, to_child in ((int(tree.left_child[node]), gl),
                                (int(tree.right_child[node]), ~gl)):
            frac = node_count(child) / cnt
            recurse(child, feats, zeros, one_list, pw_list,
                    frac * incoming_zero, incoming_one * to_child, feat)

    import sys
    limit = sys.getrecursionlimit()
    if limit < 4 * tree.num_leaves + 100:
        sys.setrecursionlimit(4 * tree.num_leaves + 100)
    recurse(0, [], [], [], [], 1.0, ones, -1)
    phi[:, -1] += tree.expected_value()


def _unwind_batch(feats, zeros, one_list, pw_list, ud, pi):
    of = one_list[pi]            # [n]
    zf = zeros[pi]               # scalar
    of_nz = of != 0
    of_safe = np.where(of_nz, of, 1.0)
    pw_list = [p.copy() for p in pw_list]
    next_one = pw_list[ud].copy()
    for i in range(ud - 1, -1, -1):
        tmp = pw_list[i]
        a = next_one * ((ud + 1) / (i + 1)) / of_safe
        b = tmp * (ud + 1) / (zf * (ud - i)) if zf != 0 else tmp * 0.0
        new_pw = np.where(of_nz, a, b)
        next_one = np.where(of_nz,
                            tmp - new_pw * zf * ((ud - i) / (ud + 1)),
                            next_one)
        pw_list[i] = new_pw
    # features/fractions shift left over the removed slot; pweights do NOT
    # shift — the loop above recomputed pw[0..ud-1] and the last is dropped
    # (mirrors scalar _unwind: in-place overwrite + path.pop())
    feats = feats[:pi] + feats[pi + 1:]
    zeros = zeros[:pi] + zeros[pi + 1:]
    one_list = one_list[:pi] + one_list[pi + 1:]
    pw_list = pw_list[:ud]
    return feats, zeros, one_list, pw_list


def _unwound_sum_batch(zeros, one_list, pw_list, ud, pi):
    of = one_list[pi]
    zf = zeros[pi]
    of_nz = of != 0
    of_safe = np.where(of_nz, of, 1.0)
    next_one = pw_list[ud]
    total = np.zeros_like(next_one)
    for i in range(ud - 1, -1, -1):
        a = next_one * ((ud + 1) / (i + 1)) / of_safe
        b = (pw_list[i] / (zf * ((ud - i) / (ud + 1)))
             if zf != 0 else pw_list[i] * 0.0)
        total += np.where(of_nz, a, b)
        next_one = np.where(of_nz,
                            pw_list[i] - a * zf * ((ud - i) / (ud + 1)),
                            next_one)
    return total
