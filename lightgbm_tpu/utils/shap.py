"""Exact TreeSHAP (unique-path algorithm).

reference: src/io/tree.cpp TreeSHAP / Tree::PredictContrib (tree.h:137),
which implements Lundberg et al.'s algorithm 2.  Host-side NumPy/recursion;
trees are small so this is fine off the hot path.
"""

from __future__ import annotations

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, f=-1, z=0.0, o=0.0, w=0.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend(path, unique_depth, zero_fraction, one_fraction, feature_index):
    path.append(_PathElement(feature_index, zero_fraction, one_fraction,
                             1.0 if unique_depth == 0 else 0.0))
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (unique_depth - i) / (unique_depth + 1)


def _unwind(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_sum(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((unique_depth - i) / (unique_depth + 1))
        else:
            total += path[i].pweight / (zero_fraction * ((unique_depth - i) / (unique_depth + 1)))
    return total


def tree_shap(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate SHAP values of one sample into phi [num_features+1]."""

    def node_count(node):
        return tree.internal_count[node] if node >= 0 else tree.leaf_count[~node]

    def node_value(node):
        return tree.internal_value[node] if node >= 0 else tree.leaf_value[~node]

    def recurse(node, path, parent_zero, parent_one, parent_feature):
        unique_depth = len(path)
        path = [p.copy() for p in path]
        _extend(path, unique_depth, parent_zero, parent_one, parent_feature)
        if node < 0:  # leaf
            for i in range(1, unique_depth + 1):
                w = _unwound_sum(path, unique_depth, i)
                el = path[i]
                phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * node_value(node)
            return
        hot = tree.left_child[node] if _goes_left(tree, x, node) else tree.right_child[node]
        cold = tree.right_child[node] if _goes_left(tree, x, node) else tree.left_child[node]
        hot_frac = node_count(hot) / max(node_count(node), 1e-30)
        cold_frac = node_count(cold) / max(node_count(node), 1e-30)
        incoming_zero, incoming_one = 1.0, 1.0
        path_index = 0
        feat = int(tree.split_feature[node])
        while path_index <= unique_depth:
            if path[path_index].feature_index == feat:
                break
            path_index += 1
        if path_index != unique_depth + 1:
            incoming_zero = path[path_index].zero_fraction
            incoming_one = path[path_index].one_fraction
            _unwind(path, unique_depth, path_index)
        recurse(hot, path, hot_frac * incoming_zero, incoming_one, feat)
        recurse(cold, path, cold_frac * incoming_zero, 0.0, feat)

    recurse(0, [], 1.0, 1.0, -1)
    # bias term: expected value
    phi[-1] += tree.expected_value()


def _goes_left(tree, x, node):
    fval = x[tree.split_feature[node]]
    return bool(np.asarray(tree._decide(np.array([fval]), node))[0])
