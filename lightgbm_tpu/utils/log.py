"""Logging, mirroring reference utils/log.h:26-118 semantics.

Levels: Fatal < Warning < Info < Debug.  ``Fatal`` raises (the reference
throws std::runtime_error caught at API boundaries).
"""

from __future__ import annotations

import sys

_LEVELS = {"fatal": -1, "warning": 0, "info": 1, "debug": 2}
_current_level = 1


def reset_log_level(level: str) -> None:
    global _current_level
    _current_level = _LEVELS[level.lower()]


def set_verbosity(verbosity: int) -> None:
    global _current_level
    _current_level = max(-1, min(int(verbosity), 2))


def log_debug(msg: str) -> None:
    if _current_level >= 2:
        print(f"[LightGBM-TPU] [Debug] {msg}", file=sys.stderr)


def log_info(msg: str) -> None:
    if _current_level >= 1:
        print(f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _current_level >= 0:
        print(f"[LightGBM-TPU] [Warning] {msg}", file=sys.stderr)


def log_fatal(msg: str) -> None:
    raise RuntimeError(f"[LightGBM-TPU] [Fatal] {msg}")
