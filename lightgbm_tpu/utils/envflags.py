"""Central registry of every environment flag the project reads.

The codebase is steered by ``LGBM_TPU_*`` / ``LIGHTGBM_TPU_*`` (library
behavior) and ``BENCH_*`` (bench driver) env gates.  Before this module
they lived as string literals scattered over ~20 files with no single
place answering "what knobs exist, what do they default to, and where
are they documented".  Every flag must be declared here — ``tpulint``'s
``env-flag-registry`` rule (tools/lint/) fails any matching string
literal in the tree that this registry does not know, any registry
entry whose name is absent from its declared doc file, and any stale
entry no code reads anymore.

This module is declarative and import-cheap (stdlib only, no jax): the
reading call sites keep their existing ``os.environ.get(...)`` idiom —
rewiring ~70 call sites through one accessor would churn every module
for zero behavioral gain — but new flags MUST be registered here first
or lint fails the PR by name.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, Optional


@dataclasses.dataclass(frozen=True)
class EnvFlag:
    """One environment knob: its default (textual, '' = unset), the
    module that reads it, a one-line doc, and the docs/ file that must
    mention it by name (the lint docs anchor)."""

    name: str
    default: str
    consumer: str
    doc: str
    docfile: str


def _f(name: str, default: str, consumer: str, doc: str,
       docfile: str) -> EnvFlag:
    return EnvFlag(name, default, consumer, doc, docfile)


_PERF = "docs/PERF.md"
_PERFORMANCE = "docs/PERFORMANCE.md"
_OBS = "docs/OBSERVABILITY.md"
_LIFECYCLE = "docs/LIFECYCLE.md"

FLAGS: Dict[str, EnvFlag] = {f.name: f for f in [
    # ------------------------------------------------ kernel/planner gates
    _f("LGBM_TPU_FUSED", "1", "ops/fused.py",
       "fused histogram->split megakernel eligibility ('0' disables)", _PERF),
    _f("LGBM_TPU_SHARED_FRONTIER", "1", "ops/fused.py",
       "sharded fused training reuses ONE accumulate program for root "
       "and every level ('0' disables)", _PERF),
    _f("LGBM_TPU_AUTOTUNE", "1", "ops/planner.py",
       "measured-timings kernel election ('0' = analytic model only)",
       _PERF),
    _f("LGBM_TPU_AUTOTUNE_DIR", "", "ops/planner.py",
       "measured-timings store dir (default: <compile cache>/autotune)",
       _PERF),
    _f("LGBM_TPU_SHAPE_BUCKETS", "", "ops/planner.py",
       "pad training rows to ladder rungs so nearby sizes share one "
       "compiled program ('1' on, '0' off; default: accelerators only)",
       _PERF),
    _f("LGBM_TPU_SEGHIST", "", "ops/histogram.py",
       "force a histogram kernel family, bypassing the planner", _PERF),
    _f("LGBM_TPU_TABLE_MATMUL", "", "ops/histogram.py",
       "'0' demotes take_from_table's matmul gather to plain gather",
       _PERFORMANCE),
    _f("LGBM_TPU_SMALL_ROUNDS", "1", "ops/histogram.py",
       "small-frontier rounds kernel election ('0' disables)", _PERFORMANCE),
    _f("LGBM_TPU_PACK", "1", "grower_rounds.py",
       "packed per-level rounds program ('0' disables)", _PERFORMANCE),
    _f("LGBM_TPU_ROUTER", "1", "grower_rounds.py",
       "in-program row router ('0' disables)", _PERFORMANCE),
    _f("LGBM_TPU_HBM_BYTES", "", "ops/planner.py",
       "override detected device HBM capacity (bytes)", _PERF),
    _f("LGBM_TPU_VMEM_BYTES", "", "ops/planner.py",
       "override the VMEM budget the fused-kernel model plans against",
       _PERF),
    _f("LGBM_TPU_HOST_BYTES", "", "ops/planner.py",
       "override the host-RSS budget for the streaming planner", _PERF),
    _f("LGBM_TPU_TILE_ROWS", "", "ops/planner.py",
       "force the histogram row-tile size ('0' = untiled)", _PERF),
    _f("LGBM_TPU_ICI_GBPS", "", "ops/planner.py",
       "per-link ICI bandwidth (GB/s) for the collective link model",
       _PERF),
    _f("LGBM_TPU_DCN_GBPS", "", "ops/planner.py",
       "DCN bandwidth (GB/s) for the collective link model", _PERF),
    _f("LGBM_TPU_HIER_REDUCE", "", "ops/planner.py",
       "force ('1') / forbid ('0') tiered ICIxDCN reductions", _PERF),
    _f("LGBM_TPU_PINNED_REDUCE", "", "ops/planner.py",
       "pin the tiered-reduction variant the planner would elect", _PERF),
    _f("LGBM_TPU_PREDICT_KERNEL", "", "ops/planner.py",
       "pin the predict traversal variant (while/fori/fused), bypassing "
       "the measured + analytic election", _PERF),
    _f("LGBM_TPU_PREDICT_CHUNK", "", "ops/planner.py",
       "force the predict device chunk / CSR densify chunk (rows)", _PERF),
    _f("LGBM_TPU_PREDICT_EPILOGUE", "", "predict.py",
       "'0' pins the host float64 leaf-sum epilogue (skips the device "
       "bit-exactness probe)", _PERF),
    _f("LGBM_TPU_INGEST_KERNEL", "", "ops/planner.py",
       "pin the device-ingest binning variant ('kernel'/'host'), "
       "bypassing the measured + analytic election", _PERF),
    _f("LGBM_TPU_INGEST_CHUNK", "", "ops/planner.py",
       "force the streamed-ingest chunk size (rows)", _PERF),
    # ------------------------------------------------------ data plane
    _f("LGBM_TPU_STREAM", "", "ops/planner.py",
       "force ('1') / forbid ('0') out-of-core row-block streaming", _PERF),
    _f("LGBM_TPU_STREAM_BLOCK_ROWS", "", "ops/planner.py",
       "force the streaming row-block size", _PERF),
    _f("LGBM_TPU_STREAM_DIR", "", "data/stream.py",
       "directory for the spill blockstore (default: a tmpdir)", _PERF),
    _f("LGBM_TPU_FREE_BINNED", "", "boosting/gbdt.py",
       "'1' frees the host binned matrix after device upload", _PERF),
    _f("LGBM_TPU_CHUNK", "", "boosting/macro.py",
       "macro-chunk size override ('0'/'off' disables chunking)", _PERF),
    _f("LGBM_TPU_MODEL_BATCH", "", "ops/planner.py",
       "cap the batched model-axis lane chunk ('0'/'off' forces "
       "sequential training)", _PERF),
    _f("LGBM_TPU_COMPILE_CACHE", "", "utils/platform.py, fleet/aot.py",
       "persistent XLA compile-cache + AOT-export directory", _PERF),
    _f("LGBT_DEFER_HOST_TREES", "", "boosting/gbdt.py",
       "'1' defers host tree fetch to training end (legacy prefix)", _PERF),
    # ------------------------------------------------------ model lifecycle
    _f("LGBM_TPU_LIFECYCLE_DIR", "", "lifecycle/rollout.py",
       "bundle + rollout-journal directory for the model lifecycle",
       _LIFECYCLE),
    _f("LGBM_TPU_LIFECYCLE_DRIFT_BUDGET", "10.0", "lifecycle/rollout.py",
       "max candidate-vs-live raw-score drift a rollout tolerates",
       _LIFECYCLE),
    _f("LGBM_TPU_LIFECYCLE_P99_MS", "", "lifecycle/rollout.py",
       "candidate p99 latency ceiling (ms) for the rollout gates",
       _LIFECYCLE),
    _f("LGBM_TPU_LIFECYCLE_MIRROR", "0.25", "lifecycle/rollout.py",
       "fraction of live requests mirrored to the candidate", _LIFECYCLE),
    _f("LGBM_TPU_LIFECYCLE_RAMP", "0.05,0.25,0.5", "lifecycle/rollout.py",
       "comma list of staged canary traffic fractions", _LIFECYCLE),
    # ------------------------------------------------------ parallel plane
    _f("LGBM_TPU_NUM_SLICES", "", "parallel/learners.py",
       "slice count for the simulated/hybrid multi-host mesh", _PERF),
    _f("LGBM_TPU_SLICE_DEVICES", "", "parallel/network.py",
       "devices per slice for the hybrid mesh plan", _PERF),
    # ------------------------------------------------------ observability
    _f("LIGHTGBM_TPU_TIMETAG", "", "utils/timer.py",
       "'1' timer table at exit; 'json'/'json:<path>' machine form", _OBS),
    _f("LIGHTGBM_TPU_TRACE", "", "obs/trace.py",
       "'1' record spans; any other value also dumps Chrome JSON there",
       _OBS),
    _f("LIGHTGBM_TPU_TRACE_MAX_EVENTS", "1000000", "obs/trace.py",
       "cap on the in-process span list", _OBS),
    _f("LIGHTGBM_TPU_FLIGHT", "1", "obs/flight.py",
       "flight recorder armed (default on); '0' disarms", _OBS),
    _f("LIGHTGBM_TPU_FLIGHT_EVENTS", "2048", "obs/flight.py",
       "flight ring capacity", _OBS),
    _f("LIGHTGBM_TPU_FLIGHT_DIR", "", "obs/flight.py",
       "flight bundle directory (default cwd)", _OBS),
    _f("LIGHTGBM_TPU_FLIGHT_MAX_DUMPS", "8", "obs/flight.py",
       "per-process flight dump budget", _OBS),
    _f("LIGHTGBM_TPU_WATCHDOG", "", "obs/watchdog.py",
       "'1' starts the SLO sentry thread at engine/server init", _OBS),
    _f("LIGHTGBM_TPU_WATCHDOG_INTERVAL_S", "5", "obs/watchdog.py",
       "sentry check interval (seconds)", _OBS),
    _f("LIGHTGBM_TPU_SLO_TREES_PER_SEC", "", "obs/watchdog.py",
       "training throughput floor (trees/sec) the sentry enforces", _OBS),
    _f("LIGHTGBM_TPU_SLO_SERVING_P99_MS", "", "obs/watchdog.py",
       "serving p99 latency ceiling (ms)", _OBS),
    _f("LIGHTGBM_TPU_SLO_MODEL_AGE_S", "", "obs/watchdog.py",
       "deployed-model freshness ceiling (seconds since promotion)",
       _OBS),
    _f("LIGHTGBM_TPU_SLO_AVAILABILITY", "", "obs/watchdog.py",
       "per-model windowed availability floor (0..1) the sentry "
       "enforces; typed shed/expired excluded", _OBS),
    _f("LIGHTGBM_TPU_SLO_HEARTBEAT_S", "300", "obs/watchdog.py",
       "heartbeat staleness threshold (seconds)", _OBS),
    _f("LIGHTGBM_TPU_METRICS_PORT", "", "obs/http.py",
       "opt-in HTTP metrics port ('0' = ephemeral)", _OBS),
    _f("LIGHTGBM_TPU_METRICS_HOST", "127.0.0.1", "obs/http.py",
       "bind host for the HTTP metrics endpoint", _OBS),
    # ------------------------------------------------- co-resident train+serve
    _f("LGBM_TPU_CORESIDENT_CHUNK_CAP", "", "coresident/scheduler.py",
       "macro-chunk cap ceiling for co-resident refreshes (default: the "
       "LGBM_TPU_CHUNK cap)", _PERF),
    _f("LGBM_TPU_CORESIDENT_THROTTLE_S", "0.02", "coresident/scheduler.py",
       "host-side yield per engine consult while brownout-throttled "
       "(seconds)", _PERF),
    _f("LGBM_TPU_CORESIDENT_RECOVERY_S", "1.0", "coresident/scheduler.py",
       "quiet time after the last breach ping before throttled/paused "
       "training resumes at full cap (seconds)", _PERF),
    # ------------------------------------------------------ bench workload
    _f("BENCH_ROWS", "11000000", "bench.py",
       "full-stage training rows", _PERF),
    _f("BENCH_TREES", "500", "bench.py", "full-stage tree count", _PERF),
    _f("BENCH_LEAVES", "255", "bench.py", "num_leaves for bench stages",
       _PERF),
    _f("BENCH_BIN", "63", "bench.py", "max_bin for bench stages", _PERF),
    _f("BENCH_CPU_ROWS", "200000", "bench.py",
       "CPU-fallback stage rows", _PERF),
    _f("BENCH_CPU_TREES", "50", "bench.py",
       "CPU-fallback stage tree count", _PERF),
    _f("BENCH_SMOKE_ROWS", "500000", "bench.py", "smoke-stage rows", _PERF),
    _f("BENCH_SMOKE_TREES", "3", "bench.py",
       "smoke-stage tree count", _PERF),
    _f("BENCH_RANK_QUERIES", "12000", "bench.py",
       "ranking-stage query count", _PERF),
    _f("BENCH_RANK_DOCS", "100", "bench.py",
       "ranking-stage docs per query", _PERF),
    _f("BENCH_RANK_TREES", "100", "bench.py",
       "ranking-stage tree count", _PERF),
    _f("BENCH_STREAM_ROWS", "100000000", "bench.py",
       "out-of-core streaming stage rows", _PERF),
    _f("BENCH_STREAM_TREES", "3", "bench.py",
       "out-of-core streaming stage tree count", _PERF),
    _f("BENCH_BULK_ROWS", "10000000", "bench.py",
       "bulk offline-scoring stage rows", _PERF),
    _f("BENCH_TOTAL_BUDGET", "6600", "bench.py",
       "wall-clock budget (seconds) the stage gates spend against", _PERF),
    _f("BENCH_STALL_TIMEOUT", "2400", "bench.py",
       "driver-side worker stall kill timer (seconds)", _PERF),
    _f("BENCH_EXTRA_PARAMS", "", "bench.py",
       "JSON dict merged into every bench stage's train params", _PERF),
    # ------------------------------------------------------ bench plumbing
    _f("BENCH_STAGE", "", "bench.py",
       "internal: which worker the re-exec'd child runs", _PERF),
    _f("BENCH_JOURNAL", "", "bench.py",
       "journal path ('0' disables; default ./bench_journal.json)", _PERF),
    _f("BENCH_ONLY", "", "bench.py",
       "comma list of worker stages to run exclusively", _PERF),
    _f("BENCH_WORKER_ROWS", "", "bench.py",
       "internal: row count handed to the TPU worker's full stage", _PERF),
    _f("BENCH_WORKER_ALLOW_CPU", "", "bench.py",
       "'1' lets the TPU worker run on a CPU backend", _PERF),
    _f("BENCH_FORCE_CPU", "", "bench.py",
       "'1' runs only the CPU-fallback stage", _PERF),
    _f("BENCH_PROFILE", "", "bench.py",
       "'1' captures a jax.profiler trace around the train loop", _OBS),
    # ------------------------------------------------------ bench skips
    _f("BENCH_SKIP_KERNEL_PROBE", "", "bench.py",
       "'1' skips the kernel bit-exactness probe", _PERF),
    _f("BENCH_SKIP_DISPATCH_PROBE", "", "bench.py",
       "'1' skips the dispatch-latency probe", _PERF),
    _f("BENCH_SKIP_HIST_PROBE", "", "bench.py",
       "'1' skips the histogram-variant probe", _PERF),
    _f("BENCH_SKIP_STREAM_PROBE", "", "bench.py",
       "'1' skips the streaming-plane probe", _PERF),
    _f("BENCH_SKIP_COLLECTIVE_PROBE", "", "bench.py",
       "'1' skips the collective-plane probe", _PERF),
    _f("BENCH_SKIP_SMOKE", "", "bench.py", "'1' skips the smoke stage",
       _PERF),
    _f("BENCH_SKIP_STREAM", "", "bench.py",
       "'1' skips the out-of-core streaming stage", _PERF),
    _f("BENCH_SKIP_RANKING", "", "bench.py",
       "'1' skips the ranking stage", _PERF),
    _f("BENCH_SKIP_SERVING", "", "bench.py",
       "'1' skips the serving stage", _PERF),
    _f("BENCH_SKIP_FLEET", "", "bench.py",
       "'1' skips the fleet AND fleet_failover stages", _PERF),
    _f("BENCH_FLEET_DEVICES", "3", "bench.py",
       "simulated device count for the fleet_failover drill", _PERF),
    _f("BENCH_SKIP_RESILIENCE", "", "bench.py",
       "'1' skips the resilience stage", _PERF),
    _f("BENCH_SKIP_LIFECYCLE", "", "bench.py",
       "'1' skips the model-lifecycle stage", _PERF),
    _f("BENCH_SKIP_CORESIDENT", "", "bench.py",
       "'1' skips the co-resident train+serve stage", _PERF),
    _f("BENCH_SKIP_OBS", "", "bench.py",
       "'1' skips obs_dump/obs_doctor stages + the measured-MFU table",
       _OBS),
    _f("BENCH_SKIP_LINT", "", "bench.py",
       "'1' skips the journaled tpulint stage", _PERF),
    _f("BENCH_SKIP_SWEEP", "", "bench.py",
       "'1' skips the batched model-axis sweep probe", _PERF),
    _f("BENCH_SKIP_PREDICT_PROBE", "", "bench.py",
       "'1' skips the inference-kernel probe", _PERF),
    _f("BENCH_SKIP_BULK_SCORE", "", "bench.py",
       "'1' skips the bulk offline-scoring stage", _PERF),
    _f("BENCH_SKIP_INGEST_PROBE", "", "bench.py",
       "'1' skips the device-ingest binning probe", _PERF),
    _f("BENCH_SKIP_INGEST_11M", "", "bench.py",
       "'1' skips the streamed 11M-row ingest stage", _PERF),
]}


def lookup(name: str) -> Optional[EnvFlag]:
    """The registry entry for ``name``, or None for unknown flags."""
    return FLAGS.get(name)


def all_flags() -> Iterable[EnvFlag]:
    return FLAGS.values()


def get(name: str) -> str:
    """Read ``name`` from the environment with its REGISTERED default.
    Raises KeyError for unregistered names — the programmatic analogue
    of the lint rule, for new call sites that want registry-backed
    defaults instead of inline literals."""
    return os.environ.get(name, FLAGS[name].default)
