"""Pluggable file IO: scheme-routed open with a registration seam.

reference: VirtualFileReader/VirtualFileWriter (src/io/file_io.cpp) — local
files by default, an HDFS backend compiled in with USE_HDFS
(CMakeLists.txt:13).  Here the seam is runtime: ``register_file_system``
installs an opener for a URL scheme; unregistered ``scheme://`` paths fall
back to fsspec when installed (which covers hdfs://, gs://, s3://, ...);
plain paths use the builtin ``open``.
"""

from __future__ import annotations

from typing import Callable, Dict

_OPENERS: Dict[str, Callable] = {}


def register_file_system(scheme: str, opener: Callable) -> None:
    """Install ``opener(path, mode) -> file-like`` for ``scheme://`` paths
    (the USE_HDFS build-option analogue, made a runtime registry)."""
    _OPENERS[scheme] = opener


def unregister_file_system(scheme: str) -> None:
    _OPENERS.pop(scheme, None)


def open_file(path, mode: str = "r"):
    """Open ``path`` through the registered backend for its scheme.

    reference: VirtualFileReader::Make / VirtualFileWriter::Make pick the
    HDFS reader for ``hdfs://`` prefixes (file_io.cpp).
    """
    path = str(path)
    if "://" in path:
        scheme = path.split("://", 1)[0]
        if scheme in _OPENERS:
            return _OPENERS[scheme](path, mode)
        try:
            import fsspec
            return fsspec.open(path, mode).open()
        except (ImportError, ValueError) as e:
            raise OSError(
                f"no file system registered for {scheme}:// and fsspec "
                f"cannot handle it ({e}); register_file_system({scheme!r}, "
                "opener) to add one") from e
    return open(path, mode)


def exists(path) -> bool:
    path = str(path)
    if "://" in path:
        try:
            with open_file(path, "r"):
                return True
        except Exception:
            # registered openers are not bound to raise OSError for a
            # missing path (e.g. a dict-backed test FS raises KeyError)
            return False
    import os
    return os.path.exists(path)
