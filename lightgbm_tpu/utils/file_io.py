"""Pluggable file IO: scheme-routed open with a registration seam.

reference: VirtualFileReader/VirtualFileWriter (src/io/file_io.cpp) — local
files by default, an HDFS backend compiled in with USE_HDFS
(CMakeLists.txt:13).  Here the seam is runtime: ``register_file_system``
installs an opener for a URL scheme; unregistered ``scheme://`` paths fall
back to fsspec when installed (which covers hdfs://, gs://, s3://, ...);
plain paths use the builtin ``open``.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

_OPENERS: Dict[str, Callable] = {}
_REMOVERS: Dict[str, Callable] = {}


def register_file_system(scheme: str, opener: Callable,
                         remover: Optional[Callable] = None) -> None:
    """Install ``opener(path, mode) -> file-like`` for ``scheme://`` paths
    (the USE_HDFS build-option analogue, made a runtime registry).

    ``remover(path)`` is optional; backends without one simply skip
    deletions (checkpoint retention logs and moves on)."""
    _OPENERS[scheme] = opener
    if remover is not None:
        _REMOVERS[scheme] = remover
    else:
        _REMOVERS.pop(scheme, None)


def unregister_file_system(scheme: str) -> None:
    _OPENERS.pop(scheme, None)
    _REMOVERS.pop(scheme, None)


def open_file(path, mode: str = "r"):
    """Open ``path`` through the registered backend for its scheme.

    reference: VirtualFileReader::Make / VirtualFileWriter::Make pick the
    HDFS reader for ``hdfs://`` prefixes (file_io.cpp).
    """
    path = str(path)
    if "://" in path:
        scheme = path.split("://", 1)[0]
        if scheme in _OPENERS:
            return _OPENERS[scheme](path, mode)
        try:
            import fsspec
            return fsspec.open(path, mode).open()
        except (ImportError, ValueError) as e:
            raise OSError(
                f"no file system registered for {scheme}:// and fsspec "
                f"cannot handle it ({e}); register_file_system({scheme!r}, "
                "opener) to add one") from e
    return open(path, mode)


import contextlib


@contextlib.contextmanager
def open_atomic(path, mode: str = "w"):
    """Streaming sibling of ``write_atomic``: yields a writable handle
    backed by a temp sibling; a clean exit fsyncs and lands it via
    ``os.replace``, any exception removes the temp.  For payloads too
    large to assemble in memory (binary dataset caches, per-row
    prediction output) — O(1) extra RAM, same crash-safety contract.
    ``scheme://`` paths pass through ``open_file`` (atomicity is the
    backend's contract, as in ``write_atomic``).

    Only ``w``/``wb`` modes: ``x`` would advertise exclusive-create
    semantics the final ``os.replace`` cannot honor, and appends have
    no atomic equivalent.  Non-regular destinations (FIFOs, character
    devices like ``/dev/stdout``) stream through with their NATIVE
    semantics — a FIFO write blocks until a reader attaches, exactly as
    ``> fifo`` would; replacing a user's pipe with a regular file is
    not this seam's call.  Symlinks write atomically THROUGH to the
    resolved target (the link survives; a link to a directory raises)."""
    path = str(path)
    if "w" not in mode:
        raise ValueError(
            f"open_atomic supports only 'w'/'wb' modes, got {mode!r}")
    if "://" in path:
        with open_file(path, mode) as fh:
            yield fh
        return
    import os
    import uuid
    # symlinked destinations ("latest" model/checkpoint links): write
    # atomically THROUGH the link — temp sibling + replace of the
    # resolved target, so the link survives and its readers still never
    # see a torn file.  Genuinely non-regular targets (/dev/stdout,
    # FIFOs, character devices) cannot be renamed into and get plain
    # write-through semantics instead.
    path = os.path.realpath(path)
    if os.path.exists(path) and not os.path.isfile(path):
        with open(path, mode) as fh:
            yield fh
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # O_EXCL + mode 0o666: unique temp sibling whose final permissions are
    # umask-honoring exactly like a plain open() (the kernel applies the
    # umask atomically — no process-global umask flip, no 0600 surprise
    # for whoever serves the model next)
    tmp = os.path.join(d, ".{}.tmp.{}.{}".format(
        os.path.basename(path), os.getpid(), uuid.uuid4().hex[:8]))
    fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o666)
    try:
        with os.fdopen(fd, mode) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def write_atomic(path, data) -> None:
    """Crash-safe write of ``data`` (str or bytes) to ``path``.

    Local paths: parent directories are created, the payload goes to a
    temp sibling in the SAME directory (same filesystem, so the final
    rename cannot cross devices), is fsync'd, and lands via ``os.replace``
    — a reader never observes a truncated file, no matter when the writer
    dies.  ``scheme://`` paths route through the ``open_file`` seam; their
    atomicity is the backend's contract (object stores commit on close),
    and the checksummed checkpoint manifest catches the ones that lie.
    Payloads too large to hold in memory stream through ``open_atomic``.
    """
    mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
    with open_atomic(path, mode) as fh:
        fh.write(data)


def remove(path) -> bool:
    """Best-effort delete through the scheme registry; returns True when
    the file is known gone, False when it could not be deleted (no
    remover, or the backend refused).  Never raises — callers doing
    retention cleanup must not die over an undeletable old file."""
    path = str(path)
    if "://" in path:
        scheme = path.split("://", 1)[0]
        if scheme in _REMOVERS:
            try:
                _REMOVERS[scheme](path)
                return True
            except Exception:
                return False
        if scheme in _OPENERS:
            return False
        try:
            import fsspec
            fs, p = fsspec.core.url_to_fs(path)
            fs.rm(p)
            return True
        except Exception:
            return False
    import os
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return True
    except OSError:
        return False


def exists(path) -> bool:
    path = str(path)
    if "://" in path:
        try:
            with open_file(path, "r"):
                return True
        except Exception:
            # registered openers are not bound to raise OSError for a
            # missing path (e.g. a dict-backed test FS raises KeyError)
            return False
    import os
    return os.path.exists(path)
