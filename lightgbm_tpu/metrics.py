"""Evaluation metrics.

reference: src/metric/ — Metric interface (include/LightGBM/metric.h:24),
factory (src/metric/metric.cpp:17-56), regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp, map_metric.hpp,
xentropy_metric.hpp, dcg_calculator.cpp.

Metrics run on host NumPy: they are O(n) or O(n log n) once per iteration,
off the device critical path (scores are fetched once per eval).  Each
metric returns (name, value, higher_better).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .dataset import Metadata


class Metric:
    name = "none"
    higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.label = np.asarray(metadata.label, np.float64)
        self.weight = (np.asarray(metadata.weight, np.float64)
                       if metadata.weight is not None else None)
        self.sum_weight = (float(self.weight.sum()) if self.weight is not None
                           else float(num_data))
        self.num_data = num_data

    def eval(self, score: np.ndarray, objective) -> List[Tuple[str, float, bool]]:
        raise NotImplementedError

    def names(self) -> List[str]:
        """Names this metric will emit from :meth:`eval`, derivable without
        an evaluation pass (reference: Metric::GetName, metric.h:40)."""
        return [self.name]

    def _avg(self, pointwise: np.ndarray) -> float:
        if self.weight is not None:
            return float((pointwise * self.weight).sum() / self.sum_weight)
        return float(pointwise.mean()) if len(pointwise) else 0.0


class _PointwiseRegressionMetric(Metric):
    """reference: RegressionMetric template (regression_metric.hpp:18)."""

    convert = True  # apply objective's ConvertOutput (AverageIfNonEmpty style)

    def point_loss(self, score: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, v: float) -> float:
        return v

    def eval(self, score, objective):
        if self.convert and objective is not None:
            # float64: convert_output may hand back a jax f32 array, and
            # f32 pointwise math here would diverge from an feval
            # computing the same quantity in numpy f64 (reference metrics
            # are double end-to-end)
            score = np.asarray(objective.convert_output(score), np.float64)
        else:
            # custom objective (objective None): raw scores stand in for
            # outputs (reference metric Eval with objective==nullptr)
            score = np.asarray(score, np.float64)
        return [(self.name, self.transform(self._avg(self.point_loss(score))), self.higher_better)]


class L2Metric(_PointwiseRegressionMetric):
    name = "l2"

    def point_loss(self, s):
        return (s - self.label) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def transform(self, v):
        return math.sqrt(v)


class L1Metric(_PointwiseRegressionMetric):
    name = "l1"

    def point_loss(self, s):
        return np.abs(s - self.label)


class QuantileMetric(_PointwiseRegressionMetric):
    name = "quantile"

    def point_loss(self, s):
        a = self.config.alpha
        d = self.label - s
        return np.where(d >= 0, a * d, (a - 1) * d)


class HuberMetric(_PointwiseRegressionMetric):
    name = "huber"

    def point_loss(self, s):
        a = self.config.alpha
        d = np.abs(s - self.label)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseRegressionMetric):
    name = "fair"

    def point_loss(self, s):
        c = self.config.fair_c
        x = np.abs(s - self.label)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseRegressionMetric):
    name = "poisson"

    def point_loss(self, s):
        eps = 1e-10
        s = np.maximum(s, eps)
        return s - self.label * np.log(s)


class MAPEMetric(_PointwiseRegressionMetric):
    name = "mape"

    def point_loss(self, s):
        return np.abs((self.label - s)) / np.maximum(1.0, np.abs(self.label))


class GammaMetric(_PointwiseRegressionMetric):
    name = "gamma"

    def point_loss(self, s):
        # negative gamma log-likelihood with shape=1 (reference: GammaMetric)
        eps = 1e-10
        s = np.maximum(s, eps)
        return self.label / s + np.log(s)


class GammaDevianceMetric(_PointwiseRegressionMetric):
    name = "gamma_deviance"

    def point_loss(self, s):
        eps = 1e-10
        r = self.label / np.maximum(s, eps)
        return 2.0 * (np.log(np.maximum(1.0 / np.maximum(r, eps), eps)) + r - 1.0)


class TweedieMetric(_PointwiseRegressionMetric):
    name = "tweedie"

    def point_loss(self, s):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(s, eps)
        a = self.label * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return -a + b


class BinaryLoglossMetric(_PointwiseRegressionMetric):
    """reference: binary_metric.hpp:115 (prob via objective ConvertOutput)."""

    name = "binary_logloss"

    def point_loss(self, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(self.label * np.log(p) + (1 - self.label) * np.log(1 - p))


class BinaryErrorMetric(_PointwiseRegressionMetric):
    name = "binary_error"

    def point_loss(self, p):
        pred = (p > 0.5).astype(np.float64)
        return (pred != self.label).astype(np.float64)


class AUCMetric(Metric):
    """reference: binary_metric.hpp:159 (rank-based with weights)."""

    name = "auc"
    higher_better = True

    def eval(self, score, objective):
        score = np.asarray(score, np.float64).reshape(-1)
        w = self.weight if self.weight is not None else np.ones_like(score)
        order = np.argsort(-score, kind="mergesort")
        s, lbl, ww = score[order], self.label[order], w[order]
        # group tied scores
        pos_w = ww * (lbl > 0)
        neg_w = ww * (lbl <= 0)
        # unique score groups
        boundaries = np.nonzero(np.diff(s))[0] + 1
        pos_g = np.add.reduceat(pos_w, np.r_[0, boundaries]) if len(s) else np.array([])
        neg_g = np.add.reduceat(neg_w, np.r_[0, boundaries]) if len(s) else np.array([])
        cum_neg = np.cumsum(neg_g) - neg_g
        auc_sum = float((pos_g * (cum_neg + neg_g * 0.5)).sum())
        tot_pos, tot_neg = float(pos_w.sum()), float(neg_w.sum())
        if tot_pos == 0 or tot_neg == 0:
            return [(self.name, 1.0, True)]
        # auc_sum currently counts pos ranked ABOVE... invert to standard
        auc = 1.0 - auc_sum / (tot_pos * tot_neg)
        return [(self.name, auc, True)]


class MultiLoglossMetric(Metric):
    """reference: multiclass_metric.hpp (softmax probabilities)."""

    name = "multi_logloss"

    def eval(self, score, objective):
        p = np.asarray(objective.convert_output(score)
                       if objective is not None else score,
                       np.float64)  # [K, n]
        eps = 1e-15
        idx = self.label.astype(np.int64)
        pt = np.clip(p[idx, np.arange(p.shape[1])], eps, 1.0)
        return [(self.name, self._avg(-np.log(pt)), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective):
        p = np.asarray(score, np.float64)  # [K, n]
        k = self.config.multi_error_top_k
        idx = self.label.astype(np.int64)
        true_score = p[idx, np.arange(p.shape[1])]
        # reference (multiclass_metric.hpp MultiErrorMetric): a row is
        # CORRECT iff #(scores >= true score, ties included) <= top_k,
        # and the emitted name is multi_error@k for k > 1
        num_larger = (p >= true_score[None, :]).sum(axis=0)
        err = (num_larger > k).astype(np.float64)
        name = self.name if k <= 1 else f"{self.name}@{k}"
        return [(name, self._avg(err), False)]

    def names(self):
        k = self.config.multi_error_top_k
        return [self.name if k <= 1 else f"{self.name}@{k}"]


class AucMuMetric(Metric):
    """reference: multiclass_metric.hpp auc_mu (average pairwise class AUC)."""

    name = "auc_mu"
    higher_better = True

    def eval(self, score, objective):
        p = np.asarray(score, np.float64)  # [K, n]
        K = p.shape[0]
        lbl = self.label.astype(np.int64)
        w = self.weight if self.weight is not None else np.ones(p.shape[1])
        total = 0.0
        cnt = 0
        for a in range(K):
            for b in range(a + 1, K):
                mask = (lbl == a) | (lbl == b)
                if mask.sum() == 0:
                    continue
                s = p[a, mask] - p[b, mask]
                y = (lbl[mask] == a).astype(np.float64)
                ww = w[mask]
                total += _weighted_auc(s, y, ww)
                cnt += 1
        return [(self.name, total / max(cnt, 1), True)]


def _weighted_auc(score, label, weight):
    order = np.argsort(-score, kind="mergesort")
    s, lbl, ww = score[order], label[order], weight[order]
    pos_w = ww * (lbl > 0)
    neg_w = ww * (lbl <= 0)
    boundaries = np.nonzero(np.diff(s))[0] + 1
    pos_g = np.add.reduceat(pos_w, np.r_[0, boundaries])
    neg_g = np.add.reduceat(neg_w, np.r_[0, boundaries])
    cum_neg = np.cumsum(neg_g) - neg_g
    auc_sum = float((pos_g * (cum_neg + neg_g * 0.5)).sum())
    tot_pos, tot_neg = float(pos_w.sum()), float(neg_w.sum())
    if tot_pos == 0 or tot_neg == 0:
        return 1.0
    return 1.0 - auc_sum / (tot_pos * tot_neg)


class DCGCalculator:
    """reference: include/LightGBM/metric.h:63-137, src/metric/dcg_calculator.cpp."""

    def __init__(self, label_gain: Optional[Sequence[float]] = None):
        if not label_gain:
            label_gain = [(1 << i) - 1 for i in range(31)]
        self.label_gain = np.asarray(label_gain, np.float64)

    def dcg_at_k(self, k: int, label: np.ndarray, score: np.ndarray) -> float:
        order = np.argsort(-score, kind="mergesort")
        top = label[order[:k]].astype(np.int64)
        discounts = 1.0 / np.log2(np.arange(len(top)) + 2.0)
        return float((self.label_gain[top] * discounts).sum())

    def max_dcg_at_k(self, k: int, label: np.ndarray) -> float:
        top = np.sort(label.astype(np.int64))[::-1][:k]
        discounts = 1.0 / np.log2(np.arange(len(top)) + 2.0)
        return float((self.label_gain[top] * discounts).sum())


class NDCGMetric(Metric):
    """reference: rank_metric.hpp:19 NDCGMetric."""

    name = "ndcg"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("ndcg metric requires query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.calc = DCGCalculator(self.config.label_gain)
        self.eval_at = list(self.config.eval_at)

    def eval(self, score, objective):
        score = np.asarray(score, np.float64).reshape(-1)
        results = []
        nq = len(self.qb) - 1
        # per-query weights (reference: query_weights)
        for k in self.eval_at:
            vals = np.empty(nq)
            for q in range(nq):
                lo, hi = self.qb[q], self.qb[q + 1]
                lbl = self.label[lo:hi]
                maxdcg = self.calc.max_dcg_at_k(k, lbl)
                if maxdcg <= 0:
                    vals[q] = 1.0
                else:
                    vals[q] = self.calc.dcg_at_k(k, lbl, score[lo:hi]) / maxdcg
            results.append((f"ndcg@{k}", float(vals.mean()), True))
        return results

    def names(self):
        # the same eval_at snapshot eval() iterates (taken at init), so
        # GetEvalNames/GetEvalCounts always agree with the emitted values
        ks = getattr(self, "eval_at", self.config.eval_at)
        return [f"ndcg@{k}" for k in ks]


class MapMetric(Metric):
    """reference: map_metric.hpp MAP@k."""

    name = "map"
    higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("map metric requires query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.eval_at = list(self.config.eval_at)

    def eval(self, score, objective):
        score = np.asarray(score, np.float64).reshape(-1)
        results = []
        nq = len(self.qb) - 1
        for k in self.eval_at:
            vals = np.empty(nq)
            for q in range(nq):
                lo, hi = self.qb[q], self.qb[q + 1]
                lbl = (self.label[lo:hi] > 0).astype(np.float64)
                order = np.argsort(-score[lo:hi], kind="mergesort")
                rel = lbl[order[:k]]
                hits = np.cumsum(rel)
                prec = hits / (np.arange(len(rel)) + 1.0)
                npos = min(int(lbl.sum()), k)
                vals[q] = float((prec * rel).sum() / npos) if npos > 0 else 1.0
            results.append((f"map@{k}", float(vals.mean()), True))
        return results

    def names(self):
        ks = getattr(self, "eval_at", self.config.eval_at)
        return [f"map@{k}" for k in ks]


class CrossEntropyMetric(_PointwiseRegressionMetric):
    """reference: xentropy_metric.hpp (labels in [0,1], prob input)."""

    name = "cross_entropy"

    def point_loss(self, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        y = self.label
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective):
        score = np.asarray(score, np.float64).reshape(-1)
        hhat = np.log1p(np.exp(score))
        y = self.label
        w = self.weight if self.weight is not None else np.ones_like(y)
        loss = -y * np.log(np.maximum(1.0 - np.exp(-w * hhat), 1e-15)) + (1.0 - y) * w * hhat
        return [(self.name, float(loss.mean()), False)]


class KLDivMetric(_PointwiseRegressionMetric):
    name = "kullback_leibler"

    def point_loss(self, p):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        y = np.clip(self.label, eps, 1 - eps)
        return (y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p)))


_REGISTRY = {c.name: c for c in (
    L2Metric, RMSEMetric, L1Metric, QuantileMetric, HuberMetric, FairMetric,
    PoissonMetric, MAPEMetric, GammaMetric, GammaDevianceMetric, TweedieMetric,
    BinaryLoglossMetric, BinaryErrorMetric, AUCMetric, MultiLoglossMetric,
    MultiErrorMetric, AucMuMetric, NDCGMetric, MapMetric, CrossEntropyMetric,
    CrossEntropyLambdaMetric, KLDivMetric,
)}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """reference: Metric::CreateMetric (src/metric/metric.cpp:17)."""
    from .config import _METRIC_ALIASES
    name = _METRIC_ALIASES.get(name, name)
    # reference: "na"/"null"/"custom" disable built-in metrics (metric.cpp:17)
    if name.lower() in ("none", "na", "null", "custom"):
        return None
    if name not in _REGISTRY:
        # reference: Metric::CreateMetric returns nullptr for unknown
        # names and training proceeds without it (src/metric/metric.cpp)
        from .utils.log import log_warning
        log_warning(f"Unknown metric {name!r} (ignored)")
        return None
    return _REGISTRY[name](config)
