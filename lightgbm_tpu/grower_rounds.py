"""Batched-frontier leaf-wise tree growth (the TPU-fast grower).

Same semantics as :mod:`.grower` (LightGBM best-first growth,
reference: src/treelearner/serial_tree_learner.cpp:149-193) but the
``lax.while_loop`` advances a ROUND of splits per iteration instead of one
split, so a 255-leaf tree takes ~log2(255)+eps iterations instead of 254.
Rationale: on TPU the dominant cost of the serial grower is not compute but
the per-iteration execution of a ~1.6k-op loop body (measured: ~6 ms fixed
per split at 100k-500k rows, ~99% of train time); batching the frontier
amortizes that body over up to ``budget`` splits.

Exactness.  Best-first growth applies, at every step, the max-gain leaf
(ties: smallest leaf index — the reference's ArgMax over the leaf array).
A round here applies the top ``k = min(#positive-gain leaves, leaf budget)``
candidates in that same (gain desc, leaf asc) order, which is exactly the
sequence best-first would produce PROVIDED no child created by the round
outranks the round's weakest applied candidate (a child that outranks it
would, under best-first, have been split before the weaker candidate —
potentially consuming budget and changing the applied set).  That proviso
is checked at runtime AFTER the children's best splits are known: if any
new child's gain >= min(applied gains), the round is rolled back to a
single best-first step (the fallback reuses the round's own computation —
the argmax leaf's partition/histogram/search results are slices of the
batched ones, because per-leaf candidates are independent of one another).
Hence trees — including node/leaf numbering — are structurally identical
to the serial grower's for every gain pattern; adversarial
(gain-increasing) patterns only lose the batching speedup, not exactness.
Float fields (histogram sums, gains, leaf values) agree to float32
accumulation order only: the segment scatter sums bins in a different
order than the serial kernels — the same class of difference as the
reference's CPU vs GPU histograms (docs/GPU-Performance.rst accuracy
tables).  Structure can differ only on exact float ties in gains.

Support matrix: EFB bundles, bagging/GOSS weights, per-tree and per-node
column sampling, extra_trees, monotone constraints, max_depth, and
data-parallel row sharding (``axis_name`` -> histogram/scalar psums).
Voting-parallel, feature-parallel, CEGB and forced splits stay on the
serial grower (GBDT dispatches automatically; see _build_jit_fns).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .dataset import FeatureMeta
from .grower import GrowerConfig, TreeArrays, _LeafBest, _psum, row_goes_left
from .ops.histogram import (build_histogram, build_histogram_int,
                            capacity_schedule, compacted_segment_histogram,
                            compacted_segment_histogram_int, pack_cols_u32,
                            pack_cols_u32_quant, psum_quant_hist,
                            quant_levels, resolve_hist_method,
                            take_from_table, use_sorted_seghist)
from .ops.split import (MAX_CAT_WORDS, SplitResult, best_split_for_leaf,
                        leaf_output, quant_rescale_hist)


def _pad_scatter(arr: jax.Array, idx: jax.Array, val: jax.Array,
                 sel: jax.Array) -> jax.Array:
    """``arr[idx] = val`` for lanes where ``sel``; others hit a dummy row."""
    M = arr.shape[0]
    pad = jnp.zeros((1,) + arr.shape[1:], arr.dtype)
    ext = jnp.concatenate([arr, pad], axis=0)
    safe = jnp.where(sel, idx, M)
    return ext.at[safe].set(val.astype(arr.dtype))[:M]


def grow_tree_rounds(binned_t, *args, **kwargs):
    """Grow one tree, batched-frontier (full signature:
    ``_grow_tree_rounds_traced``).  Span-wrapped like ``grow_tree``:
    records trace-construction time per compile (docs/OBSERVABILITY.md).
    """
    from .obs.trace import span as _span
    with _span("trace.grow_tree_rounds", rows=int(binned_t.shape[1])):
        return _grow_tree_rounds_traced(binned_t, *args, **kwargs)


def _grow_tree_rounds_traced(
    binned_t: jax.Array,        # [G, n] uint8/16 feature-major (rows
                                #   possibly per-shard)
    grad: jax.Array,            # [n] f32
    hess: jax.Array,            # [n] f32
    row_mask: jax.Array,        # [n] f32 bagging/GOSS weights (0 = excluded)
    meta: FeatureMeta,
    cfg: GrowerConfig,
    feature_mask: Optional[jax.Array] = None,   # [F] per-tree col sample
    axis_name: Optional[str] = None,            # mesh axis sharding ROWS
    monotone_constraints: Optional[jax.Array] = None,  # [F] i32 in {-1,0,1}
    rng_key: Optional[jax.Array] = None,
    meta_arrays: Optional[tuple] = None,    # runtime (num_bin, missing_type,
                                            # default_bin, is_cat, feat_group,
                                            # feat_start) — shares the
                                            # compiled program across
                                            # same-shaped datasets
    quant_vals: Optional[tuple] = None,     # cfg.quant: (gq, hq, g_scale,
                                            # h_scale) — see grower.grow_tree
):
    """Grow one tree; returns (TreeArrays, leaf_id [n] i32)."""
    meta = meta.resolved()
    G, n = binned_t.shape
    L = cfg.num_leaves
    Lm1 = max(L - 1, 1)
    B = cfg.num_bins
    Bg = meta.max_group_bin if meta.has_bundles else B
    hp = cfg.hp
    F = len(meta.num_bin)

    if meta_arrays is not None:
        (num_bin, missing_type, default_bin, is_cat,
         feat_group, feat_start) = meta_arrays
    else:
        num_bin = jnp.asarray(meta.num_bin)
        missing_type = jnp.asarray(meta.missing_type)
        default_bin = jnp.asarray(meta.default_bin)
        is_cat = jnp.asarray(meta.is_categorical)
        feat_group = jnp.asarray(meta.feat_group)
        feat_start = jnp.asarray(meta.feat_start)
    has_cat = bool(meta.is_categorical.any())

    # quantized-gradient mode (see grower.grow_tree): integer [2, *, Bg]
    # i32 histogram cache + int8 segment kernels; the int->f32 rescale
    # happens once per leaf search (quant_rescale_hist)
    quant = cfg.quant
    rows_global = n * max(cfg.num_machines, 1)
    # planner-selected row tiling (ops/planner.py): all histogram passes
    # stream tiles of this many rows; 0/None = untiled
    tile = cfg.tile_rows if cfg.tile_rows > 0 else None
    if quant:
        if quant_vals is None:
            raise ValueError("cfg.quant requires quant_vals="
                             "(gq, hq, g_scale, h_scale)")
        q_grad, q_hess, g_scale, h_scale = quant_vals
        q_levels = quant_levels(cfg.quant_bins)

        def split_conv(ghist, cnt):
            return quant_rescale_hist(ghist, g_scale, h_scale, cnt)
    else:
        hist_fn = functools.partial(build_histogram, num_bins=Bg,
                                    method=cfg.hist_method,
                                    tile_rows=tile)

        def split_conv(ghist, cnt):
            return ghist
    caps = capacity_schedule(n) if cfg.compact else [n]
    use_mc = monotone_constraints is not None
    use_rng = hp.extra_trees or cfg.bynode_feature_cnt > 0
    # fused Pallas histogram→split megakernel arm (ops/fused.py): per
    # ROUND, one kernel streams every binned row tile HBM→VMEM once,
    # accumulates all K candidates' smaller-child bins in a VMEM arena,
    # derives each sibling from the parent histograms in-kernel and
    # scans both children's per-feature gains before writing back only
    # the smaller-child histograms (the cache's subtraction input) and
    # the [2K, F] best tuples — the staged pipeline's [K,ch,F,B] segment
    # output + [2K,ch,F,B] scan re-read round-trip never touches HBM.
    # Sharded training runs the SEAM-SPLIT form of the same kernel
    # (accumulate → psum of only the smaller-child hists → sibling-derive
    # + scan on the reduced arena); categorical columns accumulate in the
    # same arena (their numeric tuples are overridden by the shared cat
    # scan in pick_fused_best's merge) and monotone constraints/bounds
    # ride into the in-kernel scan.  Only EFB bundles and per-node
    # randomness still fall back to the staged family (same trees: the
    # scan body is shared — ops.split.numeric_feature_scan).
    use_fused = (cfg.hist_method == "fused"
                 and not meta.has_bundles and not use_rng)
    # fused u32 column records for the arena's single gather (sorted-path
    # only: gather cost scales with element count — pack_cols_u32; the
    # quantized record fuses (gq, hq, member) into ONE word, Wb+1 vs
    # Wb+3).  LGBM_TPU_PACK=0 falls back to the separate gathers
    # (compile-cost bisect hook).  Under planner tiling the whole-dataset
    # record arena is NOT hoisted (cfg.hist_pack cleared / tile set):
    # the kernels assemble records per tile inside their loops instead.
    # The fused arm gathers nothing — the record arena would be dead
    # weight.
    use_pack = (use_sorted_seghist() and cfg.hist_pack and tile is None
                and not use_fused
                and os.environ.get("LGBM_TPU_PACK") != "0")
    if not use_pack:
        packed = None
    elif quant:
        packed = pack_cols_u32_quant(binned_t, q_grad, q_hess, row_mask > 0)
    else:
        packed = pack_cols_u32(binned_t, grad, hess, row_mask)
    # router-matmul candidate routing (see body): O(n)/round instead of
    # the scan's O(k*n); numeric-only (categorical bitsets don't ride an
    # f32 table) and accelerator-shaped.  LGBM_TPU_ROUTER=0 forces the
    # scan (bisect/testing hook)
    use_router = (use_sorted_seghist() and not meta.is_categorical.any()
                  and os.environ.get("LGBM_TPU_ROUTER") != "0")
    # segment-histogram precision follows the resolved histogram method so
    # parent - smaller-child subtraction stays consistent: only the bf16
    # one-hot matmul is inexact; every other kernel accumulates f32-exact
    seg_f32 = resolve_hist_method(cfg.hist_method) != "matmul"

    if meta.has_bundles:
        b_idx = jnp.arange(B, dtype=jnp.int32)

        def expand_hist(ghist, sg, sh, cnt):
            """[3, G, Bg] group hist -> [3, F, B] (FixHistogram bin-0
            reconstruction; see grower.py)."""
            gather_bins = jnp.clip(feat_start[:, None] + b_idx[None, :] - 1,
                                   0, Bg - 1)
            taken = ghist[:, feat_group[:, None], gather_bins]
            valid = (b_idx[None, :] >= 1) & (b_idx[None, :] < num_bin[:, None])
            h = jnp.where(valid[None, :, :], taken, 0.0)
            totals = jnp.stack([sg, sh, cnt])
            return h.at[:, :, 0].set(totals[:, None] - h.sum(axis=2))
    else:
        def expand_hist(ghist, sg, sh, cnt):
            return ghist

    # max splits committed per round.  Any cap preserves exactness (the
    # round applies a PREFIX of the best-first order and the validation
    # check still guards interleaving); it bounds the changed-slot search
    # width and the segment-histogram slot axis.
    KCAP = min(Lm1, max(1, cfg.round_width))

    mc_j = jnp.asarray(monotone_constraints) if use_mc else None
    if use_rng and rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    if use_fused:
        from .ops.fused import (fused_frontier_accumulate,
                                fused_frontier_splits, fused_sibling_scan,
                                pick_fused_best, shared_frontier_enabled)
        from .ops.histogram import _vals_t, _vals_t_int
        from .ops.split import feature_best_splits
        fused_vals = (_vals_t_int(q_grad, q_hess, row_mask > 0) if quant
                      else _vals_t(grad, hess, row_mask))
        fused_scales = (g_scale, h_scale) if quant else None
        fused_ftile = cfg.fused_feat_tile or None
        fused_brows = cfg.fused_block_rows or None
        # static categorical column index set for pick_fused_best's merge
        cat_idx = (tuple(int(i) for i, v in
                         enumerate(meta.is_categorical) if v)
                   if has_cat else None)
        # the shared frontier program (docs/PERF.md): on the sharded seam
        # the ROOT histogram rides the SAME accumulate program as every
        # level (slot 0 = all member rows), so one Mosaic kernel serves
        # root + levels and the compile ladder shrinks by one program
        use_shared_root = (axis_name is not None
                           and shared_frontier_enabled())

    # ---- per-leaf best-split search, vmapped over all L slots ----------
    def leaf_key(parent, side):
        # node-identity key: stable across application order, so batched
        # and sequential growth draw the same randomness per node
        return jax.random.fold_in(jax.random.fold_in(rng_key, parent + 1),
                                  side)

    def one_leaf_best(ghist, sg, sh, cnt, depth, bmin, bmax, parent, side):
        fm = feature_mask
        eru = None
        if use_rng:
            key = leaf_key(parent, side)
            if cfg.bynode_feature_cnt > 0:
                u = jax.random.uniform(jax.random.fold_in(key, 0), (F,))
                kth = -lax.top_k(-u, cfg.bynode_feature_cnt)[0][-1]
                bn = (u <= kth).astype(jnp.float32)
                fm = bn if fm is None else fm * bn
            if hp.extra_trees:
                eru = jax.random.uniform(jax.random.fold_in(key, 1), (F, 2))
        bounds = (bmin, bmax) if use_mc else None
        hist = expand_hist(split_conv(ghist, cnt), sg, sh, cnt)
        r = best_split_for_leaf(
            hist, sg, sh, cnt, num_bin, missing_type, default_bin, is_cat,
            hp, feature_mask=fm, monotone_constraints=mc_j,
            leaf_output_bounds=bounds, has_categorical=has_cat,
            extra_rand_u=eru)
        if cfg.max_depth > 0:
            r = r._replace(gain=jnp.where(depth >= cfg.max_depth,
                                          -jnp.inf, r.gain))
        return r

    search_all = jax.vmap(one_leaf_best)

    def cache_from(sr: SplitResult) -> _LeafBest:
        return _LeafBest(
            gain=sr.gain, feature=sr.feature, threshold=sr.threshold,
            default_left=sr.default_left,
            left_sum_grad=sr.left_sum_grad, left_sum_hess=sr.left_sum_hess,
            left_count=sr.left_count,
            right_sum_grad=sr.right_sum_grad,
            right_sum_hess=sr.right_sum_hess, right_count=sr.right_count,
            is_categorical=sr.is_categorical, cat_bitset=sr.cat_bitset)

    # ---- root ----------------------------------------------------------
    # reduction policy over the (possibly tiered, see parallel/
    # collectives.py) data axis — one closure per grower, like grower.py
    hier_rd, pinned_rd = cfg.hier_reduce, cfg.pinned_reduce

    def psum_(x):
        return _psum(x, axis_name, hier_rd, pinned_rd)

    if quant:
        member = row_mask > 0
        if use_fused and use_shared_root:
            root_local = fused_frontier_accumulate(
                binned_t, fused_vals, jnp.where(member, 0, KCAP), KCAP,
                Bg, feat_tile=fused_ftile, block_rows=fused_brows,
                tile_rows=tile)[0]
        else:
            root_local = build_histogram_int(
                binned_t, q_grad, q_hess, member, Bg,
                method=cfg.hist_method, levels=q_levels, tile_rows=tile)
        root_hist = psum_quant_hist(root_local, axis_name, rows_global,
                                    cfg.quant_bins, hierarchical=hier_rd)
        root_sg = psum_(jnp.sum(jnp.where(member, q_grad, 0).astype(
            jnp.int32))).astype(jnp.float32) * g_scale
        root_sh = psum_(jnp.sum(jnp.where(member, q_hess, 0).astype(
            jnp.int32))).astype(jnp.float32) * h_scale
        root_cnt = psum_(jnp.sum(member.astype(jnp.float32)))
    else:
        if use_fused and use_shared_root:
            root_local = fused_frontier_accumulate(
                binned_t, fused_vals, jnp.where(row_mask > 0, 0, KCAP),
                KCAP, Bg, feat_tile=fused_ftile, block_rows=fused_brows,
                tile_rows=tile)[0]
        else:
            root_local = hist_fn(binned_t, grad, hess, row_mask)
        root_hist = psum_(root_local)
        root_sg = psum_(jnp.sum(grad * row_mask))
        root_sh = psum_(jnp.sum(hess * row_mask))
        root_cnt = psum_(jnp.sum(row_mask))

    tree = TreeArrays.empty(L)
    hist_cache = jnp.zeros((L, 2, G, Bg), jnp.int32).at[0].set(root_hist) \
        if quant else \
        jnp.zeros((L, 3, G, Bg), jnp.float32).at[0].set(root_hist)
    leaf_sg = jnp.zeros(L, jnp.float32).at[0].set(root_sg)
    leaf_sh = jnp.zeros(L, jnp.float32).at[0].set(root_sh)
    leaf_cnt = jnp.zeros(L, jnp.float32).at[0].set(root_cnt)
    leaf_parent_side = jnp.zeros(L, jnp.int32)
    leaf_min = jnp.full(L, -jnp.inf, jnp.float32)
    leaf_max = jnp.full(L, jnp.inf, jnp.float32)
    leaf_id = jnp.zeros(n, jnp.int32)

    best = cache_from(search_all(
        hist_cache, leaf_sg, leaf_sh, leaf_cnt, tree.leaf_depth,
        leaf_min, leaf_max, tree.leaf_parent, leaf_parent_side))

    class Carry(NamedTuple):
        tree: TreeArrays
        best: _LeafBest
        hist: jax.Array
        leaf_sg: jax.Array
        leaf_sh: jax.Array
        leaf_cnt: jax.Array
        leaf_parent_side: jax.Array
        leaf_id: jax.Array
        split_idx: jax.Array
        leaf_min: jax.Array
        leaf_max: jax.Array

    iota_L = jnp.arange(L, dtype=jnp.int32)

    def active_gains(c: Carry):
        active = iota_L < c.tree.num_leaves
        return jnp.where(active, c.best.gain, -jnp.inf)

    def cond(c: Carry):
        return (c.split_idx < L - 1) & (jnp.max(active_gains(c)) > 0.0)

    def apply_round(c: Carry, sel, rank, k, gl, seg, crank):
        """Commit the splits of the ``sel`` leaves (rank = application
        order within the round; ``crank`` = per-row candidate rank from the
        candidate scan, KCAP for rows not in a candidate leaf); returns the
        updated carry WITHOUT a refreshed best cache (the caller searches
        afterwards)."""
        b = c.best
        node_of = c.split_idx + rank                  # [L] new node ids
        newleaf_of = c.tree.num_leaves + rank         # [L] right-child leaves

        feat = b.feature
        lg, lh, lc = b.left_sum_grad, b.left_sum_hess, b.left_count
        rg, rh, rc = b.right_sum_grad, b.right_sum_hess, b.right_count

        tree = c.tree
        # fix the parents' dangling child pointers (parents are nodes from
        # earlier rounds; within-round parents don't exist by construction)
        pn = jnp.maximum(tree.leaf_parent, 0)
        fixl = sel & (tree.leaf_parent >= 0) & (c.leaf_parent_side == 0)
        fixr = sel & (tree.leaf_parent >= 0) & (c.leaf_parent_side == 1)
        left_child = _pad_scatter(tree.left_child, pn, node_of, fixl)
        right_child = _pad_scatter(tree.right_child, pn, node_of, fixr)
        # write the new node rows
        parent_out = leaf_output(c.leaf_sg, c.leaf_sh, hp.lambda_l1,
                                 hp.lambda_l2, hp.max_delta_step)
        new_depth = tree.leaf_depth + 1
        ps = functools.partial(_pad_scatter, idx=node_of, sel=sel)
        tree = tree._replace(
            split_feature=ps(tree.split_feature, val=feat),
            threshold_bin=ps(tree.threshold_bin, val=b.threshold),
            default_left=ps(tree.default_left, val=b.default_left),
            is_categorical=ps(tree.is_categorical, val=b.is_categorical),
            cat_bitset=ps(tree.cat_bitset, val=b.cat_bitset),
            left_child=ps(left_child, val=~iota_L),
            right_child=ps(right_child, val=~newleaf_of),
            split_gain=ps(tree.split_gain, val=b.gain),
            internal_value=ps(tree.internal_value, val=parent_out),
            internal_weight=ps(tree.internal_weight, val=c.leaf_sh),
            internal_count=ps(tree.internal_count, val=c.leaf_cnt),
            leaf_parent=_pad_scatter(
                jnp.where(sel, node_of, tree.leaf_parent),
                newleaf_of, node_of, sel),
            leaf_depth=_pad_scatter(
                jnp.where(sel, new_depth, tree.leaf_depth),
                newleaf_of, new_depth, sel),
            num_leaves=tree.num_leaves + k,
        )
        leaf_parent_side = _pad_scatter(
            jnp.where(sel, 0, c.leaf_parent_side),
            newleaf_of, jnp.ones(L, jnp.int32), sel)

        # -- rows: those in a selected leaf that go right get the new leaf.
        # The right-child leaf of the rank-r candidate is num_leaves + r,
        # so the update is pure arithmetic on the per-row candidate rank —
        # no [n]-sized gather from a leaf table (measured ~130 ms per
        # gathered pass at 11M rows on v5e, tpu_probe_r5.json).
        new_leaf_id = jnp.where((crank < k) & ~gl,
                                c.tree.num_leaves + crank, c.leaf_id)

        # -- leaf stats (left child keeps the leaf index: elementwise)
        leaf_sg = _pad_scatter(jnp.where(sel, lg, c.leaf_sg),
                               newleaf_of, rg, sel)
        leaf_sh = _pad_scatter(jnp.where(sel, lh, c.leaf_sh),
                               newleaf_of, rh, sel)
        leaf_cnt = _pad_scatter(jnp.where(sel, lc, c.leaf_cnt),
                                newleaf_of, rc, sel)

        # -- histograms: seg holds the SMALLER child of each selected leaf
        small_left = lc <= rc
        small = seg[jnp.clip(rank, 0, KCAP - 1)]       # [L, 3, G, Bg]
        hist_left = jnp.where(small_left[:, None, None, None],
                              small, c.hist - small)
        hist_right = c.hist - hist_left
        selb = sel[:, None, None, None]
        hist = _pad_scatter(jnp.where(selb, hist_left, c.hist),
                            newleaf_of, hist_right, sel)

        # -- monotone bound propagation (see grower.py apply_split)
        leaf_min, leaf_max = c.leaf_min, c.leaf_max
        if use_mc:
            l_min, l_max, r_min, r_max = child_bounds(c)
            leaf_min = _pad_scatter(jnp.where(sel, l_min, leaf_min),
                                    newleaf_of, r_min, sel)
            leaf_max = _pad_scatter(jnp.where(sel, l_max, leaf_max),
                                    newleaf_of, r_max, sel)

        return Carry(tree, c.best, hist, leaf_sg, leaf_sh, leaf_cnt,
                     leaf_parent_side, new_leaf_id, c.split_idx + k,
                     leaf_min, leaf_max)

    def child_bounds(c: Carry):
        """Per-leaf monotone bounds the two children of each leaf's cached
        split would inherit ([L] vectors; see grower.py apply_split)."""
        b = c.best
        lg, lh = b.left_sum_grad, b.left_sum_hess
        rg, rh = b.right_sum_grad, b.right_sum_hess
        p_min, p_max = c.leaf_min, c.leaf_max
        l_out = jnp.clip(leaf_output(lg, lh, hp.lambda_l1, hp.lambda_l2,
                                     hp.max_delta_step), p_min, p_max)
        r_out = jnp.clip(leaf_output(rg, rh, hp.lambda_l1, hp.lambda_l2,
                                     hp.max_delta_step), p_min, p_max)
        mid = (l_out + r_out) * 0.5
        mc_f = mc_j[jnp.clip(b.feature, 0, F - 1)]
        upd = (~b.is_categorical) & (mc_f != 0)
        l_min = jnp.where(upd & (mc_f < 0), jnp.maximum(p_min, mid), p_min)
        l_max = jnp.where(upd & (mc_f > 0), jnp.minimum(p_max, mid), p_max)
        r_min = jnp.where(upd & (mc_f > 0), jnp.maximum(p_min, mid), p_min)
        r_max = jnp.where(upd & (mc_f < 0), jnp.minimum(p_max, mid), p_max)
        return l_min, l_max, r_min, r_max

    iota_K = jnp.arange(KCAP, dtype=jnp.int32)

    def cache_scatter(base: _LeafBest, ids, res: SplitResult, valid):
        """Overwrite cache rows ``ids`` (where ``valid``) with ``res``."""
        new = cache_from(res)
        return jax.tree_util.tree_map(
            lambda b_, v: _pad_scatter(b_, ids, v, valid), base, new)

    def body(c: Carry) -> Carry:
        gains = active_gains(c)
        pos = gains > 0.0
        npos = jnp.sum(pos.astype(jnp.int32))
        budget = (L - c.tree.num_leaves).astype(jnp.int32)
        k = jnp.minimum(jnp.minimum(npos, budget), KCAP)
        # total order (gain desc, leaf asc) = successive best-first ArgMax
        # picks (reference: SerialTreeLearner::Train loop :175-193)
        order = jnp.argsort(-gains, stable=True)
        rank = jnp.zeros(L, jnp.int32).at[order].set(iota_L)

        # -- candidate routing: per-row goes-left bit, candidate rank, and
        # smaller-child membership for the whole batch.
        b = c.best
        idl = jnp.clip(order[:KCAP], 0, L - 1)          # candidate leaves

        if use_router:
            # ROUTER MATMUL (numeric features, accelerator path): ONE
            # [9, n] take_from_table one-hot matmul hands every row its
            # leaf's split params, then one fused [G, n] select-reduce
            # reads the row's split-feature bin — O(G*n) total per round
            # (~one binned-matrix stream, the cost the expanded segment
            # histogram already pays) vs the scan's O(k*n) column passes:
            # a clear win on the wide rounds (k up to 128) and a ~one-
            # stream overhead on narrow ones.  All table values are
            # integers < 2^16 or flags: exact in f32.
            feat_l = jnp.clip(b.feature, 0, F - 1)
            live_l = pos & (rank < k)
            tbl = jnp.stack([
                jnp.where(live_l, rank, KCAP).astype(jnp.float32),   # crank
                feat_group[feat_l].astype(jnp.float32),              # group
                b.threshold.astype(jnp.float32),
                b.default_left.astype(jnp.float32),
                missing_type[feat_l].astype(jnp.float32),
                default_bin[feat_l].astype(jnp.float32),
                num_bin[feat_l].astype(jnp.float32),
                feat_start[feat_l].astype(jnp.float32),
                (b.left_count <= b.right_count).astype(jnp.float32),
            ], axis=1)                                   # [L, 9]
            prm = take_from_table(tbl, c.leaf_id, leading=True)  # [9, n]
            crank = prm[0].astype(jnp.int32)
            grp = prm[1].astype(jnp.int32)
            thr_r = prm[2].astype(jnp.int32)
            dl_r = prm[3] > 0.5
            mt_r = prm[4].astype(jnp.int32)
            db_r = prm[5].astype(jnp.int32)
            nb_r = prm[6].astype(jnp.int32)
            fs_r = prm[7].astype(jnp.int32)
            sl_r = prm[8] > 0.5
            # row's bin of its leaf's split feature: a select-reduce over
            # the feature-major matrix (exactly one group matches; fused —
            # no [n, G] intermediate, no serialized gather)
            iota_G = jnp.arange(G, dtype=jnp.int32)
            col = jnp.sum(jnp.where(iota_G[:, None] == grp[None, :],
                                    binned_t.astype(jnp.int32), 0), axis=0)
            dec = col - fs_r + 1
            binf = jnp.where((dec >= 1) & (dec < nb_r), dec, 0)
            # the numeric fast path of the one documented decision-rule
            # mirror (DenseBin::SplitInner) — per-row params broadcast
            gl = row_goes_left(binf, thr_r, dl_r, None, None,
                               mt_r, db_r, nb_r)
            row_small = gl == sl_r
        else:
            # candidate scan: one step per candidate reads its split
            # feature as a CONTIGUOUS column of the transposed matrix and
            # broadcasts scalar split params (kept for categorical splits
            # — the per-row bitset test doesn't ride an f32 table — and
            # for CPU, where one-hot matmuls lose)
            def cstep(carry, kk):
                def live(carry):
                    gl_a, crank_a, small_a = carry
                    leaf = idl[kk]
                    feat = jnp.clip(b.feature[leaf], 0, F - 1)
                    col = lax.dynamic_index_in_dim(binned_t,
                                                   feat_group[feat], 0,
                                                   keepdims=False)   # [n]
                    nb = num_bin[feat]
                    dec = col.astype(jnp.int32) - feat_start[feat] + 1
                    binf = jnp.where((dec >= 1) & (dec < nb), dec, 0)
                    glk = row_goes_left(
                        binf, b.threshold[leaf], b.default_left[leaf],
                        b.is_categorical[leaf] if has_cat else None,
                        b.cat_bitset[leaf] if has_cat else None,
                        missing_type[feat], default_bin[feat], nb)
                    mk = c.leaf_id == leaf
                    sl = b.left_count[leaf] <= b.right_count[leaf]
                    return (jnp.where(mk, glk, gl_a),
                            jnp.where(mk, kk, crank_a),
                            jnp.where(mk, glk == sl, small_a))
                # skip the O(n) column read + masking for dead candidate
                # lanes (late-tree rounds often have k of 1-2 of KCAP)
                return lax.cond(kk < k, live, lambda c_: c_, carry), None

            (gl, crank, row_small), _ = lax.scan(
                cstep,
                (jnp.zeros(n, jnp.bool_), jnp.full(n, KCAP, jnp.int32),
                 jnp.zeros(n, jnp.bool_)),
                jnp.arange(KCAP, dtype=jnp.int32))

        # smaller-child segment histograms: one pass for the whole
        # candidate batch (slot r = the round's r-th candidate)
        small_left = b.left_count <= b.right_count
        slot = jnp.where(row_small, crank, KCAP)
        if use_fused:
            seg = None      # the fused megakernel produces it below
        elif quant:
            seg = psum_quant_hist(compacted_segment_histogram_int(
                binned_t, q_grad, q_hess, row_mask, slot, KCAP, Bg, caps,
                num_live=k, packed=packed, levels=q_levels,
                tile_rows=tile),
                axis_name, rows_global, cfg.quant_bins,
                hierarchical=hier_rd)
        else:
            seg = _psum(compacted_segment_histogram(
                binned_t, grad, hess, row_mask, slot, KCAP, Bg, caps,
                f32_vals=seg_f32, num_live=k, packed=packed,
                tile_rows=tile), axis_name, hier_rd, pinned_rd)

        # -- candidate children's best splits, BEFORE committing anything:
        # per-leaf candidates are independent, so lane i's results are
        # valid under any commit that includes candidate i.  Left children
        # keep the parent's leaf slot; stats come from the cache.
        ph = c.hist[idl]                                # [K, 3, G, Bg]
        lg_, lh_, lc_ = (b.left_sum_grad[idl], b.left_sum_hess[idl],
                         b.left_count[idl])
        rg_, rh_, rc_ = (b.right_sum_grad[idl], b.right_sum_hess[idl],
                         b.right_count[idl])
        depth_c = c.tree.leaf_depth[idl] + 1
        if use_fused:
            # fused megakernel (ops/fused.py): one streamed pass builds
            # the K smaller-child histograms in VMEM, derives each
            # sibling from the parent arena in-kernel and scans both
            # children; only `seg` + the [2K, F] per-feature-best
            # tuples return — the staged arm's seg/scan HBM round-trip
            # is deleted.  The pick + depth gate mirror search_all's
            # best_split_for_leaf + gain gating exactly.
            csums = jnp.stack([jnp.concatenate([lg_, rg_]),
                               jnp.concatenate([lh_, rh_]),
                               jnp.concatenate([lc_, rc_])])   # [3, 2K]
            if use_mc:
                bl_min, bl_max, br_min, br_max = child_bounds(c)
                f_bounds = (jnp.concatenate([bl_min[idl], br_min[idl]]),
                            jnp.concatenate([bl_max[idl], br_max[idl]]))
            else:
                f_bounds = None
            if axis_name is None:
                seg, nfb = fused_frontier_splits(
                    binned_t, fused_vals, slot, KCAP, Bg, csums,
                    small_left[idl], ph, num_bin, missing_type,
                    default_bin, hp, quant_scales=fused_scales,
                    monotone_constraints=mc_j, child_bounds=f_bounds,
                    feat_tile=fused_ftile, block_rows=fused_brows,
                    tile_rows=tile)
            else:
                # THE COLLECTIVE SEAM (sharded data-parallel): gains are
                # not summable across shards but the smaller-child hists
                # are — accumulate LOCALLY in the VMEM arena, reduce
                # exactly those [K, ch, G, Bg] hists over the (possibly
                # tiered) data axes, then sibling-derive + scan the
                # REDUCED arena in the standalone epilogue kernel.  The
                # reduction routing is byte-identical to the staged arm's
                # (psum_quant_hist / _psum), and integer accumulation is
                # associative, so sharded fused == sharded staged
                # bit-for-bit in quantized mode.
                seg_local = fused_frontier_accumulate(
                    binned_t, fused_vals, slot, KCAP, Bg,
                    feat_tile=fused_ftile, block_rows=fused_brows,
                    tile_rows=tile)
                if quant:
                    seg = psum_quant_hist(seg_local, axis_name,
                                          rows_global, cfg.quant_bins,
                                          hierarchical=hier_rd)
                else:
                    seg = psum_(seg_local)
                nfb = fused_sibling_scan(
                    seg, csums, num_bin, missing_type, default_bin, hp,
                    small_left=small_left[idl], parent_hist=ph,
                    quant_scales=fused_scales,
                    monotone_constraints=mc_j, child_bounds=f_bounds,
                    feat_tile=fused_ftile)
            if has_cat:
                # categorical merge: the arena accumulated the cat
                # columns too (same segment reduction) — derive the
                # children's cat slices from the cached parents, rescale
                # (the slice's default count factor is bit-identical to
                # the full hist's: integer hess totals match across
                # features), and run the SHARED cat scan; the tuples
                # override the kernel's numeric ones in the pick below.
                ci = jnp.asarray(cat_idx, jnp.int32)
                sm_c = seg[:, :, ci, :]
                ph_c = ph[:, :, ci, :]
                slc = small_left[idl][:, None, None, None]
                hl_c = jnp.where(slc, sm_c, ph_c - sm_c)
                chc = jnp.concatenate([hl_c, ph_c - hl_c])  # [2K,ch,Fc,B]
                if quant:
                    chc = quant_rescale_hist(chc, g_scale, h_scale,
                                             csums[2])
                nb_c, mt_c, db_c = (num_bin[ci], missing_type[ci],
                                    default_bin[ci])
                ic_c = is_cat[ci]
                cat_fb = jax.vmap(
                    lambda hh, sg_, sh_, cn_: feature_best_splits(
                        hh, sg_, sh_, cn_, nb_c, mt_c, db_c, ic_c, hp,
                        has_categorical=True))(
                    chc, csums[0], csums[1], csums[2])
            else:
                cat_fb = None
            res = pick_fused_best(nfb, csums[0], csums[1], csums[2],
                                  feature_mask=feature_mask,
                                  cat_best=cat_fb, cat_idx=cat_idx)
            if cfg.max_depth > 0:
                dd = jnp.concatenate([depth_c, depth_c])
                res = res._replace(gain=jnp.where(
                    dd >= cfg.max_depth, -jnp.inf, res.gain))
        else:
            sl = small_left[idl][:, None, None, None]
            h_left = jnp.where(sl, seg, ph - seg)
            h_right = ph - h_left
            if use_mc:
                bl_min, bl_max, br_min, br_max = child_bounds(c)
                bmin = jnp.concatenate([bl_min[idl], br_min[idl]])
                bmax = jnp.concatenate([bl_max[idl], br_max[idl]])
            else:
                bmin = bmax = jnp.zeros(2 * KCAP, jnp.float32)
            node_of_k = c.split_idx + iota_K            # candidate node ids
            res = search_all(
                jnp.concatenate([h_left, h_right]),
                jnp.concatenate([lg_, rg_]), jnp.concatenate([lh_, rh_]),
                jnp.concatenate([lc_, rc_]),
                jnp.concatenate([depth_c, depth_c]), bmin, bmax,
                jnp.concatenate([node_of_k, node_of_k]),
                jnp.concatenate([jnp.zeros(KCAP, jnp.int32),
                                 jnp.ones(KCAP, jnp.int32)]))

        # -- maximal exact prefix: candidate i (in gain order) is the
        # best-first pop at step i iff its gain >= every child spawned by
        # candidates 0..i-1 (ties go to the existing leaf: children's leaf
        # numbers are always larger, and the reference ArgMax takes the
        # smallest leaf number).
        cg = jnp.where(jnp.isnan(res.gain), -jnp.inf, res.gain)
        pair_max = jnp.maximum(cg[:KCAP], cg[KCAP:])
        pair_max = jnp.where(iota_K < k, pair_max, -jnp.inf)
        pcm = jax.lax.cummax(pair_max)                  # children of 0..i
        sel_sorted = gains[idl]                         # gains by rank
        follow = (iota_K == 0) | (sel_sorted >= jnp.concatenate(
            [jnp.full((1,), -jnp.inf), pcm[:-1]]))
        if cfg.rounds_relaxed:
            # "fast" mode: always commit the whole batch.  Deviates from
            # strict best-first only when a child would have outranked a
            # batched candidate AND the leaf budget later binds — the same
            # class of tree-shape deviation the reference accepts between
            # its CPU and GPU learners.  ~log2(num_leaves) rounds, never a
            # short prefix.
            m = k
        else:
            m = jnp.minimum(k, jnp.cumprod(
                follow.astype(jnp.int32)).sum().astype(jnp.int32))

        sel_m = pos & (rank < m)
        cm = apply_round(c, sel_m, rank, m, gl, seg, crank)
        idc = jnp.concatenate([idl, jnp.clip(c.tree.num_leaves + iota_K,
                                             0, L - 1)])
        valid_m = jnp.concatenate([iota_K < m, iota_K < m])
        return cm._replace(best=cache_scatter(c.best, idc, res, valid_m))

    init = Carry(tree, best, hist_cache, leaf_sg, leaf_sh, leaf_cnt,
                 leaf_parent_side, leaf_id, jnp.array(0, jnp.int32),
                 leaf_min, leaf_max)
    out = lax.while_loop(cond, body, init)

    # finalize leaf values (reference: CalculateSplittedLeafOutput; clamped
    # to monotone bounds like grower.py; quantized renewal re-fits from
    # TRUE f32 sums — see grower.grow_tree's finalize)
    tree = out.tree
    leaf_sh_out = out.leaf_sh
    if quant and cfg.quant_renew:
        from .ops.renew import quant_train_renew_leaf
        sg_t, sh_t = quant_train_renew_leaf(out.leaf_id, grad, hess,
                                            row_mask, L)
        sg_t = _psum(sg_t, axis_name, hier_rd, pinned_rd)
        sh_t = _psum(sh_t, axis_name, hier_rd, pinned_rd)
        lv = leaf_output(sg_t, sh_t, hp.lambda_l1, hp.lambda_l2,
                         hp.max_delta_step)
        leaf_sh_out = sh_t
    else:
        lv = leaf_output(out.leaf_sg, out.leaf_sh, hp.lambda_l1,
                         hp.lambda_l2, hp.max_delta_step)
    if use_mc:
        lv = jnp.clip(lv, out.leaf_min, out.leaf_max)
    active = iota_L < tree.num_leaves
    tree = tree._replace(
        leaf_value=jnp.where(active, lv, 0.0),
        leaf_weight=jnp.where(active, leaf_sh_out, 0.0),
        leaf_count=jnp.where(active, out.leaf_cnt, 0.0),
    )
    return tree, out.leaf_id
